"""Validated accessors for the ``JEPSEN_TPU_*`` environment flags.

Every read of a ``JEPSEN_TPU_*`` variable anywhere in the tree goes
through this module; the ``env-flag-accessor`` rule in
``jepsen_tpu.analysis`` enforces that mechanically. Why it exists: a
malformed flag value must fail loudly at the read site, not silently
revert a measured default. The motivating incident is the round-5
pallas flip — with the old raw read (``flag == "1"``), a stray
``JEPSEN_TPU_PALLAS=yes`` would have silently disabled the measured
54x win, and nothing would have said so.

Contract:

* ``env_bool`` flags are strict tri-state: unset means "use the code
  default", ``"1"`` means on, ``"0"`` means off, and anything else
  raises :class:`EnvFlagError`.
* ``env_choice`` flags accept exactly the listed strings.
* Names must carry the ``JEPSEN_TPU_`` prefix — the accessor refuses
  to read anything else, so the namespace stays greppable.

This module must stay importable with no JAX and no device runtime:
the static-analysis pass (and its CI gate) runs CPU-only before any
backend exists, and engine modules import it at module scope.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

PREFIX = "JEPSEN_TPU_"


class EnvFlagError(ValueError):
    """A JEPSEN_TPU_* variable is set to a value outside its contract."""


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw string value of a prefixed flag (no validation beyond
    the namespace check). Prefer the typed accessors below."""
    if not name.startswith(PREFIX):
        raise EnvFlagError(
            f"{name!r} is not a {PREFIX}* flag — the accessor only "
            f"serves the jepsen_tpu namespace")
    return os.environ.get(name, default)


def env_bool(name: str, default: Optional[bool] = None) -> Optional[bool]:
    """Strict tri-state boolean flag.

    Unset -> ``default`` (pass ``None`` to mean "let the code pick a
    platform default"), ``"1"`` -> True, ``"0"`` -> False. Any other
    value raises :class:`EnvFlagError` instead of silently counting as
    an opt-out — the exact failure mode that nearly reverted the
    measured pallas default in round 5.
    """
    raw = env_raw(name)
    if raw is None:
        return default
    if raw == "1":
        return True
    if raw == "0":
        return False
    raise EnvFlagError(
        f"{name}={raw!r}: must be '1' (on) or '0' (off); unset the "
        f"variable to get the default")


def env_choice(name: str, choices: Sequence[str],
               default: Optional[str] = None,
               what: str = "value") -> Optional[str]:
    """A flag restricted to an explicit set of strings. Unset ->
    ``default``; anything outside ``choices`` raises
    :class:`EnvFlagError` (the message names ``what`` so callers'
    error-matching tests read naturally)."""
    raw = env_raw(name)
    if raw is None:
        return default
    if raw in choices:
        return raw
    raise EnvFlagError(
        f"{name}={raw!r}: unknown {what} (expected one of "
        f"{tuple(choices)})")


def env_int(name: str, default: Optional[int] = None,
            min_value: Optional[int] = None,
            what: str = "value") -> Optional[int]:
    """An integer flag. Unset -> ``default``; a non-integer value or
    one below ``min_value`` raises :class:`EnvFlagError` — same
    fail-loud contract as the other accessors (a malformed cache-size
    flag must not silently disable the cache or blow up later in an
    unrelated stack)."""
    raw = env_raw(name)
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise EnvFlagError(
            f"{name}={raw!r}: must be an integer {what}; unset the "
            f"variable to get the default")
    if min_value is not None and v < min_value:
        raise EnvFlagError(
            f"{name}={raw!r}: {what} must be >= {min_value}")
    return v


def env_float(name: str, default: Optional[float] = None,
              min_value: Optional[float] = None,
              what: str = "value") -> Optional[float]:
    """A float flag (seconds-style knobs). Unset -> ``default``; a
    non-numeric value or one below ``min_value`` raises
    :class:`EnvFlagError` — a malformed watchdog timeout must not
    silently disable the watchdog (the exact no-op failure the whole
    module exists to prevent)."""
    raw = env_raw(name)
    if raw is None:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise EnvFlagError(
            f"{name}={raw!r}: must be a number {what}; unset the "
            f"variable to get the default")
    import math
    if not math.isfinite(v):
        # float() happily parses "inf"/"nan", but a non-finite
        # watchdog/backoff would blow up far from the read site
        # (Thread.join(inf) raises OverflowError per dispatch) — the
        # exact silent-misconfiguration mode this accessor prevents
        raise EnvFlagError(
            f"{name}={raw!r}: {what} must be finite")
    if min_value is not None and v < min_value:
        raise EnvFlagError(
            f"{name}={raw!r}: {what} must be >= {min_value}")
    return v


def env_path(name: str, what: str = "path") -> Optional[str]:
    """A tri-state *destination* flag: unset or ``"0"`` -> ``None``
    (feature off), ``"1"`` -> ``""`` (feature on, caller picks the
    default destination), anything else -> that value as a filesystem
    path (feature on, write there). Whitespace-only values raise —
    a stray ``JEPSEN_TPU_TRACE=" "`` must not silently create a
    directory named after the typo. Used by the telemetry flags
    (``JEPSEN_TPU_TRACE``, ``JEPSEN_TPU_JAX_PROFILE``)."""
    raw = env_raw(name)
    if raw is None or raw == "0":
        return None
    if raw == "1":
        return ""
    if not raw.strip():
        raise EnvFlagError(
            f"{name}={raw!r}: must be '0' (off), '1' (on, default "
            f"destination), or a {what}")
    return raw


# Registry of the JEPSEN_TPU_* flags in circulation — one line per
# flag, naming the accessor and the owning module, so the namespace
# stays auditable in one place (the env-flag-accessor lint rule keeps
# every READ going through this module; this table documents what a
# grep for the prefix should find):
#
#   JEPSEN_TPU_PALLAS        env_bool    parallel.bitdense — closure
#                            kernel default (r5 on-chip verdict)
#   JEPSEN_TPU_CLOSURE       env_choice  parallel.bitdense — XLA loop
#                            shape ("while"/"fori")
#   JEPSEN_TPU_BUCKET        env_choice  parallel.engine — batch
#                            bucketing strategy ("tier"/"exact")
#   JEPSEN_TPU_DEDUPE        env_choice  parallel.engine — sparse
#                            frontier dedupe strategy ("sort"/"hash":
#                            lexsort vs delta-frontier closure over a
#                            device-resident hash visited-set, also
#                            sharded by owner in parallel.sharded);
#                            opt-in until bench records a win
#   JEPSEN_TPU_SPARSE_PALLAS env_bool    parallel.engine — fuse the
#                            hash dedupe path into the VMEM-resident
#                            pallas frontier kernel
#                            (parallel.sparse_kernels; whole-event
#                            closure single-device, per-iteration
#                            insert in parallel.sharded); "1" forces
#                            it on (interpret mode off-TPU, like
#                            JEPSEN_TPU_PALLAS); opt-in until
#                            tools/perf_ab.py's hash-pallas strategy
#                            records the on-chip win
#   JEPSEN_TPU_SEARCH_STATS  env_bool    parallel.engine — device-
#                            resident search telemetry: when on, the
#                            engine jits (sparse XLA + pallas,
#                            bitdense, sharded, streaming-resumable)
#                            additionally return a per-event stats
#                            block computed on device (frontier-width
#                            trajectory, closure iterations, delta
#                            split, hash-table load factor, bucketed
#                            probe-length histogram, pad waste),
#                            threaded into result "stats" dicts, the
#                            engine.search.* registry names (/metrics),
#                            Perfetto counter tracks, and `jepsen
#                            report --search`; default off — results,
#                            bench schema, and trace files byte-
#                            identical to the pre-stats engine
#   JEPSEN_TPU_CONFIG_PACK   env_bool    parallel.engine — pack each
#                            configuration's (state, mask_lo, mask_hi)
#                            triple into the minimal word the event
#                            family needs (state field + C mask bits,
#                            1-2 uint32 lanes instead of 3): shrinks
#                            the frontier, the hash visited-set, the
#                            FrontierCheckpoint carry boundary, and
#                            the sharded all-to-all payloads, and
#                            widens the sparse kernels' width-aware
#                            VMEM gate; families past 64 bits run
#                            unpacked (tagged). Verdicts and counters
#                            are representation-independent
#                            (parity-pinned); opt-in until the chip
#                            A/B (tools/perf_ab.py hash-packed)
#                            records the win
#   JEPSEN_TPU_VMEM_BUDGET   env_int     parallel.sparse_kernels — the
#                            probe-state VMEM budget (bytes) gating
#                            the fused/tiled sparse kernels (default
#                            4 MiB, min 64 KiB): the one knob that
#                            re-gates every sparse kernel for a
#                            different TPU generation without a code
#                            edit
#   JEPSEN_TPU_PROBE_LIMIT   env_int     parallel.engine — bounded
#                            linear-probe length of the hash
#                            visited-set (default 32, min 1); one
#                            knob for the XLA and pallas hash paths;
#                            exhaustion escalates capacity, never
#                            drops a config
#   JEPSEN_TPU_PIPELINE      env_bool    parallel.engine — route
#                            check_batch through the pipelined
#                            executor (parallel.pipeline); opt-in
#                            until bench records a win
#   JEPSEN_TPU_STEAL         env_bool    parallel.engine — skew-driven
#                            key work-stealing in the multi-key
#                            executors (parallel.elastic): buckets
#                            dispatch in device-aligned rounds and a
#                            scheduler migrates pending keys between
#                            per-device queues from observed
#                            search-stats/cost signals; results
#                            bit-identical to the static placement
#                            (parity-pinned); opt-in until
#                            tools/perf_ab.py's steal arm records the
#                            win
#   JEPSEN_TPU_STEAL_ROUND   env_int     parallel.elastic — keys per
#                            device per dispatch round of the stealing
#                            executor (default 1, min 1): smaller =
#                            more observation/rebalance points, larger
#                            = fewer, bigger device programs
#   JEPSEN_TPU_RESHARD       env_bool    parallel.engine/sharded —
#                            re-shard-on-escalation: a sharded search
#                            (incl. the batch overflow escalation
#                            tier) starts on a narrow device slice and
#                            capacity overflow RECRUITS devices along
#                            MeshPlan.ladder's rungs (wider 1-D, then
#                            2-D slice promotion) at flat per-device
#                            capacity before growing tables; overflow
#                            semantics and ceilings unchanged; opt-in
#                            until the perf_ab reshard arm records the
#                            win
#   JEPSEN_TPU_DIST          env_bool    parallel.meshplan — arm the
#                            jax.distributed multi-host handshake
#                            (meshplan.distributed_init): off/unset =
#                            single-host, no initialize call ever;
#                            "1" REQUIRES the three companion flags
#                            below (a half-configured pod plan raises
#                            at the read site instead of hanging in a
#                            collective)
#   JEPSEN_TPU_DIST_COORD    env_raw     parallel.meshplan — the
#                            jax.distributed coordinator address
#                            (host:port), validated for the colon
#   JEPSEN_TPU_DIST_NPROC    env_int     parallel.meshplan — total
#                            process count of the distributed run
#                            (min 1)
#   JEPSEN_TPU_DIST_PROC     env_int     parallel.meshplan — this
#                            process's id (0-based, < NPROC)
#   JEPSEN_TPU_ENCODE_CACHE  env_int     parallel.pipeline — encode
#                            cache capacity in entries (0 disables)
#   JEPSEN_TPU_COMPILE_CACHE env_path    parallel.programs — the
#                            compile-economics master switch
#                            (docs/performance.md "Compile
#                            economics"): unset/"0" off (plain jit
#                            dispatch, byte-identical results and
#                            schemas), "1" arms the in-process program
#                            registry (AOT lower().compile() engine
#                            programs, engine.programs.* counters,
#                            serve.compile_secs histogram, freeze-time
#                            program manifests for warm rehome
#                            handoff), <dir> additionally persists
#                            serialized executables there so a
#                            restarted replica cold-starts warm
#                            (loads are version/fingerprint-guarded:
#                            a mismatch degrades to a fresh compile,
#                            counted load_errors — never a wrong
#                            program); bench.py reuses the same dir
#                            for its jax compilation cache
#   JEPSEN_TPU_CANON_SHAPES  env_bool    parallel.programs — shape
#                            canonicalization: quantize one-shot and
#                            resumable-chunk event-row counts onto the
#                            EVENT_QUANTUM ladder (the streaming
#                            chunk-padding precedent) so the
#                            fleet-wide program population is dozens,
#                            not one per history length; pad rows are
#                            scan no-ops — verdict/counterexample/
#                            max-frontier/configs-stepped identical
#                            (parity-pinned); opt-in until perf_ab's
#                            compile record shows the population win
#                            against the pad-waste telemetry
#   JEPSEN_TPU_PRECOMPILE    env_bool    parallel.programs — ladder
#                            precompile: a background best-effort
#                            thread pre-compiles the next capacity
#                            rung (N doubled, same event shapes)
#                            above every live AOT program so a
#                            mid-incident escalation re-dispatch
#                            finds its program resident (counted
#                            engine.programs.precompiles); requires
#                            JEPSEN_TPU_COMPILE_CACHE armed
#   JEPSEN_TPU_TEST_WEDGE    env_bool    resilience.faults — legacy
#                            alias for the bench child-wedge seam; =1
#                            now injects an implicit `wedge@child`
#                            fault rule (prefer JEPSEN_TPU_FAULTS)
#   JEPSEN_TPU_FAULTS        env_raw     resilience.faults — the
#                            deterministic fault-injection plan:
#                            comma-separated `<kind>@<site>[:<count>]`
#                            specs (`wedge@dispatch:2`,
#                            `raise@transfer:every=3`,
#                            `flaky@search:n=1`); validated by
#                            faults.parse_spec — a malformed spec
#                            raises FaultSpecError (an EnvFlagError),
#                            never a silent no-op
#   JEPSEN_TPU_WATCHDOG      env_float   resilience.supervisor —
#                            bounded wait (seconds) on every
#                            supervised device dispatch; a dispatch
#                            past the bound raises DispatchWedged
#                            instead of hanging the process (the r05
#                            make_c_api_client signature). Unset/0 =
#                            off (the supervised call is a near-zero-
#                            overhead passthrough)
#   JEPSEN_TPU_DISPATCH_RETRIES env_int  resilience.supervisor — extra
#                            attempts after a transient dispatch
#                            failure while the breaker stays closed
#                            (default 1, min 0)
#   JEPSEN_TPU_BREAKER_THRESHOLD env_int resilience.breaker —
#                            consecutive dispatch failures that open a
#                            backend's circuit breaker (default 3,
#                            min 1)
#   JEPSEN_TPU_BREAKER_BACKOFF env_float resilience.breaker — base
#                            open-state backoff seconds (default 1.0;
#                            doubles per re-open, jittered, capped)
#   JEPSEN_TPU_TRACE         env_path    obs — span tracing: "0"/unset
#                            off (a true no-op), "1" on (artifacts land
#                            in the store run dir / bench trace dir),
#                            <path> on + Chrome trace JSON written there
#   JEPSEN_TPU_JAX_PROFILE   env_path    obs — wrap device dispatch in
#                            jax.profiler.trace(<dir>) with
#                            TraceAnnotation-named steps so host spans
#                            line up with the TPU timeline in Perfetto
#   JEPSEN_TPU_SERVE_QUEUE   env_int     serve.service — per-key
#                            pending-delta queue bound (default 64,
#                            min 1); a full queue BLOCKS the producer
#                            (backpressure), never buffers unboundedly
#   JEPSEN_TPU_SERVE_GLOBAL  env_int     serve.service — global
#                            pending-ops hard bound across all keys
#                            (default 65536, min 1); the service's
#                            memory ceiling for unapplied deltas
#   JEPSEN_TPU_SERVE_HIGH_WATER env_int  serve.service — pending-ops
#                            level past which NEW deltas are shed with
#                            a structured {shed, reason} response
#                            (default: 3/4 of the global bound; 0
#                            disables shedding — producers then block
#                            at the hard bound instead)
#   JEPSEN_TPU_SERVE_EVICT_SECS env_float serve.service — idle seconds
#                            before a key's frontier is frozen to the
#                            checkpoint store and its in-memory state
#                            dropped (default 300; 0 disables; thaw on
#                            the next delta is transparent and
#                            digest-guarded)
#   JEPSEN_TPU_SERVE_WAL     env_path    serve.service — the delta WAL
#                            + checkpoint-store directory: unset/"0"
#                            no WAL (in-memory service, no eviction),
#                            "1" store/serve_wal, <path> there; every
#                            ADMITTED delta is fsynced here before the
#                            producer sees {"accepted"}
#   JEPSEN_TPU_SERVE_WAL_SEGMENT_BYTES env_int serve.wal — auto-rotate
#                            a key's active WAL segment once it grows
#                            past this many bytes (0/unset = no auto
#                            rotation; DeltaWAL.rotate() always
#                            available): segmented files are what
#                            per-tenant WAL quotas meter and replica
#                            handoff ships
#   JEPSEN_TPU_SERVE_REPL    env_choice  serve.fleet — WAL segment
#                            replication mode: "off" (default) |
#                            "async" (segments ship to the key's ring
#                            successor from a background thread;
#                            serve.repl_lag_keys gauges the lag, which
#                            is also the loss window if the primary's
#                            DISK dies) | "sync" (the producer's ack
#                            waits for successor durability — a dead
#                            node with a dead disk then loses nothing
#                            acknowledged); a non-off mode with no
#                            replication target wired (replicator= /
#                            --repl-dir) raises at service
#                            construction instead of silently
#                            protecting nothing
#   JEPSEN_TPU_FLEET_INTERVAL env_float  serve.fleet — supervisor
#                            heartbeat interval seconds (default 2.0,
#                            min 0.01): how often every replica's
#                            /healthz is polled and breakers advance
#   JEPSEN_TPU_FLEET_THRESHOLD env_int   serve.fleet — consecutive
#                            /healthz misses before a replica is
#                            declared dead and its keys rehomed
#                            (default 3, min 1; the PR-6 breaker
#                            threshold, per replica)
#   JEPSEN_TPU_FLEET_REHOME_RETRIES env_int serve.fleet — bounded
#                            rehome attempts per supervision tick
#                            (default 3, min 1; exponential backoff
#                            between attempts, then the next tick
#                            retries — a rehome is idempotent)
#   JEPSEN_TPU_TENANTS       env_raw     serve.tenancy — the tenant
#                            table: comma-separated
#                            `<name>[:token=T][:weight=W][:ops=N]
#                            [:keys=N][:wal=BYTES]` declarations,
#                            strictly validated (TenantSpecError, an
#                            EnvFlagError, on any malformed field —
#                            a typo'd tenant plan must never silently
#                            run un-isolated); unset = single-tenant
#                            mode, byte-identical to the PR 7/8
#                            service
#   JEPSEN_TPU_TENANT_OPS    env_int     serve.tenancy — default
#                            per-tenant pending-ops quota when a
#                            tenant declares no `ops=` (0/unset =
#                            derive each tenant's bound as its weight
#                            share of the shed high-water)
#   JEPSEN_TPU_TENANT_KEYS   env_int     serve.tenancy — default
#                            per-tenant concurrent-key quota when a
#                            tenant declares no `keys=` (0/unset =
#                            unlimited)
#   JEPSEN_TPU_TENANT_WAL_BYTES env_int  serve.tenancy — default
#                            per-tenant WAL-bytes quota when a tenant
#                            declares no `wal=` (0/unset = unlimited);
#                            a tenant past it sheds new deltas until
#                            the operator rotates/archives its keys
#   JEPSEN_TPU_TENANT_QUANTUM env_int    serve.tenancy — deficit-
#                            round-robin quantum: ops of service
#                            credit one weight unit banks per worker
#                            cycle (default 512, min 1); smaller =
#                            finer-grained fairness, larger = bigger
#                            batched device programs
#   JEPSEN_TPU_INGRESS_PORT  env_int     serve.ingress — the HTTP
#                            delta-ingress port for `jepsen serve
#                            --checker` (streamed-JSONL POST
#                            /v1/deltas + /v1/result + /v1/finalize,
#                            per-tenant bearer-token auth; 0 =
#                            OS-assigned; unset = stdio only);
#                            `--ingress-port` overrides
#   JEPSEN_TPU_OPS_PORT      env_int     obs.httpd — the live ops
#                            endpoint port for `jepsen serve
#                            --checker` (/metrics Prometheus text,
#                            /healthz, /status; 0 = OS-assigned;
#                            unset = no endpoint, serve behavior
#                            byte-identical to the pre-ops service);
#                            `--ops-port` overrides
#   JEPSEN_TPU_PROBE_INTERVAL env_float  probe — continuous chip
#                            watch: re-run the subprocess probe_json
#                            every N seconds on a daemon thread and
#                            publish probe.chip_healthy /
#                            probe.last_ok_age_secs gauges (feeding
#                            /healthz + flight dumps); unset/0 = off
#                            (no thread)
#   JEPSEN_TPU_SLOW_DELTA_SECS env_float serve.service — slow-delta
#                            forensics threshold: a delta whose
#                            ingest->verdict latency crosses this many
#                            seconds lands a structured record (stage
#                            breakdown admission/backpressure/wal/
#                            queue/device/publish, verdict, resilience
#                            note, search-stats block when armed) in a
#                            bounded newest-wins ring — surfaced on
#                            /status, drained into slow_deltas.jsonl
#                            by export_run, rendered by `jepsen report
#                            --slow`, and flight-dumped on the worst
#                            offender. Also arms per-delta trace
#                            identity (delta_id minting + WAL id
#                            stamping) like JEPSEN_TPU_TRACE /
#                            JEPSEN_TPU_FLIGHT_RECORDER do. Unset/0 =
#                            off — serve results, WAL bytes, /status
#                            and /metrics schema byte-identical to the
#                            pre-forensics service
#   JEPSEN_TPU_FLIGHT_RECORDER env_int   obs.tracer — crash flight
#                            recorder: retain the last N closed spans
#                            in a bounded ring EVEN WITH TRACING OFF
#                            ("1" = the default 256; N>=2 = that
#                            capacity), dumped as a Chrome-trace file
#                            (+ metric delta) on DispatchWedged,
#                            breaker open, serve shed, or serve
#                            worker error; unset/0 = off — span() is
#                            then the historical no-op singleton
#   JEPSEN_TPU_LEDGER        env_path    obs.ledger — the decision
#                            ledger: append one durable JSONL evidence
#                            record per device dispatch / escalation /
#                            reshard / steal / serve publish (shape
#                            fingerprint, strategy vector, secs,
#                            stats digest, outcome) into bounded
#                            segments under the given dir ("1" =
#                            store/ledger). Aggregated on /ledger,
#                            snapshotted into run dirs as
#                            ledger.jsonl, joined by `jepsen report
#                            --plan`. Unset/"0" = off — no file, no
#                            obs.ledger.* metric, results/bench/
#                            trace/metrics byte-identical
#   JEPSEN_TPU_LEDGER_SEGMENT_BYTES env_int obs.ledger — rotate the
#                            active ledger segment past this many
#                            bytes (default 1048576, min 4096)
#   JEPSEN_TPU_LEDGER_SEGMENTS env_int   obs.ledger — retained
#                            segment count; older segments are
#                            unlinked, bounding the ledger's disk
#                            footprint (default 8, min 2)
#   JEPSEN_TPU_LEDGER_FLOOR  env_int     obs.advisor — `jepsen report
#                            --plan` per-cell sample floor: a
#                            shape×strategy cell with fewer ledger
#                            records recommends nothing
#                            ("insufficient evidence") instead of
#                            guessing (default 3, min 1)
#   JEPSEN_TPU_SLO_ACK_SECS  env_float   obs.slo — the serve ack-
#                            latency SLO target (seconds, objective
#                            99%): arms the two-window burn-rate
#                            gauges serve.slo.ack_burn_rate[window=
#                            fast|slow] derived from serve.ack_secs
#                            histogram deltas on every /metrics
#                            refresh, and the /healthz "slo" check.
#                            Unset/0 = off — /metrics and /healthz
#                            byte-identical
#   JEPSEN_TPU_SLO_BURN_MAX  env_float   obs.slo — degrade /healthz
#                            readiness when the FAST-window burn rate
#                            exceeds this (burn 1.0 = consuming error
#                            budget exactly on schedule); unset/0 =
#                            never degrade, gauges only
#   JEPSEN_TPU_AUTO          env_bool    parallel.planner — the self-
#                            tuning strategy planner: per slot-window
#                            bucket, pick the strategy vector (dedupe,
#                            pallas closure, pack, pipeline, steal)
#                            from a per-shape decision table seeded by
#                            the `jepsen report --plan` advisor join
#                            and updated online (EWMA per
#                            shape×strategy cell) from every
#                            dispatch's measured secs; below the
#                            JEPSEN_TPU_LEDGER_FLOOR sample floor the
#                            static defaults run and the dispatch only
#                            contributes evidence. A plan routes only
#                            between parity-pinned paths — never a
#                            verdict change. Results/"plan" blocks,
#                            /status rows, kind=plan ledger records,
#                            engine.plan.* metrics, /plan endpoint;
#                            table durable beside the ledger segments.
#                            Unset/"0" = off, byte-identical
#                            (docs/performance.md "Auto planner")
#   JEPSEN_TPU_AUTO_EXPLORE  env_int     parallel.planner — run the
#                            least-sampled non-chosen arm every Nth
#                            auto decision per shape group so a stale
#                            seed self-corrects (default 8, min 0;
#                            0 = never explore)
