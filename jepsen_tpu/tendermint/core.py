"""Tendermint test suite assembly
(reference: tendermint/src/jepsen/tendermint/core.clj).

Clients (cas-register, set), the byzantine dup-validator grudges, the
crash/truncate and changing-validators nemeses, the nemesis menu, the
workload map, and the top-level test constructor. The system under
test's data plane is reached through a *transport*:
test["transport_for"](test, node) -> client.SocketTransport |
client.HttpTransport — local runs point every node at native
merkleeyes instances, cluster runs at tendermint RPC."""

from __future__ import annotations

import contextlib
import logging
import math
from typing import Callable, Dict, Optional

from jepsen_tpu import checker as jchecker
from jepsen_tpu import client as jclient
from jepsen_tpu import control as c
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu import nemesis as jnemesis
from jepsen_tpu.checker import timeline as jtimeline
from jepsen_tpu.history import Op
from jepsen_tpu.models import CASRegister
from jepsen_tpu.nemesis import time as nt
from jepsen_tpu.tendermint import client as tc
from jepsen_tpu.tendermint import db as td
from jepsen_tpu.tendermint import validator as tv
from jepsen_tpu.workloads import noop_test

log = logging.getLogger(__name__)


# ------------------------------------------------------- op generators


def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rand.randint(0, 9)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [gen.rand.randint(0, 9), gen.rand.randint(0, 9)]}


# ------------------------------------------------------------- clients


def _transport(test, node):
    tf = test.get("transport_for")
    assert tf is not None, "test map has no :transport_for"
    return tf(test, node)


@contextlib.contextmanager
def _map_errors(o: Op, crash: str):
    """Shared tx-error taxonomy (core.clj:57-75,116-138): code 8 ->
    :fail precondition-failed, code 7 -> :fail not-found, connection
    refused -> :fail, other network faults -> `crash` (:info for
    writes, :fail for reads) with an indeterminate error."""
    try:
        yield
    except tc.Unauthorized:
        o["type"] = "fail"
        o["error"] = "precondition-failed"
    except tc.BaseUnknownAddress:
        o["type"] = "fail"
        o["error"] = "not-found"
    except ConnectionRefusedError:
        o["type"] = "fail"
        o["error"] = "connection-refused"
    except (ConnectionError, TimeoutError, OSError) as e:
        o["type"] = crash
        o["error"] = f"indeterminate: {e}"


class CasRegisterClient(jclient.Client):
    """read/write/cas on independent [k v] tuples (core.clj:33-80).
    Error mapping: code 8 -> :fail precondition-failed; code 7 -> :fail
    not-found; connection refused -> :fail; timeouts and other network
    faults crash (:info) for writes, :fail for reads."""

    def __init__(self, node=None):
        self.node = node

    def open(self, test, node):
        return CasRegisterClient(node)

    def invoke(self, test, op):
        o = Op(op)
        k, v = op.get("value")
        crash = "fail" if op.get("f") == "read" else "info"
        t = _transport(test, self.node)
        with _map_errors(o, crash):
            f = op.get("f")
            if f == "read":
                o["type"] = "ok"
                o["value"] = independent.KV(k, tc.read(t, k))
            elif f == "write":
                tc.write(t, k, v)
                o["type"] = "ok"
            elif f == "cas":
                old, new = v
                tc.cas(t, k, old, new)
                o["type"] = "ok"
            else:
                raise ValueError(f"unknown f {f!r}")
        return o

    def is_reusable(self, test):
        return True


class SetClient(jclient.Client):
    """CAS-append to a vector per key (core.clj:82-139): :init writes
    [], :add CASes v onto the current vector, :read returns the set."""

    def __init__(self, node=None):
        self.node = node

    def open(self, test, node):
        return SetClient(node)

    def invoke(self, test, op):
        import time as _time
        o = Op(op)
        k, v = op.get("value")
        crash = "fail" if op.get("f") == "read" else "info"
        t = _transport(test, self.node)
        with _map_errors(o, crash):
            f = op.get("f")
            if f == "init":
                tries = 0
                while True:
                    try:
                        tc.write(t, k, [])
                        break
                    except Exception:  # noqa: BLE001 - retry w/ backoff
                        if tries >= 10:
                            raise
                        _time.sleep(0.05 * (2 ** tries))
                        tries += 1
                o["type"] = "ok"
            elif f == "add":
                s = tc.read(t, k) or []
                tc.cas(t, k, s, list(s) + [v])
                o["type"] = "ok"
            elif f == "read":
                got = tc.read(t, k)
                o["type"] = "ok"
                o["value"] = independent.KV(k, set(got or []))
            else:
                raise ValueError(f"unknown f {f!r}")
        return o

    def is_reusable(self, test):
        return True


# ------------------------------------------- byzantine partition shapes


def peekaboo_dup_validators_grudge(test) -> Callable:
    """Isolates all-but-one node of every dup group (core.clj:140-159):
    one randomly chosen member of each dup group stays with the
    majority; the rest are exiled into singleton components."""
    def grudge(nodes):
        cfg = test["validator_config"][0]
        groups = tv.dup_groups(cfg)
        chosen = [gen.rand.choice(sorted(g)) for g in groups["dups"]]
        exiles = [[n for n in g if n != ch]
                  for g, ch in zip(groups["dups"], chosen)]
        main = [n for g in groups["singles"] for n in g] + chosen
        return jnemesis.complete_grudge([main] + exiles)
    return grudge


def split_dup_validators_grudge(test) -> Callable:
    """Splits the net into n components, each with one member of the
    dup group and a share of the rest (core.clj:161-179)."""
    def grudge(nodes):
        cfg = test["validator_config"][0]
        groups = tv.dup_groups(cfg)
        n = max((len(g) for g in groups["dups"]), default=1)
        shuffled_groups = [sorted(g) for g in groups["groups"]]
        gen.rand.shuffle(shuffled_groups)
        for g in shuffled_groups:
            gen.rand.shuffle(g)
        flat = [node for g in shuffled_groups for node in g]
        components = [[] for _ in range(n)]
        for i, node in enumerate(flat):
            components[i % n].append(node)
        return jnemesis.complete_grudge([comp for comp in components
                                         if comp])
    return grudge


# ----------------------------------------------------- custom nemeses


class CrashTruncateNemesis(jnemesis.Nemesis):
    """Kill both daemons, truncate a file's tail, restart
    (core.clj:181-217), on a fixed random subset of nodes."""

    def __init__(self, test, file: str, fraction: float = 1 / 3):
        nodes = sorted(test.get("nodes") or [])
        gen.rand.shuffle(nodes)
        k = int(math.floor(fraction * len(nodes)))
        self.file = file
        self.faulty_nodes = nodes[:k]

    def invoke(self, test, op):
        if op.get("f") == "stop":
            return jnemesis._ok(op)
        assert op.get("f") == "crash"

        def crash(t, node):
            td.stop_tendermint(t, node)
            td.stop_merkleeyes(t, node)
            with c.su():
                c.exec_("truncate", "-c", "-s",
                        f"-{gen.rand.randint(0, 1048575)}",
                        td.base_dir(t) + self.file)
            td.start_merkleeyes(t, node)
            td.start_tendermint(t, node)
            return "crashed"

        res = c.on_nodes(test, crash, self.faulty_nodes)
        return jnemesis._ok(op, value=res)

    def teardown(self, test):
        c.on_nodes(test, td.start_merkleeyes, self.faulty_nodes)
        c.on_nodes(test, td.start_tendermint, self.faulty_nodes)

    def fs(self):
        return {"crash", "stop"}


def crash_nemesis() -> jnemesis.NodeStartStopper:
    """Kill merkleeyes + tendermint on all nodes (core.clj:219-223).
    Daemon control shells out, so each call runs inside on_nodes to
    bind the node's control session."""
    def bound(f):
        def g(test, node):
            return c.on_nodes(test, f, [node])[node]
        return g
    return jnemesis.NodeStartStopper(
        lambda nodes: list(nodes), bound(td.stop), bound(td.start))


class ChangingValidatorsNemesis(jnemesis.Nemesis):
    """Applies validator transitions to the cluster (core.clj:225-278):
    pre-step the local config, perform the change (valset CAS / node
    create / destroy), then post-step. On failure the local config is
    rolled back and the error propagates as an :info op."""

    def _invoke(self, test, op):
        if op.get("f") == "stop":
            return jnemesis._ok(op)
        assert op.get("f") == "transition", op
        t = op.get("value")
        box = test["validator_config"]
        before = box[0]
        box[0] = tv.pre_step(before, t)
        ty = t["type"]
        if ty == "add":
            v = t["validator"]
            tc.with_any_node(test, tc.validator_set_cas, t["version"],
                             v["pub_key"], v["votes"])
        elif ty == "remove":
            tc.with_any_node(test, tc.validator_set_cas, t["version"],
                             t["pub_key"], 0)
        elif ty == "alter-votes":
            tc.with_any_node(test, tc.validator_set_cas, t["version"],
                             t["pub_key"], t["votes"])
        elif ty == "create":
            def create(tst, node):
                td.write_validator(tst, node, t["validator"])
                td.start(tst, node)
            c.on_nodes(test, create, [t["node"]])
        elif ty == "destroy":
            def destroy(tst, node):
                td.stop(tst, node)
                td.reset_validator(tst, node)
            c.on_nodes(test, destroy, [t["node"]])
        else:
            box[0] = before
            raise ValueError(f"unknown transition {ty!r}")
        box[0] = tv.post_step(box[0], t)
        return jnemesis._ok(op, value="done")

    def invoke(self, test, op):  # noqa: F811 - wraps _invoke w/ rollback
        box = test["validator_config"]
        before = box[0]
        try:
            return self._invoke(test, op)
        except (tc.Unauthorized, tc.BaseUnknownAddress) as e:
            if getattr(e, "prior_indeterminate", False):
                # The rejection came after an earlier node's network
                # failure — the change may have landed there (and the
                # retry's CAS then lost against the new version). Not
                # definite: fall through to the indeterminate handling.
                raise
            # Definite failure: the valset CAS was rejected by the app
            # on the first attempt, so nothing changed on the cluster —
            # roll the local config back to the pre-transition state.
            box[0] = before
            raise
        except Exception:
            # Indeterminate (network error, timeout, node crash): the
            # change MAY have been applied on the cluster. Keep the
            # pre-step config — it retains the prospective validator so
            # the next refresh_config can reconcile either outcome
            # (core.clj leaves pre-step state in place for exactly this
            # reason). Rolling back here would make a landed validator
            # unrecognizable: validator_set_to_vote_map would raise on
            # every later refresh and the transition generator would be
            # stuck on a permanently stale config.
            raise

    def fs(self):
        return {"transition", "stop"}


# --------------------------------------------------------- nemesis menu


def refresh_config(test):
    """Reconcile the local validator config with a transactional read
    of the cluster's validator set (validator.clj:961-977
    refresh-config!). Returns the (possibly unchanged) config."""
    box = test["validator_config"]
    try:
        vs = tc.with_any_node(test, tc.validator_set)
        if vs is not None:
            box[0] = tv.current_config(box[0], vs)
    except Exception as e:  # noqa: BLE001 - cluster may be unreachable
        log.debug("refresh_config failed: %r", e)
    return box[0]


def nemesis_package(test) -> dict:
    """{nemesis, generator} per profile (core.clj:287-340)."""
    kind = test.get("nemesis_name", "none")
    if kind == "changing-validators":
        return {"nemesis": ChangingValidatorsNemesis(),
                "generator": gen.stagger(1, tv.generator(
                    test.get("refresh_config", refresh_config)))}
    if kind == "peekaboo-dup-validators":
        return {"nemesis":
                jnemesis.partitioner(peekaboo_dup_validators_grudge(test)),
                "generator": [{"type": "info", "f": "start"},
                              gen.sleep(5),
                              {"type": "info", "f": "stop"}]}
    if kind == "split-dup-validators":
        return {"nemesis":
                jnemesis.partitioner(split_dup_validators_grudge(test)),
                "generator": gen.once({"type": "info", "f": "start"})}
    if kind == "half-partitions":
        return {"nemesis": jnemesis.partition_random_halves(),
                "generator": [gen.sleep(5), {"type": "info", "f": "start"},
                              gen.sleep(30), {"type": "info", "f": "stop"}]}
    if kind == "ring-partitions":
        return {"nemesis": jnemesis.partition_majorities_ring(),
                "generator": [gen.sleep(5), {"type": "info", "f": "start"},
                              gen.sleep(30), {"type": "info", "f": "stop"}]}
    if kind == "single-partitions":
        return {"nemesis": jnemesis.partition_random_node(),
                "generator": [gen.sleep(5), {"type": "info", "f": "start"},
                              gen.sleep(30), {"type": "info", "f": "stop"}]}
    if kind == "clocks":
        return {"nemesis": nt.clock_nemesis(),
                "generator": gen.stagger(0.5, nt.clock_gen())}
    if kind == "crash":
        return {"nemesis": crash_nemesis(),
                "generator": [gen.sleep(15), {"type": "info", "f": "start"},
                              {"type": "info", "f": "stop"}]}
    if kind == "truncate-merkleeyes":
        return {"nemesis": CrashTruncateNemesis(
                    test, "/jepsen/jepsen.db/000001.log"),
                "generator": gen.delay(1, gen.repeat(
                    {"type": "info", "f": "crash"}))}
    if kind == "truncate-tendermint":
        return {"nemesis": CrashTruncateNemesis(test, "/data/cs.wal/wal"),
                "generator": gen.delay(1, gen.repeat(
                    {"type": "info", "f": "crash"}))}
    if kind == "deployed-mix":
        # The deployed-cluster fault sweep in one profile: a network
        # partition (MemNet grudge or iptables, whichever net the test
        # carries), one validator-set ADD through the live app, and a
        # crash+truncate cycle — staged deterministically so a single
        # e2e drives deploy -> faults -> final reads -> verdict (the
        # closest runnable parallel of the reference's docker run,
        # README.md:19-35). The ADD transition is the one family that
        # never touches node daemons, so the stage is safe on any
        # topology; destroy/create coverage lives in the
        # changing-validators profile.
        return {"nemesis": jnemesis.compose([
                    ({"start": "start", "stop": "stop"},
                     jnemesis.partition_random_halves()),
                    ({"transition": "transition"},
                     ChangingValidatorsNemesis()),
                    ({"crash": "crash"},
                     CrashTruncateNemesis(
                         test, "/jepsen/jepsen.db/000001.log")),
                ]),
                "generator": [gen.sleep(1),
                              {"type": "info", "f": "start"},
                              gen.sleep(2.5),
                              {"type": "info", "f": "stop"},
                              gen.sleep(0.5),
                              gen.once(_add_transition_op),
                              gen.sleep(0.5),
                              {"type": "info", "f": "crash"}]}
    if kind == "local-kill":
        return {"nemesis": LocalKillNemesis(),
                "generator": gen.cycle_gen([
                    gen.sleep(1.5), {"type": "info", "f": "kill"},
                    gen.sleep(0.7), {"type": "info", "f": "restart"}])}
    if kind == "none":
        return {"nemesis": jnemesis.noop(), "generator": None}
    raise ValueError(f"unknown nemesis profile {kind!r}")


class LocalKillNemesis(jnemesis.Nemesis):
    """Crash nemesis for LOCAL mode (LocalMerkleeyesDB): SIGKILLs the
    shared native merkleeyes mid-run and restarts it on the same WAL —
    the docker-less parallel of the cluster `crash` nemesis. Committed
    txs must survive via WAL replay; in-flight ops surface as
    indeterminate (client.py maps connection errors), and the history
    must still check linearizable."""

    def setup(self, test):
        self.db = test["db"]
        assert hasattr(self.db, "kill_server"), \
            "local-kill requires a LocalMerkleeyesDB"
        return self

    def invoke(self, test, op):
        if op["f"] == "kill":
            self.db.kill_server()
            return jnemesis._ok(op, value="killed (SIGKILL, WAL kept)")
        if op["f"] == "restart":
            self.db.restart_server()
            return jnemesis._ok(op, value="restarted (WAL replayed)")
        raise ValueError(f"unknown local-kill op {op['f']!r}")

    def teardown(self, test):
        # leave the server down/up as-is: DB.teardown owns shutdown
        return None


def _add_transition_op(test, ctx):
    """One validator-set ADD against the LIVE config (a transactional
    valset read via refresh_config, then a fresh random validator at
    the read version — the version CAS proves the read was current)."""
    cfg = test.get("refresh_config", refresh_config)(test)
    return {"type": "info", "f": "transition",
            "value": {"type": "add", "version": cfg["version"],
                      "validator": tv.gen_validator()}}


NEMESES = ["changing-validators", "peekaboo-dup-validators",
           "split-dup-validators", "half-partitions", "ring-partitions",
           "single-partitions", "clocks", "crash", "truncate-merkleeyes",
           "truncate-tendermint", "local-kill", "deployed-mix", "none"]


# ------------------------------------------------------------ workloads


def workload(test) -> dict:
    """{client, concurrency, generator, final_generator, checker}
    (core.clj:342-387)."""
    n = len(test.get("nodes") or [])
    kind = test.get("workload", "cas-register")
    ops_per_key = test.get("ops_per_key", 120)

    if kind == "cas-register":
        def per_key(k):
            return gen.limit(ops_per_key,
                             gen.stagger(0.1,
                                         gen.reserve(n, r,
                                                     gen.mix([w, cas]))))
        return {
            "client": CasRegisterClient(),
            "concurrency": 2 * n,
            "generator": independent.concurrent_generator(
                2 * n, _naturals(), per_key),
            "final_generator": None,
            "checker": {"linear": independent.checker(
                jchecker.linearizable(CASRegister(),
                                      algorithm=test.get(
                                          "algorithm", "linear")))}}

    if kind == "set":
        max_key = [0]

        def per_key(k):
            max_key[0] = max(max_key[0], k)

            def add(test_, ctx, _c=[0]):  # noqa: B006 - per-key counter
                _c[0] += 1
                return {"type": "invoke", "f": "add", "value": _c[0]}
            return gen.phases(gen.once({"type": "invoke", "f": "init",
                                        "value": None}),
                              gen.stagger(0.5, add))

        def final():
            return independent.concurrent_generator(
                2 * n, iter(range(max_key[0] + 1)),
                lambda k: gen.once({"type": "invoke", "f": "read",
                                    "value": None}))
        # linearizable mode: each per-key sub-history is additionally a
        # knossos-style GSet linearizability check — and GSet packs onto
        # the device (bitmask state), so the whole keyed batch rides the
        # TPU engine (analyzer :jax), not a host timeline scan
        checkers = {"set": independent.checker(jchecker.set_checker())}
        if test.get("linearizable"):
            from jepsen_tpu.models import GSet
            checkers["linear"] = independent.checker(
                jchecker.linearizable(GSet(), algorithm=test.get(
                    "algorithm", "competition")))
        return {
            "client": SetClient(),
            "concurrency": 2 * n,
            "generator": independent.concurrent_generator(
                2 * n, _naturals(), per_key),
            "final_generator": final,  # thunk: built after main phase
            "checker": checkers}

    raise ValueError(f"unknown workload {kind!r}")


WORKLOADS = ["cas-register", "set"]


def _naturals():
    k = 0
    while True:
        yield k
        k += 1


# --------------------------------------------------------- test builder


def test_map(opts: Optional[Dict] = None) -> Dict:
    """Assemble the full tendermint test map (core.clj:389-423)."""
    opts = dict(opts or {})
    t = noop_test()
    t.update(opts)
    t.setdefault("workload", "cas-register")
    t.setdefault("nemesis_name", "none")
    t["name"] = (f"tendermint {t['workload']} {t['nemesis_name']}")
    t.setdefault("validator_config", [None])
    t.setdefault("transport_for", td.local_transport_for)

    nem = nemesis_package(t)
    wl = workload(t)
    checker = jchecker.compose({
        "timeline": independent.checker(jtimeline.html()),
        "perf": jchecker.perf_checker(),
        **wl["checker"]})

    main = gen.time_limit(t.get("time_limit", 30),
                          gen.clients(wl["generator"],
                                      nem["generator"]))
    phases = [main,
              gen.nemesis(gen.once({"type": "info", "f": "stop"})),
              gen.sleep(t.get("quiesce", 1))]
    final = wl.get("final_generator")
    if final is not None:
        # built lazily after the main phase (core.clj:371-377 delay)
        phases.append(_DeferredClients(final))
    group = wl["concurrency"]
    user_c = opts.get("concurrency")
    if user_c and user_c != group:
        if user_c % group == 0:
            group = user_c
        else:
            # Round up to the nearest whole key-group (2 x nodes): the
            # independent generator needs full groups, and honoring the
            # user's magnitude loudly beats crashing on the CLI default.
            rounded = max(group, math.ceil(user_c / group) * group)
            log.warning(
                "concurrency %d is not a multiple of the workload's "
                "key-group size %d (2 x nodes); using %d",
                user_c, group, rounded)
            group = rounded
    t.update({"client": wl["client"],
              "concurrency": group,
              "generator": gen.phases(*phases),
              "nemesis": nem["nemesis"],
              "checker": checker})
    return t


class _DeferredClients(gen.Generator):
    """Builds its inner generator at first use — the reference's
    (delay ...) final generator (core.clj:371-377)."""

    def __init__(self, thunk):
        self.thunk = thunk
        self.inner = None

    def _force(self):
        if self.inner is None:
            self.inner = gen.clients(self.thunk())
        return self.inner

    def op(self, test, ctx):
        res = gen.gen_op(self._force(), test, ctx)
        if res is None:
            return None
        o, g2 = res
        self.inner = g2
        return o, self

    def update(self, test, ctx, event):
        if self.inner is not None:
            self.inner = gen.gen_update(self.inner, test, ctx, event)
        return self
