"""Client for the native merkleeyes server (native/merkleeyes/).

Speaks the framed session protocol documented in
native/merkleeyes/README.md — the capability parallel of the
tendermint↔merkleeyes ABCI socket link (merkleeyes/cmd/merkleeyes/
main.go:26-57, tendermint/db.clj:84-87). Also knows how to build and
spawn the server binary for local integration runs."""

from __future__ import annotations

import os
import socket
import subprocess
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from jepsen_tpu.tendermint import gowire as w

NATIVE_DIR = Path(__file__).resolve().parents[2] / "native" / "merkleeyes"
BINARY = NATIVE_DIR / "build" / "merkleeyes"

# Message types (server.cc)
MSG_INFO = 0x10
MSG_CHECK_TX = 0x11
MSG_DELIVER_TX = 0x12
MSG_BEGIN_BLOCK = 0x13
MSG_END_BLOCK = 0x14
MSG_COMMIT = 0x15
MSG_QUERY = 0x16
MSG_ECHO = 0x17
MSG_FLUSH = 0x18

# Error codes (app.go:33-40)
OK = 0
CODE_UNKNOWN_REQUEST = 2
CODE_ENCODING_ERROR = 3
CODE_BAD_NONCE = 4
CODE_UNKNOWN_TX_TYPE = 5
CODE_INTERNAL = 6
CODE_BASE_UNKNOWN_ADDRESS = 7
CODE_UNAUTHORIZED = 8


@dataclass
class TxResult:
    code: int
    data: bytes = b""
    log: str = ""

    @property
    def ok(self) -> bool:
        return self.code == OK


@dataclass
class QueryResult:
    code: int
    height: int = 0
    index: int = 0  # 0 = "no index" (proto3 conflates unset with 0)
    key: bytes = b""
    value: bytes = b""
    log: str = ""

    @property
    def ok(self) -> bool:
        return self.code == OK


def client_for(address, proto: str = "abci", timeout: float = 10.0):
    """The client class for a session protocol, unconnected: "abci"
    (tendermint v0.34 ABCI socket protocol) or "custom" (this build's
    compact protocol). The single proto->client dispatch point."""
    if proto == "abci":
        from jepsen_tpu.tendermint.abci import AbciClient
        return AbciClient(address, timeout=timeout)
    if proto == "custom":
        return MerkleeyesClient(address, timeout=timeout)
    raise ValueError(f"unknown merkleeyes protocol {proto!r}")


class MerkleeyesClient:
    """One framed-protocol session. Address: ('unix', path) or
    ('tcp', (host, port))."""

    def __init__(self, address, timeout: float = 10.0):
        self.address = address
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None

    # -- connection ---------------------------------------------------

    def connect(self) -> "MerkleeyesClient":
        kind, addr = self.address
        if kind == "unix":
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(addr)
        self.sock = s
        return self

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def __enter__(self):
        return self.connect()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- framing ------------------------------------------------------

    def _roundtrip(self, msg_type: int, body: bytes = b"") -> bytes:
        assert self.sock is not None, "not connected"
        payload = bytes([msg_type]) + body
        self.sock.sendall(w.uvarint(len(payload)) + payload)
        return self._read_frame()

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("merkleeyes closed the connection")
            out += chunk
        return out

    def _read_frame(self) -> bytes:
        length, shift = 0, 0
        while True:
            b = self._read_exact(1)[0]
            length |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return self._read_exact(length)

    # -- ABCI surface -------------------------------------------------

    def info(self) -> Tuple[int, bytes]:
        """(height, last committed app hash)."""
        resp = self._roundtrip(MSG_INFO)
        code, pos = w.read_uvarint(resp, 1)
        assert code == OK, code
        height, pos = w.read_varint(resp, pos)
        apphash, _ = w.read_bytes(resp, pos)
        return height, apphash

    def _tx_result(self, resp: bytes) -> TxResult:
        code, pos = w.read_uvarint(resp, 1)
        data, pos = w.read_bytes(resp, pos)
        log, _ = w.read_bytes(resp, pos)
        return TxResult(code, data, log.decode("utf-8", "replace"))

    def check_tx(self, tx: bytes) -> TxResult:
        return self._tx_result(self._roundtrip(MSG_CHECK_TX, tx))

    def deliver_tx(self, tx: bytes) -> TxResult:
        return self._tx_result(self._roundtrip(MSG_DELIVER_TX, tx))

    def begin_block(self):
        self._roundtrip(MSG_BEGIN_BLOCK)

    def end_block(self) -> List[Tuple[bytes, int]]:
        resp = self._roundtrip(MSG_END_BLOCK)
        code, pos = w.read_uvarint(resp, 1)
        assert code == OK, code
        n, pos = w.read_uvarint(resp, pos)
        updates = []
        for _ in range(n):
            pk, pos = w.read_bytes(resp, pos)
            power, pos = w.read_varint(resp, pos)
            updates.append((pk, power))
        return updates

    def commit(self) -> bytes:
        resp = self._roundtrip(MSG_COMMIT)
        code, pos = w.read_uvarint(resp, 1)
        assert code == OK, code
        apphash, _ = w.read_bytes(resp, pos)
        return apphash

    def query(self, path: str, data: bytes = b"") -> QueryResult:
        body = w.encode_bytes(path) + data
        resp = self._roundtrip(MSG_QUERY, body)
        code, pos = w.read_uvarint(resp, 1)
        height, pos = w.read_varint(resp, pos)
        index, pos = w.read_varint(resp, pos)
        key, pos = w.read_bytes(resp, pos)
        value, pos = w.read_bytes(resp, pos)
        log, _ = w.read_bytes(resp, pos)
        # the ABCI arm cannot transmit the -1 "no index" sentinel
        # (proto3 conflates unset with 0); clamp here so QueryResult is
        # identical across both protocols
        return QueryResult(code, height, max(index, 0), key, value,
                           log.decode("utf-8", "replace"))

    def echo(self, data: bytes) -> bytes:
        resp = self._roundtrip(MSG_ECHO, data)
        return resp[2:]

    # -- convenience: tx + block + commit in one shot -----------------

    def tx_commit(self, tx: bytes) -> TxResult:
        """DeliverTx inside its own block, then commit — the shape of
        tendermint's /broadcast_tx_commit (tendermint/client.clj:79-93)."""
        self.begin_block()
        r = self.deliver_tx(tx)
        self.end_block()
        self.commit()
        return r


# -------------------------------------------------------- local server


def build(force: bool = False) -> Path:
    """Builds the native binary via make; returns its path."""
    if force or not BINARY.exists():
        subprocess.run(["make", "-s"], cwd=NATIVE_DIR, check=True)
    return BINARY


@dataclass
class LocalServer:
    """A locally spawned merkleeyes process on a unix socket.

    proto selects the session protocol: "abci" (default — the real
    tendermint v0.34 ABCI socket protocol, jepsen_tpu.tendermint.abci)
    or "custom" (this build's original compact protocol)."""

    sock_path: str
    wal_path: Optional[str] = None
    proc: Optional[subprocess.Popen] = None
    extra_args: List[str] = field(default_factory=list)
    proto: str = "abci"

    def start(self) -> "LocalServer":
        binary = build()
        args = [str(binary), "--listen", f"unix:{self.sock_path}",
                "--proto", self.proto]
        if self.wal_path:
            args += ["--wal", self.wal_path]
        args += self.extra_args
        self.proc = subprocess.Popen(
            args, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if os.path.exists(self.sock_path):
                try:
                    # __enter__ performs the connect — client() would
                    # connect twice and leak the first socket
                    with client_for(("unix", self.sock_path),
                                    self.proto) as cl:
                        cl.echo(b"ping")
                    return self
                except OSError:
                    pass
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"merkleeyes exited with {self.proc.returncode}")
            time.sleep(0.02)
        raise TimeoutError("merkleeyes did not come up")

    def stop(self):
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
            self.proc = None

    def kill(self):
        """SIGKILL — the crash-nemesis path: no graceful shutdown, no
        flush; recovery must come from the WAL. Idempotent. The stale
        socket file is removed so a later start()'s readiness probe
        cannot race against it."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            self.proc = None
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass

    def client(self):
        """A connected client speaking this server's protocol."""
        return client_for(("unix", self.sock_path), self.proto).connect()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
