"""Tendermint + merkleeyes deployment
(reference: tendermint/src/jepsen/tendermint/db.clj).

Two modes:

- **Cluster mode** (`TendermintDB`): installs the tendermint and
  merkleeyes binaries on each node via the control plane, writes
  genesis / validator-key / node-key JSON, and runs both daemons with
  pidfiles (db.clj:21-219). The merkleeyes binary deployed is this
  repo's native C++ one — `make` locally, ship the binary (nodes are
  assumed ABI-compatible; pass merkleeyes_url to install a prebuilt
  archive instead, as the reference does for both components).
- **Local mode** (`LocalMerkleeyesDB`): one shared native merkleeyes
  process on a unix socket stands in for the whole replicated cluster —
  consensus collapses to a single linearizable app, which is exactly
  what a correctness test of the *harness* wants (the atom-db pattern,
  tests.clj:27-67, but through the real native server)."""

from __future__ import annotations

import json
import logging
import tempfile
import threading
from typing import Optional

from jepsen_tpu import control as c
from jepsen_tpu import db as jdb
from jepsen_tpu.control import util as cu
from jepsen_tpu.nemesis import time as nt
from jepsen_tpu.tendermint import merkleeyes as me
from jepsen_tpu.tendermint import validator as tv

log = logging.getLogger(__name__)

BASE_DIR = "/opt/tendermint"  # tendermint/util.clj:4


CONFIG_TOML = """\
# config.toml tuned for fast 5-node commits
# (tendermint/resources/config.toml:1-19)
[consensus]
timeout_commit = "0ms"
skip_timeout_commit = true
peer_gossip_sleep_duration = "10ms"

[p2p]
flush_throttle_timeout = "10ms"

[rpc]
laddr = "tcp://0.0.0.0:{rpc_port}"
"""


def node_base_dir(test, node) -> str:
    """Per-node base dir. A real cluster shares BASE_DIR per machine;
    a single-host multi-node deployment (Local remote, the docker-less
    parallel of the reference's 5-container run, docker/README.md)
    gives every node its own directory via test["base_dirs"]."""
    dirs = test.get("base_dirs") or {}
    if node is not None and node in dirs:
        return dirs[node]
    return test.get("base_dir", BASE_DIR)


def base_dir(test) -> str:
    """The CURRENT node's base dir: inside on_nodes the control scope
    carries the node, so every path helper below is per-node exactly
    where commands run per-node."""
    return node_base_dir(test, c.scope.host)


def socket_file(test) -> str:
    return base_dir(test) + "/merkleeyes.sock"


def socket_addr(test) -> str:
    return "unix://" + socket_file(test)


def merkleeyes_log(test) -> str:
    return base_dir(test) + "/merkleeyes.log"


def tendermint_log(test) -> str:
    return base_dir(test) + "/tendermint.log"


def merkleeyes_pid(test) -> str:
    return base_dir(test) + "/merkleeyes.pid"


def rpc_port(test, node=None) -> int:
    """The node's tendermint RPC port. Real clusters keep the default
    on every machine; single-host multi-node deployments give each
    node its own via test["rpc_ports"]."""
    ports = test.get("rpc_ports") or {}
    if node is None:
        node = c.scope.host
    return int(ports.get(node, 26657))


def tendermint_pid(test) -> str:
    return base_dir(test) + "/tendermint.pid"


# -------------------------------------------------- per-node file writes


def _write_json(path: str, data) -> None:
    import os as _os
    fd, tmp = tempfile.mkstemp(suffix=".json")
    try:
        with _os.fdopen(fd, "w") as f:
            json.dump(data, f)
        c.upload([tmp], path)
    finally:
        _os.unlink(tmp)


def write_validator(test, node, validator: dict) -> None:
    """priv_validator_key.json + empty state (db.clj:28-38)."""
    with c.su():
        _write_json(base_dir(test) + "/config/priv_validator_key.json",
                    validator)
        _write_json(base_dir(test) + "/data/priv_validator_state.json", {})


def write_node_key(test, node, node_key: dict) -> None:
    """(db.clj:40-47)."""
    with c.su():
        _write_json(base_dir(test) + "/config/node_key.json", node_key)


def write_genesis(test, genesis: dict) -> None:
    """(db.clj:49-56)."""
    with c.su():
        _write_json(base_dir(test) + "/config/genesis.json", genesis)


def write_config(test) -> None:
    """(db.clj:58-64)."""
    import os as _os
    with c.su():
        fd, tmp = tempfile.mkstemp(suffix=".toml")
        try:
            with _os.fdopen(fd, "w") as f:
                f.write(CONFIG_TOML.format(rpc_port=rpc_port(test)))
            c.upload([tmp], base_dir(test) + "/config/config.toml")
        finally:
            _os.unlink(tmp)


def node_id(test, node) -> Optional[str]:
    """(db.clj:66-73)."""
    cfg = (test.get("validator_config") or [None])[0] or {}
    return ((cfg.get("node_keys") or {}).get(node) or {}).get("id")


def persistent_peers(test, node) -> str:
    """--p2p.persistent_peers value (db.clj:75-82)."""
    return ",".join(f"{node_id(test, n)}@{n}:26656"
                    for n in test.get("nodes") or [] if n != node)


# ------------------------------------------------------ daemon control


def start_tendermint(test, node) -> str:
    """(db.clj:94-108)."""
    with c.su(), c.cd(base_dir(test)):
        cu.start_daemon(
            {"logfile": tendermint_log(test),
             "pidfile": tendermint_pid(test), "chdir": base_dir(test)},
            "./tendermint", "--home", base_dir(test), "node",
            "--proxy_app", socket_addr(test),
            "--p2p.persistent_peers", persistent_peers(test, node))
    return "started"


def start_merkleeyes(test, node) -> str:
    """(db.clj:110-122). Runs this repo's native server."""
    with c.su(), c.cd(base_dir(test)):
        cu.start_daemon(
            {"logfile": merkleeyes_log(test),
             "pidfile": merkleeyes_pid(test), "chdir": base_dir(test)},
            "./merkleeyes/merkleeyes", "--listen",
            f"unix:{socket_file(test)}",
            # the real tendermint binary drives --proxy_app over the
            # v0.34 ABCI socket protocol (native/merkleeyes/src/abci.h)
            "--proto", "abci",
            "--wal", base_dir(test) + "/jepsen/jepsen.db/000001.log")
    return "started"


def await_tendermint_rpc(test, node, timeout: float) -> None:
    """Bounded NODE-SIDE poll of tendermint's RPC /status endpoint —
    a real readiness wait where the reference sleeps a flat second
    after start (db.clj:204). Runs through the control plane (curl on
    the node against its own localhost), so Local remotes and real
    clusters behave identically. Raises TimeoutError when the RPC
    never comes up."""
    import time as _time
    port = rpc_port(test, node)
    deadline = _time.monotonic() + timeout
    while True:
        try:
            c.exec_("curl", "-sf", "--max-time", "2",
                    f"http://127.0.0.1:{port}/status")
            return
        except c.RemoteError as err:
            if err.exit in (126, 127):
                # missing/unrunnable curl is a node-image problem, not
                # "not ready" — burning the timeout would misdirect
                raise
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"tendermint RPC on {node}:{port} not ready after "
                    f"{timeout}s")
            _time.sleep(0.25)


def stop_tendermint(test, node) -> str:
    with c.su():
        cu.stop_daemon(tendermint_pid(test))
    return "stopped"


def stop_merkleeyes(test, node) -> str:
    with c.su():
        cu.stop_daemon(merkleeyes_pid(test))
        c.exec_("rm", "-rf", socket_file(test))
    return "stopped"


def start(test, node):
    """(db.clj:133-136)."""
    start_merkleeyes(test, node)
    start_tendermint(test, node)
    return "started"


def stop(test, node):
    """(db.clj:138-141)."""
    stop_tendermint(test, node)
    stop_merkleeyes(test, node)
    return "stopped"


def reset_validator(test, node) -> None:
    """Wipe identity + data, preserving binaries and genesis
    (db.clj:155-161)."""
    with c.su():
        bd = base_dir(test)
        c.exec_("bash", "-c", c.lit(c.escape(
            f"rm -rf {bd}/data {bd}/jepsen "
            f"{bd}/config/priv_validator_key.json "
            f"{bd}/config/node_key.json")))


class TendermintDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """Full cluster deployment (db.clj:163-219). Options:
    tendermint_url / merkleeyes_url — archives to install (merkleeyes
    defaults to shipping the locally built native binary)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self._lock = threading.Lock()  # on_nodes runs setup in parallel

    def setup(self, test, node):
        bd = base_dir(test)
        with c.su():
            if self.opts.get("tendermint_url"):
                cu.install_archive(self.opts["tendermint_url"],
                                   bd + "/tendermint-dist")
                c.exec_("cp", bd + "/tendermint-dist/tendermint", bd + "/")
            if self.opts.get("merkleeyes_url"):
                cu.install_archive(self.opts["merkleeyes_url"],
                                   bd + "/merkleeyes")
            else:
                with self._lock:  # make must not run concurrently
                    binary = me.build()
                c.exec_("mkdir", "-p", bd + "/merkleeyes")
                c.upload([str(binary)], bd + "/merkleeyes/merkleeyes")
                c.exec_("chmod", "+x", bd + "/merkleeyes/merkleeyes")
            c.exec_("mkdir", "-p", bd + "/config", bd + "/data",
                    bd + "/jepsen/jepsen.db")
            write_config(test)

        # One node computes the initial validator config; the rest wait
        # on the lock and reuse it — the synchronize-barrier equivalent
        # (db.clj:180-192). on_nodes runs setups in parallel threads.
        with self._lock:
            box = test.setdefault("validator_config", [None])
            if box[0] is None:
                box[0] = tv.initial_config(test)

        vc = box[0]
        write_genesis(test, tv.genesis(vc))
        v = vc["validators"].get(vc["nodes"].get(node))
        if v is not None:
            write_validator(test, node, v)
        write_node_key(test, node, vc["node_keys"].get(node) or {})

        start_merkleeyes(test, node)
        start_tendermint(test, node)
        if test.get("await_rpc_timeout"):
            await_tendermint_rpc(test, node, test["await_rpc_timeout"])
        if test.get("seed_app_valset") and node == consensus_node(test):
            seed_app_valset(test, node)
        with self._lock:
            # /opt/jepsen is per-MACHINE: single-host multi-node
            # deployments would otherwise race N gccs onto one binary;
            # on a real cluster this merely serializes an idempotent
            # per-node compile
            nt.install()

    def teardown(self, test, node):
        try:
            stop(test, node)
        finally:
            with c.su():
                c.exec_("rm", "-rf", base_dir(test))

    # Process protocol: used by the crash nemesis / combined packages.
    def start(self, test, node):
        return start(test, node)

    def kill(self, test, node):
        return stop(test, node)

    def log_files(self, test, node):
        bd = base_dir(test)
        return [tendermint_log(test), merkleeyes_log(test),
                bd + "/config/priv_validator_key.json",
                bd + "/config/node_key.json",
                bd + "/config/genesis.json"]


def db(opts: Optional[dict] = None) -> TendermintDB:
    return TendermintDB(opts)


# ------------------------------------------------------------ local mode


class LocalMerkleeyesDB(jdb.DB):
    """One shared native merkleeyes process standing in for the cluster.
    setup/teardown manage the process; `transport_for` points every
    node at it."""

    def __init__(self, workdir: Optional[str] = None):
        self.workdir = workdir
        self.server: Optional[me.LocalServer] = None
        self._lock = threading.Lock()

    def setup(self, test, node):
        with self._lock:
            self._setup_locked(test)

    def _setup_locked(self, test):
        if self.server is None:
            d = self.workdir or tempfile.mkdtemp(prefix="merkleeyes-")
            self.server = me.LocalServer(
                sock_path=d + "/merkleeyes.sock",
                wal_path=d + "/merkleeyes.wal").start()
            test["merkleeyes_sock"] = self.server.sock_path

    def teardown(self, test, node):
        if self.server is not None:
            self.server.stop()
            self.server = None

    # ---- crash-nemesis surface (local parallel of the cluster kill
    # nemesis): SIGKILL the shared process / restart it on the SAME
    # wal path, so committed txs must come back via WAL replay
    def kill_server(self):
        with self._lock:
            if self.server is not None:
                self.server.kill()

    def restart_server(self):
        with self._lock:
            if self.server is not None and self.server.proc is None:
                self.server.start()


def local_transport_for(test, node):
    """transport factory for local mode: every node reaches the one
    shared server."""
    from jepsen_tpu.tendermint import client as tc
    sock = test.get("merkleeyes_sock")
    assert sock, "local merkleeyes is not running (no :merkleeyes_sock)"
    return tc.SocketTransport(("unix", sock))


def http_transport_for(test, node):
    """transport factory for cluster mode: tendermint RPC on the node,
    at the node's configured port (test["rpc_ports"] honored end to
    end: config.toml, readiness poll, and clients agree)."""
    from jepsen_tpu.tendermint import client as tc
    return tc.HttpTransport(node, port=rpc_port(test, node))


# ------------------------------------------- single-host cluster mode


def seed_app_valset(test, node, timeout: float = 10.0) -> None:
    """InitChain stand-in for stub-tendermint deployments (opt-in via
    test["seed_app_valset"]): push the genesis validators into the
    deployed app's validator set, which the REAL binary does on chain
    start via ABCI InitChain (the reference leaves this to tendermint,
    db.clj:49-56 only writes genesis.json). Without it the app's
    valset is empty and the first refresh_config would reconcile the
    genesis validators away. Polls the daemon's socket: start_daemon
    backgrounds with no readiness wait."""
    import time as _time

    from jepsen_tpu.tendermint import client as tc
    t = tc.SocketTransport(
        ("unix", node_base_dir(test, node) + "/merkleeyes.sock"))
    vc = test["validator_config"][0]
    deadline = _time.monotonic() + timeout
    for pub, v in sorted(vc["validators"].items()):
        while True:
            try:
                tc.validator_set_change(t, pub, v["votes"])
                break
            except OSError:
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.05)


def consensus_node(test) -> str:
    """The node whose deployed merkleeyes stands in for the replicated
    state machine under routed_transport_for."""
    return test.get("consensus_node") or (test.get("nodes") or ["n1"])[0]


class _PartitionedTransport:
    """A transport on the wrong side of a grudge: every use times out.
    Raised at USE (not open) so the clients' _map_errors taxonomy
    applies — writes/cas surface as indeterminate :info, reads as
    :fail — exactly how a minority node's RPC behaves in the real
    cluster: the connection opens, the commit never comes."""

    def __init__(self, node, target):
        self.node, self.target = node, target

    def _cut(self):
        raise TimeoutError(
            f"partition: {self.node} cannot reach {self.target}")

    def broadcast_tx(self, tx):
        self._cut()

    def abci_query(self, path, data):
        self._cut()


def routed_transport_for(test, node):
    """Cluster-mode transport for a single-host deployment (Local
    remote): every client routes to the consensus node's DEPLOYED
    merkleeyes socket — consensus collapses to one linearizable app,
    as in local mode, but through the daemon TendermintDB actually
    deployed and manages — and the route honors the test's net: a
    client whose node holds a grudge against the consensus node gets
    the partitioned transport above. The remaining distance to the
    reference's semantics is real replication (the real tendermint
    binary + docker, README.md:19-35)."""
    from jepsen_tpu.tendermint import client as tc
    target = consensus_node(test)
    net = test.get("net")
    if (net is not None and node is not None and node != target
            and not net.reachable(node, target)):
        return _PartitionedTransport(node, target)
    return tc.SocketTransport(
        ("unix", node_base_dir(test, target) + "/merkleeyes.sock"))
