"""Validator cluster-configuration state machine
(reference: tendermint/src/jepsen/tendermint/validator.clj).

Tracks which validators exist, how many votes each controls, and which
node runs which validator — including deliberately *byzantine* setups
where one validator key runs on several nodes. Provides:

- vote allocation incl. the byzantine weighting math
  (validator.clj:267-337)
- safety invariants (quorum, fault bound, ghost/zombie limits,
  omnipotent-byzantine bound — validator.clj:558-673 assert-valid)
- legal random transitions (create/destroy/add/remove/alter-votes,
  validator.clj:684-843)
- reconciliation with a transactional read of the cluster's validator
  set (validator.clj:868-930 current-config)

A config is a plain dict:

    {"validators":  {pub_key: {"pub_key", "priv_key", "votes"}},
     "nodes":       {node: pub_key},
     "node_keys":   {node: node_key},
     "node_set":    set of nodes,
     "version":     int,
     "prospective_validators": {pub_key: validator},
     "super_byzantine_validators": bool,
     "max_byzantine_vote_fraction": Fraction}
"""

from __future__ import annotations

import os as _os
from fractions import Fraction
from typing import Callable, Dict, List, Optional

from jepsen_tpu import generator as gen

GHOST_LIMIT = 2    # validators with no node (validator.clj:600-604)
ZOMBIE_LIMIT = 2   # nodes running a non-member validator (clj:617-621)
QUORUM = Fraction(2, 3)       # validator.clj:633-637
FAULT_LIMIT = Fraction(1, 3)  # validator.clj:646-650


class IllegalTransition(AssertionError):
    """A transition would violate the cluster invariants."""


def gen_validator(votes: int = 2) -> dict:
    """A fresh validator with a random 32-byte key (the reference shells
    out to `tendermint gen_validator`, validator.clj:356-365; key
    *structure* is what matters to the state machine)."""
    key = _os.urandom(32).hex().upper()
    return {"pub_key": key,
            "priv_key": _os.urandom(64).hex().upper(),
            "votes": votes}


def gen_node_key() -> dict:
    """(validator.clj:367-375)."""
    return {"id": _os.urandom(20).hex(),
            "priv_key": _os.urandom(64).hex().upper()}


def config(opts: Optional[dict] = None) -> dict:
    """(validator.clj:383-421)."""
    out = {"validators": {},
           "nodes": {},
           "node_keys": {},
           "node_set": set(),
           "version": -1,
           "max_byzantine_vote_fraction": Fraction(1, 3),
           "super_byzantine_validators": False}
    out.update(opts or {})
    out["prospective_validators"] = {}
    return out


# ------------------------------------------------------ derived views


def nodes_running_validators(cfg: dict) -> Dict[str, List[str]]:
    """pub_key -> [nodes running it] (validator.clj:246-255)."""
    out: Dict[str, List[str]] = {}
    for node, key in cfg["nodes"].items():
        out.setdefault(key, []).append(node)
    return out


def byzantine_validators(cfg: dict) -> List[dict]:
    """Validators running on more than one node (validator.clj:257-265)."""
    return [cfg["validators"][k]
            for k, nodes in nodes_running_validators(cfg).items()
            if len(nodes) > 1 and k in cfg["validators"]]


def byzantine_validator_keys(cfg: dict) -> List[str]:
    return [v["pub_key"] for v in byzantine_validators(cfg)]


def running_validators(cfg: dict) -> List[dict]:
    """Validators running on at least one node (validator.clj:541-547)."""
    keys = set(cfg["nodes"].values())
    return [cfg["validators"][k] for k in keys if k in cfg["validators"]]


def ghost_validators(cfg: dict) -> List[dict]:
    """Members not running anywhere (validator.clj:549-555)."""
    running = {v["pub_key"] for v in running_validators(cfg)}
    return [v for k, v in cfg["validators"].items() if k not in running]


def total_votes(cfg: dict) -> int:
    """(validator.clj:496-503)."""
    return sum(v["votes"] for v in cfg["validators"].values())


def vote_fractions(cfg: dict) -> Dict[str, Fraction]:
    """(validator.clj:532-539)."""
    total = total_votes(cfg)
    return {k: Fraction(v["votes"], total)
            for k, v in cfg["validators"].items()}


def dup_groups(cfg: dict) -> dict:
    """{groups, singles, dups} of node groups by validator
    (validator.clj:569-583)."""
    groups = list(nodes_running_validators(cfg).values())
    return {"groups": groups,
            "singles": [g for g in groups if len(g) == 1],
            "dups": [g for g in groups if len(g) > 1]}


def compact_config(cfg: dict) -> dict:
    """Human-readable summary (validator.clj:511-530)."""
    return {"version": cfg["version"],
            "total_votes": total_votes(cfg),
            "validators": {k[:5]: {"votes": v["votes"]}
                           for k, v in sorted(cfg["validators"].items())},
            "nodes": {n: k[:5] for n, k in cfg["nodes"].items()},
            "prospective": sorted(k[:5]
                                  for k in cfg["prospective_validators"])}


# -------------------------------------------------- initial allocation


def initial_validator_votes(cfg: dict) -> Dict[str, int]:
    """Votes per validator; byzantine (dup) validators get just shy of
    1/3 — or 2/3 with super_byzantine_validators (validator.clj:267-337,
    derivation in the reference's comment):

      normal node weight 2; n validators total.
      regular dup:  dup weight n-2    of total 3n-4   (< 1/3)
      super dup:    dup weight 4(n-1)-1 of 6(n-1)-1   (→ 2/3)
    """
    bs = byzantine_validators(cfg)
    if not bs:
        return {k: 2 for k in cfg["validators"]}
    assert len(bs) == 1, \
        "Only know how to deal with 1 or 0 byzantine validators"
    b = bs[0]["pub_key"]
    n = len(cfg["validators"])
    votes = {k: 2 for k in cfg["validators"] if k != b}
    if cfg.get("super_byzantine_validators"):
        votes[b] = 4 * (n - 1) - 1
    else:
        votes[b] = n - 2
    return votes


def with_initial_validator_votes(cfg: dict) -> dict:
    """(validator.clj:339-353)."""
    votes = initial_validator_votes(cfg)
    validators = {k: dict(v, votes=votes[k])
                  for k, v in cfg["validators"].items()}
    return dict(cfg, validators=validators)


def initial_config(test: dict,
                   gen_validator_fn: Callable = gen_validator,
                   gen_node_key_fn: Callable = gen_node_key) -> dict:
    """Initial config for a test's nodes: one validator per node, unless
    dup_validators collapses the first node onto the second node's
    validator (validator.clj:423-473)."""
    nodes_list = list(test.get("nodes") or [])
    per_node = {n: gen_validator_fn() for n in nodes_list}
    nodes = {n: v["pub_key"] for n, v in per_node.items()}
    validators = {v["pub_key"]: v for v in per_node.values()}

    if test.get("dup_validators") and len(nodes_list) >= 2:
        n1, n2 = nodes_list[0], nodes_list[1]
        del validators[nodes[n1]]
        nodes[n1] = nodes[n2]

    cfg = config({
        "validators": validators,
        "nodes": nodes,
        "node_keys": {n: gen_node_key_fn() for n in nodes_list},
        "node_set": set(nodes_list),
        "super_byzantine_validators":
            bool(test.get("super_byzantine_validators")),
        "max_byzantine_vote_fraction":
            test.get("max_byzantine_vote_fraction", Fraction(1, 3))})
    return with_initial_validator_votes(cfg)


def genesis(cfg: dict) -> dict:
    """genesis.json structure (validator.clj:475-488)."""
    vals = []
    for v in cfg["validators"].values():
        names = [n for n, k in cfg["nodes"].items() if k == v["pub_key"]]
        assert names, f"validator {v['pub_key'][:8]} runs nowhere"
        vals.append({"power": str(v["votes"]),
                     "name": names[0],
                     "pub_key": v["pub_key"]})
    return {"app_hash": "",
            "chain_id": "jepsen",
            "genesis_time": "2020-12-09T12:11:22.481331Z",
            "validators": vals}


# ---------------------------------------------------------- invariants


def at_least_one_running_validator(cfg) -> bool:
    return bool(running_validators(cfg))  # validator.clj:585-590


def omnipotent_byzantines(cfg) -> bool:
    """Any byzantine validator at/above the byzantine vote bound?
    (validator.clj:592-604)."""
    vfs = vote_fractions(cfg)
    threshold = cfg["max_byzantine_vote_fraction"]
    return any(threshold <= vfs[k] for k in byzantine_validator_keys(cfg))


def too_many_ghosts(cfg) -> bool:
    """(validator.clj:606-615)."""
    members = set(cfg["validators"])
    running = set(cfg["nodes"].values())
    return GHOST_LIMIT < len(members - running)


def too_many_zombies(cfg) -> bool:
    """(validator.clj:623-631)."""
    members = set(cfg["validators"])
    return ZOMBIE_LIMIT < sum(1 for k in cfg["nodes"].values()
                              if k not in members)


def quorum(cfg) -> bool:
    """Running votes strictly exceed 2/3 of total (validator.clj:639-644)."""
    total = total_votes(cfg)
    if total == 0:
        return False
    running = sum(v["votes"] for v in running_validators(cfg))
    return QUORUM < Fraction(running, total)


def faulty(cfg) -> bool:
    """Byzantine + ghost votes at/above 1/3 (validator.clj:652-661)."""
    total = total_votes(cfg)
    if total == 0:
        return True
    bad_keys = ({v["pub_key"] for v in byzantine_validators(cfg)}
                | {v["pub_key"] for v in ghost_validators(cfg)})
    bad = sum(cfg["validators"][k]["votes"] for k in bad_keys)
    return FAULT_LIMIT <= Fraction(bad, total)


def assert_valid(cfg: dict) -> dict:
    """(validator.clj:663-678)."""
    def check(ok, why):
        if not ok:
            raise IllegalTransition(why + ": " + repr(compact_config(cfg)))
    check(at_least_one_running_validator(cfg), "no running validators")
    check(not omnipotent_byzantines(cfg), "omnipotent byzantine validator")
    check(not too_many_ghosts(cfg), "too many ghosts")
    check(not too_many_zombies(cfg), "too many zombies")
    check(quorum(cfg), "no quorum")
    check(not faulty(cfg), "too many faulty votes")
    check(all(n in cfg["node_set"] for n in cfg["nodes"]),
          "node outside node set")
    check(all(v["votes"] > 0 for v in cfg["validators"].values()),
          "non-positive votes")
    return cfg


# --------------------------------------------------------- transitions
# {"type": "create"|"destroy"|"add"|"remove"|"alter-votes", ...}


def pre_step(cfg: dict, t: dict) -> dict:
    """The in-between state entered when a transition is *requested*
    but not yet known to have happened (validator.clj:689-704)."""
    ty = t["type"]
    if ty == "add":
        v = t["validator"]
        assert v["pub_key"] not in cfg["validators"]
        prospective = dict(cfg["prospective_validators"])
        prospective[v["pub_key"]] = v
        cfg = dict(cfg, prospective_validators=prospective)
    return assert_valid(cfg)


def post_step(cfg: dict, t: dict) -> dict:
    """Complete a transition (validator.clj:706-747)."""
    ty = t["type"]
    if ty == "create":
        n, v = t["node"], t["validator"]
        assert n not in cfg["nodes"]
        cfg = dict(cfg,
                   nodes={**cfg["nodes"], n: v["pub_key"]},
                   node_keys={**cfg["node_keys"], n: t.get("node_key")})
    elif ty == "destroy":
        n = t["node"]
        nodes = dict(cfg["nodes"])
        node_keys = dict(cfg["node_keys"])
        nodes.pop(n, None)
        node_keys.pop(n, None)
        cfg = dict(cfg, nodes=nodes, node_keys=node_keys)
    elif ty == "add":
        v = t["validator"]
        assert v["pub_key"] not in cfg["validators"]
        prospective = dict(cfg["prospective_validators"])
        prospective.pop(v["pub_key"], None)
        cfg = dict(cfg,
                   prospective_validators=prospective,
                   validators={**cfg["validators"], v["pub_key"]: v})
    elif ty == "remove":
        validators = dict(cfg["validators"])
        validators.pop(t["pub_key"], None)
        cfg = dict(cfg, validators=validators)
    elif ty == "alter-votes":
        k, votes = t["pub_key"], t["votes"]
        v = cfg["validators"][k]
        cfg = dict(cfg, validators={**cfg["validators"],
                                    k: dict(v, votes=votes)})
    else:
        raise ValueError(f"unknown transition type {ty!r}")
    return assert_valid(cfg)


def step(cfg: dict, t: dict) -> dict:
    """pre_step then post_step; raises IllegalTransition when the
    result would violate invariants (validator.clj:749-757)."""
    return post_step(pre_step(cfg, t), t)


def rand_transition(test: dict, cfg: dict,
                    gen_validator_fn: Callable = gen_validator,
                    gen_node_key_fn: Callable = gen_node_key) -> Optional[dict]:
    """One random (possibly illegal) transition (validator.clj:765-823).
    Weights match the reference's condp thresholds: create 1/5,
    destroy 1/5, add 1/5, remove 1/5, alter-votes 1/5."""
    roll = gen.rand.random()
    if roll >= 4 / 5:
        free = sorted(cfg["node_set"] - set(cfg["nodes"]))
        if not cfg["validators"] or not free:
            return None
        v = gen.rand.choice(sorted(cfg["validators"]))
        return {"type": "create", "node": gen.rand.choice(free),
                "validator": cfg["validators"][v],
                "node_key": gen_node_key_fn()}
    if roll >= 3 / 5:
        taken = sorted(cfg["nodes"])
        if not taken:
            return None
        return {"type": "destroy", "node": gen.rand.choice(taken)}
    if roll >= 2 / 5:
        return {"type": "add", "version": cfg["version"],
                "validator": gen_validator_fn()}
    if roll >= 1 / 5:
        if not cfg["validators"]:
            return None
        k = gen.rand.choice(sorted(cfg["validators"]))
        return {"type": "remove", "version": cfg["version"], "pub_key": k}
    if not cfg["validators"]:
        return None
    k = gen.rand.choice(sorted(cfg["validators"]))
    votes = cfg["validators"][k]["votes"]
    return {"type": "alter-votes", "version": cfg["version"], "pub_key": k,
            "votes": max(1, votes + gen.rand.randint(-5, 5))}


def rand_legal_transition(test: dict, cfg: dict, max_tries: int = 100,
                          **kw) -> dict:
    """Retry rand_transition until one steps legally
    (validator.clj:825-843)."""
    for _ in range(max_tries):
        t = rand_transition(test, cfg, **kw)
        if t is None:
            continue
        try:
            step(cfg, t)
            return t
        except (IllegalTransition, AssertionError):
            continue
    raise RuntimeError(
        f"Unable to generate state transition from "
        f"{compact_config(cfg)!r} in less than {max_tries} tries")


# --------------------------------------- reconciliation with the cluster


def validator_set_to_vote_map(cfg: dict, validator_set: dict) -> Dict:
    """Cluster read {version, validators:[{pub_key, power}]} -> full
    pub_key -> votes map (validator.clj:861-885). Unknown keys raise."""
    out = {}
    for v in validator_set.get("validators") or []:
        k = v["pub_key"]
        if k not in cfg["validators"] and \
                k not in cfg["prospective_validators"]:
            raise RuntimeError(
                f"Don't recognize cluster validator {v!r}; "
                f"where did it come from?")
        out[k] = v["power"]
    return out


def clear_removed_nodes(cfg: dict, votes: Dict) -> dict:
    """Drop members the cluster no longer knows (validator.clj:887-896)."""
    return dict(cfg, validators={k: v for k, v in cfg["validators"].items()
                                 if k in votes})


def update_known_nodes(cfg: dict, votes: Dict) -> dict:
    """Fold cluster votes in; promote prospective validators that now
    appear (validator.clj:898-928)."""
    validators = dict(cfg["validators"])
    prospective = dict(cfg["prospective_validators"])
    for k, power in votes.items():
        if k in validators:
            validators[k] = dict(validators[k], votes=power)
        else:
            v = prospective.pop(k, None)
            assert v is not None, \
                f"Don't recognize validator {k}; where did it come from?"
            validators[k] = dict(v, votes=power)
    return dict(cfg, validators=validators,
                prospective_validators=prospective)


def current_config(cfg: dict, cluster_validator_set: dict) -> dict:
    """Merge our view with a transactional cluster read
    (validator.clj:930-946)."""
    votes = validator_set_to_vote_map(cfg, cluster_validator_set)
    out = update_known_nodes(clear_removed_nodes(cfg, votes), votes)
    return dict(out, version=cluster_validator_set.get("version"))


class TransitionGenerator(gen.Generator):
    """Emits {:f :transition, :value legal-transition} ops against the
    test's live validator config (validator.clj:948-989). The config
    lives in test["validator_config"], a one-element list acting as the
    reference's atom; refresh_fn (optional) re-reads it from the
    cluster before each op."""

    def __init__(self, refresh_fn: Optional[Callable] = None):
        self.refresh_fn = refresh_fn

    def op(self, test, ctx):
        box = test.get("validator_config")
        if not box or box[0] is None:
            return None
        cfg = self.refresh_fn(test) if self.refresh_fn else box[0]
        try:
            t = rand_legal_transition(test, cfg)
        except RuntimeError:
            return None
        o = gen.fill_in_op({"type": "info", "f": "transition", "value": t},
                           ctx)
        return o, self

    def update(self, test, ctx, event):
        return self


def generator(refresh_fn: Optional[Callable] = None) -> TransitionGenerator:
    return TransitionGenerator(refresh_fn)
