"""go-wire style binary tx encoding
(reference: tendermint/src/jepsen/tendermint/gowire.clj:5-109).

Byte strings are uvarint-length-prefixed; integers are 8-byte
big-endian; a tx is nonce[12] ∥ type-byte ∥ args (merkleeyes
README "Formatting", app.go:488-520)."""

from __future__ import annotations

import os
import struct
from typing import Union

NONCE_LENGTH = 12

# Tx type bytes (app.go:22-30; tendermint/client.clj:113-122)
TX_SET = 0x01
TX_RM = 0x02
TX_GET = 0x03
TX_CAS = 0x04
TX_VALSET_CHANGE = 0x05
TX_VALSET_READ = 0x06
TX_VALSET_CAS = 0x07


def uvarint(n: int) -> bytes:
    """Unsigned LEB128, as Go's binary.PutUvarint (gowire.clj:20-41)."""
    assert n >= 0
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def read_uvarint(data: bytes, pos: int = 0) -> tuple:
    """(value, new_pos); raises on truncation."""
    v, shift = 0, 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated uvarint")
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def varint(n: int) -> bytes:
    """Signed zigzag varint (binary.PutVarint)."""
    return uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


def read_varint(data: bytes, pos: int = 0) -> tuple:
    uv, pos = read_uvarint(data, pos)
    v = uv >> 1
    return (~v if uv & 1 else v), pos


def encode_bytes(b: Union[bytes, str]) -> bytes:
    """uvarint(len) ∥ raw (gowire.clj:43-61)."""
    if isinstance(b, str):
        b = b.encode("utf-8")
    return uvarint(len(b)) + b


def read_bytes(data: bytes, pos: int = 0) -> tuple:
    n, pos = read_uvarint(data, pos)
    if len(data) - pos < n:
        raise ValueError("truncated bytes field")
    return data[pos:pos + n], pos + n


def u64be(n: int) -> bytes:
    """8-byte big-endian (app.go:528-534 decodeInt's inverse)."""
    return struct.pack(">Q", n)


def nonce() -> bytes:
    """A fresh 12-byte random nonce (client.clj's nonce generation)."""
    return os.urandom(NONCE_LENGTH)


def tx(type_byte: int, *args: bytes, nonce_: bytes = None) -> bytes:
    """nonce ∥ type ∥ args (gowire.clj:103-109)."""
    n = nonce_ if nonce_ is not None else nonce()
    assert len(n) == NONCE_LENGTH
    return n + bytes([type_byte]) + b"".join(args)


# -- the tx constructors the clients use (client.clj:130-206) ---------


def set_tx(key, value, nonce_=None) -> bytes:
    return tx(TX_SET, encode_bytes(key), encode_bytes(value), nonce_=nonce_)


def rm_tx(key, nonce_=None) -> bytes:
    return tx(TX_RM, encode_bytes(key), nonce_=nonce_)


def get_tx(key, nonce_=None) -> bytes:
    return tx(TX_GET, encode_bytes(key), nonce_=nonce_)


def cas_tx(key, compare, set_value, nonce_=None) -> bytes:
    return tx(TX_CAS, encode_bytes(key), encode_bytes(compare),
              encode_bytes(set_value), nonce_=nonce_)


def valset_change_tx(pubkey: bytes, power: int, nonce_=None) -> bytes:
    assert len(pubkey) == 32
    return tx(TX_VALSET_CHANGE, encode_bytes(pubkey), u64be(power),
              nonce_=nonce_)


def valset_read_tx(nonce_=None) -> bytes:
    return tx(TX_VALSET_READ, nonce_=nonce_)


def valset_cas_tx(version: int, pubkey: bytes, power: int,
                  nonce_=None) -> bytes:
    assert len(pubkey) == 32
    return tx(TX_VALSET_CAS, u64be(version), encode_bytes(pubkey),
              u64be(power), nonce_=nonce_)
