"""Client for the merkleeyes data plane
(reference: tendermint/src/jepsen/tendermint/client.clj).

Two transports behind one API:

- `SocketTransport` — speaks directly to the native merkleeyes server
  (native/merkleeyes/), one block per tx, mirroring tendermint's
  /broadcast_tx_commit semantics. The local / integration-test path.
- `HttpTransport` — tendermint RPC on :26657 (/broadcast_tx_commit,
  /abci_query), for driving a real cluster (client.clj:59-102).

Values are EDN-encoded bytes (jepsen_tpu.codec) — the capability
parallel of the reference's fressian value encoding (client.clj:137-152).
Tx error codes map to typed exceptions: 7 -> BaseUnknownAddress
(read of a missing key returns None instead), 8 -> Unauthorized
(client.clj:58-66 validate-tx-code)."""

from __future__ import annotations

import json as _json
from typing import Any, Optional

from jepsen_tpu import codec
from jepsen_tpu.tendermint import gowire as w
from jepsen_tpu.tendermint import merkleeyes as me

PORT = 26657  # tendermint RPC (client.clj:68)


class TxError(RuntimeError):
    def __init__(self, code, log=""):
        super().__init__(f"tx failed with code {code}: {log}")
        self.code = code
        self.log = log


class Unauthorized(TxError):
    """Code 8: CAS mismatch / valset version mismatch."""


class BaseUnknownAddress(TxError):
    """Code 7: key not found."""


def validate_tx_code(code: int, log: str = ""):
    """(client.clj:58-66)."""
    if code == 0:
        return
    if code == me.CODE_BASE_UNKNOWN_ADDRESS:
        raise BaseUnknownAddress(code, log)
    if code == me.CODE_UNAUTHORIZED:
        raise Unauthorized(code, log)
    raise TxError(code, log)


class SocketTransport:
    """Direct connection to a native merkleeyes server. Speaks the real
    tendermint v0.34 ABCI socket protocol by default (proto="abci"),
    so local integration runs exercise the same bytes a tendermint
    node's --proxy_app link carries; proto="custom" selects the
    server's legacy compact protocol."""

    def __init__(self, address, proto: str = "abci"):
        self.address = address  # ("unix", path) | ("tcp", (host, port))
        self.proto = proto

    def _client(self):
        return me.client_for(self.address, self.proto)

    def broadcast_tx(self, tx: bytes) -> me.TxResult:
        with self._client() as cl:
            r = cl.tx_commit(tx)
        validate_tx_code(r.code, r.log)
        return r

    def abci_query(self, path: str, data: bytes) -> me.QueryResult:
        with self._client() as cl:
            return cl.query(path, data)


class HttpTransport:
    """Tendermint RPC over HTTP (client.clj:79-102). Used against real
    clusters; requires network reachability to node:26657."""

    def __init__(self, node: str, timeout: float = 10.0,
                 port: int = PORT):
        self.node = node
        self.timeout = timeout
        self.port = port

    def _get(self, path: str, params: dict) -> dict:
        import urllib.parse
        import urllib.request
        url = (f"http://{self.node}:{self.port}{path}?"
               + urllib.parse.urlencode(params))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return _json.loads(resp.read().decode("utf-8"))

    def broadcast_tx(self, tx: bytes) -> me.TxResult:
        body = self._get("/broadcast_tx_commit",
                         {"tx": "0x" + tx.hex()})
        if body.get("error") or "result" not in body:
            # RPC-level failure (mempool full, timeout, catching up):
            # the tx outcome is indeterminate — surface it, never :ok.
            err = body.get("error") or {}
            raise TxError(me.CODE_INTERNAL,
                          str(err.get("message") or err or
                              "no result in RPC response"))
        result = (body.get("result") or {})
        for stage in ("check_tx", "deliver_tx"):
            st = result.get(stage) or {}
            validate_tx_code(int(st.get("code") or 0), st.get("log") or "")
        import base64
        data = base64.b64decode(result.get("deliver_tx", {})
                                .get("data") or "")
        return me.TxResult(0, data, "")

    def abci_query(self, path: str, data: bytes) -> me.QueryResult:
        body = self._get("/abci_query",
                         {"path": _json.dumps(path),
                          "data": "0x" + data.hex(), "prove": "false"})
        resp = ((body.get("result") or {}).get("response") or {})
        import base64
        value = base64.b64decode(resp.get("value") or "")
        return me.QueryResult(int(resp.get("code") or 0),
                              int(resp.get("height") or 0),
                              int(resp.get("index") or -1),
                              base64.b64decode(resp.get("key") or ""),
                              value, resp.get("log") or "")


# --------------------------------------------------- merkleeyes KV API


def _k(k) -> bytes:
    return codec.encode(k)


def write(transport, k, v) -> None:
    """Set k = v (client.clj:137-140)."""
    transport.broadcast_tx(w.set_tx(_k(k), codec.encode(v)))


def read(transport, k) -> Any:
    """Transactional read; None when absent (client.clj:142-149 — the
    reference's read throws :base-unknown-address, which its clients
    map to nil-valued :fail; returning None here keeps reads total)."""
    try:
        r = transport.broadcast_tx(w.get_tx(_k(k)))
    except BaseUnknownAddress:
        return None
    return codec.decode(r.data)


def cas(transport, k, v, v2) -> None:
    """Compare-and-set k: v -> v2 (client.clj:151-154). Raises
    Unauthorized on mismatch, BaseUnknownAddress when k is unset."""
    transport.broadcast_tx(
        w.cas_tx(_k(k), codec.encode(v), codec.encode(v2)))


def local_read(transport, k) -> Any:
    """Non-transactional read from one node's committed state
    (client.clj:184-196)."""
    q = transport.abci_query("/store", _k(k))
    if q.code == me.CODE_BASE_UNKNOWN_ADDRESS or not q.value:
        return None
    return codec.decode(q.value)


# ------------------------------------------------------- validator set


def validator_set(transport) -> dict:
    """Transactional read of the validator set (client.clj:156-163):
    {"version": int, "validators": [{"pub_key": hex, "power": int}]}."""
    r = transport.broadcast_tx(w.valset_read_tx())
    return _json.loads(r.data.decode("utf-8"))


def validator_set_change(transport, pub_key_hex: str, power: int) -> None:
    """(client.clj:165-171)."""
    transport.broadcast_tx(
        w.valset_change_tx(bytes.fromhex(pub_key_hex), power))


def validator_set_cas(transport, version: int, pub_key_hex: str,
                      power: int) -> None:
    """(client.clj:173-179)."""
    transport.broadcast_tx(
        w.valset_cas_tx(version, bytes.fromhex(pub_key_hex), power))


def with_any_node(test, f, *args, transport_for=None):
    """Try f(transport, *args) against each node until one answers
    (client.clj:198-210).

    A TxError raised after an earlier node failed with a network error
    carries ``prior_indeterminate=True``: the earlier attempt may have
    committed (e.g. a timeout after the tx landed), so the app-level
    rejection is NOT proof the operation never happened — callers that
    roll back on definite failures must check this flag."""
    from jepsen_tpu import generator as gen
    nodes = list(test.get("nodes") or [])
    gen.rand.shuffle(nodes)
    transport_for = transport_for or test.get("transport_for")
    assert transport_for is not None, "test has no transport_for"
    last = None
    for node in nodes:
        try:
            return f(transport_for(test, node), *args)
        except (ConnectionError, OSError, TimeoutError) as e:
            last = e
        except TxError as e:
            e.prior_indeterminate = last is not None
            raise
    if last is not None:
        raise last
    return None
