"""Tendermint test suite: the worked example bundled with the framework
(reference: tendermint/ — cli.clj, core.clj, client.clj, gowire.clj,
db.clj, validator.clj) plus the native merkleeyes app it exercises
(native/merkleeyes/, C++)."""
