"""Tendermint suite CLI (reference: tendermint/src/jepsen/tendermint/cli.clj).

    python -m jepsen_tpu.tendermint.cli test \
        --workload cas-register --nemesis half-partitions \
        --time-limit 60 [--local]

`--local` runs against one shared native merkleeyes instance (no
cluster needed); without it, nodes are driven over SSH and tendermint
RPC (requires --tendermint-url for the consensus binary, as the
reference's tarball flags do, cli.clj:8-19)."""

from __future__ import annotations

import sys
from typing import Dict, Optional

from jepsen_tpu import cli as jcli
from jepsen_tpu.tendermint import core as tcore
from jepsen_tpu.tendermint import db as td


def extend_parser(p):
    # --workload / --nemesis already exist on the base parser; add only
    # the suite-specific flags (cli.clj:8-19).
    for sp_name in ("test", "analyze", "test-all"):
        sp = p._jepsen_subparsers[sp_name]
        sp.add_argument("--local", action="store_true",
                        help="single local native merkleeyes, no cluster")
        sp.add_argument("--dup-validators", action="store_true")
        sp.add_argument("--super-byzantine-validators", action="store_true")
        sp.add_argument("--tendermint-url")
        sp.add_argument("--merkleeyes-url")
    return p


def test_fn(options: Dict) -> Dict:
    args = options.get("args") or {}
    opts = dict(options)
    opts["workload"] = options.get("workload") or "cas-register"
    opts["nemesis_name"] = options.get("nemesis") or "none"
    if opts["workload"] not in tcore.WORKLOADS:
        print(f"unknown workload {opts['workload']!r}; "
              f"choose from {tcore.WORKLOADS}", file=sys.stderr)
        raise SystemExit(jcli.EXIT_BAD_ARGS)
    if opts["nemesis_name"] not in tcore.NEMESES:
        print(f"unknown nemesis {opts['nemesis_name']!r}; "
              f"choose from {tcore.NEMESES}", file=sys.stderr)
        raise SystemExit(jcli.EXIT_BAD_ARGS)
    if options.get("time-limit") is not None:
        opts["time_limit"] = options["time-limit"]
    opts["dup_validators"] = bool(args.get("dup_validators"))
    opts["super_byzantine_validators"] = \
        bool(args.get("super_byzantine_validators"))
    if args.get("local"):
        opts["db"] = td.LocalMerkleeyesDB()
        opts["transport_for"] = td.local_transport_for
        opts.setdefault("ssh", {})["dummy"] = True
        if not options.get("explicit-nodes"):
            # one logical node unless the user asked for more — local
            # mode shares a single server, extra nodes add nothing.
            opts["nodes"] = ["n1"]
            raw = str(args.get("concurrency") or "")
            if raw.endswith("n"):
                # per-node spec: recompute for the collapsed node count
                opts["concurrency"] = jcli.parse_concurrency(raw, 1)
            # absolute values pass through untouched
    else:
        opts["db"] = td.db({"tendermint_url": args.get("tendermint_url"),
                            "merkleeyes_url": args.get("merkleeyes_url")})
        opts["transport_for"] = td.http_transport_for
    return tcore.test_map(opts)


def main(argv: Optional[list] = None) -> int:
    return jcli.run_cli(test_fn, argv=argv, prog="jepsen-tendermint",
                        extend_parser=extend_parser)


if __name__ == "__main__":
    sys.exit(main())
