"""Client side of the tendermint v0.34 ABCI socket protocol.

This is the exact wire protocol a real tendermint binary speaks to its
--proxy_app (reference: merkleeyes/cmd/merkleeyes/main.go:26-57 serves
the Go app via tendermint's abci/server; merkleeyes/go.mod pins
tendermint v0.34.1-dev1). Framing is uvarint-length-delimited protobuf:

    frame = uvarint(len(body)) ∥ body

where body is a ``tendermint.abci.Request`` / ``Response`` — a oneof
over per-method messages. Field numbers follow tendermint v0.34
proto/tendermint/abci/types.proto. The encoder below is hand-rolled
(scalar / bytes / submessage fields only) so the framework carries no
protobuf dependency.

`AbciClient` drives the native merkleeyes server in its default
``--proto abci`` mode with the same method surface as the legacy
`MerkleeyesClient`, so transports and tests can swap protocols freely —
every integration test that uses it is exercising the same bytes a real
tendermint node would send.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from jepsen_tpu.tendermint import merkleeyes as me

# Request oneof field numbers (types.proto, tendermint v0.34).
REQ_ECHO = 1
REQ_FLUSH = 2
REQ_INFO = 3
REQ_SET_OPTION = 4
REQ_INIT_CHAIN = 5
REQ_QUERY = 6
REQ_BEGIN_BLOCK = 7
REQ_CHECK_TX = 8
REQ_DELIVER_TX = 9
REQ_END_BLOCK = 10
REQ_COMMIT = 11

# Response oneof field numbers.
RESP_EXCEPTION = 1
RESP_ECHO = 2
RESP_FLUSH = 3
RESP_INFO = 4
RESP_SET_OPTION = 5
RESP_INIT_CHAIN = 6
RESP_QUERY = 7
RESP_BEGIN_BLOCK = 8
RESP_CHECK_TX = 9
RESP_DELIVER_TX = 10
RESP_END_BLOCK = 11
RESP_COMMIT = 12


# ------------------------------------------------------- wire encoding

# Framing varints are Go binary.Uvarint — exactly gowire's encoding.
from jepsen_tpu.tendermint.gowire import uvarint, read_uvarint  # noqa: E402


def tag(field: int, wire: int) -> bytes:
    return uvarint((field << 3) | wire)


def varint_field(field: int, v: int) -> bytes:
    """Varint-typed field; proto3 omits zeros. Negative int64 takes the
    10-byte two's-complement form (ABCI never sends them here)."""
    if v == 0:
        return b""
    return tag(field, 0) + uvarint(v & 0xFFFFFFFFFFFFFFFF)


def bytes_field(field: int, b: bytes) -> bytes:
    if not b:
        return b""
    return tag(field, 2) + uvarint(len(b)) + b


def str_field(field: int, s: str) -> bytes:
    return bytes_field(field, s.encode("utf-8"))


def msg_field(field: int, sub: bytes) -> bytes:
    """Submessage — emitted even when empty (oneof arm presence)."""
    return tag(field, 2) + uvarint(len(sub)) + sub


def parse_fields(buf: bytes) -> Dict[int, list]:
    """Flat protobuf field scan: field -> [values] (int for varint,
    bytes for length-delimited). Unknown wire types are skipped."""
    out: Dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        t, pos = read_uvarint(buf, pos)
        field, wire = t >> 3, t & 7
        if wire == 0:
            v, pos = read_uvarint(buf, pos)
        elif wire == 2:
            n, pos = read_uvarint(buf, pos)
            v = buf[pos:pos + n]
            pos += n
        elif wire == 1:
            v = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wire == 5:
            v = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"bad wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def first(fields: Dict[int, list], field: int, default=None):
    vs = fields.get(field)
    return vs[0] if vs else default


def validator_update(pubkey: bytes, power: int) -> bytes:
    """ValidatorUpdate{pub_key:1 = PublicKey{ed25519:1}, power:2}."""
    pk = bytes_field(1, pubkey)
    return msg_field(1, pk) + varint_field(2, power)


def parse_validator_update(buf: bytes) -> Tuple[bytes, int]:
    f = parse_fields(buf)
    pk_msg = first(f, 1, b"")
    pubkey = first(parse_fields(pk_msg), 1, b"") if pk_msg else b""
    return pubkey, first(f, 2, 0)


class AbciError(RuntimeError):
    """ResponseException from the app."""


class AbciClient(me.MerkleeyesClient):
    """One ABCI socket session against the native merkleeyes (or any
    v0.34 ABCI app). Address: ('unix', path) or ('tcp', (host, port)).

    Connection handling and uvarint framing are inherited from
    MerkleeyesClient (both protocols share them); every protocol-level
    method is overridden with the protobuf encoding."""

    def roundtrip(self, req_arm: int, req_body: bytes,
                  resp_arm: int) -> Dict[int, list]:
        """Send Request{arm: body}, read the Response, return the
        selected arm's parsed fields. Raises AbciError on exception."""
        assert self.sock is not None, "not connected"
        frame = msg_field(req_arm, req_body)
        self.sock.sendall(uvarint(len(frame)) + frame)
        resp = parse_fields(self._read_frame())
        exc = first(resp, RESP_EXCEPTION)
        if exc is not None:
            f = parse_fields(exc)
            raise AbciError(first(f, 1, b"").decode("utf-8", "replace"))
        body = first(resp, resp_arm)
        if body is None:
            raise AbciError(
                f"expected Response arm {resp_arm}, got {sorted(resp)}")
        return parse_fields(body)

    # -- ABCI surface (same shape as MerkleeyesClient) ----------------

    def echo(self, data: bytes) -> bytes:
        f = self.roundtrip(REQ_ECHO, bytes_field(1, data), RESP_ECHO)
        return first(f, 1, b"")

    def flush(self):
        self.roundtrip(REQ_FLUSH, b"", RESP_FLUSH)

    def info(self) -> Tuple[int, bytes]:
        """(last_block_height, last_block_app_hash)."""
        body = str_field(1, "0.34.1")  # RequestInfo.version
        f = self.roundtrip(REQ_INFO, body, RESP_INFO)
        return first(f, 4, 0), first(f, 5, b"")

    def init_chain(self, validators: List[Tuple[bytes, int]],
                   chain_id: str = "jepsen") -> bytes:
        """Returns the app_hash. validators: [(ed25519 pubkey, power)]."""
        body = str_field(2, chain_id)
        for pk, power in validators:
            body += msg_field(4, validator_update(pk, power))
        f = self.roundtrip(REQ_INIT_CHAIN, body, RESP_INIT_CHAIN)
        return first(f, 3, b"")

    def _tx(self, arm: int, resp_arm: int, tx: bytes) -> me.TxResult:
        f = self.roundtrip(arm, bytes_field(1, tx), resp_arm)
        return me.TxResult(first(f, 1, 0), first(f, 2, b""),
                           first(f, 3, b"").decode("utf-8", "replace"))

    def check_tx(self, tx: bytes) -> me.TxResult:
        return self._tx(REQ_CHECK_TX, RESP_CHECK_TX, tx)

    def deliver_tx(self, tx: bytes) -> me.TxResult:
        return self._tx(REQ_DELIVER_TX, RESP_DELIVER_TX, tx)

    def begin_block(self):
        self.roundtrip(REQ_BEGIN_BLOCK, b"", RESP_BEGIN_BLOCK)

    def end_block(self, height: int = 0) -> List[Tuple[bytes, int]]:
        f = self.roundtrip(REQ_END_BLOCK, varint_field(1, height),
                           RESP_END_BLOCK)
        return [parse_validator_update(vu) for vu in f.get(1, [])]

    def commit(self) -> bytes:
        f = self.roundtrip(REQ_COMMIT, b"", RESP_COMMIT)
        return first(f, 2, b"")

    def query(self, path: str, data: bytes = b"") -> me.QueryResult:
        body = bytes_field(1, data) + str_field(2, path)
        f = self.roundtrip(REQ_QUERY, body, RESP_QUERY)
        # proto3 cannot distinguish index 0 from unset; like the
        # reference's ResponseQuery.Index, absent means 0.
        return me.QueryResult(
            first(f, 1, 0), first(f, 9, 0), first(f, 5, 0),
            first(f, 6, b""), first(f, 7, b""),
            first(f, 3, b"").decode("utf-8", "replace"))

    # tx_commit (DeliverTx in its own block + commit) is inherited: the
    # parent implementation calls this class's overridden methods.
