"""Transactional-anomaly cycle checker — the elle 0.1.2 capability rebuilt
TPU-first (reference call surface: jepsen/src/jepsen/tests/cycle.clj,
tests/cycle/append.clj, tests/cycle/wr.clj; anomaly taxonomy documented at
tests/cycle/wr.clj:31-45).

Transactions become nodes in a dependency graph with typed edges:

  ww  write-write   T1's write of version v precedes T2's write of v'
  wr  write-read    T2 read the version T1 wrote
  rw  anti-dep      T1 read a version that T2 overwrote
  rt  realtime      T1 completed before T2 was invoked
  p   process       T1 preceded T2 on the same process

Anomalies are cycles in restricted subgraphs (Adya's taxonomy):

  G0        cycle of only ww edges
  G1c       cycle of ww+wr edges (at least one wr)
  G-single  cycle with exactly one rw edge
  G2        cycle with one or more rw edges

Strongly connected components are found two ways: an iterative Tarjan on
the host for small graphs, and — the TPU path — boolean transitive
closure by repeated squaring of the adjacency matrix on the MXU
(`jnp.dot` over bfloat16 lifts reachability onto the systolic array;
SCC = R & R.T), which turns the irregular graph walk into dense matmuls
for histories with thousands of transactions.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

# Edge types, in explanation-priority order.
WW, WR, RW, RT, PROC = "ww", "wr", "rw", "rt", "process"

# Device SCC pays off once the adjacency matrix is big enough to fill the
# MXU; below this we stay on host.
_DEVICE_SCC_MIN_NODES = 1024


class Graph:
    """Directed multigraph over txn ids with typed edges."""

    def __init__(self):
        # a -> b -> set of edge types
        self.out: Dict[int, Dict[int, Set[str]]] = {}

    def add(self, a: int, b: int, typ: str) -> None:
        if a == b:
            return
        self.out.setdefault(a, {}).setdefault(b, set()).add(typ)
        self.out.setdefault(b, {})

    def add_node(self, a: int) -> None:
        self.out.setdefault(a, {})

    def nodes(self) -> List[int]:
        return list(self.out)

    def edge_types(self, a: int, b: int) -> Set[str]:
        return self.out.get(a, {}).get(b, set())

    def merge(self, other: "Graph") -> "Graph":
        for a, bs in other.out.items():
            self.add_node(a)
            for b, ts in bs.items():
                for t in ts:
                    self.add(a, b, t)
        return self

    def restrict(self, types: Set[str], nodes: Optional[Set[int]] = None) -> "Graph":
        g = Graph()
        for a, bs in self.out.items():
            if nodes is not None and a not in nodes:
                continue
            g.add_node(a)
            for b, ts in bs.items():
                if nodes is not None and b not in nodes:
                    continue
                keep = ts & types
                for t in keep:
                    g.add(a, b, t)
        return g

    def __len__(self):
        return len(self.out)


# ------------------------------------------------------------------- SCC


def tarjan_sccs(g: Graph) -> List[List[int]]:
    """Iterative Tarjan; returns SCCs with >1 node (self-loops excluded
    by construction — Graph.add drops a==b)."""
    index: Dict[int, int] = {}
    low: Dict[int, int] = {}
    on_stack: Set[int] = set()
    stack: List[int] = []
    sccs: List[List[int]] = []
    counter = [0]

    for root in g.nodes():
        if root in index:
            continue
        work: List[Tuple[int, int]] = [(root, 0)]
        while work:
            v, pi = work.pop()
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            succs = list(g.out.get(v, {}))
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work.append((v, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return sccs


_scc_closure_jit = None  # memoized jit wrapper (see _get_scc_closure)


def _get_scc_closure():
    """The jitted transitive-closure program, built ONCE and memoized
    in a module global. jax stays a lazy import (this module must be
    usable with no backend), but the wrapper must not be re-created per
    device_sccs call — a fresh jax.jit each call would never reuse the
    compile cache (found by `jepsen-tpu lint`, recompile-closure-
    capture); the memo makes the jit effectively module-level, so the
    suppression below records intent, not a hazard."""
    global _scc_closure_jit
    if _scc_closure_jit is not None:
        return _scc_closure_jit
    import jax
    import jax.numpy as jnp
    from jax import lax

    def closure(adj, steps: int):
        r = jnp.minimum(adj + jnp.eye(adj.shape[0], dtype=adj.dtype), 1.0)

        def body(_, r):
            rr = jnp.dot(r.astype(jnp.bfloat16), r.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
            return jnp.minimum(rr, 1.0).astype(adj.dtype)

        r = lax.fori_loop(0, steps, body, r)
        return jnp.logical_and(r > 0, r.T > 0)

    # jepsen-lint: disable=recompile-closure-capture
    _scc_closure_jit = jax.jit(closure, static_argnums=1)
    return _scc_closure_jit


def device_sccs(g: Graph) -> List[List[int]]:
    """SCCs via MXU transitive closure: R := A | I, square ceil(log2 n)
    times (boolean matmul = bfloat16 dot > 0), SCC membership = R & R.T.
    One XLA program; the graph walk becomes dense systolic-array work."""
    import math

    import numpy as np

    ids = sorted(g.nodes())
    n = len(ids)
    if n == 0:
        return []
    pos = {v: i for i, v in enumerate(ids)}
    a = np.zeros((n, n), dtype=np.float32)
    for u, bs in g.out.items():
        for v in bs:
            a[pos[u], pos[v]] = 1.0
    # static trip count computed host-side (it was np math inside the
    # traced closure before — legal but a purity-rule exception for no
    # gain)
    steps = max(1, math.ceil(math.log2(max(2, n))))
    s = np.asarray(_get_scc_closure()(a, steps))
    seen: Set[int] = set()
    sccs: List[List[int]] = []
    for i in range(n):
        if i in seen:
            continue
        members = np.nonzero(s[i])[0]
        comp = [ids[j] for j in members]
        seen.update(int(j) for j in members)
        if len(comp) > 1:
            sccs.append(comp)
    return sccs


def sccs(g: Graph) -> List[List[int]]:
    if len(g) >= _DEVICE_SCC_MIN_NODES:
        return device_sccs(g)
    return tarjan_sccs(g)


# --------------------------------------------------------------- cycles


def _bfs_path(g: Graph, src: int, dst: int,
              types: Optional[Set[str]] = None) -> Optional[List[int]]:
    """Shortest path src..dst (inclusive) using only edges of `types`
    (None = any). src == dst finds the shortest cycle through src."""
    parent: Dict[int, int] = {}
    q = deque([src])
    seen = {src} if src != dst else set()
    while q:
        v = q.popleft()
        for w, ts in g.out.get(v, {}).items():
            if types is not None and not (ts & types):
                continue
            if w == dst:
                path = [w, v]
                while path[-1] != src:
                    path.append(parent[path[-1]])
                path.reverse()
                return path
            if w not in seen:
                seen.add(w)
                parent[w] = v
                q.append(w)
    return None


def find_cycle(g: Graph, scc: Sequence[int],
               types: Optional[Set[str]] = None) -> Optional[List[int]]:
    """A cycle [v0, v1, ..., v0] inside scc using only `types` edges."""
    members = set(scc)
    sub = g.restrict(types if types is not None else {WW, WR, RW, RT, PROC},
                     members)
    for v in scc:
        p = _bfs_path(sub, v, v)
        if p:
            return p
    return None


def find_cycle_with_one(g: Graph, scc: Sequence[int], one: str,
                        rest: Set[str]) -> Optional[List[int]]:
    """A cycle containing exactly one edge of type `one`, all others drawn
    from `rest` — the G-single search (one rw edge, back via ww/wr)."""
    members = set(scc)
    sub_rest = g.restrict(rest, members)
    for a in scc:
        for b, ts in g.out.get(a, {}).items():
            if b not in members or one not in ts:
                continue
            back = _bfs_path(sub_rest, b, a)
            if back is not None:
                return [a] + back
    return None


# ---------------------------------------------------------- explanation


def explain_cycle(cycle: List[int], g: Graph,
                  explainer: Callable[[int, int, Set[str]], str]) -> List[str]:
    out = []
    for a, b in zip(cycle, cycle[1:]):
        out.append(explainer(a, b, g.edge_types(a, b)))
    return out


def _default_explainer(by_id: Dict[int, dict]) -> Callable:
    def show(i: int) -> dict:
        return {k: v for k, v in by_id.get(i, {}).items()
                if not str(k).startswith("_")}

    def explain(a: int, b: int, types: Set[str]) -> str:
        t = next((x for x in (WW, WR, RW, RT, PROC) if x in types), "?")
        return f"T{a} {show(a)} --[{t}]--> T{b} {show(b)}"
    return explain


# --------------------------------------------------------------- check


#: anomaly -> (edge types allowed, required type, "exactly-one" type)
_CYCLE_SPECS = [
    ("G0", {WW, RT, PROC}, None, None),
    ("G1c", {WW, WR, RT, PROC}, WR, None),
    ("G-single", {WW, WR, RT, PROC}, None, RW),
    ("G2", {WW, WR, RW, RT, PROC}, RW, None),
]


def cycle_anomalies(g: Graph, explainer: Optional[Callable] = None,
                    by_id: Optional[Dict[int, dict]] = None) -> Dict[str, list]:
    """Classify every SCC into the most severe anomaly classes it exhibits.
    Returns anomaly-name -> list of {"cycle": [...ids...], "steps": [...]}."""
    if explainer is None:
        explainer = _default_explainer(by_id or {})
    found: Dict[str, list] = {}
    for comp in sccs(g):
        for name, types, required, exactly_one in _CYCLE_SPECS:
            if exactly_one is not None:
                cyc = find_cycle_with_one(g, comp, exactly_one,
                                          types - {exactly_one})
            else:
                cyc = find_cycle(g, comp, types)
                if cyc is not None and required is not None:
                    if not any(required in g.edge_types(a, b)
                               for a, b in zip(cyc, cyc[1:])):
                        cyc = None
            if cyc is not None:
                found.setdefault(name, []).append({
                    "cycle": cyc,
                    "steps": explain_cycle(cyc, g, explainer),
                })
                break  # most severe classification for this SCC wins
    return found


def check(analyzer: Callable, history) -> Dict:
    """elle.core/check equivalent (tests/cycle.clj:9-16): `analyzer` maps a
    history to (graph, explainer, by_id); cycles become anomalies."""
    g, explainer, by_id = analyzer(history)
    anomalies = cycle_anomalies(g, explainer, by_id)
    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies),
        "anomalies": anomalies,
    }


# ------------------------------------------------- generic graph builders


def realtime_graph(oks: List[dict]) -> Graph:
    """rt edges: T1's completion precedes T2's invocation. Uses the
    reduced form: edge only from each txn to the txns invoked after it and
    before any later completion (transitively implied edges dropped)."""
    import bisect

    g = Graph()
    for t in oks:
        g.add_node(t["_id"])
    # oks carry "_invoke_index"/"_complete_index"/"_id" annotations.
    starts = sorted(oks, key=lambda o: o["_invoke_index"])
    invs = [t["_invoke_index"] for t in starts]
    # suffix_min[i] = min complete index among starts[i:]
    suffix_min = [0] * (len(starts) + 1)
    suffix_min[len(starts)] = float("inf")
    for i in range(len(starts) - 1, -1, -1):
        suffix_min[i] = min(starts[i]["_complete_index"], suffix_min[i + 1])
    for t1 in oks:
        i = bisect.bisect_right(invs, t1["_complete_index"])
        if i >= len(starts):
            continue
        horizon = suffix_min[i]
        for j in range(i, len(starts)):
            if invs[j] > horizon:
                break
            g.add(t1["_id"], starts[j]["_id"], RT)
    return g


def process_graph(oks: List[dict]) -> Graph:
    g = Graph()
    by_proc: Dict = {}
    for o in sorted(oks, key=lambda o: o["_invoke_index"]):
        by_proc.setdefault(o.get("process"), []).append(o)
    for chain in by_proc.values():
        for t1, t2 in zip(chain, chain[1:]):
            g.add(t1["_id"], t2["_id"], PROC)
    return g
