"""List-append transactional checker (elle.list-append capability;
call surface jepsen/src/jepsen/tests/cycle/append.clj:11-27).

Transactions append unique values to per-key lists and read whole lists.
Because reads expose the full list, the version order per key is directly
observable: every read is a prefix of the key's final append order, so
incompatible reads are themselves an anomaly ("incompatible-order"), and
ww/wr/rw edges fall out of the longest observed order.

Checked anomalies: internal, G1a (aborted read), G1b (intermediate read),
dirty-update, incompatible-order, and the cycle family G0/G1c/G-single/G2
(classification machinery in jepsen_tpu.elle).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from jepsen_tpu import elle
from jepsen_tpu.elle import Graph, RW, WR, WW, txn as txn_mod

DEFAULT_ANOMALIES = ["G1", "G2"]

#: anomaly aliases -> concrete anomalies (wr.clj:47-48: G2 implies
#: G-single and G1c; G1 implies G1a, G1b, G1c; G1c implies G0)
_EXPANSION = {
    "G1": {"G1a", "G1b", "G1c", "G0"},
    "G1c": {"G1c", "G0"},
    "G2": {"G2", "G-single", "G1c", "G0"},
    "G-single": {"G-single", "G1c", "G0"},
}


def expand_anomalies(names) -> Set[str]:
    out: Set[str] = set()
    for n in names:
        n = str(n).lstrip(":")
        out |= _EXPANSION.get(n, {n})
    return out | {"internal", "incompatible-order", "dirty-update"}


# ----------------------------------------------------------- single-txn


def internal_cases(oks: List[dict]) -> List[dict]:
    """Reads inconsistent with the txn's own prior reads/appends
    (elle `internal`). Expected list state per key is tracked through the
    txn; a read must equal expectation when known, or end with the txn's
    own prior appends when the prefix is unknown."""
    bad = []
    for o in oks:
        # key -> (known_prefix_or_None, [own appends since])
        state: Dict = {}
        for f, k, v in o.get("value") or []:
            if f == "append":
                known, own = state.get(k, (None, []))
                state[k] = (known, own + [v])
            else:  # read
                got = list(v) if v is not None else []
                if k in state:
                    known, own = state[k]
                    if known is not None:
                        expected = known + own
                        if got != expected:
                            bad.append({"op": dict(o), "mop": [f, k, v],
                                        "expected": expected})
                            continue
                    elif own and got[-len(own):] != own:
                        bad.append({"op": dict(o), "mop": [f, k, v],
                                    "expected": ["...", *own]})
                        continue
                state[k] = (got, [])
    return bad


# -------------------------------------------------------- version orders


class IncompatibleOrder(Exception):
    def __init__(self, key, readings):
        super().__init__(f"incompatible reads of key {key}")
        self.case = {"key": key, "values": readings}


def _key_orders(oks: List[dict]) -> Tuple[Dict, List[dict]]:
    """key -> append order [v1 v2 ...], from reads (longest read wins;
    all reads must be prefixes of it) extended with appends whose position
    is known: the longest-read order, then any appends by the reading txns
    immediately after their observed prefix. Returns (orders, error-cases)."""
    longest: Dict = {}
    reads_by_key: Dict[int, List[list]] = {}
    for o in oks:
        for f, k, v in o.get("value") or []:
            if f == "r" and v is not None:
                got = list(v)
                reads_by_key.setdefault(k, []).append(got)
                if len(got) > len(longest.get(k, [])):
                    longest[k] = got
    errors = []
    orders: Dict = {}
    for k, lead in longest.items():
        ok = True
        for r in reads_by_key[k]:
            if lead[:len(r)] != r:
                errors.append({"key": k, "values": [lead, r]})
                ok = False
                break
        if ok:
            orders[k] = lead
    return orders, errors


# ------------------------------------------------------- graph building


def graph(oks: List[dict]) -> Tuple[Graph, Dict, Dict, List[dict]]:
    """Build the ww/wr/rw dependency graph. Returns
    (graph, appender-map key->v->txn-id, orders, incompatible-order cases)."""
    g = Graph()
    for o in oks:
        g.add_node(o["_id"])
    appender: Dict[int, Dict] = {}
    for o in oks:
        for f, k, v in o.get("value") or []:
            if f == "append":
                appender.setdefault(k, {})[v] = o["_id"]
    orders, incompat = _key_orders(oks)

    for k, order in orders.items():
        writer = appender.get(k, {})
        # ww: consecutive appends in the version order
        for v1, v2 in zip(order, order[1:]):
            a, b = writer.get(v1), writer.get(v2)
            if a is not None and b is not None:
                g.add(a, b, WW)
    # wr + rw per read. The observed list is a prefix of the key's final
    # append order, so every committed append NOT in the observed list
    # happened after the read: reader --rw--> its appender. This covers
    # appends whose exact position is unknown (e.g. two txns that both
    # read [] and appended — mutual rw, no later read needed).
    for o in oks:
        for f, k, rv in o.get("value") or []:
            if f != "r" or rv is None:
                continue
            got = list(rv)
            if got:
                w = appender.get(k, {}).get(got[-1])
                if w is not None and w != o["_id"]:
                    g.add(w, o["_id"], WR)
            got_set = set(got)
            for v, w2 in appender.get(k, {}).items():
                if v not in got_set and w2 != o["_id"]:
                    g.add(o["_id"], w2, RW)
    return g, appender, orders, incompat


# ---------------------------------------------------------------- check


def check(opts: Optional[Dict], history) -> Dict:
    """elle.list-append/check equivalent. opts: anomalies (default
    [G1 G2]), additional-graphs ("realtime"/"process")."""
    o = opts or {}
    wanted = expand_anomalies(o.get("anomalies", DEFAULT_ANOMALIES))
    oks = txn_mod.ok_txns(history)
    by_id = {t["_id"]: t for t in oks}
    anomalies: Dict[str, list] = {}

    if "internal" in wanted:
        cases = internal_cases(oks)
        if cases:
            anomalies["internal"] = cases

    failed = txn_mod.failed_writes(history, "append")
    inter = txn_mod.intermediate_writes(oks, "append")
    for t in oks:
        for f, k, v in t.get("value") or []:
            if f != "r" or v is None:
                continue
            for x in v:
                if "G1a" in wanted and x in failed.get(k, ()):
                    anomalies.setdefault("G1a", []).append(
                        {"op": dict(t), "mop": [f, k, list(v)], "value": x})
                src = inter.get(k, {}).get(x)
                # an intermediate read shows a txn's non-final append of k
                # as the *last* element — the final append is missing
                if ("G1b" in wanted and src is not None
                        and src["_id"] != t["_id"] and list(v)[-1] == x):
                    anomalies.setdefault("G1b", []).append(
                        {"op": dict(t), "mop": [f, k, list(v)], "value": x})

    g, _appender, _orders, incompat = graph(oks)
    if incompat:
        anomalies["incompatible-order"] = incompat

    extra = o.get("additional-graphs") or []
    if "realtime" in extra:
        g.merge(elle.realtime_graph(oks))
    if "process" in extra:
        g.merge(elle.process_graph(oks))

    cyc = elle.cycle_anomalies(g, by_id=by_id)
    for name, cases in cyc.items():
        if name in wanted:
            anomalies[name] = cases

    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies),
        "anomalies": anomalies,
    }


def gen(opts: Optional[Dict] = None):
    """Generator of append/read txns (tests/cycle/append.clj:24-27)."""
    return txn_mod.txn_generator(opts, "append")
