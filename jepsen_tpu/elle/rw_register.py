"""Write/read register transactional checker (elle.rw-register capability;
call surface jepsen/src/jepsen/tests/cycle/wr.clj:9-54, anomaly taxonomy
documented there).

Writes are unique per key, so write-read dependencies are exact: reading
value v identifies the (unique) transaction that wrote it. Version orders
— needed for ww and rw edges — are only partially observable and are
inferred per the reference's option set (wr.clj:17-29):

  sequential-keys    each process's txn order gives per-key write order
  linearizable-keys  realtime order of non-overlapping writing txns
  wfr-keys           writes follow reads within a transaction

Default anomalies: [G2 G1a G1b internal] (wr.clj:49-50), which — via the
implication lattice — catches everything.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from jepsen_tpu import elle
from jepsen_tpu.elle import Graph, RW, WR, WW, txn as txn_mod
from jepsen_tpu.elle.list_append import expand_anomalies

DEFAULT_ANOMALIES = ["G2", "G1a", "G1b", "internal"]


def internal_cases(oks: List[dict]) -> List[dict]:
    """A txn's read disagrees with its own prior write or read of the key
    (wr.clj:44-45)."""
    bad = []
    for o in oks:
        state: Dict = {}
        for f, k, v in o.get("value") or []:
            if f == "w":
                state[k] = v
            else:
                if k in state and state[k] != v:
                    bad.append({"op": dict(o), "mop": [f, k, v],
                                "expected": state[k]})
                state[k] = v
    return bad


def _version_graph(oks: List[dict], opts: Dict) -> Dict[int, Graph]:
    """key -> directed graph over written values (+ None as the initial
    version), one edge per inferred version-order constraint."""
    vgs: Dict[int, Graph] = {}

    def vg(k) -> Graph:
        if k not in vgs:
            vgs[k] = Graph()
        return vgs[k]

    # within-txn write order: w k=v1 ... w k=v2 means v1 precedes v2
    for o in oks:
        last: Dict = {}
        for f, k, v in o.get("value") or []:
            if f == "w":
                if k in last:
                    vg(k).add(last[k], v, "v")
                last[k] = v

    if opts.get("wfr-keys"):
        for o in oks:
            reads: Dict = {}
            for f, k, v in o.get("value") or []:
                if f == "r":
                    reads.setdefault(k, v)
                elif f == "w" and k in reads and reads[k] != v:
                    vg(k).add(reads[k], v, "v")

    if opts.get("sequential-keys"):
        by_proc: Dict = {}
        for o in sorted(oks, key=lambda o: o["_invoke_index"]):
            by_proc.setdefault(o.get("process"), []).append(o)
        for chain in by_proc.values():
            last: Dict = {}
            for o in chain:
                for f, k, v in o.get("value") or []:
                    if f != "w":
                        continue
                    if k in last and last[k] != v:
                        vg(k).add(last[k], v, "v")
                    last[k] = v

    if opts.get("linearizable-keys"):
        writes: Dict[int, List[Tuple[int, int, object]]] = {}
        for o in oks:
            for f, k, v in o.get("value") or []:
                if f == "w":
                    writes.setdefault(k, []).append(
                        (o["_invoke_index"], o["_complete_index"], v))
        for k, ws in writes.items():
            for inv1, comp1, v1 in ws:
                for inv2, _comp2, v2 in ws:
                    if comp1 < inv2 and v1 != v2:
                        vg(k).add(v1, v2, "v")
    return vgs


def graph(oks: List[dict], opts: Optional[Dict] = None) -> Tuple[Graph, Dict]:
    """Dependency graph over txns: exact wr edges plus ww/rw edges from
    the inferred per-key version graphs. Returns (graph, writer-map)."""
    o = opts or {}
    g = Graph()
    writer: Dict[int, Dict] = {}
    for t in oks:
        g.add_node(t["_id"])
        for f, k, v in t.get("value") or []:
            if f == "w":
                writer.setdefault(k, {})[v] = t["_id"]

    # wr: reading v depends on its unique writer
    for t in oks:
        for f, k, v in t.get("value") or []:
            if f == "r" and v is not None:
                w = writer.get(k, {}).get(v)
                if w is not None and w != t["_id"]:
                    g.add(w, t["_id"], WR)

    # readers index, built once: (k, v) -> [txn ids that read k=v]
    readers: Dict[tuple, List[int]] = {}
    for t in oks:
        for f, k, v in t.get("value") or []:
            if f == "r":
                readers.setdefault((k, v), []).append(t["_id"])

    vgs = _version_graph(oks, o)
    for k, vg in vgs.items():
        wk = writer.get(k, {})
        for v1, succs in vg.out.items():
            a = wk.get(v1)
            for v2 in succs:
                b = wk.get(v2)
                if a is not None and b is not None:
                    g.add(a, b, WW)
                if b is None:
                    continue
                # rw: anyone who read v1 is overwritten by v2's writer
                for rid in readers.get((k, v1), ()):
                    if rid != b:
                        g.add(rid, b, RW)
    return g, writer


def check(opts: Optional[Dict], history) -> Dict:
    """elle.rw-register/check equivalent (wr.clj:14-54)."""
    o = opts or {}
    wanted = expand_anomalies(o.get("anomalies", DEFAULT_ANOMALIES))
    oks = txn_mod.ok_txns(history)
    by_id = {t["_id"]: t for t in oks}
    anomalies: Dict[str, list] = {}

    if "internal" in wanted:
        cases = internal_cases(oks)
        if cases:
            anomalies["internal"] = cases

    failed = txn_mod.failed_writes(history, "w")
    inter = txn_mod.intermediate_writes(oks, "w")
    for t in oks:
        for f, k, v in t.get("value") or []:
            if f != "r" or v is None:
                continue
            if "G1a" in wanted and v in failed.get(k, ()):
                anomalies.setdefault("G1a", []).append(
                    {"op": dict(t), "mop": [f, k, v]})
            src = inter.get(k, {}).get(v)
            if "G1b" in wanted and src is not None and src["_id"] != t["_id"]:
                anomalies.setdefault("G1b", []).append(
                    {"op": dict(t), "mop": [f, k, v]})

    g, _writer = graph(oks, o)
    extra = o.get("additional-graphs") or []
    if "realtime" in extra:
        g.merge(elle.realtime_graph(oks))
    if "process" in extra:
        g.merge(elle.process_graph(oks))

    cyc = elle.cycle_anomalies(g, by_id=by_id)
    for name, cases in cyc.items():
        if name in wanted:
            anomalies[name] = cases

    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies),
        "anomalies": anomalies,
    }


def gen(opts: Optional[Dict] = None):
    """Generator of write/read txns (wr.clj:9-12)."""
    return txn_mod.txn_generator(opts, "w")
