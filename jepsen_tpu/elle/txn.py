"""Transaction micro-op helpers and generators (elle's txn model, surfaced
through jepsen.tests.cycle.append/wr gen wrappers — tests/cycle/append.clj:
24-27, tests/cycle/wr.clj:9-12).

A transaction is a list of micro-ops ("mops"): [f, k, v] with
f in {"r", "w", "append"}. Invocations carry nil read values; completions
fill them in:

    invoke  {"f": "txn", "value": [["r", 3, None], ["append", 3, 2]]}
    ok      {"f": "txn", "value": [["r", 3, [1]],  ["append", 3, 2]]}
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from jepsen_tpu import generator as gen

DEFAULTS = {
    "key-count": 2,
    "min-txn-length": 1,
    "max-txn-length": 2,
    "max-writes-per-key": 32,
}


def _txn_stream(opts: Optional[Dict], write_f: str) -> Iterator[list]:
    """Infinite stream of txn mop-lists. Keys come from a sliding active
    pool of `key-count` keys; a key retires once it has taken
    max-writes-per-key writes (elle wr-txns semantics)."""
    o = {**DEFAULTS, **(opts or {})}
    key_count = o["key-count"]
    lo, hi = o["min-txn-length"], o["max-txn-length"]
    max_writes = o["max-writes-per-key"]
    active: List[int] = list(range(key_count))
    next_key = key_count
    writes: Dict[int, int] = {}

    while True:
        length = gen.rand.randint(lo, hi)
        txn = []
        for _ in range(length):
            k = active[gen.rand.randrange(len(active))]
            if gen.rand.random() < 0.5:
                txn.append(["r", k, None])
            else:
                v = writes.get(k, 0) + 1
                if v > max_writes:
                    i = active.index(k)
                    active[i] = next_key
                    k = next_key
                    next_key += 1
                    v = 1
                writes[k] = v
                txn.append([write_f, k, v])
        yield txn


def txn_generator(opts: Optional[Dict], write_f: str):
    """A jepsen generator of {"f": "txn"} invocations."""
    stream = _txn_stream(opts, write_f)

    def next_op(_test=None, _ctx=None):
        return {"f": "txn", "value": next(stream)}

    return next_op


def wr_txns(opts: Optional[Dict] = None) -> Iterator[list]:
    return _txn_stream(opts, "w")


def append_txns(opts: Optional[Dict] = None) -> Iterator[list]:
    return _txn_stream(opts, "append")


# ------------------------------------------------------- history plumbing


def ok_txns(history) -> List[dict]:
    """Completed ok txn ops annotated with _id / _invoke_index /
    _complete_index; _id indexes into the returned list."""
    open_by_process: Dict = {}
    out: List[dict] = []
    for i, o in enumerate(history):
        if o.get("f") != "txn":
            continue
        p = o.get("process")
        t = o.get("type")
        if t == "invoke":
            open_by_process[p] = i
        elif t == "ok":
            inv = open_by_process.pop(p, i)
            rec = dict(o)
            rec["_invoke_index"] = inv
            rec["_complete_index"] = i
            rec["_id"] = len(out)
            out.append(rec)
        else:
            open_by_process.pop(p, None)
    return out


def failed_writes(history, write_f: str) -> Dict[int, set]:
    """key -> set of values written by :fail txns (known not committed) —
    the G1a source set."""
    out: Dict[int, set] = {}
    invokes: Dict = {}
    for o in history:
        if o.get("f") != "txn":
            continue
        t = o.get("type")
        p = o.get("process")
        if t == "invoke":
            invokes[p] = o
        elif t == "fail":
            inv = invokes.pop(p, None)
            if inv is None:
                continue
            for mop in inv.get("value") or []:
                f, k, v = mop
                if f == write_f:
                    out.setdefault(k, set()).add(v)
        elif t == "ok":
            invokes.pop(p, None)
    return out


def intermediate_writes(oks: List[dict], write_f: str) -> Dict[int, Dict]:
    """key -> value -> txn, for every write that is NOT the txn's final
    write of that key — the G1b source set."""
    out: Dict[int, Dict] = {}
    for o in oks:
        last: Dict[int, int] = {}
        mops = o.get("value") or []
        for i, (f, k, v) in enumerate(mops):
            if f == write_f:
                last[k] = i
        for i, (f, k, v) in enumerate(mops):
            if f == write_f and last[k] != i:
                out.setdefault(k, {})[v] = o
    return out
