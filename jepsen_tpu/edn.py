"""Minimal EDN reader/writer.

Speaks enough EDN to round-trip the reference's on-disk artifacts:
`history.edn` (one op map per line, written by `util/pwrite-history!`,
reference jepsen/src/jepsen/store.clj:351-362) and `results.edn`
(reference jepsen/src/jepsen/store.clj:385-397).

Mapping to Python:
    nil            -> None
    true/false     -> bool
    integers       -> int          (incl. trailing N bigints)
    floats         -> float        (incl. trailing M decimals)
    strings        -> str
    :keyword       -> Keyword      (interned; == compares by name)
    symbol         -> Symbol
    \\c chars      -> str of length 1
    (...) [...]    -> list
    {...}          -> dict
    #{...}         -> frozenset
    #tag value     -> Tagged(tag, value)   (#inst kept as Tagged)
"""

from __future__ import annotations

import io
from typing import Any, Iterator


class Keyword:
    """An EDN keyword (`:foo` / `:foo/bar`). Interned: equal names are `is`."""

    __slots__ = ("name",)
    _interned: dict = {}

    def __new__(cls, name: str):
        k = cls._interned.get(name)
        if k is None:
            k = object.__new__(cls)
            k.name = name
            cls._interned[name] = k
        return k

    def __repr__(self):
        return ":" + self.name

    def __hash__(self):
        return hash(self.name) ^ 0x9E3779B9

    def __eq__(self, other):
        if isinstance(other, Keyword):
            return self.name == other.name
        return NotImplemented

    def __reduce__(self):  # pickle support
        return (Keyword, (self.name,))


class Symbol:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return self.name

    def __hash__(self):
        return hash(self.name) ^ 0x85EBCA6B

    def __eq__(self, other):
        return isinstance(other, Symbol) and self.name == other.name


class Tagged:
    """A tagged literal `#tag value` we don't interpret."""

    __slots__ = ("tag", "value")

    def __init__(self, tag: str, value: Any):
        self.tag = tag
        self.value = value

    def __repr__(self):
        return f"#{self.tag} {self.value!r}"

    def __eq__(self, other):
        return (
            isinstance(other, Tagged)
            and self.tag == other.tag
            and self.value == other.value
        )

    def __hash__(self):
        return hash((self.tag, repr(self.value)))


_WS = set(" \t\r\n,")
_DELIM = set('()[]{}"; ')
_CHAR_NAMES = {
    "newline": "\n",
    "space": " ",
    "tab": "\t",
    "return": "\r",
    "backspace": "\b",
    "formfeed": "\f",
}
_STR_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    '"': '"',
    "\\": "\\",
    "b": "\b",
    "f": "\f",
}


class _Reader:
    def __init__(self, s: str):
        self.s = s
        self.i = 0
        self.n = len(s)

    def _skip_ws(self):
        s, n = self.s, self.n
        while self.i < n:
            c = s[self.i]
            if c in _WS:
                self.i += 1
            elif c == ";":
                while self.i < n and s[self.i] != "\n":
                    self.i += 1
            elif c == "#" and self.i + 1 < n and s[self.i + 1] == "_":
                self.i += 2
                self.read()  # discard next form
            else:
                return

    def eof(self) -> bool:
        self._skip_ws()
        return self.i >= self.n

    def read(self) -> Any:
        self._skip_ws()
        if self.i >= self.n:
            raise EOFError("EDN: unexpected end of input")
        c = self.s[self.i]
        if c == "(":
            return self._read_seq(")")
        if c == "[":
            return self._read_seq("]")
        if c == "{":
            return self._read_map()
        if c == '"':
            return self._read_string()
        if c == ":":
            return self._read_keyword()
        if c == "\\":
            return self._read_char()
        if c == "#":
            return self._read_dispatch()
        return self._read_atom()

    def _read_seq(self, close: str) -> list:
        self.i += 1
        out = []
        while True:
            self._skip_ws()
            if self.i >= self.n:
                raise EOFError(f"EDN: unterminated sequence, expected {close}")
            if self.s[self.i] == close:
                self.i += 1
                return out
            out.append(self.read())

    def _read_map(self) -> dict:
        self.i += 1
        out = {}
        while True:
            self._skip_ws()
            if self.i >= self.n:
                raise EOFError("EDN: unterminated map")
            if self.s[self.i] == "}":
                self.i += 1
                return out
            k = self.read()
            v = self.read()
            out[_freeze(k)] = v

    def _read_string(self) -> str:
        self.i += 1
        buf = io.StringIO()
        s, n = self.s, self.n
        while self.i < n:
            c = s[self.i]
            if c == '"':
                self.i += 1
                return buf.getvalue()
            if c == "\\":
                self.i += 1
                if self.i >= n:
                    raise EOFError("EDN: unterminated string")
                e = s[self.i]
                if e == "u":
                    hexs = s[self.i + 1 : self.i + 5]
                    if len(hexs) < 4:
                        raise EOFError("EDN: unterminated string")
                    buf.write(chr(int(hexs, 16)))
                    self.i += 5
                    continue
                buf.write(_STR_ESCAPES.get(e, e))
                self.i += 1
            else:
                buf.write(c)
                self.i += 1
        raise EOFError("EDN: unterminated string")

    def _read_keyword(self) -> Keyword:
        self.i += 1
        return Keyword(self._read_token())

    def _read_char(self) -> str:
        self.i += 1
        tok = self._read_token()
        if len(tok) == 1:
            return tok
        if tok in _CHAR_NAMES:
            return _CHAR_NAMES[tok]
        if tok.startswith("u"):
            return chr(int(tok[1:], 16))
        raise ValueError(f"EDN: bad char literal \\{tok}")

    def _read_dispatch(self) -> Any:
        self.i += 1
        c = self.s[self.i]
        if c == "{":  # set
            return frozenset(_freeze(x) for x in self._read_seq_set())
        # tagged literal
        tag = self._read_token()
        value = self.read()
        return Tagged(tag, value)

    def _read_seq_set(self) -> list:
        return self._read_seq("}")

    def _read_token(self) -> str:
        start = self.i
        s, n = self.s, self.n
        while self.i < n and s[self.i] not in _WS and s[self.i] not in _DELIM:
            self.i += 1
        return s[start : self.i]

    def _read_atom(self) -> Any:
        tok = self._read_token()
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        c = tok[0]
        if c.isdigit() or (c in "+-" and len(tok) > 1 and tok[1].isdigit()):
            return _parse_num(tok)
        return Symbol(tok)


def _parse_num(tok: str):
    t = tok
    if t.endswith("N") or t.endswith("M"):
        t = t[:-1]
    if "/" in t:  # ratio -> float
        num, den = t.split("/")
        return int(num) / int(den)
    try:
        if any(ch in t for ch in ".eE") and not t.startswith("0x"):
            return float(t)
        return int(t, 0) if t.startswith(("0x", "-0x")) else int(t)
    except ValueError:
        return float(t)


def _freeze(x: Any) -> Any:
    """Make a parsed form hashable so it can be a map key / set element."""
    if isinstance(x, list):
        return tuple(_freeze(e) for e in x)
    if isinstance(x, dict):
        return tuple(sorted(((k, _freeze(v)) for k, v in x.items()), key=repr))
    return x


# ---------------------------------------------------------------- public API


def loads(s: str) -> Any:
    """Parse a single EDN form."""
    return _Reader(s).read()


def loads_all(s: str) -> list:
    """Parse every form in the string (e.g. a whole history.edn file)."""
    r = _Reader(s)
    out = []
    while not r.eof():
        out.append(r.read())
    return out


def iter_forms(s: str) -> Iterator[Any]:
    r = _Reader(s)
    while not r.eof():
        yield r.read()


def dumps(x: Any) -> str:
    buf = io.StringIO()
    _write(x, buf)
    return buf.getvalue()


def _write(x: Any, w: io.StringIO):
    if x is None:
        w.write("nil")
    elif x is True:
        w.write("true")
    elif x is False:
        w.write("false")
    elif isinstance(x, Keyword):
        w.write(":" + x.name)
    elif isinstance(x, Symbol):
        w.write(x.name)
    elif isinstance(x, str):
        w.write('"')
        w.write(
            x.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        )
        w.write('"')
    elif isinstance(x, (int, float)):
        w.write(repr(x))
    elif isinstance(x, Tagged):
        w.write(f"#{x.tag} ")
        _write(x.value, w)
    elif isinstance(x, dict):
        w.write("{")
        first = True
        for k, v in x.items():
            if not first:
                w.write(", ")
            first = False
            _write(k, w)
            w.write(" ")
            _write(v, w)
        w.write("}")
    elif isinstance(x, (frozenset, set)):
        w.write("#{")
        w.write(" ".join(dumps(e) for e in x))
        w.write("}")
    elif isinstance(x, (list, tuple)):
        w.write("[")
        w.write(" ".join(dumps(e) for e in x))
        w.write("]")
    else:
        # fall back to string representation
        _write(str(x), w)
