"""``jepsen report --plan`` — the offline strategy advisor.

Joins three evidence sources into ONE per-shape recommended-strategy
table — the artifact the ``JEPSEN_TPU_AUTO=1`` planner
(``parallel.planner``) seeds its live decision table from, built here
as read-only provenance:

  ledger   the decision ledger's dispatch/escalation/reshard/steal
           records (``obs.ledger``) — live traffic's shape×strategy
           cells with measured wall secs
  bench    ``bench_results/`` perf_ab JSONL — the recorded A/B
           verdicts per axis: closure (``xla/pallas/fori_secs``),
           dedupe (``sort/hash/hash-pallas/hash-packed_secs``),
           elastic (``static_secs`` vs ``steal_secs`` /
           ``reshard_secs``), plus the flip-rule verdict records
  gates    ``sparse_kernels.gate_coverage`` records riding the same
           bench JSONL — which kernel would run per layout, chip-free

The join is deliberately conservative: a recommendation only comes
from a ledger cell with at least ``JEPSEN_TPU_LEDGER_FLOOR`` records
— a shape below the floor says **insufficient evidence**, never a
guess (wrong-plan recovery is free, but an unevidenced plan is still
noise). Bench evidence upgrades or contests confidence; it never
substitutes for live samples, because the bench shapes are synthetic
adversarial histories, not the operator's traffic.

Determinism: every iteration is sorted, floats are rounded, nothing
timestamps the output — the same inputs render byte-identical tables
(pinned by tests/test_ledger.py on a committed fixture).

Import-safe: no JAX — ``jepsen report`` runs on a box whose device
runtime may be wedged.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from jepsen_tpu.obs import ledger as _ledger

#: strategy-axis vocabulary of the perf_ab ``{variant}_secs`` keys
CLOSURE_VARIANTS = ("xla", "pallas", "fori")
DEDUPE_VARIANTS = ("sort", "hash", "hash-pallas", "hash-packed")
ELASTIC_ARMS = ("steal", "reshard")

PLAN_VERSION = 1


# --------------------------------------------------- bench evidence


def load_bench_dir(path: str) -> List[dict]:
    """Every decodable JSONL record under ``path`` (files sorted,
    torn lines skipped — the ``load_records`` posture)."""
    out: List[dict] = []
    if not path or not os.path.isdir(path):
        return out
    for name in sorted(os.listdir(path)):
        if not name.endswith(".jsonl"):
            continue
        try:
            with open(os.path.join(path, name)) as fh:
                for ln in fh:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            continue
    return out


def _axis_wins(records: List[dict],
               variants: Tuple[str, ...]) -> Dict[str, dict]:
    """Per-variant win tally for one strategy axis over the bench
    lines that measured it: ``{variant: {"wins": n, "shapes": [...]}}``
    where a win is the strictly smallest ``{variant}_secs`` on a
    shape that measured >= 2 variants of the axis."""
    tally: Dict[str, dict] = {}
    for rec in records:
        shape = rec.get("shape")
        if not isinstance(shape, str):
            continue
        timed = [(v, rec[f"{v}_secs"]) for v in variants
                 if isinstance(rec.get(f"{v}_secs"), (int, float))]
        if len(timed) < 2:
            continue
        winner = min(timed, key=lambda t: (t[1], t[0]))[0]
        cell = tally.setdefault(winner, {"wins": 0, "shapes": []})
        cell["wins"] += 1
        cell["shapes"].append(shape)
    for cell in tally.values():
        cell["shapes"] = sorted(set(cell["shapes"]))
    return tally


def _elastic_verdicts(records: List[dict]) -> Dict[str, dict]:
    """steal/reshard vs the static placement: per arm, on how many
    shapes the arm beat ``static_secs``."""
    out: Dict[str, dict] = {}
    for arm in ELASTIC_ARMS:
        measured = wins = 0
        for rec in records:
            a, s = rec.get(f"{arm}_secs"), rec.get("static_secs")
            if isinstance(a, (int, float)) \
                    and isinstance(s, (int, float)):
                measured += 1
                if a < s:
                    wins += 1
        if measured:
            out[arm] = {"measured": measured, "wins": wins}
    return out


def bench_evidence(records: List[dict]) -> dict:
    """The bench half of the join: per-axis win tallies, elastic arm
    verdicts, the recorded flip-rule verdict lines, and the
    gate_coverage records."""
    return {
        "closure": _axis_wins(records, CLOSURE_VARIANTS),
        "dedupe": _axis_wins(records, DEDUPE_VARIANTS),
        "elastic": _elastic_verdicts(records),
        "verdicts": sorted(
            (r for r in records if "verdict" in r and "backend" in r),
            key=lambda r: json.dumps(r, sort_keys=True)),
        "gates": sorted(
            (r for r in records if "gate_coverage" in r),
            key=lambda r: str(r.get("shape"))),
    }


def _axis_best(tally: Dict[str, dict]) -> Optional[str]:
    """The axis winner by total bench wins (ties break to the
    lexicographically-first variant — deterministic, and the tie
    says the evidence doesn't separate them anyway)."""
    if not tally:
        return None
    return max(sorted(tally), key=lambda v: tally[v]["wins"])


# -------------------------------------------------- the plan table


def _shape_group(rec: dict) -> Optional[str]:
    """The plan-table row a ledger record belongs to: engine + event
    family + slot width. Capacity tier N is folded INTO the strategy
    comparison (a strategy that avoids escalation shows up as fewer
    high-tier cells), not the row key — the planner picks per
    (family, C) bucket, which is what ``bucket_key`` quantizes."""
    shape = rec.get("shape")
    if not isinstance(shape, dict):
        return None
    parts = [f"engine={rec.get('engine', '?')}"]
    for k in ("family", "C"):
        if shape.get(k) is not None:
            parts.append(f"{k}={shape[k]}")
    return ",".join(parts)


def build_plan(ledger_records: List[dict], bench_records: List[dict],
               floor: Optional[int] = None,
               auto_table: Optional[dict] = None) -> dict:
    """The joined plan document (machine-readable; ``render_plan``
    makes it human-readable). Per shape group, the recommended
    strategy is the strategy vector whose ledger cell has the lowest
    mean secs AMONG cells meeting the sample floor; a group with no
    cell at the floor recommends nothing ("insufficient evidence").

    ``kind=plan`` records (the live planner's own decisions,
    ``parallel.planner``) feed the FOURTH confidence tier: when the
    newest online decision for a group picked the vector this join
    recommends, confidence says ``auto-online`` — the fleet's live
    table already converged there, which outranks what the synthetic
    bench shapes prefer. ``auto_table`` (a durable ``plan_table.json``
    document, ``planner.load_table``) rides along verbatim under
    ``"auto"`` so one report shows the offline join AND the live
    table."""
    floor = _ledger.sample_floor(floor)
    bench = bench_evidence(bench_records)
    groups: Dict[str, Dict[str, dict]] = {}
    auto_latest: Dict[str, dict] = {}
    for rec in ledger_records:
        if rec.get("kind") == "plan":
            g = _shape_group(rec)
            if g is not None:
                auto_latest[g] = rec   # newest wins (segment order)
            continue
        if rec.get("kind") not in ("dispatch", "escalation"):
            continue
        g = _shape_group(rec)
        if g is None:
            continue
        sig = _ledger.strategy_sig(rec.get("strategy"))
        cell = groups.setdefault(g, {}).setdefault(
            sig, {"count": 0, "total_secs": 0.0, "keys": 0,
                  "strategy": rec.get("strategy") or {}})
        cell["count"] += 1
        if isinstance(rec.get("secs"), (int, float)):
            cell["total_secs"] += float(rec["secs"])
        if isinstance(rec.get("keys"), int):
            cell["keys"] += rec["keys"]
    bench_dedupe = _axis_best(bench["dedupe"])
    bench_closure = _axis_best(bench["closure"])
    shapes: List[dict] = []
    for g in sorted(groups):
        cells = groups[g]
        rows = []
        for sig in sorted(cells):
            c = cells[sig]
            rows.append({"strategy": sig, "count": c["count"],
                         "keys": c["keys"],
                         "mean_secs": round(
                             c["total_secs"] / max(1, c["count"]), 6),
                         "detail": c["strategy"]})
        evidence = sum(r["count"] for r in rows)
        eligible = [r for r in rows if r["count"] >= floor]
        entry = {"shape": g, "evidence": evidence, "cells": rows}
        if not eligible:
            best = max(rows, key=lambda r: r["count"])
            entry["recommend"] = None
            entry["confidence"] = (
                f"insufficient evidence (best cell n={best['count']} "
                f"< floor {floor})")
        else:
            win = min(eligible,
                      key=lambda r: (r["mean_secs"], r["strategy"]))
            entry["recommend"] = win["strategy"]
            entry["mean_secs"] = win["mean_secs"]
            detail = win["detail"] or {}
            conf = "ledger-only"
            led_dedupe = detail.get("dedupe")
            if bench_dedupe is not None and led_dedupe is not None:
                # bench dedupe variants fold the kernel in
                # (hash-pallas/hash-packed); compare on the base axis
                conf = ("bench-agrees"
                        if str(bench_dedupe).startswith(
                            str(led_dedupe))
                        else f"bench-prefers-{bench_dedupe}")
            pr = auto_latest.get(g)
            if pr is not None and pr.get("source") == "online":
                # lazy + import-safe: parallel.planner holds no JAX;
                # its arm mapping is the one vocabulary both tables
                # speak, so agreement is checked in it
                from jepsen_tpu.parallel import planner as _planner_mod
                led_arm = _planner_mod._arm_from_detail(detail)
                vec = pr.get("strategy") or {}
                if vec and all(led_arm.get(k) == v
                               for k, v in vec.items()):
                    conf = "auto-online"
            entry["confidence"] = conf
        shapes.append(entry)
    doc = {"version": PLAN_VERSION, "floor": floor,
           "shapes": shapes,
           "bench": {"closure": bench["closure"],
                     "dedupe": bench["dedupe"],
                     "elastic": bench["elastic"],
                     "closure_best": bench_closure,
                     "dedupe_best": bench_dedupe,
                     "verdicts": bench["verdicts"]},
           "gates": bench["gates"],
           "ledger_records": len(ledger_records)}
    if auto_table is not None:
        doc["auto"] = auto_table
    return doc


def _fmt_secs(v) -> str:
    return "-" if v is None else f"{float(v):.6g}"


def render_plan(plan: dict) -> str:
    """The plan document as the operator table ``jepsen report
    --plan`` prints."""
    lines = ["# Strategy plan (decision ledger + perf_ab + "
             "gate_coverage)", ""]
    lines.append(f"ledger records: {plan.get('ledger_records', 0)}   "
                 f"shape groups: {len(plan.get('shapes') or [])}   "
                 f"sample floor: {plan.get('floor')}")
    lines.append("")
    lines.append("## Per-shape recommendations")
    lines.append("")
    shapes = plan.get("shapes") or []
    if not shapes:
        lines.append("(no dispatch evidence in the ledger — run with "
                     "JEPSEN_TPU_LEDGER=1 to record some)")
    for s in shapes:
        lines.append(f"shape {s['shape']}  (n={s['evidence']})")
        if s.get("recommend") is None:
            lines.append(f"    {s['confidence']}")
        else:
            lines.append(f"    recommend: {s['recommend']}")
            lines.append(f"    mean_secs: "
                         f"{_fmt_secs(s.get('mean_secs'))}   "
                         f"confidence: {s['confidence']}")
        for c in s.get("cells") or []:
            lines.append(f"      cell n={c['count']:<4} "
                         f"mean={_fmt_secs(c['mean_secs']):<10} "
                         f"{c['strategy']}")
        lines.append("")
    bench = plan.get("bench") or {}
    lines.append("## Bench axis verdicts (perf_ab)")
    lines.append("")
    any_bench = False
    for axis in ("closure", "dedupe"):
        tally = bench.get(axis) or {}
        if tally:
            any_bench = True
            best = bench.get(f"{axis}_best")
            parts = [f"{v}:{tally[v]['wins']}" for v in sorted(tally)]
            lines.append(f"{axis}: best={best}  wins " +
                         "  ".join(parts))
    for arm, v in sorted((bench.get("elastic") or {}).items()):
        any_bench = True
        lines.append(f"{arm}: wins {v['wins']}/{v['measured']} "
                     f"measured shapes vs static")
    for v in bench.get("verdicts") or []:
        any_bench = True
        lines.append(f"recorded verdict [{v.get('backend')}]: "
                     f"{v.get('verdict')} ratios={v.get('ratios')}")
    if not any_bench:
        lines.append("(no perf_ab evidence — point --bench-dir at a "
                     "bench_results/ directory)")
    lines.append("")
    gates = plan.get("gates") or []
    if gates:
        lines.append("## Kernel gates (gate_coverage)")
        lines.append("")
        for g in gates:
            gc = g.get("gate_coverage") or {}
            wr = gc.get("would_run") or {}
            lines.append(f"{g.get('shape')}: C={gc.get('C')} "
                         f"N={gc.get('capacity')} "
                         f"packable={gc.get('packable')} "
                         f"unpacked->{wr.get('unpacked')} "
                         f"packed->{wr.get('packed')}")
        lines.append("")
    auto = plan.get("auto")
    if auto is not None:
        lines.append("## Auto planner live table (JEPSEN_TPU_AUTO)")
        lines.append("")
        agroups = auto.get("groups") or {}
        if not agroups:
            lines.append("(plan_table.json present but empty)")
        for g in sorted(agroups):
            row = agroups[g]
            lines.append(f"group {g}  "
                         f"(decisions={row.get('decisions', 0)})")
            cells = row.get("cells") or {}
            for sig in sorted(cells):
                c = cells[sig]
                lines.append(
                    f"      cell n={c.get('n', 0):<4} "
                    f"live={c.get('n_live', 0):<4} "
                    f"ewma={_fmt_secs(c.get('ewma', c.get('ewma_secs')))}"
                    f"{' seeded' if c.get('seeded') else '':<8} "
                    f"{sig}")
        lines.append("")
    return "\n".join(lines) + "\n"
