"""Metrics registry: counters / gauges / histograms under stable
dotted names.

This absorbs the one-off telemetry the checker grew ad hoc —
``pipeline_stats`` dicts, encode-cache hit/miss counters,
``configs_stepped``, capacity-escalation retries, overflow
re-dispatches — so every layer increments the same named metric and
every consumer (bench split lines, the end-of-run summary table, the
JSONL export) reads one source of truth. The naming scheme is
``<layer>.<thing>`` (docs/observability.md lists every name in
circulation); names are cheap to mint but MUST stay stable once a
bench line or test reads them.

Always on: a counter increment is a lock + integer add — unlike spans
there is no trace-time cost worth gating, and the end-of-run summary
is most useful precisely when nobody thought to enable tracing.
``snapshot()`` / ``delta()`` give consumers a consistent point-in-time
read; tests reset the default registry between cases via ``reset()``.

Thread-safety: one lock per metric (pipeline pool threads bump cache
counters concurrently); registry creation is double-checked under a
registry lock so two threads minting the same name get one object.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional

# The fixed histogram bucket ladder: log-spaced upper bounds (seconds
# for the latency histograms, but unitless here), 100µs .. 60s, with
# +Inf implied by ``count``. Fixed and shared so (a) Prometheus
# exposition (obs/httpd.py) can render a proper ``histogram`` type with
# cumulative ``le`` buckets, and (b) "p99 delta latency" SLO questions
# are answerable from any snapshot without per-metric configuration.
# Values outside the ladder still land in count/total/min/max — the
# ladder only loses resolution, never observations.
BUCKET_LADDER = (0.0001, 0.00025, 0.001, 0.0025, 0.01, 0.025,
                 0.1, 0.25, 1.0, 2.5, 10.0, 60.0)


class Counter:
    """Monotonic count (events, retries, cache hits)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """A level (in-flight depth, bytes resident) with a high-water
    mark — the max is what the summary table reports for depths.
    ``nops`` counts level movements: it is how ``Registry.delta``
    tells "this gauge moved during the window and returned to the
    same level" apart from "nothing happened" (a value/max-only
    snapshot cannot)."""

    __slots__ = ("name", "value", "max", "nops", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.max = 0
        self.nops = 0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self.value = v
            self.nops += 1
            if v > self.max:
                self.max = v

    def inc(self, n=1):
        with self._lock:
            self.value += n
            self.nops += 1
            if self.value > self.max:
                self.max = self.value

    def dec(self, n=1):
        with self._lock:
            self.value -= n
            self.nops += 1

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value, "max": self.max,
                "nops": self.nops}


class Histogram:
    """Streaming aggregate of observations (seconds, sizes):
    count/total/min/max plus a fixed log-spaced bucket ladder
    (:data:`BUCKET_LADDER`). The scalar fields keep their historical
    meaning (the summary table and bench split lines read them
    unchanged); ``buckets`` is additive — cumulative ``[le, count]``
    pairs in the snapshot, the shape Prometheus exposition and
    quantile estimation need."""

    __slots__ = ("name", "count", "total", "vmin", "vmax", "_buckets",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None
        self._buckets = [0] * len(BUCKET_LADDER)
        self._lock = threading.Lock()

    def observe(self, v: float):
        with self._lock:
            self.count += 1
            self.total += v
            if self.vmin is None or v < self.vmin:
                self.vmin = v
            if self.vmax is None or v > self.vmax:
                self.vmax = v
            i = bisect_left(BUCKET_LADDER, v)
            if i < len(BUCKET_LADDER):
                self._buckets[i] += 1

    def snapshot(self) -> dict:
        with self._lock:
            raw = list(self._buckets)
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        cum: List[list] = []
        running = 0
        for le, n in zip(BUCKET_LADDER, raw):
            running += n
            cum.append([le, running])
        return {"type": "histogram", "count": count,
                "total": round(total, 6),
                "min": vmin, "max": vmax,
                "mean": round(total / count, 6) if count else None,
                "buckets": cum}


def labeled(name: str, **labels) -> str:
    """A registry name carrying label pairs: ``base[k=v,...]``. The
    registry itself treats the whole string as one opaque name (every
    label set is its own metric object); the Prometheus renderer
    (``obs.httpd``) splits the suffix back into real exposition labels
    — ``serve.ack_secs[tenant=alice]`` renders as
    ``jepsen_serve_ack_secs_bucket{tenant="alice",le=...}``. Keep
    label VALUES inside ``[A-Za-z0-9_.:-]`` (tenant names, backend
    ids); the renderer escapes anything else but dashboards read
    cleaner without the escapes."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}[{inner}]"


def split_labels(name: str):
    """``base[k=v,...]`` -> (base, {k: v}); a plain name -> (name, {})."""
    if not name.endswith("]"):
        return name, {}
    i = name.find("[")
    if i < 0:
        return name, {}
    out = {}
    for pair in name[i + 1:-1].split(","):
        k, eq, v = pair.partition("=")
        if eq:
            out[k] = v
    return name[:i], out


def hist_quantile(snap: dict, q: float) -> Optional[float]:
    """Approximate quantile from a histogram snapshot (or delta): the
    upper bound of the first cumulative bucket covering ``q`` of the
    observations — the Prometheus-style answer, without interpolation.
    Observations past the ladder answer with the streaming ``max``
    (exact only when the window owns it, i.e. ``max`` is not None)."""
    n = snap.get("count") or 0
    if not n:
        return None
    target = q * n
    for le, cumc in snap.get("buckets") or ():
        if cumc >= target:
            return le
    return snap.get("max")


class Registry:
    """Name -> metric, minted on first use. Type collisions raise: a
    name cannot be a counter in one layer and a gauge in another —
    that is exactly the drift this registry exists to end."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            ms = list(self._metrics.values())
        return {m.name: m.snapshot() for m in
                sorted(ms, key=lambda m: m.name)}

    def delta(self, before: Dict[str, dict],
              now: Optional[Dict[str, dict]] = None) -> Dict[str, dict]:
        """Type-aware diff against an earlier snapshot() — how the
        per-run export (and the bench) reports what THIS window moved
        without resetting global state mid-run. A metric with no
        activity in the window is omitted.

        counters: the value difference. histograms: count/total
        differences with the mean recomputed; min/max only when every
        observation is the window's own (no prior count) — a window
        slice of a streaming min/max is otherwise unknowable. gauges:
        included when the level moved (``nops`` advanced), reporting
        the current value; ``max`` carries the high-water only when
        this window raised it, else None — the window's own peak is
        not recoverable from level snapshots.

        Pass ``now`` (a snapshot captured by the caller) to diff two
        fixed points and reuse ``now`` as the next baseline — leaving
        no gap for concurrent increments to fall into."""
        if now is None:
            now = self.snapshot()
        out = {}
        for name, snap in now.items():
            prev = before.get(name)
            if snap["type"] == "counter":
                d = snap["value"] - (prev["value"] if prev else 0)
                if d:
                    out[name] = {"type": "counter", "value": d}
            elif snap["type"] == "gauge":
                pn = prev["nops"] if prev else 0
                if snap["nops"] != pn:
                    raised = prev is None or snap["max"] > prev["max"]
                    out[name] = {"type": "gauge", "value": snap["value"],
                                 "max": snap["max"] if raised else None,
                                 "nops": snap["nops"] - pn}
            else:
                pc = prev["count"] if prev else 0
                dc = snap["count"] - pc
                if dc:
                    dt = round(snap["total"]
                               - (prev["total"] if prev else 0.0), 6)
                    # buckets subtract pairwise: the difference of two
                    # cumulative ladders is the window's own cumulative
                    # ladder (same fixed bounds), so a per-run delta
                    # answers quantile questions exactly like a fresh
                    # registry would
                    pb = {le: c for le, c in
                          (prev.get("buckets") or ())} if prev else {}
                    db = [[le, c - pb.get(le, 0)]
                          for le, c in snap.get("buckets") or ()]
                    out[name] = {"type": "histogram", "count": dc,
                                 "total": dt,
                                 "min": snap["min"] if pc == 0 else None,
                                 "max": snap["max"] if pc == 0 else None,
                                 "mean": round(dt / dc, 6),
                                 "buckets": db}
        return out

    def reset(self):
        with self._lock:
            self._metrics.clear()


_default = Registry()


def registry() -> Registry:
    return _default


def counter(name: str) -> Counter:
    return _default.counter(name)


def gauge(name: str) -> Gauge:
    return _default.gauge(name)


def histogram(name: str) -> Histogram:
    return _default.histogram(name)
