"""SLO burn-rate tracking over the serve ack histogram
(``JEPSEN_TPU_SLO_ACK_SECS``).

The ``serve.ack_secs`` histogram already measures every producer
ack; what it cannot answer live is the SRE question "are we burning
error budget RIGHT NOW, and how fast?". This module derives the
classic two-window burn rates from histogram deltas:

    burn = (fraction of acks slower than the target in the window)
           / (1 - objective)

with the objective fixed at 99% (so budget = 1%): burn 1.0 means
"exactly consuming budget", 10 means "10x too fast — page". The
fast window (default 5 min) catches incidents, the slow window
(default 1 h) filters blips — the standard multi-window alert pair.

Sampling rides ``CheckerService.refresh_gauges()``, which the ops
httpd already calls before every render, so the gauges
(``serve.slo.ack_burn_rate[window=fast|slow]``) are point-in-time
fresh on /metrics with zero new threads. ``JEPSEN_TPU_SLO_BURN_MAX``
(default 0 = never) degrades /healthz readiness when the FAST window
burns past it — the load balancer then sheds before the slow window
confirms the incident.

Default off: with ``JEPSEN_TPU_SLO_ACK_SECS`` unset, no gauge is
minted, no check is added — /metrics and /healthz are byte-identical
to the pre-SLO service (parity-pinned).

Import-safe: no JAX (the obs contract).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from typing import List, Optional, Tuple

from jepsen_tpu import envflags
from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.obs.tracer import counter_sample

#: error budget complement: objective 99% of acks under the target
OBJECTIVE = 0.99

FAST_WINDOW_SECS = 300.0
SLOW_WINDOW_SECS = 3600.0


def resolve_target_secs(v: Optional[float] = None) -> Optional[float]:
    """The ack-latency SLO target (seconds). Unset/0 -> None: SLO
    tracking off, nothing minted."""
    if v is None:
        v = envflags.env_float("JEPSEN_TPU_SLO_ACK_SECS",
                               default=None, min_value=0.0,
                               what="ack SLO target (seconds)")
    if not v:
        return None
    return float(v)


def resolve_burn_max(v: Optional[float] = None) -> float:
    """The fast-window burn rate past which /healthz degrades
    (``JEPSEN_TPU_SLO_BURN_MAX``); 0 (the default) = never degrade —
    gauges only."""
    if v is not None:
        return float(v)
    return envflags.env_float("JEPSEN_TPU_SLO_BURN_MAX", default=0.0,
                              min_value=0.0,
                              what="burn-rate degrade threshold")


def _good_count(snap: dict, target: float) -> int:
    """Observations at or under the target, from the cumulative
    bucket ladder: the largest ``le <= target`` answers (targets
    should sit on a :data:`~jepsen_tpu.obs.metrics.BUCKET_LADDER`
    bound; an off-ladder target conservatively rounds DOWN, counting
    borderline acks as bad)."""
    i = bisect_right(_metrics.BUCKET_LADDER, target)
    if i == 0:
        return 0
    buckets = snap.get("buckets") or []
    want = _metrics.BUCKET_LADDER[i - 1]
    for le, cum in buckets:
        if le == want:
            return int(cum)
    return 0


class BurnRateTracker:
    """Two-window burn rates from timestamped histogram snapshots.
    ``sample()`` is cheap (one snapshot + ring append) and safe to
    call from every /metrics render; windows and the clock are
    injectable for tests."""

    def __init__(self, hist_name: str = "serve.ack_secs",
                 target_secs: Optional[float] = None,
                 burn_max: Optional[float] = None,
                 fast_window: float = FAST_WINDOW_SECS,
                 slow_window: float = SLOW_WINDOW_SECS,
                 clock=time.monotonic):
        self.hist_name = hist_name
        self.target = resolve_target_secs(target_secs)
        self.burn_max = resolve_burn_max(burn_max)
        self.fast_window = fast_window
        self.slow_window = slow_window
        self._clock = clock
        self._lock = threading.Lock()
        #: (t, total_count, bad_count) samples, oldest first
        self._ring: List[Tuple[float, int, int]] = []
        self._last: Optional[dict] = None

    @property
    def armed(self) -> bool:
        return self.target is not None

    def _window_burn(self, window: float, now: float
                     ) -> Optional[float]:
        """Burn over [now - window, now]: bad/total of the window's
        own observations over the budget. No traffic in the window
        (or no second sample yet) -> 0.0 — an idle service burns
        nothing."""
        base = None
        for t, count, bad in self._ring:
            if t >= now - window:
                base = (count, bad)
                break
        if base is None or not self._ring:
            return 0.0
        count, bad = self._ring[-1][1], self._ring[-1][2]
        d_count = count - base[0]
        d_bad = bad - base[1]
        if d_count <= 0:
            return 0.0
        return round((d_bad / d_count) / (1.0 - OBJECTIVE), 4)

    def sample(self, now: Optional[float] = None) -> Optional[dict]:
        """Take one snapshot, update the ring, publish the gauges +
        Perfetto counter tracks; returns ``{"fast": b, "slow": b}``
        (None when not armed)."""
        if not self.armed:
            return None
        if now is None:
            now = self._clock()
        snap = _metrics.histogram(self.hist_name).snapshot()
        count = int(snap.get("count") or 0)
        bad = count - _good_count(snap, self.target)
        with self._lock:
            self._ring.append((now, count, bad))
            # keep one sample older than the slow window as the
            # baseline; drop the rest
            cut = now - self.slow_window
            while len(self._ring) > 2 and self._ring[1][0] < cut:
                self._ring.pop(0)
            fast = self._window_burn(self.fast_window, now)
            slow = self._window_burn(self.slow_window, now)
            self._last = {"fast": fast, "slow": slow}
        _metrics.gauge(_metrics.labeled(
            "serve.slo.ack_burn_rate", window="fast")).set(fast)
        _metrics.gauge(_metrics.labeled(
            "serve.slo.ack_burn_rate", window="slow")).set(slow)
        counter_sample("serve.slo.ack_burn_rate/fast", fast)
        counter_sample("serve.slo.ack_burn_rate/slow", slow)
        return self._last

    def check(self) -> dict:
        """The /healthz check document: not-ok when the FAST window
        burns past ``burn_max`` (and a threshold is configured)."""
        with self._lock:
            last = dict(self._last or {"fast": 0.0, "slow": 0.0})
        ok = not (self.burn_max
                  and (last.get("fast") or 0.0) > self.burn_max)
        return {"ok": ok, "burn_fast": last.get("fast"),
                "burn_slow": last.get("slow"),
                "burn_max": self.burn_max,
                "target_secs": self.target}
