"""The decision ledger: durable, bounded, append-only evidence
records for every device-dispatch and strategy decision the engines
make (``JEPSEN_TPU_LEDGER``).

Why it exists (ROADMAP item 2): the engine now has ~6 orthogonal
strategy axes (dedupe sort|hash, fused|tiled|xla closure,
packed|unpacked, pipeline depth, steal, reshard rung) and all the
evidence for choosing between them per shape is ephemeral —
``search_stats`` blocks die with the result dict, registry snapshots
die with the process, the elastic cost model is in-memory only, and
the ``bench_results/`` perf_ab verdicts are never joined against live
traffic. The ledger makes that evidence durable and queryable: one
compact JSONL record per dispatch (and per escalation / reshard /
steal / publish decision), carrying

    shape       the padded-program fingerprint — event family, N
                (capacity), R (padded events), C (padded slots),
                capacity tier, pack layout
    strategy    the vector that actually ran — dedupe, closure kernel,
                pack, pipeline depth, steal, reshard rung, probe_limit
    secs        wall time between the SAME ``perf_counter`` reads the
                dispatch spans use (bench splits and ledger rows
                cannot disagree)
    stats       a summarized search_stats digest when
                JEPSEN_TPU_SEARCH_STATS is armed (load-factor peak,
                delta-split ratio, pad waste, probe p99)
    outcome     verdict class counts, overflow/escalation trail,
                fallback notes

Format (the ``DeltaWAL`` precedent, simplified for evidence):
append-only JSONL segments ``ledger.<nnnnnnnn>.jsonl`` under the
ledger dir, active segment = highest index. Rotation starts a NEW
higher-indexed file once the active one crosses
``JEPSEN_TPU_LEDGER_SEGMENT_BYTES`` — no renames, so a crash can
never corrupt a sealed segment — and retention unlinks the
lowest-indexed segments past ``JEPSEN_TPU_LEDGER_SEGMENTS`` (counted
``obs.ledger.drops``): the ledger's disk footprint is bounded by
construction, which is what ``tools/soak.py --smoke`` asserts.

Durability posture — evidence-grade, not ack-grade: every append is
flushed (a crash loses at most the OS write-back tail), fsync happens
at rotation and close. Unlike the WAL, NOTHING acknowledged depends
on a ledger record, so a torn or undecodable line anywhere — not
just the tail — is skipped and counted (``obs.ledger.corrupt_lines``)
instead of raising: a ledger hole costs evidence, never correctness.
The torn active tail is truncated before the first append of a
process (the ``_repair_tail`` contract) so restart appends never
concatenate onto partial bytes.

Default off: with ``JEPSEN_TPU_LEDGER`` unset, :func:`active` answers
None, no ``obs.ledger.*`` metric is ever minted, no file is touched,
and results / bench lines / /metrics / /status / trace files are
byte-identical to the pre-ledger tree (parity-pinned by
tests/test_ledger.py).

Consumers: the ``/ledger`` ops endpoint (``obs.httpd``) renders
:func:`ledger_doc` — newest-wins per shape×strategy cell;
``obs.export_run`` copies the records into the store run dir as
``ledger.jsonl``; ``jepsen report --plan`` (``obs.advisor``) joins
them with perf_ab JSONL + ``gate_coverage`` into the recommended-
strategy table the future ``JEPSEN_TPU_AUTO=1`` planner loads.

Import-safe: no JAX, no engine imports — same contract as the rest
of ``obs``. Never call :func:`record` inside jit-traced code
(``purity-obs-in-trace``).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from jepsen_tpu import envflags
from jepsen_tpu.obs import metrics as _metrics

_log = logging.getLogger(__name__)

LEDGER_VERSION = 1

#: default destination for ``JEPSEN_TPU_LEDGER=1`` — next to the
#: serve WAL's ``store/serve_wal`` convention
DEFAULT_DIR = os.path.join("store", "ledger")
DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_SEGMENTS = 8
DEFAULT_FLOOR = 3

_SEG_RE = re.compile(r"^ledger\.(\d{8})\.jsonl$")


def resolve_ledger_dir() -> Optional[str]:
    """The ledger directory from ``JEPSEN_TPU_LEDGER``: unset/"0" ->
    None (off), "1" -> :data:`DEFAULT_DIR`, anything else -> that
    path. Validation (whitespace-only raises) is ``env_path``'s."""
    dest = envflags.env_path("JEPSEN_TPU_LEDGER",
                             what="ledger directory")
    if dest is None:
        return None
    return dest or DEFAULT_DIR


def plan_table_path(root: str) -> str:
    """Where the ``JEPSEN_TPU_AUTO`` planner persists its decision
    table — beside the ledger segments, since the table is derived
    evidence over them (``parallel.planner``). The dir-layout
    knowledge lives here with the segments' own."""
    return os.path.join(root, "plan_table.json")


def resolve_segment_bytes(v: Optional[int] = None) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_LEDGER_SEGMENT_BYTES",
                            default=DEFAULT_SEGMENT_BYTES,
                            min_value=4096,
                            what="ledger segment size (bytes)")


def resolve_max_segments(v: Optional[int] = None) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_LEDGER_SEGMENTS",
                            default=DEFAULT_SEGMENTS, min_value=2,
                            what="retained ledger segment count")


def sample_floor(v: Optional[int] = None) -> int:
    """The advisor's per-cell evidence floor
    (``JEPSEN_TPU_LEDGER_FLOOR``): a shape cell with fewer ledger
    records than this says "insufficient evidence" instead of
    guessing."""
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_LEDGER_FLOOR",
                            default=DEFAULT_FLOOR, min_value=1,
                            what="advisor sample floor")


# ------------------------------------------------------------ writer


class DecisionLedger:
    """One process's append handle on a ledger directory (module
    docstring for the format/durability contract). Thread-safe: the
    engines append from dispatch threads, serve from its worker."""

    def __init__(self, root: str,
                 segment_bytes: Optional[int] = None,
                 max_segments: Optional[int] = None):
        self.root = root
        self.segment_bytes = resolve_segment_bytes(segment_bytes)
        self.max_segments = resolve_max_segments(max_segments)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._n = 0
        existing = _segment_indices(root)
        self._idx = existing[-1] if existing else 1
        path = self._path(self._idx)
        if os.path.exists(path):
            self._repair_tail(path)

    def _path(self, idx: int) -> str:
        return os.path.join(self.root, f"ledger.{idx:08d}.jsonl")

    @staticmethod
    def _repair_tail(path: str) -> None:
        """Truncate a torn (newline-less) trailing line before the
        first append of this process — appending after partial bytes
        would corrupt the NEXT record too (the WAL ``_repair_tail``
        contract). The lost line was never read by anything: ledger
        records are evidence, not acknowledgements."""
        try:
            with open(path, "rb+") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) == b"\n":
                    return
                fh.seek(0)
                data = fh.read()
                cut = data.rfind(b"\n")
                fh.truncate(cut + 1 if cut >= 0 else 0)
            _metrics.counter("obs.ledger.corrupt_lines").inc()
            _log.warning("ledger %s: truncated a torn trailing line "
                         "before appending", path)
        except OSError as err:
            _log.warning("ledger %s: could not repair tail (%r)",
                         path, err)

    # AUDITED I/O-under-lock: the buffered write + flush under the
    # ledger lock is what keeps two dispatch threads' records from
    # interleaving bytes; fsync only happens at rotation/close, so
    # the hot-path cost under the lock is one buffered write.
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def record(self, kind: str, **fields) -> None:
        """Append one evidence record. Never raises: an I/O failure
        costs this record (counted ``obs.ledger.drops``), never the
        dispatch that was minting it."""
        try:
            with self._lock:
                self._n += 1
                rec = {"v": LEDGER_VERSION,
                       "t": round(time.time(), 6), "n": self._n,
                       "kind": kind}
                # records stay compact: an absent field is absent,
                # not null (the export "absent, not empty" posture)
                rec.update({k: v for k, v in fields.items()
                            if v is not None})
                line = json.dumps(rec, sort_keys=True, default=str)
                if self._fh is None:
                    self._fh = open(self._path(self._idx), "a")
                self._fh.write(line + "\n")
                self._fh.flush()
                if self._fh.tell() >= self.segment_bytes:
                    self._rotate_locked()
            _metrics.counter("obs.ledger.records").inc()
        except (OSError, ValueError) as err:
            _metrics.counter("obs.ledger.drops").inc()
            _log.warning("ledger: dropped a %s record (%r)", kind,
                         err)

    # AUDITED I/O-under-lock: rotation (seal-fsync + retention unlink)
    # runs under the ledger lock from `record` BY DESIGN — it is rare
    # (once per segment_bytes of evidence) and racing it against
    # appends would tear the segment boundary.
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def _rotate_locked(self) -> None:
        """Seal the active segment (fsync — a sealed segment is never
        written again) and start the next index; then enforce the
        retained-segment bound by unlinking the oldest."""
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.flush()
                os.fsync(fh.fileno())
            finally:
                fh.close()
        self._idx += 1
        _metrics.counter("obs.ledger.rotations").inc()
        for idx in _segment_indices(self.root)[:-(self.max_segments)]:
            try:
                os.unlink(self._path(idx))
                _metrics.counter("obs.ledger.drops").inc()
            except OSError:
                pass

    # AUDITED I/O-under-lock: the export/shutdown fsync serializes
    # against appends on purpose — syncing a handle mid-append would
    # observe a torn line.
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def sync(self) -> None:
        """fsync the active segment (export / shutdown path)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.flush()
                os.fsync(fh.fileno())
            finally:
                fh.close()


# ------------------------------------------------- process singleton

_active: Optional[DecisionLedger] = None
_resolved = False
_singleton_lock = threading.Lock()


def active() -> Optional[DecisionLedger]:
    """The process ledger, or None when ``JEPSEN_TPU_LEDGER`` is off.
    Resolved once per process (``reset()`` re-resolves — tests). A
    malformed flag value raises :class:`envflags.EnvFlagError` loudly
    at the first dispatch (the envflags contract); an unwritable
    destination logs and disables — evidence must never take down the
    engine."""
    global _active, _resolved
    if _resolved:
        return _active
    with _singleton_lock:
        if _resolved:
            return _active
        root = resolve_ledger_dir()
        if root is not None:
            try:
                _active = DecisionLedger(root)
            except OSError as err:
                _log.warning("ledger: cannot open %s (%r) — ledger "
                             "disabled for this process", root, err)
                _active = None
        _resolved = True
    return _active


def record(kind: str, **fields) -> None:
    """Module-level convenience: append to the active ledger, no-op
    when off. Hook sites that build non-trivial field dicts should
    guard on :func:`active` first so the off path stays one call +
    None check."""
    led = active()
    if led is not None:
        led.record(kind, **fields)


def reset() -> None:
    """Close and forget the process ledger so the next
    :func:`active` re-reads the environment (tests)."""
    global _active, _resolved
    with _singleton_lock:
        if _active is not None:
            _active.close()
        _active = None
        _resolved = False


# ------------------------------------------------------------ reader


def _segment_indices(root: str) -> List[int]:
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def segment_paths(root: str) -> List[str]:
    """Segment files in append order (ascending index)."""
    return [os.path.join(root, f"ledger.{i:08d}.jsonl")
            for i in _segment_indices(root)]


def read_records(root: str) -> Tuple[List[dict], int]:
    """Every decodable record in the ledger dir, in append order,
    plus the count of lines skipped as torn/undecodable. Skipping is
    the whole posture (module docstring): a hole costs evidence, so
    it is counted, never raised."""
    records: List[dict] = []
    corrupt = 0
    for path in segment_paths(root):
        try:
            with open(path) as fh:
                for ln in fh:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        corrupt += 1
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
                    else:
                        corrupt += 1
        except OSError:
            continue
    return records, corrupt


def size_bytes(root: str) -> int:
    total = 0
    for path in segment_paths(root):
        try:
            total += os.path.getsize(path)
        except OSError:
            pass
    return total


# ------------------------------------------------ digests and cells


def probe_p99(hist: Optional[dict]) -> Optional[str]:
    """The bucket label covering p99 of a search-stats probe-length
    histogram ({label: count}) — the hash-table health number the
    advisor ranks probe_limit evidence by."""
    if not hist:
        return None
    items = [(lab, int(n)) for lab, n in hist.items() if n]
    total = sum(n for _, n in items)
    if not total:
        return None
    running = 0
    for lab, n in items:
        running += n
        if running >= 0.99 * total:
            return lab
    return items[-1][0]


def stats_digest(stats_blocks: List[dict]) -> Optional[dict]:
    """Summarize the per-key search_stats blocks of one dispatch into
    the compact digest the ledger record carries: worst load factor,
    mean delta-split ratio, mean pad waste, aggregate probe p99.
    Reads the block fields defensively — an absent field is absent in
    the digest, never a guess."""
    if not stats_blocks:
        return None
    digest: dict = {}
    lf = [b.get("load-factor-peak") for b in stats_blocks
          if b.get("load-factor-peak") is not None]
    if lf:
        digest["load_factor_peak"] = round(max(float(v) for v in lf), 6)
    ds = [b.get("delta-split") for b in stats_blocks
          if b.get("delta-split") is not None]
    if ds:
        digest["delta_split"] = round(
            sum(float(v) for v in ds) / len(ds), 6)
    pw = [b.get("pad-waste") for b in stats_blocks
          if b.get("pad-waste") is not None]
    if pw:
        digest["pad_waste"] = round(
            sum(float(v) for v in pw) / len(pw), 6)
    agg: dict = {}
    for b in stats_blocks:
        for lab, n in (b.get("probe-hist") or {}).items():
            agg[lab] = agg.get(lab, 0) + int(n)
    p99 = probe_p99(agg)
    if p99 is not None:
        digest["probe_p99"] = p99
    return digest or None


def verdict_class(r: Optional[dict]) -> str:
    """A result dict's verdict as the ledger's small vocabulary:
    valid / invalid / unknown."""
    if r is None:
        return "unknown"
    v = r.get("valid?")
    if v is True:
        return "valid"
    if v is False:
        return "invalid"
    return "unknown"


def shape_sig(shape: Optional[dict]) -> str:
    """A shape fingerprint dict as the stable cell-key half: sorted
    ``k=v`` pairs, so two processes (or two PRs) render the same
    shape identically."""
    if not shape:
        return "-"
    return ",".join(f"{k}={shape[k]}" for k in sorted(shape))


def strategy_sig(strategy: Optional[dict]) -> str:
    if not strategy:
        return "-"
    return ",".join(f"{k}={strategy[k]}" for k in sorted(strategy))


def cell_key(rec: dict) -> str:
    """The shape×strategy aggregation cell a record lands in —
    ``<engine>/<kind> shape|strategy``."""
    return (f"{rec.get('engine', '?')}/{rec.get('kind', '?')} "
            f"{shape_sig(rec.get('shape'))}"
            f"|{strategy_sig(rec.get('strategy'))}")


def aggregate(records: List[dict]) -> Dict[str, dict]:
    """Newest-wins per shape×strategy cell: each cell keeps its
    newest record (by append time, then sequence) plus evidence count
    and total/mean secs — the /ledger document's body and the
    advisor's per-cell input."""
    cells: Dict[str, dict] = {}
    for rec in records:
        key = cell_key(rec)
        cell = cells.get(key)
        if cell is None:
            cell = cells[key] = {"count": 0, "total_secs": 0.0,
                                 "newest": rec}
        cell["count"] += 1
        secs = rec.get("secs")
        if isinstance(secs, (int, float)):
            cell["total_secs"] += float(secs)
        newest = cell["newest"]
        if ((rec.get("t") or 0, rec.get("n") or 0)
                >= (newest.get("t") or 0, newest.get("n") or 0)):
            cell["newest"] = rec
    for cell in cells.values():
        cell["total_secs"] = round(cell["total_secs"], 6)
        cell["mean_secs"] = round(cell["total_secs"]
                                  / max(1, cell["count"]), 6)
    return cells


def ledger_doc(root: Optional[str] = None) -> dict:
    """The ``/ledger`` endpoint document: header (dir, record/
    segment/corrupt counts, bytes) + the newest-wins cell table.
    Ledger off answers ``{"ledger": {"enabled": False}, "cells": {}}``
    — a valid, empty document, the /trace posture."""
    if root is None:
        led = active()
        if led is not None:
            led.sync()
            root = led.root
        else:
            root = resolve_ledger_dir()
    if root is None:
        return {"ledger": {"enabled": False}, "cells": {}}
    records, corrupt = read_records(root)
    return {"ledger": {"enabled": True, "dir": root,
                       "records": len(records),
                       "segments": len(segment_paths(root)),
                       "corrupt": corrupt,
                       "bytes": size_bytes(root)},
            "cells": aggregate(records)}
