"""``jepsen trace`` — merge a serve fleet's per-replica trace exports
into ONE Perfetto-openable Chrome-trace file, with one process track
per replica, aligned on the wall clock.

Why it exists (docs/observability.md "End-to-end delta tracing"): a
delta's causal chain can cross a replica boundary — the old owner
admits and fsyncs it, a rehome/adoption moves the key, and the new
owner thaws/applies it. Each replica's own export is a valid trace,
but the chain is only *readable* when both sides share one time axis
and distinct process tracks. Every export stamps its wall-clock epoch
(the ``trace_epoch`` metadata event / the ``/trace`` document's
``epoch_unix``); the merge shifts each replica's microsecond
timestamps by its epoch offset from the earliest one and re-homes its
``host``/``device`` pids onto a per-replica pid block, so Perfetto
renders ``<replica>/host`` and ``<replica>/device`` tracks side by
side and a migrated delta's ``delta_id``-tagged spans line up across
them.

Inputs, mixable:

* ``--addr HOST:PORT`` (repeatable) — a live replica's ops endpoint;
  fetches ``GET /trace`` (``obs.httpd.OpsServer.trace_doc``).
* ``--dir PATH`` (repeatable) — a scratch/WAL directory; scans for
  ``trace.json`` exports and ``flight_*.trace.json`` dumps (the chaos
  harness's postmortem evidence), one input per file.
* positional ``FILE`` arguments — individual trace files.

``--validate`` alone checks files against the trace schema (the same
invariants tests/test_obs.py pins on single-process exports) without
fetching or merging — the CI hook ``tools/ci.sh`` runs over
serve_smoke's export.

Import-safe: no JAX, stdlib only — the merge runs on a coordinator
or an operator laptop that never touches a device.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

#: pid block size per replica in the merged file: original pids
#: (1 = host, 2 = device) land at base + pid, so every replica's two
#: tracks stay distinct and recoverable (base // PID_STRIDE = replica)
PID_STRIDE = 10

_VALID_PH = {"X", "M", "C"}


def load_trace_doc(path: str) -> dict:
    """Normalize one trace file — the bare event array
    (``write_chrome_trace``), the flight-dump object form, or a
    ``/trace`` fetch — into ``{"traceEvents": [...], "trace": {...}}``
    with ``epoch_unix`` recovered from the ``trace_epoch`` metadata
    event when the wrapper does not carry it."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc, "trace": {}}
    doc.setdefault("trace", {})
    if doc["trace"].get("epoch_unix") is None:
        for e in doc.get("traceEvents") or ():
            if e.get("ph") == "M" and e.get("name") == "trace_epoch":
                doc["trace"]["epoch_unix"] = (e.get("args")
                                              or {}).get("unix")
                break
    return doc


def fetch_trace(addr: str, timeout: float = 10.0) -> dict:
    """One replica's live span export: ``GET http://addr/trace``."""
    import urllib.request
    with urllib.request.urlopen(f"http://{addr}/trace",
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def merge_traces(docs: Sequence[dict],
                 names: Optional[Sequence[str]] = None) -> dict:
    """Merge per-replica trace documents into one. Each input's pids
    move to a per-replica block (``PID_STRIDE``), its process names
    become ``<replica>/host`` etc., its X-event args gain
    ``"replica"`` (the chain queries key on it), and — when every
    input carries ``epoch_unix`` — its timestamps shift onto the
    earliest replica's axis. ``trace_epoch`` metadata events are
    dropped (the merged wrapper carries the base epoch instead)."""
    names = list(names) if names is not None else [
        (d.get("trace") or {}).get("replica") or f"replica-{i}"
        for i, d in enumerate(docs)]
    epochs = [(d.get("trace") or {}).get("epoch_unix") for d in docs]
    aligned = all(e is not None for e in epochs) and epochs
    base = min(epochs) if aligned else None
    out: List[dict] = []
    for i, d in enumerate(docs):
        pid_base = PID_STRIDE * (i + 1)
        shift_us = ((epochs[i] - base) * 1e6) if aligned else 0.0
        for e in d.get("traceEvents") or ():
            if e.get("ph") == "M" and e.get("name") == "trace_epoch":
                continue
            e2 = dict(e)
            e2["pid"] = pid_base + int(e2.get("pid", 1))
            if "ts" in e2:
                e2["ts"] = round(e2["ts"] + shift_us, 1)
            if e2.get("ph") == "M" \
                    and e2.get("name") == "process_name":
                e2["args"] = {"name": f"{names[i]}/"
                                      f"{(e.get('args') or {}).get('name', '?')}"}
            elif e2.get("ph") == "X":
                e2["args"] = dict(e2.get("args") or {})
                e2["args"]["replica"] = names[i]
            out.append(e2)
    return {"traceEvents": out,
            "trace": {"replicas": list(names),
                      "epoch_unix": base, "aligned": bool(aligned)}}


def delta_id_tracks(doc: dict) -> Dict[str, set]:
    """delta_id -> the set of replica tracks its spans appear on
    (replica names in a merged doc, pids otherwise). Both the
    single-delta ``delta_id`` tag (admit/wal/ingress legs) and the
    batched ``delta_ids`` list tag (apply/thaw legs) count — together
    they ARE the delta's causal chain."""
    out: Dict[str, set] = {}
    for e in doc.get("traceEvents") or ():
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        track = args.get("replica", e.get("pid"))
        ids = []
        if args.get("delta_id"):
            ids.append(args["delta_id"])
        ids.extend(args.get("delta_ids") or ())
        for did in ids:
            out.setdefault(str(did), set()).add(track)
    return out


def cross_replica_ids(doc: dict) -> List[str]:
    """The delta ids whose chains span more than one replica track —
    the migrated deltas a merged fleet trace exists to make
    readable."""
    return sorted(did for did, tracks in delta_id_tracks(doc).items()
                  if len(tracks) > 1)


def validate_trace(doc) -> List[str]:
    """Schema-check one trace document (array or object form);
    returns the list of violations (empty = valid). The invariants
    are the ones tests/test_obs.py pins on exports: known phase
    codes, named processes, non-negative clamped timestamps, span
    ids present, and parent ids that resolve within their own
    replica's span-id space."""
    errors: List[str] = []
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    sids: Dict[object, set] = {}
    parents: List[tuple] = []
    procs = 0
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            errors.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"event {i}: unknown phase {ph!r}")
            continue
        if ph == "M":
            if e.get("name") == "process_name":
                procs += 1
            continue
        if "pid" not in e or "tid" not in e:
            errors.append(f"event {i} ({e.get('name')!r}): missing "
                          f"pid/tid")
        args = e.get("args") or {}
        # group parent resolution by replica (merged docs) or pid
        # block — span ids are only unique per source tracer
        group = args.get("replica",
                         int(e.get("pid", 0)) // PID_STRIDE)
        if ph == "C":
            if "value" not in args:
                errors.append(f"event {i} ({e.get('name')!r}): "
                              f"counter sample without value")
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i} ({e.get('name')!r}): bad ts "
                          f"{ts!r}")
        if not isinstance(dur, (int, float)) or dur < 0:
            errors.append(f"event {i} ({e.get('name')!r}): bad dur "
                          f"{dur!r}")
        if "span_id" not in args:
            errors.append(f"event {i} ({e.get('name')!r}): span "
                          f"without span_id")
        else:
            sids.setdefault(group, set()).add(args["span_id"])
        if args.get("parent_id") is not None:
            parents.append((i, e.get("name"), group,
                            args["parent_id"]))
    if not procs:
        errors.append("no process_name metadata events")
    for i, name, group, pid_ in parents:
        if pid_ not in sids.get(group, ()):
            errors.append(f"event {i} ({name!r}): parent_id {pid_} "
                          f"does not resolve")
    return errors


def _scan_dir(d: str) -> List[str]:
    """Trace files under a scratch/WAL/run directory, recursively:
    run-dir exports, flag-path exports, and flight dumps."""
    pats = ("trace.json", "*.trace.json", "flight_*.trace.json")
    out: List[str] = []
    for root, _dirs, _files in os.walk(d):
        for p in pats:
            out.extend(glob.glob(os.path.join(root, p)))
    return sorted(set(out))


def trace_main(argv: Optional[Sequence[str]] = None) -> int:
    """``jepsen trace`` — exit 0 merged/valid, 1 nothing to merge or
    validation failed, 2 a replica was unreachable, 254 usage."""
    p = argparse.ArgumentParser(
        prog="jepsen trace",
        description="merge per-replica trace exports (live /trace "
                    "endpoints, run dirs, flight dumps) into one "
                    "Perfetto file with a process track per replica, "
                    "wall-clock aligned; or --validate trace files "
                    "against the export schema")
    p.add_argument("files", nargs="*", help="trace files to merge")
    p.add_argument("--addr", action="append", default=[],
                   metavar="HOST:PORT",
                   help="a live replica's ops endpoint (repeatable): "
                        "fetch its GET /trace export")
    p.add_argument("--dir", action="append", default=[],
                   help="scan a directory (chaos scratch dir, WAL "
                        "dir, store run dir) for trace.json / "
                        "flight_*.trace.json inputs (repeatable)")
    p.add_argument("--out", default="merged_trace.json",
                   help="merged output path (default "
                        "merged_trace.json)")
    p.add_argument("--validate", action="store_true",
                   help="validate-only: check every input against "
                        "the trace schema and write nothing (the CI "
                        "hook); plain merges validate the merged "
                        "output regardless")
    p.add_argument("--timeout", type=float, default=10.0)
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 254
    inputs: List[tuple] = []   # (name, doc)

    def _named(doc: dict, fallback: str) -> tuple:
        # a /trace-shaped wrapper knows its own replica name; path-
        # derived fallbacks are uniquified below
        return ((doc.get("trace") or {}).get("replica") or fallback,
                doc)

    for path in args.files:
        try:
            inputs.append(_named(load_trace_doc(path),
                                 os.path.basename(path)))
        except (OSError, ValueError) as err:
            print(f"jepsen trace: cannot read {path}: {err}",
                  file=sys.stderr)
            return 1
    for d in args.dir:
        for path in _scan_dir(d):
            try:
                inputs.append(_named(load_trace_doc(path),
                                     os.path.relpath(path, d)))
            except (OSError, ValueError) as err:
                print(f"jepsen trace: skipping unreadable {path}: "
                      f"{err}", file=sys.stderr)
    for addr in args.addr:
        try:
            doc = fetch_trace(addr, timeout=args.timeout)
        except (OSError, ValueError) as err:
            print(f"jepsen trace: {addr} unreachable: {err}",
                  file=sys.stderr)
            return 2
        inputs.append(_named(doc, addr))
    # two inputs may legally carry the same derived name (two chaos
    # scratch dirs each holding 'r0/trace.json', two files with one
    # basename): collapsing them onto one process track would merge
    # distinct span-id spaces (a dangling parent could falsely resolve
    # against the OTHER replica's ids) and hide genuinely cross-
    # replica chains — suffix repeats deterministically instead
    seen_names: Dict[str, int] = {}
    uniq: List[tuple] = []
    for name, doc in inputs:
        n = seen_names.get(name, 0)
        seen_names[name] = n + 1
        uniq.append((name if n == 0 else f"{name}#{n + 1}", doc))
    inputs = uniq
    if not inputs:
        print("jepsen trace: nothing to merge — pass FILEs, --addr, "
              "or --dir", file=sys.stderr)
        return 1
    if args.validate:
        bad = 0
        for name, doc in inputs:
            errs = validate_trace(doc)
            for e in errs[:20]:
                print(f"jepsen trace: {name}: {e}", file=sys.stderr)
            bad += len(errs)
        if bad:
            print(f"jepsen trace: {bad} schema violation(s) across "
                  f"{len(inputs)} input(s)", file=sys.stderr)
            return 1
        print(f"jepsen trace: {len(inputs)} input(s) valid")
        return 0
    merged = merge_traces([doc for _n, doc in inputs],
                          [n for n, _d in inputs])
    errs = validate_trace(merged)
    if errs:
        for e in errs[:20]:
            print(f"jepsen trace: merged: {e}", file=sys.stderr)
        print(f"jepsen trace: merged document failed its own schema "
              f"({len(errs)} violation(s)) — not writing {args.out}",
              file=sys.stderr)
        return 1
    d = os.path.dirname(args.out)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(merged, fh)
    cross = cross_replica_ids(merged)
    spans = sum(1 for e in merged["traceEvents"]
                if e.get("ph") == "X")
    print(f"jepsen trace: merged {len(inputs)} replica trace(s) -> "
          f"{args.out} ({spans} spans, "
          f"{'wall-clock aligned' if merged['trace']['aligned'] else 'UNALIGNED (an input lacks epoch_unix)'}"
          f"); {len(cross)} cross-replica delta chain(s)"
          + (f": {', '.join(cross[:5])}"
             + ("..." if len(cross) > 5 else "") if cross else ""))
    return 0


if __name__ == "__main__":
    sys.exit(trace_main())
