"""Unified telemetry for the checker pipeline: span tracing + metrics.

One subsystem, three pieces (each documented in its module;
docs/observability.md is the operator guide):

  tracer    ``span("name", key=...)`` context managers — nested,
            contextvar-propagated (incl. across the pipeline's worker
            pool via ``ctx_runner``), wall + process CPU time, gated by
            ``JEPSEN_TPU_TRACE`` and compiled to a no-op singleton when
            off. ``timer`` is the always-measuring variant whose
            recorded span and returned wall time are the same clock
            reads — bench split lines and trace spans cannot disagree.
  metrics   counters / gauges / histograms under stable dotted names
            (``pipeline.cache.hits``, ``engine.configs_stepped``, ...)
            — always on, the home for every one-off counter the
            checker used to carry in private dicts.
  export    Chrome trace-event JSON (opens in Perfetto, one track per
            host thread + one per device bucket), JSONL into the store
            run dir, an end-of-run summary table, the
            ``JEPSEN_TPU_JAX_PROFILE`` bridge that lines host spans up
            with ``jax.profiler`` TPU captures, and the flight
            recorder's crash dump (``flight_dump``).
  httpd     the live ops surface (import ``jepsen_tpu.obs.httpd``
            explicitly): ``/metrics`` Prometheus text + ``/healthz`` +
            ``/status`` + ``/ledger`` on a stdlib HTTP daemon thread
            behind ``jepsen serve --ops-port``, plus the ``jepsen
            status`` client.

Two sibling modules ride the same contract (import them explicitly —
they are consumers, not core): ``ledger`` (JEPSEN_TPU_LEDGER — the
durable per-dispatch decision ledger; ``advisor`` joins it with bench
evidence into ``jepsen report --plan``) and ``slo``
(JEPSEN_TPU_SLO_ACK_SECS — two-window ack burn-rate gauges over the
serve histograms).

Import-safe by construction: no JAX at import time, no device init —
engine modules import this at module scope and must survive a wedged
PJRT runtime (the same contract as envflags).

NEVER call ``obs.span(...)`` or registry methods inside jit-traced
code: the side effect fires at trace time, once, not per execution —
the ``purity-obs-in-trace`` lint rule enforces this mechanically.
"""

from jepsen_tpu.obs.export import (  # noqa: F401
    chrome_trace, drain_search_stats, drain_slow_deltas, export_run,
    flight_dump, flight_reset, jsonl_events, record_search_stats,
    record_slow_delta, search_stats_records, set_flight_dir,
    slow_delta_records, summary, write_chrome_trace, write_jsonl,
    write_search_stats, write_slow_deltas,
)
from jepsen_tpu.obs.metrics import (  # noqa: F401
    BUCKET_LADDER, Registry, counter, gauge, hist_quantile, histogram,
    labeled, registry, split_labels,
)
from jepsen_tpu.obs.tracer import (  # noqa: F401
    Span, Tracer, configure, counter_sample, ctx_runner, current_span,
    device_annotation, enabled, flight_active, jax_profile_dir,
    maybe_jax_profile, reset, span, timer, tracer,
)
