"""``jepsen report --search`` — render a run's device-search telemetry
(JEPSEN_TPU_SEARCH_STATS) into the operator table that makes ROADMAP
items 2 and 5 executable: which keys run the visited table hottest
(load factor -> table sizing for the tiled-VMEM work), which escalate
capacity (re-shard candidates), and which waste the most padded rows
(bucket-policy evidence).

Input: ``search_stats.jsonl`` in a store run dir — one stats block per
line, written by ``Store.save_telemetry`` / ``obs.export_run`` from the
records the engines emit as each search finishes. Streamed keys emit a
record per delta with lifetime stats; the report keeps the newest
(most-events) record per key.

Output: ``search_report.txt`` next to the input (and stdout) — a
summary header plus worst-keys tables. Pre-parse forwarded from
``cli.py`` exactly like lint/probe/status; exit 0 report written,
1 no stats found, 254 usage. Import-safe: no JAX.

``--plan`` rides the same entry point: the strategy advisor
(``obs.advisor``) joins the decision ledger (``<run_dir>/ledger.jsonl``
or ``--ledger-dir``) with perf_ab bench JSONL (``--bench-dir``,
default ``bench_results/``) into ``plan_report.txt`` + ``plan.json``
— the per-shape recommended-strategy table, sample-floored so thin
evidence says so instead of guessing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence


def load_records(path: str) -> List[dict]:
    out = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except ValueError:
                continue   # a torn line loses one record, not the report
    return out


def dedupe_records(records: List[dict]) -> List[dict]:
    """One record per key, newest (most events — a streamed key's
    lifetime grows monotonically) wins; keyless records are kept
    as-is under synthetic indices."""
    by_key = {}
    anon = []
    for i, r in enumerate(records):
        k = r.get("key")
        if k is None:
            anon.append(r)
            continue
        kk = json.dumps(k, sort_keys=True, default=str)
        prev = by_key.get(kk)
        if prev is None or (r.get("events") or 0) >= \
                (prev.get("events") or 0):
            by_key[kk] = r
    return list(by_key.values()) + anon


def _fmt(v, nd: int = 4) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def _key_of(r: dict) -> str:
    k = r.get("key")
    return "-" if k is None else str(k)


def device_skew(r: dict):
    """max/mean of the per-device load-factor peaks across the mesh
    (sharded records; falls back to the per-device frontier-width
    peaks) — 1.0 means perfectly balanced, higher means part of the
    mesh idles while one device's table runs hot: stealable skew the
    elastic scheduler (JEPSEN_TPU_STEAL) attacks. None for
    single-device records."""
    pd = r.get("per-device") or {}
    vals = pd.get("load-factor-peak") or pd.get("width-peak")
    if not vals or len(vals) < 2:
        return None
    vals = [float(v) for v in vals if v is not None]
    if not vals:
        return None
    mean = sum(vals) / len(vals)
    return round(max(vals) / mean, 4) if mean else None


def _worst_table(rows: List[dict], field: str, title: str,
                 limit: int = 10) -> List[str]:
    ranked = [r for r in rows if r.get(field) is not None
              and r.get(field)]
    ranked.sort(key=lambda r: r[field], reverse=True)
    if not ranked:
        return []
    lines = [f"## {title}", ""]
    lines.append(f"{'key':<20} {'engine':<9} {'events':>7} "
                 f"{'peak':>8} {'dev-skew':>9} {field:>18}")
    for r in ranked[:limit]:
        lines.append(
            f"{_key_of(r)[:20]:<20} {str(r.get('engine', '-')):<9} "
            f"{_fmt(r.get('events')):>7} "
            f"{_fmt(r.get('frontier-peak')):>8} "
            f"{_fmt(r.get('device-skew')):>9} "
            f"{_fmt(r.get(field)):>18}")
    lines.append("")
    return lines


def render_search_report(records: List[dict]) -> str:
    rows = [dict(r) for r in dedupe_records(records)]
    for r in rows:
        r["device-skew"] = device_skew(r)
    lines = ["# Search telemetry report (JEPSEN_TPU_SEARCH_STATS)", ""]
    n_events = sum(r.get("events") or 0 for r in rows)
    engines = {}
    for r in rows:
        engines[r.get("engine", "?")] = \
            engines.get(r.get("engine", "?"), 0) + 1
    lines.append(f"keys: {len(rows)}   events: {n_events}   "
                 f"engines: " + ", ".join(
                     f"{k}={v}" for k, v in sorted(engines.items())))
    peaks = [r.get("frontier-peak") or 0 for r in rows]
    lines.append(f"frontier peak: max={max(peaks, default=0)}   "
                 f"escalated keys: "
                 f"{sum(1 for r in rows if r.get('capacity-tier'))}")
    # aggregate probe histogram over every hash-dedupe key
    agg: dict = {}
    for r in rows:
        for lab, n in (r.get("probe-hist") or {}).items():
            agg[lab] = agg.get(lab, 0) + int(n)
    if agg:
        total = sum(agg.values()) or 1
        lines.append("probe lengths: " + "  ".join(
            f"{lab}:{n} ({100.0 * n / total:.1f}%)"
            for lab, n in agg.items() if n))
    lines.append("")
    lines.extend(_worst_table(rows, "load-factor-peak",
                              "Worst keys by visited-table load "
                              "factor"))
    lines.extend(_worst_table(rows, "capacity-tier",
                              "Worst keys by capacity escalations"))
    lines.extend(_worst_table(rows, "pad-waste",
                              "Worst keys by pad-row waste"))
    lines.extend(_worst_table(rows, "device-skew",
                              "Worst keys by per-device skew "
                              "(stealable imbalance)"))
    if len(lines) == 5 and not agg:   # header only: nothing ranked
        lines.append("(no key exceeded any threshold — no hash load, "
                     "no escalations, no pad waste)")
        lines.append("")
    return "\n".join(lines) + "\n"


# --------------------------------------------- slow-delta forensics


def _fmt_secs(v) -> str:
    if v is None:
        return "-"
    return f"{float(v):.4g}"


def render_slow_report(records: List[dict]) -> str:
    """The ``jepsen report --slow`` table
    (JEPSEN_TPU_SLOW_DELTA_SECS): every retained slow-delta record,
    worst first — which delta, on which key, how long, and WHERE the
    time went (the stage-by-stage breakdown) — plus the worst
    offender's full context (resilience notes, search-stats block).
    One read replaces the PR-12-style manual diagnosis of a wedged
    worker."""
    rows = sorted(records,
                  key=lambda r: r.get("total_secs") or 0.0,
                  reverse=True)
    lines = ["# Slow-delta forensics (JEPSEN_TPU_SLOW_DELTA_SECS)",
             ""]
    lines.append(f"records: {len(rows)}   worst: "
                 f"{_fmt_secs(rows[0].get('total_secs')) if rows else '-'}s")
    by_stage: dict = {}
    for r in rows:
        s = r.get("slowest_stage") or "?"
        by_stage[s] = by_stage.get(s, 0) + 1
    if by_stage:
        lines.append("dominant stages: " + "  ".join(
            f"{k}:{v}" for k, v in sorted(by_stage.items())))
    lines.append("")
    lines.append(f"{'delta_id':<18} {'key':<16} {'tenant':<10} "
                 f"{'seq':>5} {'total_s':>9} {'slowest':<12} "
                 f"bp/wal/queue/device/pub")
    for r in rows:
        st = r.get("stages") or {}
        breakdown = "/".join(
            _fmt_secs(st.get(k)) for k in
            ("backpressure", "wal", "queue", "device", "publish"))
        lines.append(
            f"{str(r.get('delta_id', '-'))[:18]:<18} "
            f"{str(r.get('key', '-'))[:16]:<16} "
            f"{str(r.get('tenant') or '-')[:10]:<10} "
            f"{r.get('seq', 0) or 0:>5} "
            f"{_fmt_secs(r.get('total_secs')):>9} "
            f"{str(r.get('slowest_stage', '-')):<12} {breakdown}")
    if rows:
        worst = rows[0]
        lines.append("")
        lines.append(f"## Worst offender: {worst.get('delta_id')} "
                     f"(key {worst.get('key')})")
        for field in ("verdict", "error", "resilience", "stats"):
            if worst.get(field) is not None:
                lines.append(f"{field}: {worst[field]}")
    lines.append("")
    return "\n".join(lines) + "\n"


def _load_report_input(run_dir: str, fname: str,
                       hint: str) -> Optional[List[dict]]:
    """Read ``fname``'s records from the run dir (``report_main``
    resolved it already) and report the usual failure modes (shared
    by --search and --slow)."""
    path = os.path.join(run_dir, fname)
    if not os.path.exists(path):
        print(f"jepsen report: {path} not found — {hint}",
              file=sys.stderr)
        return None
    records = load_records(path)
    if not records:
        print(f"jepsen report: {path} holds no records",
              file=sys.stderr)
        return None
    return records


def report_main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="jepsen report",
        description="render a stored run's telemetry reports: "
                    "--search renders search_stats.jsonl "
                    "(JEPSEN_TPU_SEARCH_STATS) into search_report.txt "
                    "— worst keys by visited-table load factor, "
                    "capacity escalations, and pad-row waste; --slow "
                    "renders slow_deltas.jsonl "
                    "(JEPSEN_TPU_SLOW_DELTA_SECS) into "
                    "slow_report.txt — every slow delta's stage "
                    "breakdown, worst first; --plan joins the "
                    "decision ledger (JEPSEN_TPU_LEDGER) with "
                    "perf_ab bench JSONL into plan_report.txt — the "
                    "per-shape recommended-strategy table")
    p.add_argument("--search", action="store_true",
                   help="render the device-search telemetry report")
    p.add_argument("--slow", action="store_true",
                   help="render the slow-delta forensics report")
    p.add_argument("--plan", action="store_true",
                   help="render the strategy-advisor plan table "
                        "(decision ledger + perf_ab + gate_coverage)")
    p.add_argument("--run-dir", default=None,
                   help="store run dir holding the report input "
                        "(default: the latest stored run)")
    p.add_argument("--ledger-dir", default=None,
                   help="read --plan's ledger evidence straight from "
                        "a JEPSEN_TPU_LEDGER segment dir instead of "
                        "the run dir's ledger.jsonl snapshot")
    p.add_argument("--bench-dir", default=None,
                   help="perf_ab JSONL dir for --plan's bench "
                        "evidence (default: bench_results/ when "
                        "present)")
    p.add_argument("--floor", type=int, default=None,
                   help="--plan's per-cell sample floor (default: "
                        "JEPSEN_TPU_LEDGER_FLOOR)")
    p.add_argument("--json", action="store_true",
                   help="with --plan: print the machine-readable "
                        "plan document (sorted keys — the same "
                        "schema plan.json stores) instead of the "
                        "operator table; exit codes unchanged")
    p.add_argument("--stdout-only", action="store_true",
                   help="print the report without writing the "
                        ".txt artifact")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 254
    if not (args.search or args.slow or args.plan):
        print("jepsen report: nothing to render — pass --search, "
              "--slow, and/or --plan", file=sys.stderr)
        return 254
    # resolve the run dir ONCE so --search + --slow in one call read
    # the same run even if a new run lands mid-render. --plan with an
    # explicit --ledger-dir is the one mode that can run without a
    # stored run at all (the fleet-debug posture: point it anywhere).
    run_dir = args.run_dir
    need_run_dir = args.search or args.slow \
        or (args.plan and args.ledger_dir is None)
    if run_dir is None and need_run_dir:
        from jepsen_tpu import store as jstore
        run_dir = jstore.latest()
        if run_dir is None:
            print("jepsen report: no stored runs and no --run-dir",
                  file=sys.stderr)
            return 1
    rc = 0
    if args.search:
        records = _load_report_input(
            run_dir, "search_stats.jsonl",
            "run with JEPSEN_TPU_SEARCH_STATS=1 so the engines "
            "record per-key search stats (docs/observability.md)")
        if records is None:
            rc = 1
        else:
            text = render_search_report(records)
            sys.stdout.write(text)
            if not args.stdout_only:
                out = os.path.join(run_dir, "search_report.txt")
                with open(out, "w") as fh:
                    fh.write(text)
                print(f"report written to {out}", file=sys.stderr)
    if args.slow:
        records = _load_report_input(
            run_dir, "slow_deltas.jsonl",
            "run with JEPSEN_TPU_SLOW_DELTA_SECS=<secs> so the serve "
            "worker records slow-delta forensics "
            "(docs/observability.md)")
        if records is None:
            rc = 1
        else:
            text = render_slow_report(records)
            sys.stdout.write(text)
            if not args.stdout_only:
                out = os.path.join(run_dir, "slow_report.txt")
                with open(out, "w") as fh:
                    fh.write(text)
                print(f"report written to {out}", file=sys.stderr)
    if args.plan:
        from jepsen_tpu.obs import advisor, ledger as _ledger
        if args.ledger_dir is not None:
            records, corrupt = _ledger.read_records(args.ledger_dir)
            if not records:
                print(f"jepsen report: no ledger records under "
                      f"{args.ledger_dir} — run with "
                      f"JEPSEN_TPU_LEDGER=1 so the engines record "
                      f"dispatch evidence (docs/observability.md)",
                      file=sys.stderr)
                records = None
            elif corrupt:
                print(f"jepsen report: skipped {corrupt} corrupt "
                      f"ledger line(s)", file=sys.stderr)
        else:
            records = _load_report_input(
                run_dir, "ledger.jsonl",
                "run with JEPSEN_TPU_LEDGER=1 so the run dir "
                "snapshots dispatch evidence, or pass --ledger-dir "
                "(docs/observability.md)")
        if records is None:
            rc = 1
        else:
            bench_dir = args.bench_dir
            if bench_dir is None and os.path.isdir("bench_results"):
                bench_dir = "bench_results"
            bench = (advisor.load_bench_dir(bench_dir)
                     if bench_dir else [])
            # the live auto-planner table (JEPSEN_TPU_AUTO) rides the
            # report when its durable file sits beside the ledger
            # segments being read — one view over both evidence tiers
            auto_table = None
            table_dir = args.ledger_dir or _ledger.resolve_ledger_dir()
            if table_dir:
                from jepsen_tpu.parallel import planner as _planner_mod
                auto_table = _planner_mod.load_table(table_dir)
            plan = advisor.build_plan(records, bench,
                                      floor=args.floor,
                                      auto_table=auto_table)
            text = advisor.render_plan(plan)
            if args.json:
                sys.stdout.write(json.dumps(plan, sort_keys=True,
                                            indent=1) + "\n")
            else:
                sys.stdout.write(text)
            if not args.stdout_only:
                dest = run_dir if run_dir is not None \
                    else args.ledger_dir
                out = os.path.join(dest, "plan_report.txt")
                with open(out, "w") as fh:
                    fh.write(text)
                with open(os.path.join(dest, "plan.json"), "w") as fh:
                    json.dump(plan, fh, sort_keys=True, indent=1)
                    fh.write("\n")
                print(f"report written to {out}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(report_main())
