"""Telemetry exporters: JSONL events, Chrome trace-event JSON
(Perfetto-openable), and the end-of-run human summary table.

Chrome trace format (the ``ui.perfetto.dev`` / ``chrome://tracing``
interchange): a JSON array of event objects. We emit complete events
(``"ph": "X"`` with ``ts``/``dur`` in microseconds) plus ``"M"``
metadata events naming processes and threads:

  * pid 1 ("host"): one track per host thread that opened spans —
    the encode worker pool renders as parallel lanes under the
    pipeline's stage spans;
  * pid 2 ("device"): one track per device bucket (synthetic spans
    recorded via ``Tracer.add_span(track=...)`` for each chunk's
    dispatch->finalize window — no host thread "runs" these).

Open the file in Perfetto next to a ``jax.profiler`` capture of the
same run (``JEPSEN_TPU_JAX_PROFILE``) and the host spans line up with
the TPU timeline — docs/observability.md walks through it.

The JSONL export is the machine-readable sibling: one span object per
line (``Span.to_dict``) followed by one ``{"type": "metric", ...}``
line per registry entry — greppable, and what the store run dir keeps
(``telemetry.jsonl``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.obs import tracer as _tracer

_log = logging.getLogger(__name__)

HOST_PID = 1
DEVICE_PID = 2


def chrome_trace(tr: Optional[_tracer.Tracer] = None,
                 spans: Optional[List] = None) -> List[dict]:
    """The trace-event array for the active (or given) tracer's spans.
    Empty list when tracing is off — a valid trace document either
    way. ``spans`` overrides the tracer's buffer read — the flight
    dump passes the ring's retained spans."""
    tr = tr or _tracer.tracer()
    if tr is None:
        return []
    if spans is None:
        spans = tr.spans()
    events: List[dict] = [
        {"ph": "M", "pid": HOST_PID, "name": "process_name",
         "args": {"name": "host"}},
        {"ph": "M", "pid": DEVICE_PID, "name": "process_name",
         "args": {"name": "device"}},
        # the wall-clock epoch stamp: ts 0 of this trace on the unix
        # clock, so `jepsen trace` can merge several replicas' exports
        # onto one aligned time axis (Perfetto ignores unknown "M"
        # records)
        {"ph": "M", "pid": HOST_PID, "name": "trace_epoch",
         "args": {"unix": round(tr.epoch_unix, 6)}},
    ]
    # stable synthetic tids for device-bucket tracks, in first-seen
    # order; host tracks use the real thread idents
    track_tid: Dict[str, int] = {}
    seen_threads: Dict[int, str] = {}
    for s in spans:
        if s.track is not None:
            if s.track not in track_tid:
                tid = len(track_tid) + 1
                track_tid[s.track] = tid
                events.append({"ph": "M", "pid": DEVICE_PID, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": s.track}})
            pid, tid = DEVICE_PID, track_tid[s.track]
        else:
            tid, tname = s.thread
            if tid not in seen_threads:
                seen_threads[tid] = tname
                events.append({"ph": "M", "pid": HOST_PID, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": tname}})
            pid = HOST_PID
        args = dict(s.args)
        args["span_id"] = s.sid
        if s.parent is not None:
            args["parent_id"] = s.parent
        if s.cpu:
            args["cpu_secs"] = round(s.cpu, 6)
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "name": s.name,
            "cat": s.name.split(".")[0],
            "ts": round((s.t0 - tr.epoch) * 1e6, 1),
            "dur": round(max(s.t1 - s.t0, 0.0) * 1e6, 1),
            "args": args,
        })
    # counter tracks ("C" events): gauge levels and search-stats
    # trajectories — Perfetto renders each name as an area chart
    # aligned with the span tracks above (docs/observability.md
    # "Counter tracks"). Absent entirely when nothing sampled, so a
    # run that never touched a sampled gauge keeps its old trace file.
    for name, t, value in tr.counters():
        events.append({
            "ph": "C", "pid": HOST_PID, "tid": 0, "name": name,
            "cat": name.split(".")[0],
            "ts": round((t - tr.epoch) * 1e6, 1),
            "args": {"value": value},
        })
    return events


def write_chrome_trace(path: str,
                       tr: Optional[_tracer.Tracer] = None) -> str:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(chrome_trace(tr), fh)
    return path


def jsonl_events(tr: Optional[_tracer.Tracer] = None,
                 reg: Optional[_metrics.Registry] = None,
                 snap: Optional[Dict[str, dict]] = None) -> List[dict]:
    """``snap`` overrides the registry read — export_run passes the
    per-run delta so artifacts describe one run, not the process."""
    tr = tr or _tracer.tracer()
    if snap is None:
        snap = (reg or _metrics.registry()).snapshot()
    out: List[dict] = []
    if tr is not None:
        out.extend(s.to_dict() for s in tr.spans())
    for name, m in snap.items():
        d = dict(m)
        # the metric's own kind moves aside so every JSONL line keys
        # uniformly on "type": "span" | "metric"
        d["metric_type"] = d.pop("type")
        out.append({"type": "metric", "name": name, **d})
    return out


def write_jsonl(path: str, tr: Optional[_tracer.Tracer] = None,
                reg: Optional[_metrics.Registry] = None,
                snap: Optional[Dict[str, dict]] = None) -> str:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        for obj in jsonl_events(tr, reg, snap):
            fh.write(json.dumps(obj) + "\n")
    return path


def summary(tr: Optional[_tracer.Tracer] = None,
            reg: Optional[_metrics.Registry] = None,
            snap: Optional[Dict[str, dict]] = None) -> str:
    """The end-of-run human table: spans aggregated by name
    (count / total wall / mean / total CPU), then every registry
    metric. Plain text, aligned, stable column order — the thing a
    human reads before deciding whether to open the trace."""
    tr = tr or _tracer.tracer()
    if snap is None:
        snap = (reg or _metrics.registry()).snapshot()
    lines: List[str] = []
    if tr is not None:
        agg: Dict[str, list] = {}
        for s in tr.spans():
            a = agg.setdefault(s.name, [0, 0.0, 0.0])
            a[0] += 1
            a[1] += s.wall
            a[2] += s.cpu
        if agg:
            lines.append(f"{'span':<28} {'count':>7} {'total_s':>10} "
                         f"{'mean_ms':>10} {'cpu_s':>9}")
            for name in sorted(agg):
                n, wall, cpu = agg[name]
                lines.append(f"{name:<28} {n:>7} {wall:>10.4f} "
                             f"{wall / n * 1e3:>10.3f} {cpu:>9.4f}")
    if snap:
        if lines:
            lines.append("")
        lines.append(f"{'metric':<36} {'type':<10} value")
        for name, m in snap.items():
            if m["type"] == "counter":
                val = str(m["value"])
            elif m["type"] == "gauge":
                # max None: a per-run delta where the run's own peak
                # stayed below the process high-water (delta() doc)
                mx = "n/a" if m["max"] is None else m["max"]
                val = f"{m['value']} (max {mx})"
            else:
                val = (f"n={m['count']} total={m['total']} "
                       f"mean={m['mean']}")
            lines.append(f"{name:<36} {m['type']:<10} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------- search-stats collector

# Per-key device-search stats blocks (JEPSEN_TPU_SEARCH_STATS — see
# parallel.engine), recorded by the engines as each search finishes and
# drained into <run_dir>/search_stats.jsonl by export_run — the record
# `jepsen report --search` renders. Bounded: a long soak must not grow
# host memory through its own telemetry.
SEARCH_STATS_MAX_RECORDS = 4096
_search_stats_lock = threading.Lock()
_search_stats: list = []
_search_stats_dropped = 0


def record_search_stats(rec: dict) -> None:
    """Append one per-key search-stats record (a JSON-serializable
    dict). Past the bound the OLDEST record is dropped (counted):
    streamed keys re-record their lifetime block every delta and the
    report keeps the newest per key, so the newest evidence must be
    the side that survives."""
    global _search_stats_dropped
    with _search_stats_lock:
        if len(_search_stats) >= SEARCH_STATS_MAX_RECORDS:
            _search_stats.pop(0)
            _search_stats_dropped += 1
            _metrics.counter("obs.search_stats_dropped").inc()
        _search_stats.append(dict(rec))


def search_stats_records() -> list:
    with _search_stats_lock:
        return [dict(r) for r in _search_stats]


def drain_search_stats() -> list:
    """Hand over the collected records and clear the buffer — the same
    per-run semantics as the span buffer."""
    global _search_stats
    with _search_stats_lock:
        out = _search_stats
        _search_stats = []
        return out


def _write_jsonl_records(path: str, records: list) -> str:
    """One record per line — the shared shape of every drained-ring
    run artifact (search stats, slow deltas)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec, default=str) + "\n")
    return path


def write_search_stats(path: str, records: list) -> str:
    return _write_jsonl_records(path, records)


# ------------------------------------------- slow-delta forensics

# Bounded newest-wins ring of slow-delta records (deltas whose
# ingest->verdict latency crossed JEPSEN_TPU_SLOW_DELTA_SECS — see
# serve.service): each record is the delta's stage-by-stage timing
# breakdown plus its verdict/resilience/search-stats context. A deque
# with maxlen drops the OLDEST record past the bound — in a sustained
# slowdown the newest evidence is the side that must survive.
SLOW_DELTA_MAX_RECORDS = 256
_slow_lock = threading.Lock()
#: ring entries are ``(scope, record)`` — the collector is process-
#: global (like every obs sink) but the DATA is per CheckerService:
#: two services in one process (serve_smoke) must not read each
#: other's forensics on /status, and one service's huge offender must
#: not suppress another's flight dump. ``scope`` is the service's
#: opaque identity (None = unscoped callers, e.g. tests).
_slow_deltas: deque = deque(maxlen=SLOW_DELTA_MAX_RECORDS)
_slow_worst: Dict = {}        # scope -> worst total since arm/reset


def _slow_ring() -> deque:
    return _slow_deltas


def record_slow_delta(rec: dict, scope=None) -> bool:
    """Append one slow-delta record; returns True when this record is
    the WORST offender so far WITHIN ITS SCOPE (largest total) — the
    caller's cue to flight-dump it (``serve.service`` does, outside
    its lock)."""
    total = float(rec.get("total_secs") or 0.0)
    with _slow_lock:
        ring = _slow_ring()
        if len(ring) >= SLOW_DELTA_MAX_RECORDS:
            _metrics.counter("obs.slow_deltas_dropped").inc()
        ring.append((scope, dict(rec)))
        worst = total > _slow_worst.get(scope, 0.0)
        if worst:
            _slow_worst[scope] = total
    _metrics.counter("serve.slow_deltas").inc()
    return worst


def slow_delta_records(scope=None) -> list:
    """The retained slow-delta records, oldest first (the /status
    surface reads this without draining). ``scope`` filters to one
    recorder's records; None returns everything."""
    with _slow_lock:
        return [dict(r) for s, r in _slow_ring()
                if scope is None or s == scope]


def drain_slow_deltas() -> list:
    """Hand over ALL scopes' records and clear the ring (and every
    worst-offender high-water) — per-run semantics like the span
    buffer; the run artifact is process-wide like the trace."""
    with _slow_lock:
        out = [r for _s, r in _slow_ring()]
        _slow_ring().clear()
        _slow_worst.clear()
        return out


def write_slow_deltas(path: str, records: list) -> str:
    return _write_jsonl_records(path, records)


def _ledger_snapshot(run_dir: str) -> Optional[str]:
    """Copy the decision ledger's current records into the run dir as
    ``ledger.jsonl`` (the ``jepsen report --plan`` default input).
    Ledger off -> None, no file — run dirs stay byte-identical (the
    search-stats/slow-delta opt-in posture)."""
    from jepsen_tpu.obs import ledger as _ledger
    led = _ledger.active()
    if led is None:
        return None
    led.sync()
    records, _corrupt = _ledger.read_records(led.root)
    if not records:
        return None
    return _write_jsonl_records(os.path.join(run_dir, "ledger.jsonl"),
                                records)


# registry state at the last export_run, so each run's artifacts carry
# the metrics THIS run moved (counters as deltas), not the process's
# cumulative totals — a `--test-count 3` / test-all loop analyzes
# several runs in one process
_last_reg_snapshot: Dict[str, dict] = {}


def export_run(run_dir: str) -> Optional[dict]:
    """Write the run-dir telemetry artifacts — ``telemetry.jsonl``,
    ``trace.json`` (Chrome trace-event), ``telemetry.txt`` (summary) —
    and, when ``JEPSEN_TPU_TRACE`` named an explicit path, the Chrome
    trace there too. Returns the artifact paths, or None when tracing
    is off (the registry alone does not warrant run-dir files: every
    run would grow three artifacts nobody asked for).

    Per-run semantics: the tracer's span buffer is DRAINED after the
    export and counters are reported as deltas since the previous
    export_run — in a process that analyzes several runs, each run
    dir describes that run alone (and span memory stays bounded)."""
    global _last_reg_snapshot
    tr = _tracer.tracer()
    stats_records = drain_search_stats()
    slow_records = drain_slow_deltas()
    if tr is None or tr.flight_only:
        # a flight-only recorder (JEPSEN_TPU_FLIGHT_RECORDER with
        # tracing off) must not grow run-dir artifacts: its output
        # surface is the crash dump alone. EXCEPT search-stats and
        # slow-delta records: JEPSEN_TPU_SEARCH_STATS and
        # JEPSEN_TPU_SLOW_DELTA_SECS are their own opt-ins, and the
        # `jepsen report --search` / `--slow` inputs must land whether
        # or not tracing was also on (flags off -> no records -> still
        # None, byte-identical run dirs).
        arts = {}
        if stats_records:
            arts["search_stats"] = write_search_stats(
                os.path.join(run_dir, "search_stats.jsonl"),
                stats_records)
        if slow_records:
            arts["slow_deltas"] = write_slow_deltas(
                os.path.join(run_dir, "slow_deltas.jsonl"),
                slow_records)
        # the decision ledger is its own opt-in too
        # (JEPSEN_TPU_LEDGER): its run-dir snapshot lands whether or
        # not tracing was also on
        lg = _ledger_snapshot(run_dir)
        if lg:
            arts["ledger"] = lg
        return arts or None
    os.makedirs(run_dir, exist_ok=True)
    reg = _metrics.registry()
    # ONE snapshot serves both the per-run delta and the next
    # baseline — a counter bumped between two separate reads would
    # vanish from both this run's artifacts and the next's
    now = reg.snapshot()
    run_snap = reg.delta(_last_reg_snapshot, now)
    out = {
        "jsonl": write_jsonl(os.path.join(run_dir, "telemetry.jsonl"),
                             tr, snap=run_snap),
        "trace": write_chrome_trace(os.path.join(run_dir, "trace.json"),
                                    tr),
    }
    with open(os.path.join(run_dir, "telemetry.txt"), "w") as fh:
        fh.write(summary(tr, snap=run_snap))
    out["summary"] = os.path.join(run_dir, "telemetry.txt")
    if stats_records:
        out["search_stats"] = write_search_stats(
            os.path.join(run_dir, "search_stats.jsonl"), stats_records)
    if slow_records:
        out["slow_deltas"] = write_slow_deltas(
            os.path.join(run_dir, "slow_deltas.jsonl"), slow_records)
    lg = _ledger_snapshot(run_dir)
    if lg:
        out["ledger"] = lg
    if tr.path:
        # the buffer is drained per run, so one fixed destination would
        # only ever hold the LAST run's spans in a --test-count /
        # test-all process — run 2 onward gets a numbered sibling
        # (t.json, t.2.json, ...) instead of silently replacing run 1
        tr.flag_exports += 1
        dest = tr.path
        if tr.flag_exports > 1:
            root, ext = os.path.splitext(tr.path)
            dest = f"{root}.{tr.flag_exports}{ext or '.json'}"
        out["flag_trace"] = write_chrome_trace(dest, tr)
    _last_reg_snapshot = now
    tr.drain()
    return out


# --------------------------------------------------- flight recorder

# where crash dumps land when the caller doesn't say (the serve
# service points this at its WAL directory so postmortem evidence
# lives next to the WAL it explains)
_flight_dir = os.path.join("store", "flight")
_flight_lock = threading.Lock()
_flight_seq = 0
# a shed storm or a flapping breaker must not fill the disk with
# near-identical dumps: past the cap, dumps are counted but skipped
FLIGHT_MAX_DUMPS = 25


def set_flight_dir(path: str) -> None:
    """Redirect flight-recorder dumps (default ``store/flight``)."""
    global _flight_dir
    _flight_dir = path


def flight_reset() -> None:
    """Test isolation: restart the dump sequence (and therefore the
    per-process cap) and restore the default destination."""
    global _flight_seq, _flight_dir
    with _flight_lock:
        _flight_seq = 0
        _flight_dir = os.path.join("store", "flight")


def flight_dump(reason: str,
                dest_dir: Optional[str] = None,
                context: Optional[dict] = None) -> Optional[str]:
    """Dump the flight ring as a Chrome-trace file — the postmortem
    artifact for a crashed or degraded service when nobody had tracing
    on. Returns the path written, or None when no recorder is armed
    (the common case: JEPSEN_TPU_FLIGHT_RECORDER unset costs exactly
    this None check at the hook sites) or the per-process dump cap is
    reached.

    The file is the Perfetto-openable object form: ``traceEvents``
    (the ring's retained spans) plus a ``flight`` block carrying the
    trigger reason and the registry delta since the recorder was
    armed — spans show WHERE the time went, the delta shows WHAT
    moved (sheds, watchdog kills, breaker opens) before the trigger.
    ``context`` (JSON-serializable) rides the ``flight`` block as
    ``trigger`` — the serve hook sites pass the triggering
    ``delta_id``/``key``/``tenant`` so a ``flight_*.trace.json``
    cross-references the slow-delta or shed record that explains it.
    """
    global _flight_seq
    tr = _tracer.tracer()
    if tr is None or not _tracer.flight_active():
        return None
    with _flight_lock:
        if _flight_seq >= FLIGHT_MAX_DUMPS:
            _metrics.counter("obs.flight_dumps_skipped").inc()
            return None
        _flight_seq += 1
        seq = _flight_seq
    # NOTHING below may raise out of here: every hook site is a
    # failure path (a wedge about to become DispatchWedged, a breaker
    # opening, a shed response, a worker-error handler), and an
    # observability dump that crashes — unwritable dir, disk full on
    # the very sick node being diagnosed — would replace the
    # structured error the resilience machinery depends on
    try:
        reg = _metrics.registry()
        doc = {
            "traceEvents": chrome_trace(tr, spans=tr.ring_spans()),
            "flight": {
                "reason": reason,
                "seq": seq,
                "metrics_delta": reg.delta(tr.flight_baseline or {}),
            },
        }
        if context:
            # JSON-proof the trigger context defensively: a dump must
            # never die on an exotic key object in the context dict
            doc["flight"]["trigger"] = json.loads(
                json.dumps(context, default=str))
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                       for ch in reason) or "dump"
        d = dest_dir or _flight_dir
        path = os.path.join(d, f"flight_{safe}_{seq}.trace.json")
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(doc, fh)
    except Exception:  # noqa: BLE001 — see above
        _metrics.counter("obs.flight_dump_errors").inc()
        _log.exception("flight-recorder dump failed (reason %r)",
                       reason)
        return None
    _metrics.counter("obs.flight_dumps").inc()
    return path
