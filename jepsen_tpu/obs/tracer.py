"""Span tracing for the checker pipeline — Dapper-style nested spans
(Sigelman et al., 2010) over the host phases of a check.

A span is a named, attributed wall-clock + CPU-time interval:

    with obs.span("pipeline.dispatch", tier=8, chunk=2):
        ...

Spans nest through a ``contextvars.ContextVar``: the span active when a
child opens becomes its parent, which is what makes the per-key /
per-chunk trees in the Chrome trace render as stacks. Worker-pool
threads do not inherit contextvars automatically — the pipeline
captures the submitting thread's context via :func:`ctx_runner` so
spans opened on pool threads still hang off the submitting span (one
``Context.copy()`` per task: a Context object cannot be entered by two
threads at once).

Gating: ``JEPSEN_TPU_TRACE`` via the validated accessor
(``envflags.env_path``) — unset/``0`` disabled, ``1`` enabled,
``<path>`` enabled + the Chrome trace additionally written there at
export time. When DISABLED, ``span()`` returns a process-wide singleton
no-op context manager: no span object, no clock read, no lock — the
hot path (one call per key per stage) costs two attribute loads and a
``None`` check, and tests/test_obs.py pins a per-call CPU budget and
zero retained allocations. Flag changes after import are picked up via
:func:`reset` (tests) — a real run sets the env before the process
starts.

Thread-safety: finished spans append to one lock-protected list; the
contextvar handles per-thread currency. ``process_time()`` is
process-wide, so a span's ``cpu`` reads "CPU seconds the process spent
while this span was open" — comparable across spans only when the
machine isn't oversubscribed, which is exactly how the bench uses it.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
from collections import deque
from time import perf_counter, process_time
from time import time as wall_time
from typing import Callable, Dict, List, Optional

from jepsen_tpu import envflags

_current: contextvars.ContextVar = contextvars.ContextVar(
    "jepsen_tpu_obs_span", default=None)

# default flight-recorder ring capacity (closed spans) when
# JEPSEN_TPU_FLIGHT_RECORDER=1; N>=2 sets the capacity explicitly
FLIGHT_DEFAULT_SPANS = 256


class Tracer:
    """Collects finished spans for one tracing session.

    Two retention modes, combinable:

    * the ordinary unbounded per-run buffer (``spans()``/``drain()``),
      exported into store run dirs — full tracing;
    * a bounded ring of the last ``ring`` CLOSED spans (the flight
      recorder, ``JEPSEN_TPU_FLIGHT_RECORDER``) that survives drains —
      what a crash dump reads. ``flight_only=True`` records into the
      ring ALONE (no unbounded list: a long-lived serve process must
      stay bounded-memory with tracing off), and is invisible to
      ``enabled()``/``export_run`` so run-dir artifacts and bench trace
      pointers keep their tracing-off behavior byte-identical.
    """

    def __init__(self, path: str = "", ring: Optional[int] = None,
                 flight_only: bool = False):
        self.path = path            # JEPSEN_TPU_TRACE=<path> ("" = none)
        self.epoch = perf_counter()  # trace time origin (ts 0 in exports)
        # the same origin on the WALL clock: exports stamp it so the
        # fleet trace merge (`jepsen trace`) can align several
        # replicas' traces on one time axis — perf_counter epochs are
        # per-process and incomparable across machines/restarts
        self.epoch_unix = wall_time()
        self.flag_exports = 0       # export_run count, for <path> runs
        self.flight_only = flight_only
        self._lock = threading.Lock()
        self._spans: List["Span"] = []
        # counter-track samples: (name, t, value) triples exported as
        # Chrome "C" events (Perfetto counter tracks) — gauge levels
        # (pipeline.inflight, breaker state, serve queue depth) and the
        # search-stats trajectories line up with the span tracks
        self._counters: List[tuple] = []
        self._ring: Optional[deque] = (deque(maxlen=ring)
                                       if ring else None)
        self.flight_baseline: Optional[dict] = None
        if self._ring is not None:
            # metrics state at arm time, so a crash dump reports what
            # moved SINCE the recorder started, not process totals
            # (import here, not at module scope: metrics has no deps,
            # and tracer must stay importable first)
            from jepsen_tpu.obs import metrics as _metrics
            self.flight_baseline = _metrics.registry().snapshot()
        self._ids = itertools.count(1)

    def next_id(self) -> int:
        return next(self._ids)

    def record(self, span: "Span"):
        with self._lock:
            if self._ring is not None:
                self._ring.append(span)
            if not self.flight_only:
                self._spans.append(span)

    def ring_spans(self) -> List["Span"]:
        """The flight ring's retained spans, oldest first (empty when
        no ring is configured). NOT cleared by :meth:`drain` — the
        recorder must still answer after a per-run export."""
        with self._lock:
            return list(self._ring) if self._ring is not None else []

    def spans(self) -> List["Span"]:
        with self._lock:
            return list(self._spans)

    def record_counter(self, name: str, t: float, value) -> None:
        """Record one counter-track sample. Flight-only recorders skip
        it: the ring retains spans alone, and counter samples must not
        grow unbounded state in a tracing-off process."""
        if self.flight_only:
            return
        with self._lock:
            self._counters.append((name, t, value))

    def counters(self) -> List[tuple]:
        with self._lock:
            return list(self._counters)

    def drain(self) -> List["Span"]:
        """Hand over the finished spans and clear the buffer — how
        export_run keeps artifacts per-run (and memory bounded) in a
        process that analyzes several runs (`--test-count`,
        test-all). Counter samples clear with the spans: they share
        the per-run window."""
        with self._lock:
            out = self._spans
            self._spans = []
            self._counters = []
            return out

    def add_span(self, name: str, t0: float, t1: float,
                 track: Optional[str] = None, parent: Optional[int] = None,
                 **args) -> "Span":
        """Record an interval measured elsewhere (e.g. a device
        program's dispatch->finalize window) as a finished span on an
        explicit `track` — these become the per-device-bucket rows in
        the Chrome trace, since no host thread "runs" them."""
        s = Span(self, name, args)
        s.sid = self.next_id()
        s.parent = parent
        s.t0, s.t1 = t0, t1
        s.cpu = 0.0
        s.track = track if track is not None else "device"
        s.thread = None
        self.record(s)
        return s


class Span:
    """One open (then finished) span. Context-manager protocol; also
    usable pre-populated via Tracer.add_span. ``wall``/``cpu`` are
    valid after ``__exit__`` — :func:`timer` exploits that to make the
    recorded span and the caller's measured number one and the same
    clock read."""

    __slots__ = ("tracer", "name", "args", "sid", "parent", "t0", "t1",
                 "cpu", "_cpu0", "_tok", "thread", "track")

    def __init__(self, tracer: Optional[Tracer], name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.sid = 0
        self.parent = None
        self.t0 = self.t1 = 0.0
        self.cpu = 0.0
        self.thread = None
        self.track = None

    def __enter__(self) -> "Span":
        tr = self.tracer
        if tr is not None:
            self.sid = tr.next_id()
            par = _current.get()
            self.parent = par.sid if par is not None else None
            self._tok = _current.set(self)
        else:
            self._tok = None
        t = threading.current_thread()
        self.thread = (t.ident, t.name)
        self._cpu0 = process_time()
        self.t0 = perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.t1 = perf_counter()
        self.cpu = process_time() - self._cpu0
        if self._tok is not None:
            _current.reset(self._tok)
        if self.tracer is not None:
            self.tracer.record(self)
        return False

    def set(self, **kw):
        """Attach attributes discovered mid-span (e.g. the resolved
        capacity tier)."""
        self.args.update(kw)

    @property
    def wall(self) -> float:
        return self.t1 - self.t0

    def to_dict(self) -> dict:
        d = {"type": "span", "name": self.name, "id": self.sid,
             "parent": self.parent,
             "t0": round(self.t0, 6), "wall": round(self.wall, 6),
             "cpu": round(self.cpu, 6)}
        if self.thread is not None:
            d["thread"] = self.thread[1]
            d["tid"] = self.thread[0]
        if self.track is not None:
            d["track"] = self.track
        if self.args:
            d["args"] = dict(self.args)
        return d


class _NoopSpan:
    """The disabled-path singleton: enters/exits without touching a
    clock, a lock, or the heap. `set` swallows attributes (they were
    built by the caller either way)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kw):
        pass

    wall = 0.0
    cpu = 0.0


_NOOP = _NoopSpan()

# module tracer state: None = disabled; _UNSET = not yet resolved from
# the env (first span()/enabled() call resolves — import stays cheap
# and monkeypatched env in tests is honored if they reset() first)
_UNSET = object()
_state = _UNSET
_state_lock = threading.Lock()


def _flight_capacity() -> int:
    """JEPSEN_TPU_FLIGHT_RECORDER: unset/0 -> 0 (off), 1 -> the
    default ring capacity, N>=2 -> that capacity in spans."""
    v = envflags.env_int("JEPSEN_TPU_FLIGHT_RECORDER", default=0,
                         min_value=0, what="flight-recorder capacity")
    if v == 1:
        return FLIGHT_DEFAULT_SPANS
    return v or 0


def _resolve():
    global _state
    with _state_lock:
        if _state is _UNSET:
            path = envflags.env_path("JEPSEN_TPU_TRACE",
                                     what="trace output path")
            ring = _flight_capacity()
            if path is not None:
                _state = Tracer(path, ring=ring or None)
            elif ring:
                # flight recorder alone: spans land in the bounded
                # ring only, invisible to enabled()/export_run
                _state = Tracer("", ring=ring, flight_only=True)
            else:
                _state = None
    return _state


def enabled() -> bool:
    """Full tracing on? A flight-only recorder answers False — every
    tracing-gated consumer (run-dir export, bench trace pointers,
    ctx_runner) must keep its tracing-off behavior when only the
    crash ring is armed."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    return st is not None and not st.flight_only


def flight_active() -> bool:
    """Is a flight-recorder ring retaining spans (with or without full
    tracing)? The hook sites (supervisor wedge, breaker open, serve
    shed/worker-error) check this before dumping."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    return st is not None and st._ring is not None


def tracer() -> Optional[Tracer]:
    """The active Tracer, or None when tracing is off."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    return st


def span(name: str, **args):
    """A traced interval — the hot-path entry point. Disabled: returns
    the no-op singleton (nothing allocated beyond the caller's own
    kwargs, nothing timed)."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    if st is None:
        return _NOOP
    return Span(st, name, args)


def timer(name: str, **args) -> Span:
    """An ALWAYS-measuring interval: the context manager's
    ``wall``/``cpu`` are valid whether tracing is on or off, and when
    it is on the recorded span is the SAME clock reads — the mechanism
    by which bench split lines and trace spans can never disagree
    (one measurement site). Not for hot paths: it allocates a Span per
    call even when disabled; use :func:`span` there."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    return Span(st, name, args)


def counter_sample(name: str, value, t: Optional[float] = None) -> None:
    """Record one sample on a Perfetto counter track (a Chrome "C"
    event at export time): a gauge level, a queue depth, a breaker
    state, a frontier width. No-op when full tracing is off — the
    disabled path is one attribute load and a None/flight check, the
    same hot-path standard as span(). ``t`` (a perf_counter() read)
    backdates the sample — the search-stats exporter synthesizes a
    time axis across a device search's span window."""
    st = _state
    if st is _UNSET:
        st = _resolve()
    if st is None or st.flight_only:
        return
    st.record_counter(name, t if t is not None else perf_counter(),
                      value)


def configure(on: bool = True, path: str = "",
              ring: Optional[int] = None,
              flight_only: bool = False) -> Optional[Tracer]:
    """Programmatic gate (tests, embedding): force tracing on/off
    regardless of the env flag. Returns the new tracer (or None).
    ``ring``/``flight_only`` arm the flight recorder the way the
    JEPSEN_TPU_FLIGHT_RECORDER flag would."""
    global _state
    with _state_lock:
        _state = (Tracer(path, ring=ring, flight_only=flight_only)
                  if on else None)
    return _state


def reset():
    """Drop the session and re-resolve from the env on next use —
    how tests flip JEPSEN_TPU_TRACE mid-process."""
    global _state
    with _state_lock:
        _state = _UNSET


def current_span() -> Optional[Span]:
    return _current.get()


def ctx_runner() -> Callable:
    """Span-context propagation for worker pools. Captures the calling
    thread's context ONCE; returns ``wrap(fn) -> fn'`` where each
    ``fn'`` call runs under a fresh copy of that context, so spans
    opened on the pool thread nest under the span active at capture
    time. Disabled tracing returns the identity wrap (zero overhead).
    One ``Context.copy()`` per call is mandatory, not defensive: a
    Context raises if entered concurrently from two threads."""
    if not enabled():
        return lambda fn: fn
    ctx = contextvars.copy_context()

    def wrap(fn):
        def run(*a, **kw):
            return ctx.copy().run(fn, *a, **kw)
        return run
    return wrap


# ------------------------------------------------- jax.profiler bridge


def jax_profile_dir() -> Optional[str]:
    """The JEPSEN_TPU_JAX_PROFILE directory, or None when off. "1"
    maps to the default capture dir so the flag composes with the
    runbook's `JEPSEN_TPU_JAX_PROFILE=1 jepsen test ...` shorthand."""
    d = envflags.env_path("JEPSEN_TPU_JAX_PROFILE", what="profile dir")
    if d == "":
        return "store/jax_profile"
    return d


class _MaybeCtx:
    """Context manager that delegates to a lazily-built inner context
    (or nothing). Exists so the obs module never imports jax at import
    time — engine modules must stay import-safe under a wedged
    runtime."""

    __slots__ = ("_factory", "_inner")

    def __init__(self, factory):
        self._factory = factory
        self._inner = None

    def __enter__(self):
        if self._factory is not None:
            self._inner = self._factory()
            self._inner.__enter__()
        return self

    def __exit__(self, *exc):
        if self._inner is not None:
            return bool(self._inner.__exit__(*exc))
        return False


def maybe_jax_profile() -> _MaybeCtx:
    """jax.profiler.trace(dir) when JEPSEN_TPU_JAX_PROFILE is set, else
    a no-op — wraps a whole batched check so the TPU capture and the
    host spans share a session."""
    d = jax_profile_dir()
    if d is None:
        return _MaybeCtx(None)

    def make():
        import jax
        return jax.profiler.trace(d)
    return _MaybeCtx(make)


def device_annotation(name: str) -> _MaybeCtx:
    """jax.profiler.TraceAnnotation(name) when profiling is on, else a
    no-op — names the dispatch step in the TPU timeline so host spans
    line up with device work in Perfetto."""
    if jax_profile_dir() is None:
        return _MaybeCtx(None)

    def make():
        import jax
        return jax.profiler.TraceAnnotation(name)
    return _MaybeCtx(make)
