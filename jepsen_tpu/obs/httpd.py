"""The live ops surface: ``/metrics`` + ``/healthz`` + ``/status``
over a stdlib HTTP daemon thread.

Everything observable about a running ``jepsen serve --checker`` was
post-hoc until now — Perfetto/JSONL exports land in store run dirs
AFTER a run, and the only health signal was a one-shot ``jepsen
probe`` subprocess. This module is the pull-based surface a long-lived
service needs (the TPU-native analogue of ``jepsen.checker/perf`` +
timeline reporting — the operator-facing output layer of the
reference):

    /metrics    Prometheus text exposition rendered live from the
                metrics registry (counters, gauges + their high-water
                twins, histograms with the fixed bucket ladder) — what
                a scraper polls
    /healthz    liveness + readiness as one JSON document; HTTP 200
                when ready, 503 when degraded (worker dead, WAL
                unwritable, breaker open, queue past high-water,
                stale chip probe) — what a load balancer polls
    /status     the per-key service table (seq, pending, frontier
                live/evicted, last verdict, WAL bytes, resilience
                notes, per-key accounting) — what an operator reads,
                via ``jepsen status`` or curl

Zero new dependencies by construction: ``http.server`` threads only.
The server binds an OS-assigned port when asked for port 0 (tests,
smoke), runs as a daemon thread, and holds NO service state of its
own — every request renders fresh from the registry and the injected
callbacks, so a wedged worker cannot make ``/healthz`` lie about it.

``jepsen status`` (:func:`status_main`) is the curl-free client: it
fetches ``/status`` + ``/healthz`` from a running instance and prints
the human summary table, pre-parse forwarded from ``cli.py`` exactly
like ``lint`` and ``probe``.

Import-safe: no JAX, no engine imports — the ops surface must answer
while the device runtime is wedged, which is precisely when an
operator needs it.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Sequence

from jepsen_tpu import envflags
from jepsen_tpu.obs import metrics as _metrics

_log = logging.getLogger(__name__)

PROM_PREFIX = "jepsen_"

#: HTTP content type for Prometheus text exposition format 0.0.4
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def resolve_ops_port(cli_value: Optional[int] = None) -> Optional[int]:
    """The ops-endpoint port: an explicit ``--ops-port`` wins, else
    ``JEPSEN_TPU_OPS_PORT`` (0 = ephemeral); None when neither is set
    (the endpoint stays off and serve behavior is byte-identical to
    the pre-ops-surface service)."""
    if cli_value is not None:
        return int(cli_value)
    return envflags.env_int("JEPSEN_TPU_OPS_PORT", default=None,
                            min_value=0, what="ops endpoint port")


# ------------------------------------------------ Prometheus rendering


def prom_name(name: str) -> str:
    """A registry name as a Prometheus metric name: the dotted scheme
    maps 1:1 (dots and every other illegal character become ``_``),
    under the ``jepsen_`` namespace — ``serve.pending_ops`` ->
    ``jepsen_serve_pending_ops``. Documented as THE mapping in
    docs/observability.md; stable once a dashboard reads it."""
    out = "".join(ch if (ch.isascii() and ch.isalnum()) or ch == "_"
                  else "_" for ch in name)
    # the jepsen_ prefix already guarantees a legal leading character
    return PROM_PREFIX + out


def _fmt(v) -> str:
    """A sample value in exposition format (integers stay integral)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _esc_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    """A ``{k="v",...}`` block from a label dict (label NAMES are
    sanitized like metric names, values escaped); ``extra`` appends a
    pre-rendered pair (the histogram ``le``). Empty in, empty out."""
    pairs = [f'{prom_name(k)[len(PROM_PREFIX):]}="{_esc_label(v)}"'
             for k, v in sorted(labels.items())]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(snap: Optional[Dict[str, dict]] = None) -> str:
    """The registry snapshot as Prometheus text exposition (format
    0.0.4). Counters and gauges render as-is; a gauge's high-water
    mark rides as a ``<name>_max`` gauge twin; histograms render the
    full ``_bucket``/``_sum``/``_count`` triple with cumulative ``le``
    buckets ending at ``+Inf`` — the shape ``histogram_quantile()``
    needs for the delta-latency SLOs.

    Registry names of the form ``base[k=v,...]`` (``obs.labeled``)
    render as REAL exposition labels on the base metric —
    ``serve.ack_secs[tenant=a]`` becomes
    ``jepsen_serve_ack_secs_bucket{tenant="a",le=...}`` — so the
    per-tenant SLO series share one metric name with the unlabeled
    aggregate and ``histogram_quantile()`` can group by tenant. All
    series of one name render contiguously under one ``# TYPE`` line
    (the exposition grouping rule)."""
    if snap is None:
        snap = _metrics.registry().snapshot()
    # group by rendered metric name so labeled series and the
    # unlabeled aggregate share one contiguous TYPE block
    by_base: Dict[str, list] = {}
    for name in sorted(snap):
        base, labels = _metrics.split_labels(name)
        by_base.setdefault(prom_name(base), []).append(
            (labels, snap[name]))
    lines = []
    for pn in sorted(by_base):
        series = by_base[pn]
        typ = series[0][1]["type"]
        lines.append(f"# TYPE {pn} "
                     f"{'histogram' if typ == 'histogram' else typ}")
        max_twins = []
        for labels, m in series:
            lab = _label_str(labels)
            if m["type"] == "counter":
                lines.append(f"{pn}{lab} {_fmt(m['value'])}")
            elif m["type"] == "gauge":
                lines.append(f"{pn}{lab} {_fmt(m['value'])}")
                if m.get("max") is not None:
                    max_twins.append(f"{pn}_max{lab} "
                                     f"{_fmt(m['max'])}")
            else:
                for le, cum in m.get("buckets") or ():
                    le_pair = f'le="{_fmt(le)}"'
                    lines.append(
                        f"{pn}_bucket{_label_str(labels, le_pair)} "
                        f"{cum}")
                inf_pair = 'le="+Inf"'
                lines.append(
                    f"{pn}_bucket{_label_str(labels, inf_pair)} "
                    f"{m['count']}")
                lines.append(f"{pn}_sum{lab} {_fmt(m['total'])}")
                lines.append(f"{pn}_count{lab} {m['count']}")
                if m.get("max") is not None:
                    # streaming-max twin (the gauge-_max precedent): a
                    # quantile landing in the +Inf bucket answers with
                    # this instead of "-" — exactly the overloaded-SLO
                    # case the quantile view exists for
                    max_twins.append(f"{pn}_max{lab} "
                                     f"{_fmt(m['max'])}")
        if max_twins:
            lines.append(f"# TYPE {pn}_max gauge")
            lines.extend(max_twins)
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------- the server


class _OpsHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # the ops port may be reused quickly across smoke runs
    allow_reuse_address = True
    ops: "OpsServer" = None  # backref, set by OpsServer


class _Handler(BaseHTTPRequestHandler):
    server_version = "jepsen-ops/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
        _log.debug("ops httpd: " + fmt, *args)

    def _reply(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, doc: dict):
        self._reply(code, (json.dumps(doc, default=str, sort_keys=True)
                           + "\n").encode(), "application/json")

    def do_GET(self):  # noqa: N802 — stdlib name
        ops = self.server.ops
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                if ops.refresh_fn is not None:
                    ops.refresh_fn()
                self._reply(200, render_prometheus().encode(),
                            PROM_CONTENT_TYPE)
            elif path == "/healthz":
                if ops.refresh_fn is not None:
                    ops.refresh_fn()
                doc = (ops.health_fn() if ops.health_fn is not None
                       else {"ok": True, "checks": {}})
                self._json(200 if doc.get("ok") else 503, doc)
            elif path == "/status":
                if ops.refresh_fn is not None:
                    ops.refresh_fn()
                doc = (ops.status_fn() if ops.status_fn is not None
                       else {})
                self._json(200, doc)
            elif path == "/trace":
                # this replica's span export, live — what `jepsen
                # trace --addr` fetches and merges into ONE fleet
                # Perfetto file (obs.trace_merge). Tracing off
                # answers an empty (still valid) document; a
                # flight-only ring exports its retained spans.
                self._json(200, ops.trace_doc())
            elif path == "/ledger":
                # the decision-ledger aggregate (obs.ledger): newest-
                # wins per shape×strategy cell, plus segment/corruption
                # accounting. Ledger off answers an empty document
                # ({"ledger": {"enabled": false}, "cells": {}}) — still
                # valid JSON, so fleet scrapers need no probe.
                from jepsen_tpu.obs import ledger as _ledger_mod
                self._json(200, _ledger_mod.ledger_doc())
            elif path == "/plan":
                # the auto planner's live decision table
                # (parallel.planner): per shape-group cells, EWMA cost
                # and evidence counts. Planner off answers
                # {"auto": {"enabled": false}, "groups": {}} — still
                # valid JSON, same posture as /ledger. Import is lazy
                # AND safe: parallel.planner holds no JAX, and
                # parallel/__init__ is docstring-only, so the ops
                # surface keeps its wedged-runtime answering contract.
                from jepsen_tpu.parallel import planner as _planner_mod
                self._json(200, _planner_mod.plan_doc())
            elif path == "/":
                self._json(200, {"endpoints": ["/metrics", "/healthz",
                                               "/status", "/trace",
                                               "/ledger", "/plan"]})
            else:
                self._json(404, {"error": f"unknown path {path!r}",
                                 "endpoints": ["/metrics", "/healthz",
                                               "/status", "/trace",
                                               "/ledger", "/plan"]})
        except Exception as err:  # noqa: BLE001 — one bad render must
            # not kill the connection handler thread loop
            _log.exception("ops httpd: %s failed", path)
            try:
                self._json(500, {"error": f"{type(err).__name__}: "
                                          f"{err}"})
            except OSError:
                pass

    def do_POST(self):  # noqa: N802 — stdlib name
        ops = self.server.ops
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        # drain the (unused) body so keep-alive framing survives
        clen = int(self.headers.get("Content-Length", 0) or 0)
        if clen:
            self.rfile.read(clen)
        try:
            if path == "/adopt" and ops.adopt_fn is not None:
                # the replica-handoff trigger (serve.fleet.
                # HttpReplica): replay any WAL keys transferred into
                # this replica's WAL dir into live sessions — the
                # operator action `rehome` needs on a survivor it
                # cannot call in-process
                adopted = ops.adopt_fn()
                self._json(200, {"adopted": [str(k)
                                             for k in adopted]})
            else:
                self._json(404, {"error": f"unknown POST {path!r}",
                                 "endpoints": (["/adopt"]
                                               if ops.adopt_fn
                                               else [])})
        except Exception as err:  # noqa: BLE001 — same posture as
            # do_GET: one bad adoption answers 500, the server lives
            _log.exception("ops httpd: POST %s failed", path)
            try:
                self._json(500, {"error": f"{type(err).__name__}: "
                                          f"{err}"})
            except OSError:
                pass


class OpsServer:
    """The ops endpoint as an object: construct (binds the socket —
    port 0 gets an OS-assigned one, readable as ``.port`` before any
    request), ``start()`` the daemon thread, ``close()`` to stop.
    Callbacks:

    health_fn   -> {"ok": bool, "checks": {...}}; non-ok answers 503
    status_fn   -> the /status JSON document
    refresh_fn  -> called before every render so computed gauges
                   (queue depth, WAL lag) are point-in-time fresh
    adopt_fn    -> POST /adopt handler: CheckerService.adopt_keys —
                   the live replica-handoff trigger the fleet
                   supervisor drives on survivors (serve.fleet)

    All are optional — a bare OpsServer still serves /metrics from
    the process registry, which is exactly what a non-serve
    embedding (bench, a notebook) wants."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], dict]] = None,
                 status_fn: Optional[Callable[[], dict]] = None,
                 refresh_fn: Optional[Callable[[], None]] = None,
                 adopt_fn: Optional[Callable[[], list]] = None,
                 name: Optional[str] = None):
        self.health_fn = health_fn
        self.status_fn = status_fn
        self.refresh_fn = refresh_fn
        self.adopt_fn = adopt_fn
        self._httpd = _OpsHTTPServer((host, port), _Handler)
        self._httpd.ops = self
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        # how this replica names its process track in a merged fleet
        # trace (`jepsen trace`); defaults to the bound address
        self.name = name or f"{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def trace_doc(self) -> dict:
        """The /trace document: this process's Chrome-trace events
        (flight-only rings export their retained spans) plus the
        wall-clock epoch the fleet merge aligns replicas by. Tracing
        fully off answers ``{"traceEvents": []}`` — a valid, empty
        trace either way."""
        # functions imported from their defining modules (the obs
        # package attribute `tracer` is the accessor FUNCTION, which
        # shadows the submodule of the same name)
        from jepsen_tpu.obs.export import chrome_trace as _chrome
        from jepsen_tpu.obs.tracer import tracer as _get_tracer
        tr = _get_tracer()
        if tr is None:
            return {"traceEvents": [],
                    "trace": {"enabled": False, "replica": self.name}}
        spans = tr.ring_spans() if tr.flight_only else tr.spans()
        return {"traceEvents": _chrome(tr, spans=spans),
                "trace": {"enabled": True, "replica": self.name,
                          "epoch_unix": round(tr.epoch_unix, 6)}}

    def start(self) -> "OpsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="jepsen-ops-httpd")
            self._thread.start()
        return self

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


def start_ops_server(port: int, host: str = "127.0.0.1",
                     **kw) -> OpsServer:
    """Bind + start in one call (the CLI's entry point)."""
    return OpsServer(port=port, host=host, **kw).start()


# ------------------------------------------------ `jepsen status` CLI


def _fetch(url: str, timeout: float = 10.0):
    """(HTTP status, decoded body) for a GET — urllib only, and a 503
    from /healthz is an ANSWER (degraded), not an error."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = int(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return (f"{n}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0
    return str(n)


def parse_prometheus(body: str) -> Dict[str, dict]:
    """Parse Prometheus text exposition (our own render_prometheus
    output) back into snapshot-shaped dicts — enough structure for
    hist_quantile: histograms get {"count", "total", "buckets"},
    everything else {"value"}. Tolerates unknown lines (forward
    compatibility beats strictness in a CLI client).

    Labeled series key their entries ``name[k=v,...]`` (the registry's
    ``obs.labeled`` convention, labels sorted) — so the per-tenant SLO
    histograms parse back as distinct quantile-answerable entries
    while unlabeled names keep their historical plain-string keys."""
    import re

    types: Dict[str, str] = {}
    out: Dict[str, dict] = {}
    sample = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})? '
        r'([-+0-9.eE]+|\+Inf)$')
    pair = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    esc = re.compile(r'\\(.)')

    def _unescape(v: str) -> str:
        # single-pass, so escapes cannot cascade: sequential
        # str.replace turned the two-character value `\` + `n` (
        # rendered `\\n`) into a literal newline — exactly the
        # round-trip corruption the escaping tests pin against
        return esc.sub(
            lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)

    def _fresh():
        return {"type": "histogram", "count": 0, "total": 0.0,
                "buckets": [], "min": None, "max": None}

    def _key(name, labels):
        return _metrics.labeled(name, **labels) if labels else name

    for ln in body.splitlines():
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if not ln or ln.startswith("#"):
            continue
        m = sample.match(ln)
        if not m:
            continue
        name, lab, val = m.groups()
        labels = {k: _unescape(v)
                  for k, v in pair.findall(lab or "")}
        le = labels.pop("le", None)
        if name.endswith("_bucket"):
            base = name[: -len("_bucket")]
            h = out.setdefault(_key(base, labels), _fresh())
            if le == "+Inf":
                h["count"] = int(float(val))
            elif le is not None:
                h["buckets"].append([float(le), int(float(val))])
            continue
        if name.endswith("_sum") and types.get(
                name[: -len("_sum")]) == "histogram":
            out.setdefault(_key(name[: -len("_sum")], labels),
                           _fresh())["total"] = float(val)
            continue
        if name.endswith("_count") and types.get(
                name[: -len("_count")]) == "histogram":
            out.setdefault(_key(name[: -len("_count")], labels),
                           _fresh())["count"] = int(float(val))
            continue
        if name.endswith("_max") and types.get(
                name[: -len("_max")]) == "histogram":
            # the streaming-max twin: what hist_quantile answers with
            # for quantiles past the bucket ladder's top
            out.setdefault(_key(name[: -len("_max")], labels),
                           _fresh())["max"] = float(val)
            continue
        out[_key(name, labels)] = {"type": types.get(name, "untyped"),
                                   "value": float(val)}
    return out


def render_metrics_summary(body: str) -> str:
    """The `jepsen status --metrics` view: histograms as
    p50/p95/p99 quantile lines (hist_quantile over the cumulative
    ladder — the serve.ack_secs / serve.verdict_secs SLO answer,
    without eyeballing raw buckets), every other sample as-is. The
    raw exposition stays available with --raw."""
    parsed = parse_prometheus(body)
    lines = []
    hists = {n: m for n, m in parsed.items()
             if m.get("type") == "histogram"}
    if hists:
        lines.append(f"{'histogram':<40} {'n':>8} {'mean':>10} "
                     f"{'p50':>10} {'p95':>10} {'p99':>10}")
        for name in sorted(hists):
            m = hists[name]
            n = m["count"]
            mean = (f"{m['total'] / n:.6g}" if n else "-")
            qs = [_metrics.hist_quantile(m, q)
                  for q in (0.5, 0.95, 0.99)]
            qs = ["-" if v is None else f"{v:.6g}" for v in qs]
            lines.append(f"{name:<40} {n:>8} {mean:>10} "
                         f"{qs[0]:>10} {qs[1]:>10} {qs[2]:>10}")
        lines.append("")
    others = {n: m for n, m in parsed.items()
              if m.get("type") != "histogram"}
    if others:
        lines.append(f"{'metric':<48} {'type':<10} value")
        for name in sorted(others):
            m = others[name]
            v = m["value"]
            v = int(v) if float(v).is_integer() else v
            lines.append(f"{name:<48} {m['type']:<10} {v}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_status_table(status: dict, health: dict) -> str:
    """The human summary an operator reads: one health line, one row
    per key, then service totals."""
    lines = []
    checks = health.get("checks") or {}
    bad = sorted(k for k, v in checks.items()
                 if isinstance(v, dict) and v.get("ok") is False)
    lines.append(
        ("READY" if health.get("ok") else "DEGRADED")
        + (f" — failing checks: {', '.join(bad)}" if bad else "")
        + f" ({len(checks)} check(s))")
    keys = status.get("keys") or {}
    if keys:
        hdr = (f"{'key':<18} {'seq':>5} {'pend':>6} {'state':<9} "
               f"{'verdict':<9} {'wal':>9} {'deltas':>7} {'sheds':>6} "
               f"notes")
        lines.append(hdr)
        for k in sorted(keys, key=str):
            row = keys[k]
            verdict = row.get("verdict")
            verdict = ("-" if verdict is None
                       else str(verdict).lower())
            acct = row.get("acct") or {}
            note = ""
            res = row.get("resilience")
            if res:
                note = (res if isinstance(res, str)
                        else res.get("reason") or res.get("site")
                        or "degraded")
            if row.get("error"):
                note = (note + " " if note else "") + "ERROR"
            lines.append(
                f"{str(k)[:18]:<18} {row.get('seq', 0):>5} "
                f"{row.get('pending_ops', 0):>6} "
                f"{row.get('state', '?'):<9} {verdict:<9} "
                f"{_fmt_bytes(row.get('wal_bytes')):>9} "
                f"{acct.get('deltas', 0):>7} {acct.get('sheds', 0):>6} "
                f"{note}")
    else:
        lines.append("(no keys admitted yet)")
    tenants = status.get("tenants") or {}
    if tenants:
        lines.append(
            f"{'tenant':<14} {'w':>3} {'pend':>6} {'bound':>7} "
            f"{'keys':>5} {'sheds':>6} {'wal':>9} {'ack_p99':>9} "
            f"{'verd_p99':>9}")
        for name in sorted(tenants):
            t = tenants[name]
            acct = t.get("acct") or {}
            fmt_q = lambda v: "-" if v is None else f"{v:.4g}"  # noqa: E731
            lines.append(
                f"{name[:14]:<14} {t.get('weight', 1):>3} "
                f"{t.get('pending_ops', 0):>6} "
                f"{t.get('pending_bound', 0):>7} "
                f"{t.get('keys', 0):>5} {acct.get('sheds', 0):>6} "
                f"{_fmt_bytes(t.get('wal_bytes')):>9} "
                f"{fmt_q(t.get('ack_p99')):>9} "
                f"{fmt_q(t.get('verdict_p99')):>9}")
    lines.append(
        f"pending_ops={status.get('pending_ops', 0)} "
        f"high_water={status.get('high_water', 0)} "
        f"global_bound={status.get('global_bound', 0)} "
        f"keys={len(keys)} live={status.get('keys_live', 0)}")
    return "\n".join(lines) + "\n"


def fetch_replica(addr: str, timeout: float = 5.0) -> dict:
    """One replica's ops view over HTTP — THE fetch path both
    ``jepsen status --addr`` and the fleet supervisor
    (``serve.fleet.FleetSupervisor``) consume, so the operator table
    and the automation read one surface::

        {"addr": ..., "state": "ready" | "degraded" | "unreachable",
         "health": {...}?, "status": {...}?, "error": ...?}

    ``degraded`` is an ANSWERED /healthz that says not-ok (the
    replica lives — its WAL still acks); ``unreachable`` is no
    answer at all (the supervisor's miss signal)."""
    base = f"http://{addr}"
    try:
        hcode, hbody = _fetch(base + "/healthz", timeout)
        _scode, sbody = _fetch(base + "/status", timeout)
        health = json.loads(hbody)
        status = json.loads(sbody)
    except (OSError, ValueError) as err:
        return {"addr": addr, "state": "unreachable",
                "error": str(err)}
    state = ("ready" if hcode == 200 and health.get("ok")
             else "degraded")
    return {"addr": addr, "state": state, "health": health,
            "status": status}


#: worst-of exit codes for a fleet view (also the JSON "exit" field)
_FLEET_EXIT = {"ready": 0, "degraded": 1, "unreachable": 2}


def _fleet_status(args) -> int:
    """The multi-replica view: one section per --addr, then a fleet
    summary. Exit: 2 if any replica is unreachable, else 1 if any is
    degraded, else 0 — worst-of, so a load balancer script reads one
    code for the whole fleet. ``--json`` emits the machine-readable
    document ``{"replicas": {addr: fetch_replica(addr)},
    "fleet": {"ready": n, "degraded": n, "unreachable": n,
    "exit": worst}}`` — the same surface the fleet supervisor and CI
    consume."""
    for addr in args.addr:
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            print(f"jepsen status: bad --addr {addr!r} (expected "
                  f"HOST:PORT)", file=sys.stderr)
            return 254
    docs = {addr: fetch_replica(addr, args.timeout)
            for addr in args.addr}
    by_state = {"ready": [], "degraded": [], "unreachable": []}
    for addr in args.addr:
        by_state[docs[addr]["state"]].append(addr)
    exit_code = max((_FLEET_EXIT[d["state"]] for d in docs.values()),
                    default=0)
    if args.json:
        print(json.dumps(
            {"replicas": docs,
             "fleet": {"ready": len(by_state["ready"]),
                       "degraded": len(by_state["degraded"]),
                       "unreachable": len(by_state["unreachable"]),
                       "replicas": len(args.addr),
                       "exit": exit_code}},
            indent=2, sort_keys=True, default=str))
        return exit_code
    for addr in args.addr:
        doc = docs[addr]
        print(f"== replica {addr} ==")
        if doc["state"] == "unreachable":
            print(f"UNREACHABLE: {doc.get('error')}\n")
            continue
        sys.stdout.write(render_status_table(doc["status"],
                                             doc["health"]))
        print()
    print(f"fleet: {len(by_state['ready'])} ready, "
          f"{len(by_state['degraded'])} degraded, "
          f"{len(by_state['unreachable'])} unreachable "
          f"of {len(args.addr)} replica(s)")
    return exit_code


def status_main(argv: Optional[Sequence[str]] = None) -> int:
    """``jepsen status`` — fetch the ops surface of a running serve
    instance and print the human table (or raw JSON / raw metrics).
    Exit: 0 ready, 1 degraded (/healthz 503), 2 unreachable,
    254 usage error — so shell automation reads health without
    parsing."""
    p = argparse.ArgumentParser(
        prog="jepsen status",
        description="fetch /status + /healthz from a running `jepsen "
                    "serve --checker --ops-port N` instance and print "
                    "the operator summary; exit 0 ready / 1 degraded "
                    "/ 2 unreachable")
    p.add_argument("--port", type=int, default=None,
                   help="ops endpoint port (default: "
                        "JEPSEN_TPU_OPS_PORT)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--addr", action="append", default=None,
                   metavar="HOST:PORT",
                   help="a replica's ops endpoint (repeatable): with "
                        ">= 1 --addr the command renders one table "
                        "per replica plus a fleet summary — the "
                        "multi-replica serve view (docs/streaming.md "
                        "'Replica scale-out'); exit 2 if any replica "
                        "is unreachable, else 1 if any degraded, "
                        "else 0")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-request timeout seconds")
    p.add_argument("--json", action="store_true",
                   help="print the raw {health, status} JSON instead "
                        "of the table")
    p.add_argument("--metrics", action="store_true",
                   help="print a /metrics summary instead of the "
                        "table: histograms as p50/p95/p99 (the "
                        "serve.ack_secs / serve.verdict_secs SLO "
                        "view), counters/gauges as-is")
    p.add_argument("--raw", action="store_true",
                   help="with --metrics: dump the raw Prometheus "
                        "text exposition instead of the quantile "
                        "summary")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        # same convention as `jepsen probe`: --help exits 0, misuse
        # maps to the CLI's bad-args code instead of colliding with
        # the health exit codes
        return 0 if e.code in (0, None) else 254
    if args.addr:
        return _fleet_status(args)
    port = resolve_ops_port(args.port)
    if port is None:
        print("jepsen status: no port — pass --port, --addr, or set "
              "JEPSEN_TPU_OPS_PORT", file=sys.stderr)
        return 254
    base = f"http://{args.host}:{port}"
    try:
        if args.metrics:
            code, body = _fetch(base + "/metrics", args.timeout)
            if code != 200:
                print(f"jepsen status: {base}/metrics answered "
                      f"{code} — not a jepsen ops endpoint?",
                      file=sys.stderr)
                return 2
            sys.stdout.write(body if args.raw
                             else render_metrics_summary(body))
            return 0
        hcode, hbody = _fetch(base + "/healthz", args.timeout)
        _scode, sbody = _fetch(base + "/status", args.timeout)
    except OSError as err:
        print(f"jepsen status: {base} unreachable: {err}",
              file=sys.stderr)
        return 2
    try:
        health = json.loads(hbody)
        status = json.loads(sbody)
    except ValueError:
        # an HTTP server that isn't the ops endpoint (e.g. the web
        # results browser on serve's default port) answers HTML — a
        # wrong-target mistake, not "degraded": keep the exit-code
        # contract honest
        print(f"jepsen status: {base} did not answer JSON — not a "
              f"jepsen ops endpoint?", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"health": health, "status": status},
                         indent=2, sort_keys=True, default=str))
    else:
        sys.stdout.write(render_status_table(status, health))
    return 0 if hcode == 200 and health.get("ok") else 1


if __name__ == "__main__":
    sys.exit(status_main())
