"""Degradation paths that preserve verdicts when device dispatch dies.

The degradation contract (docs/resilience.md): a device failure
mid-check must never lose work or flip a verdict. Two shapes:

  host_check_encoded   re-check a whole encoded key on the host WGL
                       engine — correct but orders of magnitude
                       slower, so every result is tagged with a
                       structured ``resilience`` note naming what
                       degraded and why.
  host_resume          resume a sparse search from its
                       :class:`~jepsen_tpu.parallel.engine.FrontierCheckpoint`
                       on the host: the checkpointed frontier is the
                       COMPLETE set of reachable configurations at
                       event ``cp.event_index`` (the device dedupe
                       preserves completeness), so the history is
                       valid iff some frontier row linearizes the
                       remaining suffix. Each row seeds a host WGL
                       search over exactly the window machinery
                       ``engine.extract_final_paths`` already uses —
                       device-side progress is kept, only the suffix
                       re-runs on host.

Both count ``resilience.recovered_keys`` — the gauge of verdicts that
survived a device failure.

JAX-free at module scope; engine/wgl imports are lazy (this module is
imported by the engines' exception paths and must never re-enter a
wedged runtime).
"""

from __future__ import annotations

import logging
from typing import Optional

from jepsen_tpu import obs

_log = logging.getLogger(__name__)

# past this many live frontier rows, per-seed host searches would cost
# more than one whole-history WGL pass — degrade to that instead
MAX_RESUME_SEEDS = 128


def resilience_note(site: str, reason: str, degraded: str,
                    backend: Optional[str] = None, **extra) -> dict:
    """The structured ``resilience`` annotation results carry when a
    degradation path ran: what degraded, where, and why."""
    note = {"degraded": degraded, "site": site, "reason": reason}
    if backend:
        note["backend"] = backend
    note.update(extra)
    return note


def host_check_encoded(model, e, site: str, reason: str,
                       backend: Optional[str] = None) -> dict:
    """Whole-key host WGL check of an encoded history — the terminal
    degradation tier. The verdict is authoritative (WGL searches
    exhaustively); the result says loudly that the device path died."""
    from jepsen_tpu.checker import wgl
    obs.counter("resilience.recovered_keys").inc()
    _log.warning(
        "device dispatch failed at site %r (%s) — re-checking the key "
        "on the host WGL engine; the verdict is preserved but the "
        "device path is broken", site, reason)
    n_history = (max(c.complete_index for c in e.calls) + 1
                 if e.calls else 0)
    with obs.span("resilience.host_check", site=site):
        r = wgl.check_calls(model, list(e.calls), n_history)
    r["analyzer"] = "wgl"
    r["resilience"] = resilience_note(site, reason, "host-wgl", backend)
    return r


def host_resume(model, e, cp, site: str, reason: str,
                backend: Optional[str] = None,
                max_seeds: int = MAX_RESUME_SEEDS) -> dict:
    """Resume a checkpointed sparse search on the host (module
    docstring). Falls back to :func:`host_check_encoded` when the
    frontier can't seed a host search (no unpack_state, too many live
    rows, or indecisive seed searches) — slower, never wrong."""
    import numpy as np

    from jepsen_tpu import models as model_ns
    from jepsen_tpu.checker import wgl
    from jepsen_tpu.parallel import engine

    start_ev = int(cp.event_index)
    if start_ev <= 0:
        return host_check_encoded(model, e, site, reason, backend)
    if not cp.ok:
        # the device already decided before the failure: the verdict
        # is final, only the counterexample extraction remains
        r = {"valid?": False, "max-frontier": cp.maxf,
             "capacity": cp.capacity, "dedupe": "resumed",
             "configs-stepped": cp.stepped}
        r.update(engine._fail_op(e, int(cp.fail_r)))
        engine.apply_final_paths(r, model, e)
        r["resilience"] = resilience_note(
            site, reason, "checkpoint-verdict", backend,
            **{"resumed-from-event": start_ev})
        obs.counter("resilience.recovered_keys").inc()
        return r
    spec = e.spec or model_ns.pack_spec(model, e.intern)
    live_idx = np.nonzero(np.asarray(cp.live))[0]
    if (spec is None or spec.unpack_state is None
            or len(live_idx) > max_seeds):
        return host_check_encoded(model, e, site, reason, backend)

    # recovered_keys counts once per key, at whichever path actually
    # ships the verdict — the indecisive fallback below delegates to
    # host_check_encoded, which counts for itself
    occupants = engine._slot_occupants_before(e, start_ev)
    boundary = e.calls[int(e.ret_call[start_ev])].complete_index
    last_idx = max(c.complete_index for c in e.calls)
    st = np.asarray(cp.st)
    ml = np.asarray(cp.ml)
    mh = np.asarray(cp.mh)
    fail_report = None
    indecisive = False
    with obs.span("resilience.host_resume", site=site,
                  seeds=len(live_idx), from_event=start_ev):
        for i in live_idx:
            mask = int(ml[i]) | (int(mh[i]) << 32)
            linearized = frozenset(
                cid for s, cid in occupants.items() if (mask >> s) & 1)
            seed_model = spec.unpack_state(int(st[i]), e.intern)
            cs = engine._window_calls(e.calls, boundary, last_idx,
                                      linearized)
            host = wgl.check_calls(seed_model, cs, last_idx + 1)
            if host.get("valid?") is True:
                # some reachable configuration linearizes the suffix:
                # the whole history is valid — device progress kept
                obs.counter("resilience.recovered_keys").inc()
                return {
                    "valid?": True, "max-frontier": cp.maxf,
                    "capacity": cp.capacity,
                    "configs-stepped": cp.stepped,
                    "resilience": resilience_note(
                        site, reason, "host-resume", backend,
                        **{"resumed-from-event": start_ev,
                           "seeds": int(len(live_idx))}),
                }
            if host.get("valid?") is False:
                fail_report = fail_report or host
            else:
                indecisive = True
    if indecisive or fail_report is None:
        # a seed search that couldn't decide means the seeded window
        # machinery may be the wrong side — never ship a verdict off
        # an indecisive resume
        return host_check_encoded(model, e, site,
                                  reason + "; host resume indecisive",
                                  backend)
    # every reachable configuration fails to linearize the suffix:
    # invalid, with the host's consistent failure report
    obs.counter("resilience.recovered_keys").inc()
    r = {"valid?": False, "max-frontier": cp.maxf,
         "capacity": cp.capacity, "configs-stepped": cp.stepped,
         "final-paths": fail_report.get("final-paths", []),
         "configs": fail_report.get("configs", []),
         "resilience": resilience_note(
             site, reason, "host-resume", backend,
             **{"resumed-from-event": start_ev,
                "seeds": int(len(live_idx))})}
    if fail_report.get("op"):
        r["op"] = fail_report["op"]
    return r
