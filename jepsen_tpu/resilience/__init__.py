"""Resilient device dispatch: fault injection, watchdog supervision,
circuit breaking, and checkpointed degradation (docs/resilience.md).

The r05 outage proved the stack's weakest layer is the runtime
boundary: a wedged PJRT client blocks forever inside
``make_c_api_client`` with no Python-level signal, and nothing
in-process could detect, contain, or recover from it. This package is
the containment layer between the checker engines and JAX:

  faults       deterministic fault injector behind the validated
               ``JEPSEN_TPU_FAULTS`` spec — CI drives every
               degradation path on CPU
  supervisor   every device dispatch site runs through
               ``dispatch(site, thunk, backend=...)``: watchdog-
               bounded wait (``DispatchWedged`` instead of a hung
               process), breaker bookkeeping, transient-failure
               retries; a test-pinned near-zero-overhead passthrough
               when nothing is active
  breaker      per-backend circuit breaker (closed -> open on
               consecutive failures, exponential backoff with jitter,
               half-open recovery probing via the ``jepsen probe``
               subprocess contract)
  recovery     verdict-preserving degradation: whole-key host WGL
               re-checks and FrontierCheckpoint host resumes, each
               tagged with a structured ``resilience`` result note

Import-safe: no JAX anywhere at module scope (the same contract as
envflags and obs — the whole point is surviving a wedged runtime).
"""

from jepsen_tpu.resilience import breaker, faults, recovery, supervisor  # noqa: F401
from jepsen_tpu.resilience.breaker import breaker_for  # noqa: F401
from jepsen_tpu.resilience.faults import (  # noqa: F401
    FaultInjected, FaultSpecError, InjectedCrash, TransientFault,
)
from jepsen_tpu.resilience.supervisor import (  # noqa: F401
    DISPATCH_FAILURES, DeviceUnavailable, DispatchWedged, dispatch,
)


def reset():
    """Test isolation: drop the fault plan and every breaker."""
    faults.reset()
    breaker.reset()
