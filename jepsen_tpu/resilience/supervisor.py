"""Watchdog-supervised device dispatch — the seam between the engines
and JAX.

Every device dispatch site in the checker (bitdense single/batch,
sparse engine search, sharded tiers, pipeline chunk dispatch,
host->device transfers) runs through :func:`dispatch`. Three jobs:

  1. **Fault injection** (``resilience.faults``): the active
     JEPSEN_TPU_FAULTS plan can wedge, crash, or transiently fail the
     call — deterministically, so CI drives every degradation path on
     CPU.
  2. **Watchdog** (``JEPSEN_TPU_WATCHDOG=<secs>``): the dispatch runs
     on a worker thread with a bounded join. A call past the bound
     raises :class:`DispatchWedged` — the r05 hang-forever signature
     (a wedged PJRT runtime blocks in C with no Python-level signal,
     see jepsen_tpu/probe.py) becomes a structured verdict instead of
     a hung process. A REALLY wedged call cannot be cancelled; its
     daemon thread is abandoned (the documented, bounded cost — the
     breaker stops the pile-up after `threshold` of them).
  3. **Circuit breaker** (``resilience.breaker``): successes and
     failures are recorded per backend; dispatch against an open
     breaker raises :class:`DeviceUnavailable` WITHOUT touching the
     runtime, and the half-open recovery probe runs in a subprocess
     (``jepsen_tpu.probe``) so the parent never does either.

Transient failures (``flaky`` faults, real device exceptions) are
retried up to ``JEPSEN_TPU_DISPATCH_RETRIES`` times while the breaker
stays closed, under a ``resilience.retry`` span. Wedges and injected
crashes are NOT retried here — re-dispatching against a wedged
runtime piles up stuck threads, and crash recovery belongs to the
callers' degradation contracts (host fallback / checkpoint resume in
``resilience.recovery``).

The no-op contract: with no fault plan, no watchdog, and every breaker
closed, :func:`dispatch` is a passthrough — two raw env reads, one
set-truthiness check, then the call (test-pinned per-call budget,
same standard as the disabled tracer). The engines therefore route
every dispatch through it unconditionally; the
``concurrency-unsupervised-dispatch`` lint rule enforces that
mechanically.

Import-safe: no JAX at module scope (the same contract as envflags and
obs — a wedged runtime must not turn importing an engine into a hang).
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Callable, Optional

from jepsen_tpu import envflags
from jepsen_tpu import obs
from jepsen_tpu.resilience import breaker as breaker_mod
from jepsen_tpu.resilience import faults

_log = logging.getLogger(__name__)

_SITES = frozenset(faults.SITES)   # O(1) membership on the fast path

# how long an injected wedge's worker waits before self-releasing even
# if nobody calls release — belt and braces against leaked threads
_WEDGE_SELF_RELEASE_SECS = 60.0
# watchdog bound used for an injected wedge when none is configured:
# the injected hang is fake (it blocks on an Event we control), so a
# short bound keeps fault-matrix tests fast without configuring env
_INJECTED_WEDGE_TIMEOUT = 0.2


class DispatchWedged(RuntimeError):
    """A supervised dispatch exceeded its watchdog bound — the r05
    make_c_api_client signature, as a structured verdict."""

    def __init__(self, site: str, timeout: float,
                 backend: Optional[str] = None):
        super().__init__(
            f"device dispatch at site {site!r} exceeded the "
            f"{timeout:.1f}s watchdog bound"
            + (f" (backend {backend!r})" if backend else ""))
        self.site = site
        self.timeout = timeout
        self.backend = backend


class DeviceUnavailable(RuntimeError):
    """Dispatch refused or given up on for a backend — open breaker,
    or a dispatch failure the engines converted into a degradation
    signal. Carries enough structure for result annotations."""

    def __init__(self, site: str, reason: str,
                 backend: Optional[str] = None, cause=None):
        super().__init__(f"device unavailable at site {site!r}: "
                         f"{reason}")
        self.site = site
        self.reason = reason
        self.backend = backend
        self.cause = cause


# the exception classes callers degrade on (host fallback / checkpoint
# resume) rather than treat as programming errors
DISPATCH_FAILURES = (DispatchWedged, faults.InjectedCrash,
                     DeviceUnavailable)


def _resolve_watchdog() -> Optional[float]:
    """JEPSEN_TPU_WATCHDOG seconds; unset or 0 -> None (off)."""
    v = envflags.env_float("JEPSEN_TPU_WATCHDOG", default=None,
                           min_value=0.0, what="watchdog seconds")
    return v if v else None


def _resolve_retries() -> int:
    return envflags.env_int("JEPSEN_TPU_DISPATCH_RETRIES", default=1,
                            min_value=0, what="dispatch retries")


def active(backend: Optional[str] = None) -> bool:
    """Whether the full supervision path is needed. This is the no-op
    fast path's whole cost: three raw env reads + one set check. A set
    JEPSEN_TPU_DISPATCH_RETRIES activates supervision too — an
    operator who configured retries must get retries (and the breaker
    bookkeeping that rides the slow path), not a silent passthrough."""
    return (faults.active()
            or envflags.env_raw("JEPSEN_TPU_WATCHDOG") not in (None, "0")
            or envflags.env_raw("JEPSEN_TPU_DISPATCH_RETRIES") is not None
            or breaker_mod.any_tripped())


def _run_watchdogged(thunk: Callable, timeout: float, site: str,
                     backend: Optional[str]):
    """Run `thunk` on a daemon worker with a bounded join."""
    box: dict = {}

    def worker():
        try:
            box["value"] = thunk()
        except BaseException:  # noqa: BLE001 — re-raised in the parent
            box["exc"] = sys.exc_info()[1]

    t = threading.Thread(target=worker, daemon=True,
                         name=f"jepsen-dispatch-{site}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        obs.counter("resilience.watchdog_kills").inc()
        _log.warning(
            "device dispatch at site %r exceeded the %.1fs watchdog "
            "bound — abandoning the worker thread (the r05 wedge "
            "signature; see docs/resilience.md)", site, timeout)
        # postmortem evidence even with tracing off: the armed flight
        # recorder (JEPSEN_TPU_FLIGHT_RECORDER) dumps its span ring +
        # metric delta; unarmed, this is a single None check
        obs.flight_dump(f"dispatch-wedged-{site}")
        raise DispatchWedged(site, timeout, backend)
    if "exc" in box:
        raise box["exc"]
    return box["value"]


def _injected_wedge(plan_event: threading.Event, site: str,
                    timeout: float, backend: Optional[str]):
    """Simulate a never-returning dispatch: a worker blocks on the
    plan's wedge event, the watchdog times out, then the event is set
    so the worker exits instead of leaking (a real wedge can't be
    released — this seam exists precisely so tests don't need one)."""
    try:
        _run_watchdogged(
            lambda: plan_event.wait(timeout + _WEDGE_SELF_RELEASE_SECS),
            timeout, site, backend)
    finally:
        plan_event.set()
    # unreachable unless the event was already set (e.g. a concurrent
    # wedge released first): still honor the wedge contract
    raise DispatchWedged(site, timeout, backend)


def dispatch(site: str, thunk: Callable, backend: Optional[str] = None,
             watchdog: Optional[float] = None,
             retries: Optional[int] = None):
    """Run `thunk` (a zero-arg device-dispatch closure that
    MATERIALIZES its result — async dispatch must surface failures and
    hangs inside the supervised window) through the supervision seam.

    Raises:
      DeviceUnavailable   the backend's breaker is open
      DispatchWedged      watchdog bound exceeded (injected or real)
      InjectedCrash       a `raise` fault fired
      (original error)    a real/transient failure that survived the
                          retry budget
    """
    if site not in _SITES:
        raise ValueError(f"unknown dispatch site {site!r} "
                         f"(expected one of {faults.SITES})")
    if watchdog is None and retries is None and not active(backend):
        return thunk()

    wd = watchdog if watchdog is not None else _resolve_watchdog()
    budget = retries if retries is not None else _resolve_retries()
    br = breaker_mod.breaker_for(backend) if backend else None
    attempt = 0
    while True:
        if br is not None:
            allowed, reason = br.allow()
            if not allowed:
                raise DeviceUnavailable(site, reason, backend)
        # attempts after the first run under a retry span, so the
        # retry path is visible in traces of a degraded run
        ctx = (obs.span("resilience.retry", site=site, attempt=attempt)
               if attempt > 0 else _NULL_CTX)
        try:
            with ctx:
                return _one_attempt(site, thunk, backend, wd, br)
        except envflags.EnvFlagError:
            # a malformed JEPSEN_TPU_* value (fault spec, knob) is a
            # CONFIGURATION error, not a dispatch failure: it must
            # fail loudly and untouched — never retried, never
            # recorded on the breaker, never degraded to the host
            # path (a degrade here would silently run zero faults
            # while the operator believes the plan is armed)
            raise
        except (DispatchWedged, faults.InjectedCrash) as err:
            # wedges: re-dispatching a wedged runtime piles up stuck
            # threads. Injected crashes: recovery belongs to the
            # callers' degradation paths, and retrying would hide the
            # very path the fault exists to exercise.
            if br is not None:
                br.record_failure(str(err))
            raise
        except DeviceUnavailable:
            raise
        except Exception as err:  # noqa: BLE001 — transient or real
            blocked = br is not None and br.state != breaker_mod.CLOSED
            if attempt >= budget or blocked:
                # ONE breaker failure per failing dispatch CALL, not
                # per attempt: threshold N means "N failed dispatches",
                # and a transient that recovers within its budget never
                # counts at all — a deterministic non-runtime error
                # (compile bug, shape bug) therefore needs N separate
                # failing calls to open the breaker, not N/retries
                if br is not None:
                    br.record_failure(f"{type(err).__name__}: {err}")
                # budget exhausted (or the breaker tripped mid-retry):
                # surface as DeviceUnavailable so the callers'
                # degradation contract catches it — a persistent real
                # device error (the dying-chip XlaRuntimeError mode)
                # must degrade to the host path exactly like an
                # injected crash, not crash the check. The original
                # error rides `cause`/`__cause__` for diagnosis.
                raise DeviceUnavailable(
                    site,
                    f"dispatch failed after {attempt + 1} attempt(s): "
                    f"{type(err).__name__}: {err}",
                    backend, cause=err) from err
            attempt += 1
            obs.counter("resilience.retries").inc()


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CTX = _NullCtx()


def _one_attempt(site: str, thunk: Callable, backend: Optional[str],
                 wd: Optional[float], br):
    """One supervised attempt: fault decision, then the (possibly
    watchdogged) call; success recorded on the breaker."""
    rule = faults.decide(site)
    if rule is not None:
        obs.counter("resilience.faults_injected").inc()
        obs.counter(f"resilience.faults_injected.{site}").inc()
        if rule.kind == "wedge":
            plan = faults.active_plan()
            _injected_wedge(
                plan.wedge_event if plan is not None
                else threading.Event(),
                site, wd or _INJECTED_WEDGE_TIMEOUT, backend)
        elif rule.kind == "raise":
            raise faults.InjectedCrash(site, rule)
        elif rule.kind == "slow":
            # deterministic latency: the dispatch still runs and still
            # answers correctly — it just takes rule.ms longer. The
            # sleep rides INSIDE the watchdogged window, so a watchdog
            # bound below the injected delay fires exactly as it would
            # on a real slow device (a too-slow dispatch IS a wedge).
            delay, inner = rule.ms / 1000.0, thunk
            thunk = lambda: (time.sleep(delay), inner())[1]  # noqa: E731
        else:
            raise faults.TransientFault(site, rule)
    r = (_run_watchdogged(thunk, wd, site, backend) if wd
         else thunk())
    if br is not None:
        br.record_success()
    return r
