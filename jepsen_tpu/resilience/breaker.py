"""Per-backend circuit breakers for device dispatch.

The r05 wedge cost more than the first hung dispatch: rounds 3-5 kept
re-dispatching against the dead runtime, each attempt paying the full
hang-and-kill cycle, and nothing in-process remembered that the
backend was down. The breaker is that memory:

    closed     dispatch flows; consecutive failures are counted
    open       after `threshold` consecutive failures: dispatch is
               refused outright (DeviceUnavailable at the supervisor)
               until an exponential backoff (base doubling per
               re-open, deterministic jitter, capped) elapses
    half-open  one caller per window runs the recovery probe —
               ``jepsen_tpu.probe``'s subprocess ``jax.devices()``
               check, so the parent process NEVER touches the possibly
               wedged runtime directly (the probe child takes the
               hang, exactly as the r05 runbook did by hand). A
               healthy probe closes the breaker; anything else
               re-opens it with a doubled backoff.

Clock, probe, and jitter are injectable (fake-clock lifecycle tests);
defaults come from the validated ``JEPSEN_TPU_BREAKER_*`` flags.
State changes are mirrored to the ``resilience.breaker.<backend>.state``
gauge (0 closed / 1 half-open / 2 open) and the
``resilience.breaker.opens`` counter, so a trace of a degraded run
shows when and why dispatch stopped.

Import-safe: no JAX — the probe runs in a subprocess by design.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from jepsen_tpu import envflags
from jepsen_tpu import obs

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

BACKOFF_CAP_SECS = 60.0
JITTER_FRAC = 0.1
PROBE_TIMEOUT_SECS = 30.0


def _default_probe() -> bool:
    """The half-open recovery check: the same subprocess
    ``jax.devices()`` contract as ``jepsen probe`` (probe_json), so
    external automation and the breaker read one health surface."""
    from jepsen_tpu import probe
    r = probe.probe_json(timeout=PROBE_TIMEOUT_SECS, retries=1)
    return r["verdict"] == "healthy"


def _resolve_threshold() -> int:
    return envflags.env_int("JEPSEN_TPU_BREAKER_THRESHOLD", default=3,
                            min_value=1, what="breaker threshold")


def _resolve_backoff() -> float:
    return envflags.env_float("JEPSEN_TPU_BREAKER_BACKOFF", default=1.0,
                              min_value=0.0, what="breaker backoff")


class CircuitBreaker:
    """One backend's breaker. Thread-safe; all timing through the
    injected clock so the open/half-open/close lifecycle is testable
    without sleeping."""

    def __init__(self, backend: str,
                 threshold: Optional[int] = None,
                 backoff_base: Optional[float] = None,
                 backoff_cap: float = BACKOFF_CAP_SECS,
                 clock: Callable[[], float] = time.monotonic,
                 probe: Optional[Callable[[], bool]] = None,
                 rng: Optional[random.Random] = None,
                 track_global: bool = True):
        self.backend = backend
        # track_global=False keeps this breaker out of the module's
        # _tripped fast-path set: a FLEET breaker watching a PEER
        # replica's health must not push this process's own device
        # dispatches onto the slow supervised path (serve.fleet)
        self._track_global = track_global
        self.threshold = (threshold if threshold is not None
                          else _resolve_threshold())
        self.backoff_base = (backoff_base if backoff_base is not None
                             else _resolve_backoff())
        self.backoff_cap = backoff_cap
        self.clock = clock
        self.probe = probe if probe is not None else _default_probe
        # deterministic jitter: seeded per backend name (crc32, not
        # hash() — str hashing is per-process randomized), not wall
        # clock, so a reproduced run reproduces its backoff schedule
        import zlib
        self.rng = rng if rng is not None else random.Random(
            zlib.crc32(("jepsen-breaker:" + backend).encode()))
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opens = 0
        self._open_until = 0.0
        self._last_reason = ""
        self._gauge()

    # -- introspection

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {"backend": self.backend, "state": self._state,
                    "failures": self._failures, "opens": self._opens,
                    "open_until": self._open_until,
                    "reason": self._last_reason}

    def _gauge(self):
        obs.gauge(f"resilience.breaker.{self.backend}.state").set(
            _STATE_GAUGE[self._state])
        # Perfetto counter track: breaker flips render as steps on the
        # trace timeline, aligned with the dispatch spans that caused
        # them (no-op with tracing off)
        obs.counter_sample(f"resilience.breaker.{self.backend}.state",
                           _STATE_GAUGE[self._state])

    # -- transitions

    def _backoff(self) -> float:
        """Exponential in the re-open count, jittered, capped."""
        base = self.backoff_base * (2 ** max(0, self._opens - 1))
        jitter = 1.0 + JITTER_FRAC * self.rng.random()
        return min(base * jitter, self.backoff_cap)

    def _open_locked(self):
        """The one open transition (callers hold the lock): state,
        re-open count, backoff window, counter, gauge. Callers dump
        the flight recorder AFTER releasing the lock — the dump is
        file I/O, and a hung filesystem (plausible on the same sick
        node whose device just failed) must not wedge every dispatch
        blocked on this breaker's lock."""
        self._state = OPEN
        self._opens += 1
        self._open_until = self.clock() + self._backoff()
        obs.counter("resilience.breaker.opens").inc()
        self._gauge()

    def _note(self, tripped: bool) -> None:
        if self._track_global:
            _note_state(self.backend, tripped)

    def record_failure(self, reason: str = ""):
        opened = False
        with self._lock:
            self._failures += 1
            self._last_reason = reason
            if self._state == HALF_OPEN \
                    or (self._state != OPEN
                        and self._failures >= self.threshold):
                # threshold reached — or the probed dispatch itself
                # failed during half-open, which re-opens immediately
                self._open_locked()
                opened = True
            else:
                self._gauge()
            tripped = self._state != CLOSED
        self._note(tripped)
        if opened:
            # an open breaker is exactly the moment a postmortem wants
            # the last spans + metric deltas; a None check when
            # unarmed, and the dump cap bounds a flapping breaker
            obs.flight_dump(f"breaker-open-{self.backend}")

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._opens = 0   # incident over: the next one starts at
            self._state = CLOSED   # the base backoff, not an escalated one
            self._last_reason = ""
            self._gauge()
        self._note(False)

    def allow(self) -> Tuple[bool, str]:
        """Whether a dispatch may proceed now. Closed -> yes. Open ->
        no until the backoff elapses; then ONE caller per window runs
        the recovery probe (half-open): healthy closes the breaker and
        admits the dispatch, anything else re-opens with a doubled
        backoff."""
        with self._lock:
            if self._state == CLOSED:
                return True, ""
            if self._state == HALF_OPEN:
                # another caller's recovery probe is in flight (a 30s
                # subprocess in production): refuse rather than
                # stampede the recovering runtime with N probes
                return False, (
                    f"circuit breaker half-open for backend "
                    f"{self.backend!r}: recovery probe in flight")
            if self.clock() < self._open_until:
                return False, (
                    f"circuit breaker open for backend "
                    f"{self.backend!r} (last failure: "
                    f"{self._last_reason or '?'}; retry in "
                    f"{max(0.0, self._open_until - self.clock()):.1f}s)")
            # backoff elapsed: this caller probes; the state flips to
            # half-open so concurrent callers keep getting refused
            # rather than stampeding the recovering runtime
            self._state = HALF_OPEN
            self._gauge()
            probe = self.probe
        self._note(True)
        try:
            healthy = bool(probe())
        except Exception:  # noqa: BLE001 — a crashed probe is not health
            healthy = False
        with self._lock:
            if healthy:
                self._state = CLOSED
                self._failures = 0
                self._opens = 0   # incident over (record_success's rule):
                self._gauge()     # backoff escalation must not leak into
            else:                 # the NEXT, unrelated incident
                self._open_locked()
        self._note(not healthy)
        if healthy:
            return True, ""
        obs.flight_dump(f"breaker-open-{self.backend}")
        return False, (f"circuit breaker re-opened for backend "
                       f"{self.backend!r}: recovery probe unhealthy")


# ------------------------------------------------------------ registry

_registry_lock = threading.Lock()
_breakers: Dict[str, CircuitBreaker] = {}
# backends currently NOT closed — the supervisor's fast-path check is
# a single truthiness read of this set, so a fully healthy process
# never pays more than that
_tripped: set = set()


def _note_state(backend: str, tripped: bool):
    with _registry_lock:
        if tripped:
            _tripped.add(backend)
        else:
            _tripped.discard(backend)


def breaker_for(backend: str, **kw) -> CircuitBreaker:
    """The process breaker for `backend` (created on first use)."""
    backend = backend or "default"
    with _registry_lock:
        br = _breakers.get(backend)
        if br is None:
            br = _breakers[backend] = CircuitBreaker(backend, **kw)
        return br


def any_tripped() -> bool:
    """Cheap fast-path probe: is any backend's breaker not closed?"""
    return bool(_tripped)


def snapshots() -> list:
    """Every registered breaker's :meth:`CircuitBreaker.snapshot` —
    the /healthz readiness check enumerates these (an empty list means
    no dispatch has needed a breaker yet: healthy)."""
    with _registry_lock:
        brs = list(_breakers.values())
    return [b.snapshot() for b in brs]


def reset():
    """Drop every breaker (test isolation)."""
    with _registry_lock:
        _breakers.clear()
        _tripped.clear()
