"""Deterministic fault injection behind ``JEPSEN_TPU_FAULTS``.

PAPER.md's subject is a framework that exists to inject faults into
systems under test and verify they stay correct; the r05 outage showed
the checker itself had no way to practice that discipline on its own
weakest layer, the device-runtime boundary. This module is the seam:
a validated spec drives deterministic fault firings at the supervised
dispatch sites (``resilience.supervisor``), so CI can walk every
degradation path on CPU without a chip, an outage, or a race.

Spec grammar (comma-separated rules)::

    JEPSEN_TPU_FAULTS = <kind>@<site>[:<count>][,<rule>...]

    kind   wedge   the dispatch never returns (the r05 PJRT
                   make_c_api_client signature); surfaces as
                   DispatchWedged via the supervisor's watchdog
           raise   the dispatch raises (a crashed device program);
                   surfaces as InjectedCrash — not retried, the
                   callers' degradation paths take over
           flaky   a transient failure (TransientFault); the
                   supervisor retries it within the breaker budget
           slow    deterministic latency: the dispatch sleeps the
                   rule's milliseconds FIRST, then runs normally —
                   the slow-device scenario fairness and soak tests
                   need (a degraded chip that still answers); its
                   count arg is the delay (``slow@search:50`` = 50 ms,
                   ``ms=50`` likewise; default 25), and a configured
                   watchdog below the delay still fires (a slow
                   dispatch past the bound IS a wedge, by definition)
    site   dispatch   bitdense single/batch device program
           transfer   host->device placement (pad/place)
           search     sparse-engine device search
           sharded    frontier-sharded tier dispatch
           pipeline   pipelined-executor chunk dispatch
           child      bench child-process startup (the old
                      JEPSEN_TPU_TEST_WEDGE seam)
    count  N         shorthand for n=N
           n=N       fire on the first N invocations of the site
           every=K   fire on every K-th invocation (K, 2K, ...)
           (absent)  fire on every invocation

Validation is strict: an unknown kind/site/argument raises
:class:`FaultSpecError` (an ``envflags.EnvFlagError``) at the first
read — a typo'd fault plan must never silently test nothing
(satellite contract: bad specs raise, never no-op). The legacy
``JEPSEN_TPU_TEST_WEDGE=1`` bench seam maps onto an implicit
``wedge@child`` rule, so the old flag keeps working while every
consumer reads one plan.

Deterministic by construction: firing depends only on the per-site
invocation count, never on time or randomness, so a fault-matrix test
run is exactly reproducible.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from jepsen_tpu import envflags

KINDS = ("wedge", "raise", "flaky", "slow")
SITES = ("dispatch", "transfer", "search", "sharded", "pipeline",
         "child")

#: slow@<site> with no [:ms] — small enough for a fast test matrix,
#: large enough to register on the SLO histograms
DEFAULT_SLOW_MS = 25


class FaultSpecError(envflags.EnvFlagError):
    """A JEPSEN_TPU_FAULTS spec outside the grammar above."""


class FaultInjected(RuntimeError):
    """Base of the injected-failure exceptions (site + rule attached)."""

    def __init__(self, site: str, rule: "FaultRule"):
        super().__init__(f"injected {rule.kind} fault at site "
                         f"{site!r} ({rule.spec})")
        self.site = site
        self.rule = rule


class InjectedCrash(FaultInjected):
    """``raise@<site>`` — a crashed dispatch; not retried."""


class TransientFault(FaultInjected):
    """``flaky@<site>`` — a transient failure; the supervisor retries."""


@dataclass(frozen=True)
class FaultRule:
    kind: str
    site: str
    n: Optional[int] = None       # fire on the first n invocations
    every: Optional[int] = None   # fire on every k-th invocation
    spec: str = ""                # the raw rule text, for messages
    ms: int = DEFAULT_SLOW_MS     # slow-kind delay (milliseconds)

    def fires(self, count: int) -> bool:
        """Whether this rule fires on the count-th (1-based)
        invocation of its site."""
        if self.every is not None:
            return count % self.every == 0
        if self.n is not None:
            return count <= self.n
        return True


def parse_spec(raw: str) -> List[FaultRule]:
    """Parse a JEPSEN_TPU_FAULTS value into rules, strictly."""
    rules: List[FaultRule] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        head, sep, arg = part.partition(":")
        if "@" not in head:
            raise FaultSpecError(
                f"JEPSEN_TPU_FAULTS rule {part!r}: expected "
                f"<kind>@<site>[:<count>]")
        kind, _, site = head.partition("@")
        kind, site = kind.strip(), site.strip()
        if kind not in KINDS:
            raise FaultSpecError(
                f"JEPSEN_TPU_FAULTS rule {part!r}: unknown fault kind "
                f"{kind!r} (expected one of {KINDS})")
        if site not in SITES:
            raise FaultSpecError(
                f"JEPSEN_TPU_FAULTS rule {part!r}: unknown site "
                f"{site!r} (expected one of {SITES})")
        if site == "child" and kind != "wedge":
            # the bench child consults the seam once at startup and
            # only implements the wedge (the r05 signature); accepting
            # raise/flaky/slow here would be a spec that silently
            # tests nothing — the exact failure validation exists to
            # prevent
            raise FaultSpecError(
                f"JEPSEN_TPU_FAULTS rule {part!r}: site 'child' only "
                f"supports kind 'wedge' (the bench child-startup "
                f"seam)")
        n = every = None
        ms = DEFAULT_SLOW_MS
        if sep:
            arg = arg.strip()
            key, eq, val = arg.partition("=")
            if not eq:
                # a bare integer is the kind's natural argument:
                # milliseconds for slow, first-N for everything else
                key, val = ("ms" if kind == "slow" else "n"), arg
            key = key.strip()
            try:
                ival = int(val.strip())
            except ValueError:
                ival = -1
            if kind == "slow":
                if key != "ms" or ival < 1:
                    raise FaultSpecError(
                        f"JEPSEN_TPU_FAULTS rule {part!r}: bad slow "
                        f"delay {arg!r} (expected MS or ms=MS with a "
                        f"positive integer — slow fires on every "
                        f"invocation; n=/every= do not apply)")
                ms = ival
            elif key not in ("n", "every") or ival < 1:
                raise FaultSpecError(
                    f"JEPSEN_TPU_FAULTS rule {part!r}: bad count "
                    f"{arg!r} (expected N, n=N, or every=K with a "
                    f"positive integer)")
            elif key == "n":
                n = ival
            else:
                every = ival
        rules.append(FaultRule(kind, site, n, every, part, ms))
    return rules


class FaultPlan:
    """A parsed spec plus per-site invocation counters (thread-safe).

    ``decide(site)`` counts one invocation and returns the first rule
    that fires, or None. ``wedge_event`` is what an injected wedge
    blocks on — the supervisor sets it after the watchdog verdict so
    the blocked worker thread exits instead of leaking (a REAL wedge
    cannot be released; its daemon thread is the documented cost of
    the r05 failure mode, bounded by the circuit breaker)."""

    def __init__(self, rules: List[FaultRule]):
        self.rules = rules
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.wedge_event = threading.Event()

    def decide(self, site: str) -> Optional[FaultRule]:
        with self._lock:
            c = self._counts.get(site, 0) + 1
            self._counts[site] = c
        for r in self.rules:
            if r.site == site and r.fires(c):
                return r
        return None

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


# plan cache, keyed on the raw env values so an env change between
# calls rebuilds (and re-validates) the plan instead of going stale
_cache_lock = threading.Lock()
_cache: Tuple[Optional[str], Optional[str], Optional[FaultPlan]] = \
    (None, None, None)


def _raw_env() -> Tuple[Optional[str], Optional[str]]:
    return (envflags.env_raw("JEPSEN_TPU_FAULTS"),
            envflags.env_raw("JEPSEN_TPU_TEST_WEDGE"))


def active_plan() -> Optional[FaultPlan]:
    """The process fault plan, or None when no faults are configured.
    Cached on the raw env strings; parse errors raise at every read
    (fail-loud, per the envflags contract)."""
    global _cache
    raw, legacy = _raw_env()
    if raw is None and legacy in (None, "0"):
        return None
    with _cache_lock:
        craw, clegacy, plan = _cache
        if craw == raw and clegacy == legacy and plan is not None:
            return plan
        rules = parse_spec(raw) if raw else []
        # the legacy bench seam: =1 injects the child wedge (strict
        # tri-state read — a malformed value raises, as it always did)
        if envflags.env_bool("JEPSEN_TPU_TEST_WEDGE", default=False):
            rules.append(FaultRule("wedge", "child",
                                   spec="wedge@child (legacy "
                                        "JEPSEN_TPU_TEST_WEDGE=1)"))
        plan = FaultPlan(rules) if rules else None
        _cache = (raw, legacy, plan)
        return plan


def active() -> bool:
    """Cheap activity probe for the supervisor's fast path: true iff a
    fault plan is configured (raw env reads only — no parse on the
    no-op path; validation happens when the plan is actually built)."""
    raw, legacy = _raw_env()
    return raw is not None or legacy not in (None, "0")


def decide(site: str) -> Optional[FaultRule]:
    """Count one invocation of ``site`` against the active plan and
    return the rule that fires, if any."""
    plan = active_plan()
    return plan.decide(site) if plan is not None else None


def reset():
    """Drop the cached plan and its counters (test isolation)."""
    global _cache
    with _cache_lock:
        _, _, plan = _cache
        if plan is not None:
            plan.wedge_event.set()
        _cache = (None, None, None)
