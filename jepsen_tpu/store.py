"""Test persistence (reference: jepsen/src/jepsen/store.clj).

Run directories live under `store/<test-name>/<timestamp>/`
(store.clj:118-147) with `latest` and `current` symlinks
(store.clj:307-333). Each run persists:

    history.edn / history.txt   the op history (store.clj:351-362)
    test.json                   the serializable slice of the test map
                                (the fressian analogue; live objects are
                                stripped per store.clj:160-168)
    results.edn / results.json  checker output (save-2!, store.clj:385-397)
    jepsen.log                  the run log

`save_1` persists the history BEFORE analysis so a crashed checker never
loses it (core.clj:374-376); `save_2` adds results.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import shutil
from typing import Any, Dict, List, Optional

from jepsen_tpu import edn
from jepsen_tpu.history import History

BASE_DIR = "store"

NONSERIALIZABLE_KEYS = (
    # live objects stripped before writing (store.clj:160-168)
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "remote", "sessions", "store", "control",
    # a jax.sharding.Mesh of live device handles (independent's device
    # batch path reads test["mesh"])
    "mesh",
    # big run artifacts with their own files (history.edn / results.edn)
    "history", "results",
)


def _sanitize(name: str) -> str:
    return "".join(ch if (ch.isalnum() or ch in "-_. ") else "_"
                   for ch in str(name)).strip() or "test"


class Store:
    """One run's directory with writers for history/results/files."""

    def __init__(self, test_name: str, base_dir: str = BASE_DIR,
                 time: Optional[_dt.datetime] = None):
        self.test_name = _sanitize(test_name)
        t = time or _dt.datetime.now()
        self.timestamp = t.strftime("%Y%m%dT%H%M%S.%f")[:-3]
        self.dir = os.path.join(base_dir, self.test_name, self.timestamp)
        os.makedirs(self.dir, exist_ok=True)
        self._update_symlinks(base_dir)

    def _update_symlinks(self, base_dir: str):
        # store.clj:307-333 `latest` per test + global `current`
        for link_dir, name in ((os.path.join(base_dir, self.test_name),
                                "latest"),
                               (base_dir, "current")):
            link = os.path.join(link_dir, name)
            try:
                if os.path.islink(link):
                    os.unlink(link)
                os.symlink(os.path.relpath(self.dir, link_dir), link)
            except OSError:
                pass

    # ------------------------------------------------------------ paths
    def path(self, *parts) -> str:
        p = os.path.join(self.dir, *[_sanitize(str(x)) for x in parts])
        os.makedirs(os.path.dirname(p), exist_ok=True)
        return p

    def write_file(self, parts: List, content: str):
        with open(self.path(*parts), "w") as fh:
            fh.write(content)

    # ------------------------------------------------------------ saves
    def save_1(self, test: Dict, history: History):
        """History + test map — before analysis (store.clj:372-383).
        history.npz is the columnar binary sidecar (Fressian parity:
        the reference stores binary history for fast reload,
        store.clj:31-116); history.edn stays the canonical
        interchange format."""
        history.save(self.path("history.edn"))
        history.save_npz(self.path("history.npz"))
        self.write_file(["history.txt"],
                        "\n".join(_op_line(o) for o in history) + "\n")
        self.write_file(["test.json"],
                        json.dumps(serializable_test(test), indent=2,
                                   default=str))

    def save_2(self, results: Dict):
        """Results — after analysis (store.clj:385-397)."""
        self.write_file(["results.edn"], edn.dumps(results) + "\n")
        self.write_file(["results.json"],
                        json.dumps(results, indent=2, default=str))

    def save_telemetry(self) -> Optional[Dict]:
        """Telemetry artifacts when JEPSEN_TPU_TRACE is on:
        telemetry.jsonl (spans + metrics), trace.json (Chrome
        trace-event — opens in Perfetto), telemetry.txt (the summary
        table). A no-op (returns None) when tracing is off — runs must
        not grow artifacts nobody asked for. Called by core.run /
        core.analyze after save_2; safe to call again (overwrites)."""
        from jepsen_tpu import obs
        return obs.export_run(self.dir)

    # ---------------------------------------------------------- logging
    def start_logging(self) -> logging.Logger:
        """Console + per-run jepsen.log (store.clj:399-439)."""
        logger = logging.getLogger("jepsen")
        logger.setLevel(logging.INFO)
        fh = logging.FileHandler(self.path("jepsen.log"))
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s [%(threadName)s] %(message)s"))
        logger.addHandler(fh)
        self._log_handler = fh
        return logger

    def stop_logging(self):
        h = getattr(self, "_log_handler", None)
        if h is not None:
            logging.getLogger("jepsen").removeHandler(h)
            h.close()


def _op_line(o) -> str:
    return (f"{o.get('index', ''):>8} "
            f"{str(o.get('process', '')):>8} "
            f"{o.get('type', ''):>8} "
            f"{o.get('f', '')!s:>12}  {o.get('value')!r}"
            + (f"  {o.get('error')}" if o.get("error") else ""))


def serializable_test(test: Dict) -> Dict:
    return {k: v for k, v in (test or {}).items()
            if k not in NONSERIALIZABLE_KEYS}


# ------------------------------------------------------------- loading


def tests(base_dir: str = BASE_DIR) -> Dict[str, List[str]]:
    """Map of test-name -> sorted run timestamps."""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(base_dir):
        return out
    for name in sorted(os.listdir(base_dir)):
        d = os.path.join(base_dir, name)
        if name == "current" or not os.path.isdir(d) or os.path.islink(d):
            continue
        runs = sorted(r for r in os.listdir(d)
                      if not os.path.islink(os.path.join(d, r)))
        if runs:
            out[name] = runs
    return out


def latest(base_dir: str = BASE_DIR,
           test_name: Optional[str] = None) -> Optional[str]:
    """Directory of the most recent run (store.clj:296-305). With
    `test_name` (sanitized like the writer), that test's newest run —
    preferring the per-test `latest` symlink `_update_symlinks`
    maintains, falling back to a directory scan."""
    if test_name is not None:
        test_dir = os.path.join(base_dir, _sanitize(test_name))
        link = os.path.join(test_dir, "latest")
        if os.path.islink(link):
            target = os.path.join(test_dir, os.readlink(link))
            if os.path.isdir(target):
                return target
        if not os.path.isdir(test_dir):
            return None
        runs = sorted(r for r in os.listdir(test_dir)
                      if not os.path.islink(os.path.join(test_dir, r)))
        return os.path.join(test_dir, runs[-1]) if runs else None
    link = os.path.join(base_dir, "current")
    if os.path.islink(link):
        target = os.path.join(base_dir, os.readlink(link))
        if os.path.isdir(target):
            return target
    best = None
    for name, runs in tests(base_dir).items():
        for r in runs:
            d = os.path.join(base_dir, name, r)
            if best is None or r > os.path.basename(best):
                best = d
    return best


def load_run(run_dir: str) -> Dict[str, Any]:
    """Reload a stored run: {test, history, results?}."""
    out: Dict[str, Any] = {"dir": run_dir}
    tpath = os.path.join(run_dir, "test.json")
    if os.path.exists(tpath):
        with open(tpath) as fh:
            out["test"] = json.load(fh)
    # prefer the columnar sidecar: reload is numpy-speed, no EDN parse
    # (a 50k-op re-analyze otherwise pays seconds of parsing) — with a
    # loud fallback to the canonical EDN if the sidecar is unreadable.
    # A sidecar OLDER than the EDN is stale (the canonical file was
    # rewritten after the run — e.g. a hand-corrected replay) and is
    # skipped so the edit is not silently shadowed.
    npath = os.path.join(run_dir, "history.npz")
    hpath = os.path.join(run_dir, "history.edn")
    if (os.path.exists(npath) and os.path.exists(hpath)
            and os.path.getmtime(npath) < os.path.getmtime(hpath)):
        logging.getLogger(__name__).warning(
            "history.npz is older than history.edn — using the EDN "
            "(rewrite the sidecar with History.save_npz to re-enable "
            "fast reload)")
        npath = None
    if npath and os.path.exists(npath):
        try:
            out["history"] = History.load_npz(npath)
        except Exception as err:  # noqa: BLE001
            logging.getLogger(__name__).warning(
                "history.npz unreadable (%r) — falling back to "
                "history.edn", err)
    if "history" not in out and os.path.exists(hpath):
        out["history"] = History.load(hpath)
    rpath = os.path.join(run_dir, "results.json")
    if os.path.exists(rpath):
        with open(rpath) as fh:
            out["results"] = json.load(fh)
    return out


def delete(test_name: Optional[str] = None, base_dir: str = BASE_DIR):
    """Remove stored runs (store.clj delete!)."""
    target = (os.path.join(base_dir, _sanitize(test_name))
              if test_name else base_dir)
    if os.path.isdir(target):
        shutil.rmtree(target)
