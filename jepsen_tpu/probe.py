"""`jepsen probe` — bounded device-runtime health check.

The r05 chip outage (PROBES_r05.log) was diagnosed with a hand-rolled
loop: spawn ``jax.devices()`` in a throwaway subprocess under a
timeout, because a wedged PJRT runtime blocks FOREVER inside
``make_c_api_client`` with no Python-level signal delivery — the probe
process takes the hang, never the operator's shell. This module is
that loop as a first-class subcommand, emitting the same verdict-line
format the runbook used by hand:

    2026-07-31T03:46:32Z probe: HEALTHY — jax.devices() -> ['tpu'] in 2.5s (tpu platform)
    2026-07-31T02:18:07Z probe: hung past 100s (attempt 1/3)
    2026-07-31T02:28:00Z probe: WEDGED — all 3 attempts hung past 100s

Exit contract (the runbook's automation hook):

    0  healthy     jax.devices() answered within the timeout
    1  wedged      every attempt hung past the timeout (the r05
                   signature: runtime up but unreachable)
    2  no-backend  the child ran but failed (no devices / import error
                   / plugin crash) — a different failure class: retries
                   won't help, fix the environment

Usage: ``jepsen probe [--timeout 100] [--retries 3] [--interval 30]``
(also ``python -m jepsen_tpu.probe``). The parent never imports jax.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import subprocess
import sys
import time
from typing import Optional, Sequence

EXIT_HEALTHY = 0
EXIT_WEDGED = 1
EXIT_NO_BACKEND = 2

# the child: honor JAX_PLATFORMS via jax.config too (the axon plugin's
# backend hook ignores the env var alone — same pinning as bench.py),
# then enumerate devices and print one machine-parseable line
_CHILD_CODE = (
    "import json, os, sys\n"
    "import jax\n"
    "p = os.environ.get('JAX_PLATFORMS')\n"
    "if p:\n"
    "    jax.config.update('jax_platforms', p)\n"
    "ds = jax.devices()\n"
    "print('JEPSEN_PROBE ' + json.dumps(sorted({d.platform for d in ds})"
    " + [len(ds)]))\n"
)


def _now() -> str:
    return _dt.datetime.now(_dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _emit(msg: str, out=None):
    print(f"{_now()} probe: {msg}", file=out or sys.stdout, flush=True)


def probe_once(timeout: float) -> dict:
    """One bounded ``jax.devices()`` child. Returns
    {"status": "healthy"|"hung"|"failed", ...}: healthy carries
    platforms/n_devices/secs, failed carries rc + a stderr tail."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", _CHILD_CODE],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        # subprocess.run kills the child on timeout (SIGKILL after
        # terminate) — the hang dies with it, as the runbook's manual
        # `kill -9` did
        return {"status": "hung", "secs": time.monotonic() - t0}
    secs = time.monotonic() - t0
    if proc.returncode == 0:
        for ln in proc.stdout.splitlines():
            if ln.startswith("JEPSEN_PROBE "):
                import json
                payload = json.loads(ln[len("JEPSEN_PROBE "):])
                return {"status": "healthy", "secs": secs,
                        "platforms": payload[:-1],
                        "n_devices": payload[-1]}
    return {"status": "failed", "secs": secs, "rc": proc.returncode,
            "err": (proc.stderr or proc.stdout).strip()[-300:]}


def run_probe(timeout: float = 100.0, retries: int = 3,
              interval: float = 0.0, out=None,
              record: Optional[list] = None) -> int:
    """The retry loop: probe until healthy or attempts run out,
    emitting one verdict line per attempt (PROBES_r05.log format) and
    a final summary line. Returns the exit code. `record`, when a
    list, receives each attempt's raw result dict — the structured
    side of the verdict lines (probe_json builds on it)."""
    retries = max(1, retries)
    for attempt in range(1, retries + 1):
        r = probe_once(timeout)
        if record is not None:
            record.append(r)
        if r["status"] == "healthy":
            plats = r["platforms"]
            _emit(f"HEALTHY — jax.devices() -> {plats} in "
                  f"{r['secs']:.1f}s ({'/'.join(plats)} platform, "
                  f"{r['n_devices']} device(s))", out)
            return EXIT_HEALTHY
        if r["status"] == "hung":
            _emit(f"hung past {timeout:.0f}s "
                  f"(attempt {attempt}/{retries})", out)
        else:
            # a child that RAN and failed is not a wedge — retrying
            # cannot help (no plugin, no devices, import error), so
            # don't burn the operator's time on the remaining attempts
            _emit(f"NO BACKEND — jax.devices() failed rc={r['rc']} "
                  f"in {r['secs']:.1f}s ({r['err'].splitlines()[-1] if r['err'] else '?'})",
                  out)
            return EXIT_NO_BACKEND
        if attempt < retries and interval > 0:
            time.sleep(interval)
    _emit(f"WEDGED — all {retries} attempt(s) hung past "
          f"{timeout:.0f}s (the PJRT make_c_api_client wedge "
          f"signature; see PROBES_r05.log / docs/observability.md)",
          out)
    return EXIT_WEDGED


_VERDICTS = {EXIT_HEALTHY: "healthy", EXIT_WEDGED: "wedged",
             EXIT_NO_BACKEND: "no-backend"}


def probe_json(timeout: float = 100.0, retries: int = 3,
               interval: float = 0.0, out=None) -> dict:
    """The probe loop as one machine-readable document — the contract
    both ``jepsen probe --json`` and the circuit breaker's half-open
    recovery check consume (jepsen_tpu.resilience.breaker), so
    external automation and the in-process breaker read the SAME
    health surface. `out` receives the human verdict lines (default:
    discarded under --json's stdout-JSON contract; the CLI routes
    them to stderr).

    Schema: verdict (healthy|wedged|no-backend), exit (the 0/1/2
    runbook code), attempts (each raw probe_once result), elapsed_secs,
    timeout, retries; healthy additionally carries platforms and
    n_devices from the answering attempt."""
    import io
    t0 = time.monotonic()
    record: list = []
    code = run_probe(timeout=timeout, retries=retries,
                     interval=interval, out=out or io.StringIO(),
                     record=record)
    doc = {
        "verdict": _VERDICTS.get(code, "unknown"),
        "exit": code,
        "attempts": record,
        "elapsed_secs": round(time.monotonic() - t0, 3),
        "timeout": timeout,
        "retries": retries,
    }
    if code == EXIT_HEALTHY and record:
        doc["platforms"] = record[-1].get("platforms")
        doc["n_devices"] = record[-1].get("n_devices")
    return doc


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="jepsen probe",
        description="bounded device-runtime health check: subprocess "
                    "jax.devices() with timeout + retry; exit 0 "
                    "healthy / 1 wedged / 2 no-backend")
    p.add_argument("--timeout", type=float, default=100.0,
                   help="seconds before one attempt counts as hung "
                        "(default: 100, the r05 runbook's bound)")
    p.add_argument("--retries", type=int, default=3,
                   help="attempts before the WEDGED verdict")
    p.add_argument("--interval", type=float, default=0.0,
                   help="seconds between attempts")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON document on "
                        "stdout (verdict lines go to stderr); exit "
                        "code unchanged")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        # argparse exits 2 on usage errors, which collides with the
        # no-backend code — keep --help at 0 and map misuse to the
        # CLI's bad-args convention via a distinct code
        return 0 if e.code in (0, None) else 254
    if args.json:
        # verdict lines keep flowing (stderr) so an operator tailing
        # the run still sees the runbook format; stdout is exactly one
        # JSON document for automation (the breaker's contract)
        import json
        doc = probe_json(timeout=args.timeout, retries=args.retries,
                         interval=args.interval, out=sys.stderr)
        print(json.dumps(doc))
        return doc["exit"]
    return run_probe(timeout=args.timeout, retries=args.retries,
                     interval=args.interval)


if __name__ == "__main__":
    sys.exit(main())
