"""`jepsen probe` — bounded device-runtime health check.

The r05 chip outage (PROBES_r05.log) was diagnosed with a hand-rolled
loop: spawn ``jax.devices()`` in a throwaway subprocess under a
timeout, because a wedged PJRT runtime blocks FOREVER inside
``make_c_api_client`` with no Python-level signal delivery — the probe
process takes the hang, never the operator's shell. This module is
that loop as a first-class subcommand, emitting the same verdict-line
format the runbook used by hand:

    2026-07-31T03:46:32Z probe: HEALTHY — jax.devices() -> ['tpu'] in 2.5s (tpu platform)
    2026-07-31T02:18:07Z probe: hung past 100s (attempt 1/3)
    2026-07-31T02:28:00Z probe: WEDGED — all 3 attempts hung past 100s

Exit contract (the runbook's automation hook):

    0  healthy     jax.devices() answered within the timeout
    1  wedged      every attempt hung past the timeout (the r05
                   signature: runtime up but unreachable)
    2  no-backend  the child ran but failed (no devices / import error
                   / plugin crash) — a different failure class: retries
                   won't help, fix the environment

Usage: ``jepsen probe [--timeout 100] [--retries 3] [--interval 30]``
(also ``python -m jepsen_tpu.probe``). The parent never imports jax.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import logging
import subprocess
import sys
import threading
import time
from typing import Callable, Optional, Sequence

_log = logging.getLogger(__name__)

EXIT_HEALTHY = 0
EXIT_WEDGED = 1
EXIT_NO_BACKEND = 2

# the child: honor JAX_PLATFORMS via jax.config too (the axon plugin's
# backend hook ignores the env var alone — same pinning as bench.py),
# then enumerate devices and print one machine-parseable line
_CHILD_CODE = (
    "import json, os, sys\n"
    "import jax\n"
    "p = os.environ.get('JAX_PLATFORMS')\n"
    "if p:\n"
    "    jax.config.update('jax_platforms', p)\n"
    "ds = jax.devices()\n"
    "print('JEPSEN_PROBE ' + json.dumps(sorted({d.platform for d in ds})"
    " + [len(ds)]))\n"
)


def _now() -> str:
    return _dt.datetime.now(_dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _emit(msg: str, out=None):
    print(f"{_now()} probe: {msg}", file=out or sys.stdout, flush=True)


def probe_once(timeout: float) -> dict:
    """One bounded ``jax.devices()`` child. Returns
    {"status": "healthy"|"hung"|"failed", ...}: healthy carries
    platforms/n_devices/secs, failed carries rc + a stderr tail."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", _CHILD_CODE],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        # subprocess.run kills the child on timeout (SIGKILL after
        # terminate) — the hang dies with it, as the runbook's manual
        # `kill -9` did
        return {"status": "hung", "secs": time.monotonic() - t0}
    secs = time.monotonic() - t0
    if proc.returncode == 0:
        for ln in proc.stdout.splitlines():
            if ln.startswith("JEPSEN_PROBE "):
                import json
                payload = json.loads(ln[len("JEPSEN_PROBE "):])
                return {"status": "healthy", "secs": secs,
                        "platforms": payload[:-1],
                        "n_devices": payload[-1]}
    return {"status": "failed", "secs": secs, "rc": proc.returncode,
            "err": (proc.stderr or proc.stdout).strip()[-300:]}


def run_probe(timeout: float = 100.0, retries: int = 3,
              interval: float = 0.0, out=None,
              record: Optional[list] = None) -> int:
    """The retry loop: probe until healthy or attempts run out,
    emitting one verdict line per attempt (PROBES_r05.log format) and
    a final summary line. Returns the exit code. `record`, when a
    list, receives each attempt's raw result dict — the structured
    side of the verdict lines (probe_json builds on it)."""
    retries = max(1, retries)
    for attempt in range(1, retries + 1):
        r = probe_once(timeout)
        if record is not None:
            record.append(r)
        if r["status"] == "healthy":
            plats = r["platforms"]
            _emit(f"HEALTHY — jax.devices() -> {plats} in "
                  f"{r['secs']:.1f}s ({'/'.join(plats)} platform, "
                  f"{r['n_devices']} device(s))", out)
            return EXIT_HEALTHY
        if r["status"] == "hung":
            _emit(f"hung past {timeout:.0f}s "
                  f"(attempt {attempt}/{retries})", out)
        else:
            # a child that RAN and failed is not a wedge — retrying
            # cannot help (no plugin, no devices, import error), so
            # don't burn the operator's time on the remaining attempts
            _emit(f"NO BACKEND — jax.devices() failed rc={r['rc']} "
                  f"in {r['secs']:.1f}s ({r['err'].splitlines()[-1] if r['err'] else '?'})",
                  out)
            return EXIT_NO_BACKEND
        if attempt < retries and interval > 0:
            time.sleep(interval)
    _emit(f"WEDGED — all {retries} attempt(s) hung past "
          f"{timeout:.0f}s (the PJRT make_c_api_client wedge "
          f"signature; see PROBES_r05.log / docs/observability.md)",
          out)
    return EXIT_WEDGED


_VERDICTS = {EXIT_HEALTHY: "healthy", EXIT_WEDGED: "wedged",
             EXIT_NO_BACKEND: "no-backend"}


def probe_json(timeout: float = 100.0, retries: int = 3,
               interval: float = 0.0, out=None) -> dict:
    """The probe loop as one machine-readable document — the contract
    both ``jepsen probe --json`` and the circuit breaker's half-open
    recovery check consume (jepsen_tpu.resilience.breaker), so
    external automation and the in-process breaker read the SAME
    health surface. `out` receives the human verdict lines (default:
    discarded under --json's stdout-JSON contract; the CLI routes
    them to stderr).

    Schema: verdict (healthy|wedged|no-backend), exit (the 0/1/2
    runbook code), attempts (each raw probe_once result), elapsed_secs,
    timeout, retries; healthy additionally carries platforms and
    n_devices from the answering attempt."""
    import io
    t0 = time.monotonic()
    record: list = []
    code = run_probe(timeout=timeout, retries=retries,
                     interval=interval, out=out or io.StringIO(),
                     record=record)
    doc = {
        "verdict": _VERDICTS.get(code, "unknown"),
        "exit": code,
        "attempts": record,
        "elapsed_secs": round(time.monotonic() - t0, 3),
        "timeout": timeout,
        "retries": retries,
    }
    if code == EXIT_HEALTHY and record:
        doc["platforms"] = record[-1].get("platforms")
        doc["n_devices"] = record[-1].get("n_devices")
    return doc


# ------------------------------------------------ continuous chip watch


class ProbeWatch:
    """The probe loop as a background service: re-run :func:`probe_json`
    every ``interval`` seconds on a daemon thread and publish the
    verdict as live gauges —

        probe.chip_healthy       1 healthy / 0 wedged or no-backend
        probe.last_ok_age_secs   seconds since the last healthy verdict

    so ``/healthz`` reflects a PROBES_r05-style outage the moment the
    watch sees it, instead of at the next dispatch wedge. Off by
    default: armed via ``JEPSEN_TPU_PROBE_INTERVAL`` (seconds; 0/unset
    = no watch, no thread, no gauges) through
    :func:`start_watch_from_env`.

    Staleness contract for readiness: before the first tick completes
    the watch reports ok (a service must not fail readiness while the
    first 100s-timeout probe is still in flight); after that, ok means
    the last verdict was healthy AND its age is within ``max_stale``
    (default ``2*interval + timeout`` — a stuck watch loop is itself a
    health failure). The probe child takes any hang, never this
    process (the module contract above).
    """

    def __init__(self, interval: float, timeout: float = 100.0,
                 retries: int = 1, max_stale: Optional[float] = None,
                 probe: Optional[Callable[[], dict]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.retries = int(retries)
        self.max_stale = (float(max_stale) if max_stale is not None
                          else 2.0 * self.interval + self.timeout)
        self._probe = probe if probe is not None else (
            lambda: probe_json(timeout=self.timeout,
                               retries=self.retries))
        self._clock = clock
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        self.last: Optional[dict] = None
        self._last_ok: Optional[float] = None
        self._started = self._clock()

    # gauges live in obs (jax-free, same import contract as this
    # module); imported lazily so `jepsen probe` stays as light as the
    # pre-watch subcommand
    @staticmethod
    def _gauges():
        from jepsen_tpu import obs
        return obs.gauge("probe.chip_healthy"), \
            obs.gauge("probe.last_ok_age_secs")

    def _age(self, now: float) -> float:
        with self._lock:
            t0 = self._last_ok if self._last_ok is not None \
                else self._started
        return max(0.0, now - t0)

    def tick(self) -> dict:
        """One probe cycle (the loop body; callable directly in
        tests): run the probe, record, publish gauges. A probe that
        RAISES (spawn failure, ENOMEM) still counts as a completed
        tick with verdict ``probe-error`` — otherwise ``ticks`` would
        stay 0 and :meth:`status`'s first-probe-in-flight grace would
        report ok forever while chip health is completely unknown."""
        try:
            doc = self._probe()
        except Exception as err:  # noqa: BLE001 — a crashed probe is
            # not health; it must degrade readiness, not kill the loop
            _log.exception("probe watch tick failed")
            doc = {"verdict": "probe-error",
                   "error": f"{type(err).__name__}: {err}"}
        now = self._clock()
        healthy = doc.get("verdict") == "healthy"
        with self._lock:
            self.ticks += 1
            self.last = doc
            if healthy:
                self._last_ok = now
        g_h, g_age = self._gauges()
        g_h.set(1 if healthy else 0)
        g_age.set(round(self._age(now), 3))
        return doc

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — tick() already
                # absorbs probe failures as a probe-error verdict;
                # this guards the bookkeeping itself (gauge/registry
                # errors must not kill the watch loop)
                _log.exception("probe watch bookkeeping failed")
            if self._stop.wait(self.interval):
                return

    def start(self) -> "ProbeWatch":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="jepsen-probe-watch")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # an in-flight probe child may hold the loop for up to
            # `timeout` seconds; the thread is a daemon, so a bounded
            # join suffices — the child dies with the process
            self._thread.join(timeout=1.0)

    def status(self) -> dict:
        """The watch as a /healthz check entry (and the live refresh
        of the age gauge for /metrics scrapes)."""
        now = self._clock()
        age = self._age(now)
        with self._lock:
            ticks, last = self.ticks, self.last
        verdict = last.get("verdict") if last else None
        if ticks == 0:
            ok = True        # first probe still in flight: not a failure
        else:
            ok = verdict == "healthy" and age <= self.max_stale
        if ticks:
            _g_h, g_age = self._gauges()
            g_age.set(round(age, 3))
        return {"ok": ok, "verdict": verdict, "ticks": ticks,
                "last_ok_age_secs": round(age, 3),
                "interval": self.interval,
                "max_stale": self.max_stale}


def start_watch_from_env() -> Optional[ProbeWatch]:
    """Arm the continuous chip watch when
    ``JEPSEN_TPU_PROBE_INTERVAL`` names an interval (seconds; 0/unset
    = off — the default, so a bare serve carries no extra thread)."""
    from jepsen_tpu import envflags
    interval = envflags.env_float("JEPSEN_TPU_PROBE_INTERVAL",
                                  default=0.0, min_value=0.0,
                                  what="probe watch interval seconds")
    if not interval:
        return None
    return ProbeWatch(interval).start()


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="jepsen probe",
        description="bounded device-runtime health check: subprocess "
                    "jax.devices() with timeout + retry; exit 0 "
                    "healthy / 1 wedged / 2 no-backend")
    p.add_argument("--timeout", type=float, default=100.0,
                   help="seconds before one attempt counts as hung "
                        "(default: 100, the r05 runbook's bound)")
    p.add_argument("--retries", type=int, default=3,
                   help="attempts before the WEDGED verdict")
    p.add_argument("--interval", type=float, default=0.0,
                   help="seconds between attempts")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON document on "
                        "stdout (verdict lines go to stderr); exit "
                        "code unchanged")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        # argparse exits 2 on usage errors, which collides with the
        # no-backend code — keep --help at 0 and map misuse to the
        # CLI's bad-args convention via a distinct code
        return 0 if e.code in (0, None) else 254
    if args.json:
        # verdict lines keep flowing (stderr) so an operator tailing
        # the run still sees the runbook format; stdout is exactly one
        # JSON document for automation (the breaker's contract)
        import json
        doc = probe_json(timeout=args.timeout, retries=args.retries,
                         interval=args.interval, out=sys.stderr)
        print(json.dumps(doc))
        return doc["exit"]
    return run_probe(timeout=args.timeout, retries=args.retries,
                     interval=args.interval)


if __name__ == "__main__":
    sys.exit(main())
