"""Database lifecycle protocols (reference: jepsen/src/jepsen/db.clj).

`DB` (db.clj:11-13) sets up / tears down the system under test on each
node; optional capability protocols: `Process` start/kill (db.clj:18-24),
`Pause` pause/resume (db.clj:26-29), `Primary` discovery/promotion
(db.clj:31-38), `LogFiles` (db.clj:40-41). `cycle` retries setup 3x on
failure (db.clj:117-158)."""

from __future__ import annotations

import time
from typing import List, Optional

from jepsen_tpu import control as c
from jepsen_tpu.util import real_pmap


class DB:
    def setup(self, test, node) -> None:
        """Install and start the database on node."""

    def teardown(self, test, node) -> None:
        """Tear down and remove all traces of the database."""


class Process:
    """Optional: databases whose processes can be started/killed
    (db.clj:18-24)."""

    def start(self, test, node):
        raise NotImplementedError

    def kill(self, test, node):
        raise NotImplementedError


class Pause:
    """Optional: SIGSTOP/SIGCONT (db.clj:26-29)."""

    def pause(self, test, node):
        raise NotImplementedError

    def resume(self, test, node):
        raise NotImplementedError


class Primary:
    """Optional: primary discovery and promotion (db.clj:31-38)."""

    def primaries(self, test) -> List:
        raise NotImplementedError

    def setup_primary(self, test, node) -> None:
        pass


class LogFiles:
    """Optional: log paths to snarf at teardown (db.clj:40-41)."""

    def log_files(self, test, node) -> List[str]:
        return []


class Noop(DB):
    """No-op database (db.clj:43-47)."""


def noop() -> Noop:
    return Noop()


class SetupFailed(Exception):
    pass


def cycle(db: DB, test: dict, retries: int = 3) -> None:
    """Teardown then setup on every node in parallel, then promote a
    primary on the first node for Primary DBs; the whole cycle retries
    up to `retries` times on SetupFailed (db.clj:117-158)."""
    last: Optional[BaseException] = None
    for _ in range(retries):
        try:
            c.on_nodes(test, db.teardown)
            c.on_nodes(test, db.setup)
            if isinstance(db, Primary) and test.get("nodes"):
                primary = test["nodes"][0]  # core.clj:66-69 primary
                c.on_nodes(test, lambda t, n: db.setup_primary(t, n),
                           [primary])
            return
        except SetupFailed as e:
            last = e
            time.sleep(1)
    raise last if last else SetupFailed("db cycle failed")


class Tcpdump(DB, LogFiles):
    """Captures packets on each node for the duration of a test — the
    capture-as-a-DB wrapper (db.clj:49-115). Compose with a real DB via
    Composite([Tcpdump(...), real_db])."""

    def __init__(self, filter_: str = "", pcap: str = "/tmp/jepsen.pcap",
                 interface: str = "any"):
        self.filter = filter_
        self.pcap = pcap
        self.interface = interface
        self.pidfile = "/tmp/jepsen-tcpdump.pid"

    def setup(self, test, node):
        from jepsen_tpu.control import util as cu
        cu.start_daemon({"pidfile": self.pidfile, "logfile": "/dev/null"},
                        "tcpdump", "-i", self.interface, "-w", self.pcap,
                        *(self.filter.split() if self.filter else []))

    def teardown(self, test, node):
        from jepsen_tpu.control import util as cu
        cu.stop_daemon(self.pidfile)

    def log_files(self, test, node):
        return [self.pcap]


class Composite(DB, LogFiles):
    """Run several DBs in order on setup, reverse order on teardown."""

    def __init__(self, dbs: List[DB]):
        self.dbs = list(dbs)

    def setup(self, test, node):
        for db in self.dbs:
            db.setup(test, node)

    def teardown(self, test, node):
        for db in reversed(self.dbs):
            db.teardown(test, node)

    def log_files(self, test, node):
        out = []
        for db in self.dbs:
            lf = getattr(db, "log_files", None)
            if lf is not None:
                out.extend(lf(test, node))
        return out
