"""libfaketime wrappers: make a DB binary's clock run at a skewed rate
(reference: jepsen/src/jepsen/faketime.clj).

Where the clock nemesis (nemesis/time.py) skews the *whole node*,
faketime skews a *single process* by replacing its binary with a shell
wrapper that launches the original under `faketime -m -f "+OFFs xRATE"`
(faketime.clj:24-47). A rate of 1.0 is real time; 2.0 runs the victim's
clock twice as fast."""

from __future__ import annotations

from jepsen_tpu import control as c
from jepsen_tpu import generator as gen

REPO_URL = "https://github.com/wolfcw/libfaketime.git"


def install() -> None:
    """Builds libfaketime from source on the ambient node
    (faketime.clj:8-22). Requires network egress on the node; tests use
    `script`/`wrap` against a pre-installed faketime instead."""
    with c.su():
        c.exec_("mkdir", "-p", "/tmp/jepsen")
        with c.cd("/tmp/jepsen"):
            try:
                c.exec_("test", "-d", "libfaketime")
            except Exception:  # noqa: BLE001 - not cloned yet
                c.exec_("git", "clone", REPO_URL, "libfaketime")
            with c.cd("libfaketime"):
                c.exec_("make")
                c.exec_("make", "install")


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A sh script invoking cmd under a faketime wrapper with the given
    initial offset (seconds) and clock rate (faketime.clj:24-34)."""
    sign = "-" if init_offset < 0 else "+"
    mag = abs(init_offset)
    # Preserve sub-second offsets; print integers without a trailing .0
    off = str(int(mag)) if float(mag) == int(mag) else repr(float(mag))
    return ("#!/bin/bash\n"
            f'faketime -m -f "{sign}{off}s x{float(rate)}" '
            f'{cmd} "$@"\n')


def wrap(cmd: str, init_offset: float, rate: float) -> None:
    """Replaces the executable at cmd with a faketime wrapper, moving
    the original to cmd.no-faketime. Idempotent (faketime.clj:36-47)."""
    orig = cmd + ".no-faketime"
    wrapper = script(orig, init_offset, rate)

    def exists(path):
        try:
            c.exec_("test", "-e", path)
            return True
        except Exception:  # noqa: BLE001
            return False

    if not exists(orig):
        c.exec_("mv", cmd, orig)
    import tempfile
    import os
    fd, tmp = tempfile.mkstemp(suffix=".sh")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(wrapper)
        c.upload([tmp], cmd)
    finally:
        os.unlink(tmp)
    c.exec_("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Removes the wrapper, restoring the original binary
    (faketime.clj:49-55)."""
    orig = cmd + ".no-faketime"
    try:
        c.exec_("test", "-e", orig)
    except Exception:  # noqa: BLE001 - no wrapper installed
        return
    c.exec_("mv", orig, cmd)




def rand_factor(factor: float) -> float:
    """A random clock rate near 1.0 such that across repeated draws the
    fastest possible clock is exactly `factor` times the slowest:
    max = 2/(1 + 1/factor), min = max/factor (faketime.clj:57-65)."""
    mx = 2.0 / (1.0 + 1.0 / factor)
    mn = mx / factor
    return mn + gen.rand.random() * (mx - mn)
