"""Results browser (reference: jepsen/src/jepsen/web.clj).

A small HTTP server over the store directory: a home page listing every
run with its validity (web.clj:48-122), a file browser for run
directories (web.clj:258-276), and zip download of a whole run
(web.clj:277-356). Standard library only."""

from __future__ import annotations

import html
import io
import json
import os
import zipfile
from http.server import HTTPServer, SimpleHTTPRequestHandler
from typing import Optional
from urllib.parse import unquote

from jepsen_tpu import store as jstore


def _run_validity(run_dir: str):
    p = os.path.join(run_dir, "results.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            return json.load(fh).get("valid?")
    except Exception:  # noqa: BLE001
        return "unknown"


def home_html(base_dir: str) -> str:
    rows = []
    for name, runs in sorted(jstore.tests(base_dir).items()):
        for r in sorted(runs, reverse=True):
            d = os.path.join(base_dir, name, r)
            v = _run_validity(d)
            color = {True: "#9f9", False: "#f99", None: "#eee"}.get(
                v, "#ff9")
            rows.append(
                f"<tr style='background:{color}'>"
                f"<td>{html.escape(name)}</td>"
                f"<td><a href='/files/{html.escape(name)}/{html.escape(r)}/'>"
                f"{html.escape(r)}</a></td>"
                f"<td>{html.escape(str(v))}</td>"
                f"<td><a href='/zip/{html.escape(name)}/{html.escape(r)}'>"
                f"zip</a></td></tr>")
    return ("<html><head><title>jepsen_tpu</title></head><body>"
            "<h1>Tests</h1><table border=1 cellpadding=4>"
            "<tr><th>test</th><th>run</th><th>valid?</th><th></th></tr>"
            + "".join(rows) + "</table></body></html>")


def dir_html(base_dir: str, rel: str) -> str:
    d = os.path.join(base_dir, rel)
    entries = sorted(os.listdir(d))
    items = "".join(
        f"<li><a href='/files/{html.escape(rel)}/{html.escape(e)}"
        f"{'/' if os.path.isdir(os.path.join(d, e)) else ''}'>"
        f"{html.escape(e)}</a></li>"
        for e in entries)
    return (f"<html><body><h1>{html.escape(rel)}</h1>"
            f"<p><a href='/'>home</a></p><ul>{items}</ul></body></html>")


def zip_run(base_dir: str, rel: str) -> bytes:
    root = os.path.join(base_dir, rel)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                full = os.path.join(dirpath, f)
                z.write(full, os.path.relpath(full, os.path.dirname(root)))
    return buf.getvalue()


class Handler(SimpleHTTPRequestHandler):
    base_dir = jstore.BASE_DIR

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, content: bytes,
              ctype: str = "text/html; charset=utf-8",
              extra: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(content)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(content)

    def _safe_rel(self, rel: str) -> Optional[str]:
        rel = unquote(rel).strip("/")
        full = os.path.realpath(os.path.join(self.base_dir, rel))
        base = os.path.realpath(self.base_dir)
        try:
            if os.path.commonpath([full, base]) != base:
                return None  # path traversal
        except ValueError:
            return None
        return rel

    def do_GET(self):  # noqa: N802
        path = self.path.split("?")[0]
        if path in ("/", "/index.html"):
            return self._send(200, home_html(self.base_dir).encode())
        if path.startswith("/files/"):
            rel = self._safe_rel(path[len("/files/"):])
            if rel is None:
                return self._send(403, b"forbidden")
            full = os.path.join(self.base_dir, rel)
            if os.path.isdir(full):
                return self._send(200, dir_html(self.base_dir, rel).encode())
            if os.path.isfile(full):
                with open(full, "rb") as fh:
                    data = fh.read()
                ctype = ("text/plain; charset=utf-8"
                         if not full.endswith((".png", ".svg", ".zip"))
                         else self.guess_type(full))
                return self._send(200, data, ctype)
            return self._send(404, b"not found")
        if path.startswith("/zip/"):
            rel = self._safe_rel(path[len("/zip/"):])
            if rel is None or not os.path.isdir(
                    os.path.join(self.base_dir, rel)):
                return self._send(404, b"not found")
            data = zip_run(self.base_dir, rel)
            name = rel.replace("/", "_") + ".zip"
            return self._send(
                200, data, "application/zip",
                {"Content-Disposition": f"attachment; filename={name}"})
        return self._send(404, b"not found")


def serve(host: str = "0.0.0.0", port: int = 8080,
          base_dir: str = jstore.BASE_DIR) -> None:
    """Serve the store directory (web.clj:357 serve!). Blocks."""
    Handler.base_dir = base_dir
    httpd = HTTPServer((host, port), Handler)
    print(f"jepsen_tpu web: http://{host}:{port}/")
    httpd.serve_forever()


def make_server(host: str = "127.0.0.1", port: int = 0,
                base_dir: str = jstore.BASE_DIR) -> HTTPServer:
    """Non-blocking variant for tests; caller drives serve_forever."""
    Handler.base_dir = base_dir
    return HTTPServer((host, port), Handler)
