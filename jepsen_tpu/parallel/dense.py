"""Dense linearizability engine: the config space as one tensor.

The sparse engine (parallel.engine) carries an explicit frontier and
pays a sort per closure round. For the workloads the reference actually
runs — per-key histories capped at ~20 concurrent processes
(jepsen/src/jepsen/tests/linearizable_register.clj:30-32,
tendermint/src/jepsen/tendermint/core.clj:351-361) — the whole
configuration space (model-state × linearized-mask) is small enough to
hold **densely**: a boolean tensor

    B[s, m] = "config (state s, window-mask m) is reachable"

with shape [S, 2^C] (S = distinct values + nil, C = open-call window).
Then the search is pure tensor algebra, exactly what a TPU wants:

  * closure round: for every open slot j, configs without bit j extend by
    linearizing call j. The state transition is a one-hot matrix
    P[j, s, s'] (computed on device from the slot tables), so the whole
    round is einsum('jst,sm->jtm', P, B&~bit_j) — an MXU matmul batch —
    followed by a static gather that ORs the result in at m|bit_j.
  * return-of-slot-s filter: B'[:, m] = B[:, m | bit_s] for m without
    bit s, else 0 — a static index shuffle.
  * no frontier capacity, no dedupe, no overflow: the tensor IS the
    visited set, fully materialised.

Work per closure round is S·2^C·C·S MACs — for S=8, C=13 that's ~4M,
microseconds on the MXU — vs a ~N·C·log sort in sparse mode. The host
chooses dense when S·2^C fits a budget (see `fits_dense`), sparse
otherwise; both implement the spec in jepsen_tpu.checker.linear.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from jepsen_tpu.parallel.encode import EncodedHistory
from jepsen_tpu.parallel.steps import STEPS

DENSE_BUDGET = 1 << 22    # max S * 2^C cells per key
P_BUDGET = 1 << 22        # max C * S^2 cells in the transition select
CLOSURE_BUDGET = 1 << 28  # max C * S^2 * 2^C work per closure round


def fits_dense(n_states: int, n_slots: int, budget: int = DENSE_BUDGET) -> bool:
    """Admission gate. Bounds BOTH the reachable tensor B (S * 2^C)
    and the quadratic-in-S costs the impl materializes per event: the
    one-hot transition select P [C, S, S] and the closure einsum
    O(C * S^2 * 2^C). Value-rich models (FIFO interns every packed
    queue content as a state) can reach S in the tens of thousands at
    tiny C — S * 2^C alone admits those, and P alone would then be
    gigabytes (found by the differential fuzz tier: a corrupted
    24-op fifo history hit S=32768, C=5 -> a 21 GB P)."""
    M = 1 << n_slots
    return (n_slots <= 20
            and n_states * M <= budget
            and n_slots * n_states * n_states <= P_BUDGET
            and n_slots * n_states * n_states * M <= CLOSURE_BUDGET)


def _check_dense_impl(xs, state0, step_name: str, S: int, C: int,
                      lo: int = -1):
    """Scan over return events on the dense tensor. xs fields as in the
    sparse engine ([R, C] slot tables + [R] ev_slot). Returns
    (valid, fail_event)."""
    step = STEPS[step_name]
    M = 1 << C
    m_idx = jnp.arange(M, dtype=jnp.int32)
    # static per-slot tables over the mask axis
    bit_of = (jnp.int32(1) << jnp.arange(C, dtype=jnp.int32))       # [C]
    has_bit = ((m_idx[None, :] >> jnp.arange(C)[:, None]) & 1) == 1  # [C, M]
    xor_j = m_idx[None, :] ^ bit_of[:, None]                         # [C, M]
    state_codes = jnp.arange(S, dtype=jnp.int32) + lo

    # step vmapped over (slots, states): tables [C, S]
    step_js = jax.vmap(
        jax.vmap(step, in_axes=(0, None, None, None, None)),  # states
        in_axes=(None, 0, 0, 0, 0),                           # slots
    )

    def closure_cond(c):
        _, changed = c
        return changed

    def make_closure_body(ev):
        nxt, okj = step_js(state_codes, ev["slot_f"], ev["slot_a0"],
                           ev["slot_a1"], ev["slot_wild"])
        legal = okj & ev["slot_occ"][:, None]                 # [C, S]
        # one-hot transition: P[j, s, s'] (s' index = next code + 1)
        P = (jax.nn.one_hot(nxt - lo, S, dtype=jnp.float32)
             * legal[..., None].astype(jnp.float32))          # [C, S, S]

        def body(c):
            B, _ = c
            # ext[j, s, m]: config (s, m) can still linearize slot j
            ext = (B[None, :, :] & ~has_bit[:, None, :]).astype(jnp.float32)
            contrib = jnp.einsum("jst,jsm->jtm", P, ext) > 0   # [C, S, M]
            # contribution lands at m | bit_j == m ^ bit_j for m with bit set
            shifted = jnp.take_along_axis(
                contrib, jnp.broadcast_to(xor_j[:, None, :], contrib.shape),
                axis=2)
            shifted = shifted & has_bit[:, None, :]
            B2 = B | jnp.any(shifted, axis=0)
            return B2, jnp.any(B2 != B)
        return body

    def scan_step(carry, ev):
        B, ok, fail_r, r_idx = carry
        run = ok & (ev["ev_slot"] >= 0)
        B2, _ = lax.while_loop(
            closure_cond, make_closure_body(ev), (B, run))
        # filter: keep configs with bit s, clearing it
        s = jnp.maximum(ev["ev_slot"], 0)
        bit_s = jnp.int32(1) << s
        no_s = (m_idx & bit_s) == 0                            # [M]
        B3 = jnp.take(B2, m_idx | bit_s, axis=1) & no_s[None, :]
        alive = jnp.any(B3)
        failed_here = run & ~alive
        B_o = jnp.where(run, B3, B)
        ok_o = jnp.where(run, ~failed_here, ok)
        fail_o = jnp.where(failed_here & (fail_r < 0), r_idx, fail_r)
        return (B_o, ok_o, fail_o, r_idx + 1), 0

    B0 = jnp.zeros((S, 1 << C), bool).at[state0 - lo, 0].set(True)
    carry0 = (B0, jnp.array(True), jnp.int32(-1), jnp.int32(0))
    (B, ok, fail_r, _), _ = lax.scan(scan_step, carry0, xs)
    valid = ok & jnp.any(B)
    return valid, fail_r


# donation decision (recompile-donate-argnums), DECIDED: nothing
# donatable — donate_argnums=() records it. Same rationale as
# bitdense: xs tables are the only frontier-scale inputs, callers
# (differential tests, perf A/B) re-dispatch the same arrays across
# engine variants, B is built in-trace, and the outputs are scalars.
_check_dense = jax.jit(_check_dense_impl,
                       donate_argnums=(),
                       static_argnames=("step_name", "S", "C", "lo"))


# same (decided) donation as _check_dense above
@functools.partial(jax.jit,
                   donate_argnums=(),
                   static_argnames=("step_name", "S", "C", "lo"))
def _check_dense_batch(xs, state0, step_name: str, S: int, C: int,
                       lo: int = -1):
    return jax.vmap(
        lambda x, s0: _check_dense_impl(x, s0, step_name, S, C, lo)
    )(xs, state0)


def _xs_dense(e: EncodedHistory, C: int) -> dict:
    def padc(a, fill):
        out = np.full((a.shape[0], C), fill, a.dtype)
        out[:, : a.shape[1]] = a
        return jnp.asarray(out)

    return {
        "slot_f": padc(e.slot_f, -1),
        "slot_a0": padc(e.slot_a0, -1),
        "slot_a1": padc(e.slot_a1, -1),
        "slot_wild": padc(e.slot_wild, False),
        "slot_occ": padc(e.slot_occ, False),
        "ev_slot": jnp.asarray(e.ev_slot),
    }


def n_states(e: EncodedHistory) -> int:
    return e.n_states


def check_encoded_dense(e: EncodedHistory) -> dict:
    """Check one encoded history with the dense engine."""
    if e.n_returns == 0:
        return {"valid?": True, "engine": "dense"}
    S = n_states(e)
    C = e.n_slots
    valid, fail_r = _check_dense(_xs_dense(e, C), jnp.int32(e.state0),
                                 e.step_name, S, C, e.state_lo)
    out = {"valid?": bool(valid), "engine": "dense",
           "states": S, "slots": C}
    if not out["valid?"]:
        from jepsen_tpu.parallel.encode import fail_op_fields
        out.update(fail_op_fields(e, int(fail_r)))
    return out


def check_batch_dense(encs, mesh=None) -> list:
    """Batch of per-key encoded histories on the dense engine (vmap over
    keys; key axis sharded over `mesh` when divisible). Kept as the
    readable unpacked reference — production dispatch uses bitdense."""
    if not encs:
        return []
    from jepsen_tpu.parallel.encode import pad_batch
    step_name = encs[0].step_name
    xs, state0, S, C, R = pad_batch(encs, mesh=mesh)
    valid, fail_r = _check_dense_batch(xs, state0, step_name, S, C,
                                       encs[0].state_lo)
    valid = np.asarray(valid)
    fail_r = np.asarray(fail_r)
    out = []
    for k, e in enumerate(encs):
        r = {"valid?": bool(valid[k]), "engine": "dense"}
        if not r["valid?"]:
            from jepsen_tpu.parallel.encode import fail_op_fields
            r.update(fail_op_fields(e, int(fail_r[k])))
        out.append(r)
    return out
