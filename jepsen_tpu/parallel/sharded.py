"""Frontier-sharded linearizability search over a device mesh.

`engine.check_batch` parallelises over *keys* (data parallel). This
module parallelises over the *frontier* of a single giant key — the
capability CPU knossos fundamentally lacks (SURVEY.md §5.7: "shard the
search frontier, not the sequence"):

  * each of the D devices on the mesh owns N/D configuration rows;
  * the closure expands locally (vmap over local configs × slots);
  * dedupe is global: every config is **owned** by the device
    `hash(config) % D`. A config can therefore exist on exactly one
    device — the union of per-device frontiers is the exact global
    config set. This is the "device-sharded hash set deduped over the
    ICI mesh" of BASELINE.json, realised with XLA collectives instead
    of NCCL;
  * candidates travel by **owner-routed segmented all-to-all**: each
    device sorts its legal candidates by owner, packs them into D
    equal buckets of width B ≈ 2×(local candidates)/D (hash-uniform,
    overflow psum-checked), and one `lax.all_to_all` delivers every
    bucket to its owner. Per-device traffic is O(2·global/D) per round,
    vs O(global) for the naive full all-gather — a D/2× reduction that
    grows with mesh size (SURVEY.md §7.1 step 4's work exchange;
    `exchange="gather"` keeps the broadcast path for A/B measurement);
  * liveness / convergence / overflow decisions ride `psum`s.

The whole event scan runs inside one `shard_map` region: slot tables are
replicated, frontier arrays stay device-local, and the only cross-device
traffic is the closure's exchange + psums.

**Multi-slice (DCN):** give `check_encoded_sharded` a mesh whose device
array is 2-D — axis 0 = slices (DCN between them), axis 1 = chips
within a slice (ICI) — and the owner routing goes HIERARCHICAL: stage 1
delivers candidates to the owner's chip column over ICI, stage 2
crosses slices with rows pre-aggregated into ONE bucket per destination
slice. Every row still crosses DCN exactly once, but as n_slice large
messages per device per round instead of n_slice*n_chip small ones —
DCN latency punishes message count, not bytes. CI exercises this on
2x4 and 4x2 CPU meshes; psums ride both axes.
"""

from __future__ import annotations

import functools
import logging

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu import obs
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import programs
from jepsen_tpu.parallel.encode import EncodedHistory
from jepsen_tpu.parallel.engine import (N_PROBE_BUCKETS, _empty_table,
                                        _hash_insert_append, _next_pow2,
                                        _rep, _resolve_config_pack,
                                        _resolve_dedupe,
                                        _resolve_probe_limit,
                                        _resolve_reshard,
                                        _resolve_search_stats,
                                        _rows_concat, _rows_prev_same,
                                        _rows_take, _rows_where,
                                        _tag_config_pack,
                                        _tag_sparse_closure,
                                        _xs_from_encoded, pack_lanes,
                                        pack_rows_np, pack_spec_for)
from jepsen_tpu.parallel.steps import STEPS
from jepsen_tpu.parallel.meshplan import (AXIS, AX_CHIP, AX_SLICE,
                                          MeshPlan)
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)


def _shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map across jax versions: the top-level name landed
    after 0.4.x — older builds (this image's 0.4.37 among them) carry
    it as jax.experimental.shard_map.shard_map with the replication
    check named check_rep instead of check_vma. Every sharded entry
    point routes through here so the engine runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def _owned_dedupe_compact(rows, live, Nd, n_dev, my_idx, rep):
    """Keep rows owned by this device, sort-dedupe, compact to [Nd].
    Lane-generic: rows is the representation's lane tuple (the
    historical triple or the packed word) — ownership hashes, sort
    keys, and scatters all run per lane."""
    owner = rep.owner_hash(rows) % jnp.uint32(n_dev)
    live = live & (owner == my_idx)
    M = rows[0].shape[0]
    order = jnp.lexsort((*reversed(rows), (~live).astype(jnp.int8)))
    rows_s = _rows_take(rows, order)
    live_s = live[order]
    uniq = live_s & ~_rows_prev_same(rows_s)
    count = jnp.sum(uniq)
    pos = jnp.where(uniq, jnp.cumsum(uniq) - 1, M + Nd)
    new_rows = tuple(z.at[pos].set(r, mode="drop")
                     for z, r in zip(rep.zeros(Nd), rows_s))
    new_live = jnp.arange(Nd) < count
    return new_rows, new_live, count, count > Nd


def _route_stage(rows, live, dest, n_dest: int, B: int, axis: str):
    """One segmented all-to-all stage (runs INSIDE shard_map): deliver
    each live row to position `dest` along the mesh axis `axis`.

    Rows are sorted by destination (dead rows sink past bucket
    n_dest-1), each destination's bucket is padded/truncated to the
    static width B, and `lax.all_to_all(tiled)` swaps bucket d to
    device d. Returns the received rows [n_dest*B] plus a local
    overflow flag (some bucket exceeded B — the caller escalates to a
    capacity retry). Lane-generic: under JEPSEN_TPU_CONFIG_PACK the
    exchange payload is the packed word — 1-2 lanes over the
    ICI/DCN wire instead of 3, a proportional traffic cut."""
    L = rows[0].shape[0]
    key = jnp.where(live, dest.astype(jnp.int32), n_dest)
    order = jnp.argsort(key)
    rows_s = _rows_take(rows, order)
    key_s = key[order]
    starts = jnp.searchsorted(key_s, jnp.arange(n_dest))
    rank = jnp.arange(L) - starts[jnp.clip(key_s, 0, n_dest - 1)]
    in_bucket = (key_s < n_dest) & (rank < B)
    ovf = jnp.any((key_s < n_dest) & (rank >= B))
    pos = jnp.where(in_bucket, key_s * B + rank, n_dest * B)  # OOB -> drop
    bufs = tuple(
        jnp.zeros(n_dest * B, r.dtype).at[pos].set(r, mode="drop")
        for r in rows_s)
    buf_lv = jnp.zeros(n_dest * B, jnp.uint8).at[pos].set(
        in_bucket.astype(jnp.uint8), mode="drop")
    a2a = lambda a: lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                   tiled=True)
    return (tuple(a2a(b) for b in bufs), a2a(buf_lv).astype(bool), ovf)


def _route_to_owners(rows, legal, n_dev: int, B: int, rep):
    """Flat owner routing over the 1-D mesh: one stage, dest =
    hash(row) % n_dev."""
    owner = rep.owner_hash(rows) % jnp.uint32(n_dev)
    return _route_stage(rows, legal, owner, n_dev, B, AXIS)


def _sharded_scan(xs, carry0, step_name: str, Nd: int, n_dev: int,
                  my_idx, axes, route_cand, route_front,
                  dedupe: str = "sort", probe_limit: int = 0,
                  sparse_pallas: str = "off",
                  search_stats: bool = False, pack: tuple = ()):
    """The topology-independent event scan (runs INSIDE shard_map),
    from an explicit initial carry — shared by the fresh-start core and
    the resumable chunk runner.

    `axes` names the mesh axes reductions ride; `route_cand(st, ml, mh,
    live)` / `route_front(...)` deliver candidate / surviving rows to
    their hash-owner devices (returning an overflow flag) — the ONLY
    things that differ between the flat 1-D mesh, the all-gather A/B
    path, and the hierarchical multi-slice topology.

    dedupe="hash" replaces the per-iteration sort-dedupe with the
    delta-frontier closure over per-device open-addressed visited sets
    (engine._hash_insert): each device's table holds exactly the
    configs it owns, so the union of tables IS the device-sharded hash
    set of BASELINE.json, and the owner-routed all-to-all feeds
    inserts directly. Only the rows discovered last iteration expand;
    membership is cumulative across the closure iterations of one
    return event. The per-event post-filter re-route (ownership moves
    when the slot bit clears) keeps the sort-based compact — it runs
    once per event, not once per closure iteration.

    `sparse_pallas` ("off"/"on"/"interpret") fuses each iteration's
    visited-set transaction — probe, scatter-min claim, loser
    re-check, fresh-row append — into one pallas_call per device
    (sparse_kernels.hash_insert_call), keeping the received candidate
    buffer, the owned table, and the frontier tile VMEM-resident for
    the whole claim loop. The expansion and the owner routing stay in
    XLA: the all-to-all collective cannot live inside a kernel. A
    call-site whose (statically known) buffer shape exceeds the VMEM
    gate downgrades itself to the plain XLA insert.

    `pack` (static) selects the configuration-row layout
    (engine._rep): lane-generic throughout, so under
    JEPSEN_TPU_CONFIG_PACK the per-device tables, the frontier
    shards, AND the owner-routed all-to-all payloads all carry the
    packed word — 1-2 u32 lanes over the wire instead of 3."""
    step = STEPS[step_name]
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    L = rep.lanes
    if probe_limit <= 0:
        # host entry points resolve eagerly (the value keys the jit
        # cache); this is the safety net for default-arg callers
        probe_limit = _resolve_probe_limit(0)
    Td = _next_pow2(2 * Nd)

    def insert_append(c_rows, c_live, f_rows, count, table):
        """One visited-set transaction — fused kernel when enabled and
        the static shapes fit, the plain XLA form otherwise. Under
        `search_stats` an extra trailing element: the probe-length
        histogram (zeros on the fused-kernel path — the probe offsets
        never leave the kernel; the stats block notes which
        implementation ran via the result's closure tag)."""
        if sparse_pallas in ("on", "interpret"):
            from jepsen_tpu.parallel import sparse_kernels as sk
            if sk.insert_supported(int(c_rows[0].shape[0]), Nd, L):
                out = sk.hash_insert_call(
                    c_rows, c_live, f_rows, count, table,
                    probe_limit, Nd, C, pack,
                    interpret=(sparse_pallas == "interpret"))
                if search_stats:
                    return out + (jnp.zeros(N_PROBE_BUCKETS,
                                            jnp.int32),)
                return out
        return _hash_insert_append(c_rows, c_live, f_rows, count,
                                   table, probe_limit, Nd, rep,
                                   stats=search_stats)

    step_cc = jax.vmap(
        jax.vmap(step, in_axes=(None, 0, 0, 0, 0)),
        in_axes=(0, None, None, None, None),
    )

    def closure_cond(c):
        return c["changed"] & ~c["ovf"]

    def make_closure_body(ev):
        def body(c):
            rows, live = c["rows"], c["live"]
            cand_st, cand_ok = step_cc(
                rep.state(rows), ev["slot_f"], ev["slot_a0"],
                ev["slot_a1"], ev["slot_wild"])
            already = rep.mask_test(rows)
            legal = (live[:, None] & ev["slot_occ"][None, :]
                     & ~already & cand_ok)
            c_rows, c_live, route_ovf = route_cand(
                rep.candidates(rows, cand_st), legal.reshape(-1))
            all_rows = _rows_concat(rows, c_rows)
            all_live = jnp.concatenate([live, c_live])
            old_n = lax.psum(jnp.sum(live), axes)
            rows2, live2, cnt, ovf = _owned_dedupe_compact(
                all_rows, all_live, Nd, n_dev, my_idx, rep)
            new_n = lax.psum(cnt, axes)
            g_ovf = lax.psum((ovf | route_ovf).astype(jnp.int32), axes) > 0
            out = {"rows": rows2, "live": live2,
                   "changed": new_n > old_n, "ovf": g_ovf,
                   "stepped": c["stepped"] + old_n}
            if search_stats:
                out["iters"] = c["iters"] + 1
            return out
        return body

    def hash_closure_cond(c):
        return c["changed"] & ~c["ovf"]

    def make_hash_closure_body(ev):
        def body(c):
            rows = c["rows"]
            n_old, count = c["n_old"], c["count"]
            cand_st, cand_ok = step_cc(
                rep.state(rows), ev["slot_f"], ev["slot_a0"],
                ev["slot_a1"], ev["slot_wild"])
            row = jnp.arange(Nd)
            delta = (row >= n_old) & (row < count)
            already = rep.mask_test(rows)
            legal = (delta[:, None] & ev["slot_occ"][None, :]
                     & ~already & cand_ok)
            c_rows, c_live, route_ovf = route_cand(
                rep.candidates(rows, cand_st), legal.reshape(-1))
            # the gather A/B exchange broadcasts EVERY candidate to
            # every device; inserting only owned rows is what keeps
            # each table (and the frontier) a partition, not a replica
            owner = rep.owner_hash(c_rows) % jnp.uint32(n_dev)
            c_live = c_live & (owner == my_idx)
            ins = insert_append(c_rows, c_live, rows, count,
                                c["table"])
            rows2, table, count2, n_fresh, ins_ovf = ins[:5]
            l_ovf = (ins_ovf | route_ovf).astype(jnp.int32)
            g_new, g_delta, g_ovf = lax.psum(
                (n_fresh, count - n_old, l_ovf), axes)
            out = {
                "rows": rows2,
                "n_old": count,
                "count": count2,
                "table": table,
                "changed": g_new > 0,
                "ovf": c["ovf"] | (g_ovf > 0),
                "stepped": c["stepped"] + g_delta,
            }
            if search_stats:
                out["iters"] = c["iters"] + 1
                # the sort-equivalent work: the whole GLOBAL frontier
                # this iteration (what sort would have re-stepped)
                out["swork"] = c["swork"] + lax.psum(count, axes)
                out["phist"] = c["phist"] + ins[5]
            return out
        return body

    def run_closure(ev, rows, live, run, stepped):
        """-> (rows2, live2, ovf, stepped2, extras) with extras =
        (iters, swork, phist_local) under search_stats, else None."""
        if dedupe == "sort":
            carry0 = {"rows": rows, "live": live, "changed": run,
                      "ovf": jnp.array(False), "stepped": stepped}
            if search_stats:
                carry0["iters"] = jnp.int32(0)
            out = lax.while_loop(closure_cond, make_closure_body(ev),
                                 carry0)
            extras = ((out["iters"], out["stepped"] - stepped,
                       jnp.zeros(N_PROBE_BUCKETS, jnp.int32))
                      if search_stats else None)
            return (out["rows"], out["live"], out["ovf"],
                    out["stepped"], extras)
        # seed the per-event visited set with the local frontier
        # (owned rows by invariant), compacting it in the same pass;
        # the append overflow arm of insert_append is unreachable here
        # (at most Nd seed rows fit an Nd frontier), so its flag is
        # the pure probe-exhaustion signal the sort of carry expects
        seed = insert_append(rows, live, rep.zeros(Nd), jnp.int32(0),
                             _empty_table(Td, rep))
        rows0, table, m0, _, p0 = seed[:5]
        g_p0 = lax.psum(p0.astype(jnp.int32), axes) > 0
        carry0 = {
            "rows": rows0,
            "n_old": jnp.int32(0), "count": m0, "table": table,
            "changed": run, "ovf": g_p0, "stepped": stepped}
        if search_stats:
            carry0["iters"] = jnp.int32(0)
            carry0["swork"] = jnp.int32(0)
            carry0["phist"] = seed[5]
        out = lax.while_loop(
            hash_closure_cond, make_hash_closure_body(ev), carry0)
        live2 = jnp.arange(Nd) < out["count"]
        extras = ((out["iters"], out["swork"], out["phist"])
                  if search_stats else None)
        return out["rows"], live2, out["ovf"], out["stepped"], extras

    def scan_step(carry, ev):
        rows = carry[:L]
        live, ok, fail_r, r_idx, maxf, stepped = carry[L:]
        run = ok & (ev["ev_slot"] >= 0)
        rows2, live2, ovf, stepped2, extras = run_closure(
            ev, rows, live, run, stepped)
        # the hash prologue runs unconditionally (lax.scan cannot skip
        # an event): gate its probe flag so a pad/settled event never
        # leaks into the capacity-escalation decision
        ovf = run & ovf
        s = jnp.maximum(ev["ev_slot"], 0).astype(jnp.uint32)
        bits = rep.event_bits(s)
        has = rep.has_event_bit(rows2, bits)
        live3 = live2 & has
        rows3 = rep.clear_event_bit(rows2, bits, live3)
        n_live = lax.psum(jnp.sum(live3), axes)
        failed_here = run & (n_live == 0)
        # clearing the slot bit changed every survivor's hash — re-route
        # each config to its new owner device before the next closure
        r_rows, r_live, rt_ovf = route_front(rows3, live3)
        rows3, live3, _, r_ovf = _owned_dedupe_compact(
            r_rows, r_live, Nd, n_dev, my_idx, rep)
        ovf = ovf | (run & (lax.psum((r_ovf | rt_ovf).astype(jnp.int32),
                                     axes) > 0))
        new_ok = jnp.where(run, ~failed_here & ~ovf, ok)
        new_fail = jnp.where(failed_here & (fail_r < 0), r_idx, fail_r)
        rows_o = _rows_where(run, rows3, rows)
        live_o = jnp.where(run, live3, live)
        maxf = jnp.maximum(maxf, jnp.where(run,
                                           lax.psum(jnp.sum(live2), axes),
                                           0))
        stepped_o = jnp.where(run, stepped2, stepped)
        carry_o = rows_o + (live_o, new_ok, new_fail,
                            r_idx + 1, maxf, stepped_o)
        if not search_stats:
            return carry_o, ovf
        # per-event stats: width/peak/phist are DEVICE-LOCAL (the
        # per-device variants the host sums/maxes into the
        # mesh-reduced block); iters/stepped/swork are already global
        # (the closure's psums synchronize every device)
        y = {
            "ovf": ovf,
            "width": jnp.where(run, jnp.sum(live3),
                               -1).astype(jnp.int32),
            "peak": jnp.where(run, jnp.sum(live2), 0).astype(jnp.int32),
            "iters": jnp.where(run, extras[0], 0).astype(jnp.int32),
            "stepped": jnp.where(run, stepped2 - stepped,
                                 0).astype(jnp.int32),
            "swork": jnp.where(run, extras[1], 0).astype(jnp.int32),
            "phist": jnp.where(run, extras[2], 0).astype(jnp.int32),
        }
        return carry_o, y

    carry, ys = lax.scan(scan_step, carry0, xs)
    if search_stats:
        return carry, jnp.any(ys["ovf"]), ys
    return carry, jnp.any(ys)


def _sharded_core(xs, state0, step_name: str, Nd: int, n_dev: int,
                  my_idx, axes, route_cand, route_front,
                  dedupe: str = "sort", probe_limit: int = 0,
                  sparse_pallas: str = "off",
                  search_stats: bool = False, pack: tuple = ()):
    """Fresh-start wrapper over _sharded_scan: seed the initial config
    on its hash-owner device, scan the whole history, reduce to the
    (valid, fail, overflow, maxf, stepped) scalars — plus, under
    `search_stats`, the per-event stats dict (width/peak/phist with a
    leading per-device axis of 1, stacked to [n_dev, R] by the
    shard_map out_specs; iters/stepped/swork replicated)."""
    rep = _rep(pack, xs["slot_f"].shape[1])
    # initial config lives on its hash-owner device
    rows0 = rep.initial_full(state0, Nd)
    owner0 = rep.owner_hash(
        tuple(r[:1] for r in rows0))[0] % jnp.uint32(n_dev)
    live0 = (jnp.arange(Nd) < 1) & (owner0 == my_idx)
    carry0 = rows0 + (live0, jnp.array(True), jnp.int32(-1),
                      jnp.int32(0), jnp.int32(1), jnp.int32(0))
    out = _sharded_scan(xs, carry0, step_name, Nd, n_dev,
                        my_idx, axes, route_cand, route_front,
                        dedupe, probe_limit, sparse_pallas,
                        search_stats, pack)
    carry, overflow = out[0], out[1]
    live, ok, fail_r, _, maxf, stepped = carry[rep.lanes:]
    valid = ok & (lax.psum(jnp.sum(live), axes) > 0) & ~overflow
    if not search_stats:
        return valid, fail_r, overflow, maxf, stepped
    ys = out[2]
    stats = {
        "width": ys["width"][None, :],
        "peak": ys["peak"][None, :],
        "phist": ys["phist"][None, :, :],
        "iters": ys["iters"],
        "stepped": ys["stepped"],
        "swork": ys["swork"],
    }
    return valid, fail_r, overflow, maxf, stepped, stats


def _flat_routes(Nd: int, C: int, n_dev: int, rep):
    """(route_cand, route_front) for the flat 1-D topology.
    Owner-bucket widths: 2x the uniform share (hash-uniform slack),
    floored so tiny frontiers never trip the overflow path."""
    B_cand = max(64, -(-2 * Nd * C // n_dev))
    B_front = max(64, -(-2 * Nd // n_dev))
    route_cand = lambda rows, lv: _route_to_owners(
        rows, lv, n_dev, B_cand, rep)
    route_front = lambda rows, lv: _route_to_owners(
        rows, lv, n_dev, B_front, rep)
    return route_cand, route_front


def _sharded_impl(xs, state0, step_name: str, Nd: int, n_dev: int,
                  exchange: str = "route", dedupe: str = "sort",
                  probe_limit: int = 0, sparse_pallas: str = "off",
                  search_stats: bool = False, pack: tuple = ()):
    """1-D topology adapter: flat owner routing over AXIS, or the
    all-gather broadcast (A/B measurement path)."""
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    my_idx = lax.axis_index(AXIS).astype(jnp.uint32)
    if exchange == "route":
        route_cand, route_front = _flat_routes(Nd, C, n_dev, rep)
    else:
        def _bcast(rows, lv):
            g = lambda a: lax.all_gather(a, AXIS, tiled=True)
            return tuple(g(r) for r in rows), g(lv), jnp.array(False)
        route_cand = route_front = _bcast
    return _sharded_core(xs, state0, step_name, Nd, n_dev, my_idx,
                         (AXIS,), route_cand, route_front, dedupe,
                         probe_limit, sparse_pallas, search_stats,
                         pack)


def _sharded2d_impl(xs, state0, step_name: str, Nd: int,
                    n_slice: int, n_chip: int, dedupe: str = "sort",
                    probe_limit: int = 0, sparse_pallas: str = "off",
                    search_stats: bool = False, pack: tuple = ()):
    """2-D topology adapter (slice x chip): the multi-slice story.
    Owner routing is HIERARCHICAL — stage 1 delivers candidates to the
    owner's chip COLUMN over the intra-slice axis (ICI); stage 2
    crosses slices (DCN) with rows already aggregated into one bucket
    per destination slice. Each row still crosses the slice boundary
    exactly once, but DCN sees n_slice large buckets per device instead
    of n_slice*n_chip small ones — message-count, not byte-count, is
    what DCN latency punishes."""
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    D = n_slice * n_chip
    my_idx = (lax.axis_index(AX_SLICE) * n_chip
              + lax.axis_index(AX_CHIP)).astype(jnp.uint32)
    # bucket widths: 2x the uniform share at each stage; stage-2 input
    # is the stage-1 receive buffer (n_chip * B1 rows)
    B1c = max(64, -(-2 * Nd * C // n_chip))
    B2c = max(64, -(-2 * n_chip * B1c // n_slice))
    B1f = max(64, -(-2 * Nd // n_chip))
    B2f = max(64, -(-2 * n_chip * B1f // n_slice))

    def route2(rows, live, B1, B2):
        owner = rep.owner_hash(rows) % jnp.uint32(D)
        rows, live, o1 = _route_stage(
            rows, live, owner % jnp.uint32(n_chip), n_chip, B1,
            AX_CHIP)
        owner = rep.owner_hash(rows) % jnp.uint32(D)
        rows, live, o2 = _route_stage(
            rows, live, owner // jnp.uint32(n_chip), n_slice, B2,
            AX_SLICE)
        return rows, live, o1 | o2

    return _sharded_core(
        xs, state0, step_name, Nd, D, my_idx, (AX_SLICE, AX_CHIP),
        lambda rows, lv: route2(rows, lv, B1c, B2c),
        lambda rows, lv: route2(rows, lv, B1f, B2f),
        dedupe, probe_limit, sparse_pallas, search_stats, pack)


def _stats_out_specs(dev_axes):
    """out_specs for the per-event stats dict: width/peak/phist stack
    their leading per-device axis over the mesh; the psum-synchronized
    scalars stay replicated."""
    return {"width": P(dev_axes), "peak": P(dev_axes),
            "phist": P(dev_axes), "iters": P(), "stepped": P(),
            "swork": P()}


# donation decision (recompile-donate-argnums) for the two tier jits
# below, DECIDED: nothing donatable — xs/state0 are replicated inputs
# reused across the capacity-doubling retry loop in
# check_encoded_sharded (the SAME device arrays re-dispatch at doubled
# Nd), and every output is a replicated scalar, so no input could
# alias an output anyway.
@functools.partial(jax.jit,
                   donate_argnums=(),
                   static_argnames=("step_name", "Nd", "n_slice",
                                    "n_chip", "mesh", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "search_stats", "pack"))
def _check_sharded2d(xs, state0, step_name: str, Nd: int, n_slice: int,
                     n_chip: int, mesh: Mesh, dedupe: str = "sort",
                     probe_limit: int = 0, sparse_pallas: str = "off",
                     search_stats: bool = False, pack: tuple = ()):
    out_specs = (P(), P(), P(), P(), P())
    if search_stats:
        out_specs = out_specs + (
            _stats_out_specs((AX_SLICE, AX_CHIP)),)
    fn = _shard_map(
        lambda x, s0: _sharded2d_impl(x, s0, step_name, Nd, n_slice,
                                      n_chip, dedupe, probe_limit,
                                      sparse_pallas, search_stats,
                                      pack),
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(xs, state0)


# same (decided) donation as _check_sharded2d above
@functools.partial(jax.jit,
                   donate_argnums=(),
                   static_argnames=("step_name", "Nd", "n_dev",
                                    "mesh", "exchange", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "search_stats", "pack"))
def _check_sharded(xs, state0, step_name: str, Nd: int, n_dev: int,
                   mesh: Mesh, exchange: str = "route",
                   dedupe: str = "sort", probe_limit: int = 0,
                   sparse_pallas: str = "off",
                   search_stats: bool = False, pack: tuple = ()):
    out_specs = (P(), P(), P(), P(), P())
    if search_stats:
        out_specs = out_specs + (_stats_out_specs(AXIS),)
    fn = _shard_map(
        lambda x, s0: _sharded_impl(x, s0, step_name, Nd, n_dev, exchange,
                                    dedupe, probe_limit, sparse_pallas,
                                    search_stats, pack),
        mesh=mesh,
        in_specs=(P(), P()),       # tables + state replicated
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(xs, state0)


def _sharded_resume_impl(xs, carry, step_name: str, Nd: int,
                         n_dev: int, dedupe: str = "sort",
                         probe_limit: int = 0,
                         sparse_pallas: str = "off",
                         pack: tuple = ()):
    """Resume-from-carry adapter (runs INSIDE shard_map), 1-D topology.

    Restored rows arrive laid out however the host scattered them — a
    checkpoint may be resumed on a DIFFERENT mesh size — so the first
    act is one owner-routing round delivering every row to its current
    hash-owner; from then on the invariant the scan relies on (each
    live row lives on its owner device) holds. Returns the final carry
    (frontier sharded, scalars replicated) plus the overflow flag."""
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    L = rep.lanes
    my_idx = lax.axis_index(AXIS).astype(jnp.uint32)
    route_cand, route_front = _flat_routes(Nd, C, n_dev, rep)
    rows, rest = carry[:L], carry[L:]
    live = rest[0]

    # the restore route's destinations are maximally SKEWED, not
    # hash-uniform — on the same mesh every one of a device's rows goes
    # back to that one device — so it gets worst-case buckets (B = Nd)
    # rather than route_front's 2x-uniform slack; it runs once per
    # chunk, so the O(n_dev * Nd) receive buffer is fine
    r_rows, r_live, rt_ovf = _route_to_owners(rows, live, n_dev, Nd,
                                              rep)
    rows2, live2, _, d_ovf = _owned_dedupe_compact(
        r_rows, r_live, Nd, n_dev, my_idx, rep)
    pre_ovf = lax.psum((rt_ovf | d_ovf).astype(jnp.int32), (AXIS,)) > 0

    carry0 = rows2 + (live2,) + rest[1:]
    carry, scan_ovf = _sharded_scan(xs, carry0, step_name, Nd, n_dev,
                                    my_idx, (AXIS,), route_cand,
                                    route_front, dedupe, probe_limit,
                                    sparse_pallas, pack=pack)
    return carry, scan_ovf | pre_ovf


# donation decision, DECIDED: the resumable carry tuple DONATES — the
# host places fresh device arrays from the (canonical, host-side)
# FrontierCheckpoint on every chunk dispatch including the
# overflow-retry, and the output carry aliases it shape-for-shape; at
# the top capacity tiers the carry is the peak-HBM buffer. xs stays
# undonated (replicated event tables, nothing to alias).
@functools.partial(jax.jit,
                   donate_argnames=("carry",),
                   static_argnames=("step_name", "Nd", "n_dev",
                                    "mesh", "dedupe", "probe_limit",
                                    "sparse_pallas", "pack"))
def _check_sharded_resume(xs, carry, step_name: str, Nd: int,
                          n_dev: int, mesh: Mesh, dedupe: str = "sort",
                          probe_limit: int = 0,
                          sparse_pallas: str = "off",
                          pack: tuple = ()):
    L = pack_lanes(pack, xs["slot_f"].shape[1])
    carry_specs = tuple([P(AXIS)] * L) + (P(AXIS),) \
        + tuple([P()] * 5)
    fn = _shard_map(
        lambda x, c: _sharded_resume_impl(x, c, step_name, Nd, n_dev,
                                          dedupe, probe_limit,
                                          sparse_pallas, pack),
        mesh=mesh,
        in_specs=(P(), carry_specs),
        out_specs=(carry_specs, P()),
        check_vma=False,
    )
    return fn(xs, carry)


def _sharded_resume2d_impl(xs, carry, step_name: str, Nd: int,
                           n_slice: int, n_chip: int,
                           dedupe: str = "sort", probe_limit: int = 0,
                           sparse_pallas: str = "off",
                           pack: tuple = ()):
    """Resume-from-carry adapter for the HIERARCHICAL 2-D topology —
    the 2-D twin of _sharded_resume_impl, built so the elastic ladder
    can promote a mid-search frontier from a 1-D slice onto extra
    slices (the DCN axis) without restarting the scan.

    The restore route gets worst-case buckets at both stages (the
    rows arrive laid out however the previous — possibly narrower,
    possibly flat — topology left them): stage 1 may send all of a
    device's Nd rows to one chip column, stage 2 all of the received
    n_chip*Nd rows to one slice. It runs once per chunk, so the
    O(N)-row receive buffer is the same posture as the 1-D restore."""
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    L = rep.lanes
    D = n_slice * n_chip
    my_idx = (lax.axis_index(AX_SLICE) * n_chip
              + lax.axis_index(AX_CHIP)).astype(jnp.uint32)
    B1c = max(64, -(-2 * Nd * C // n_chip))
    B2c = max(64, -(-2 * n_chip * B1c // n_slice))
    B1f = max(64, -(-2 * Nd // n_chip))
    B2f = max(64, -(-2 * n_chip * B1f // n_slice))

    def route2(rows, live, B1, B2):
        owner = rep.owner_hash(rows) % jnp.uint32(D)
        rows, live, o1 = _route_stage(
            rows, live, owner % jnp.uint32(n_chip), n_chip, B1,
            AX_CHIP)
        owner = rep.owner_hash(rows) % jnp.uint32(D)
        rows, live, o2 = _route_stage(
            rows, live, owner // jnp.uint32(n_chip), n_slice, B2,
            AX_SLICE)
        return rows, live, o1 | o2

    rows, rest = carry[:L], carry[L:]
    live = rest[0]
    r_rows, r_live, pre1 = _route_stage(
        rows, live,
        (rep.owner_hash(rows) % jnp.uint32(D)) % jnp.uint32(n_chip),
        n_chip, Nd, AX_CHIP)
    owner2 = rep.owner_hash(r_rows) % jnp.uint32(D)
    r_rows, r_live, pre2 = _route_stage(
        r_rows, r_live, owner2 // jnp.uint32(n_chip), n_slice,
        n_chip * Nd, AX_SLICE)
    rows2, live2, _, d_ovf = _owned_dedupe_compact(
        r_rows, r_live, Nd, D, my_idx, rep)
    pre_ovf = lax.psum((pre1 | pre2 | d_ovf).astype(jnp.int32),
                       (AX_SLICE, AX_CHIP)) > 0

    carry0 = rows2 + (live2,) + rest[1:]
    carry, scan_ovf = _sharded_scan(
        xs, carry0, step_name, Nd, D, my_idx, (AX_SLICE, AX_CHIP),
        lambda r, lv: route2(r, lv, B1c, B2c),
        lambda r, lv: route2(r, lv, B1f, B2f),
        dedupe, probe_limit, sparse_pallas, pack=pack)
    return carry, scan_ovf | pre_ovf


# donation decision, DECIDED: same as _check_sharded_resume — the
# carry tuple donates (rebuilt per chunk from the host checkpoint,
# output aliases it), xs stays undonated (replicated event tables).
@functools.partial(jax.jit,
                   donate_argnames=("carry",),
                   static_argnames=("step_name", "Nd", "n_slice",
                                    "n_chip", "mesh", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "pack"))
def _check_sharded_resume2d(xs, carry, step_name: str, Nd: int,
                            n_slice: int, n_chip: int, mesh: Mesh,
                            dedupe: str = "sort",
                            probe_limit: int = 0,
                            sparse_pallas: str = "off",
                            pack: tuple = ()):
    L = pack_lanes(pack, xs["slot_f"].shape[1])
    dev_axes = (AX_SLICE, AX_CHIP)
    carry_specs = tuple([P(dev_axes)] * L) + (P(dev_axes),) \
        + tuple([P()] * 5)
    fn = _shard_map(
        lambda x, c: _sharded_resume2d_impl(x, c, step_name, Nd,
                                            n_slice, n_chip, dedupe,
                                            probe_limit, sparse_pallas,
                                            pack),
        mesh=mesh,
        in_specs=(P(), carry_specs),
        out_specs=(carry_specs, P()),
        check_vma=False,
    )
    return fn(xs, carry)


def check_encoded_sharded_elastic(e: EncodedHistory, mesh: Mesh,
                                  capacity: int = 8192,
                                  max_capacity: int = 1 << 22,
                                  start_devices: int = 0,
                                  checkpoint_every: int = 256,
                                  dedupe=None, probe_limit: int = 0,
                                  sparse_pallas=None,
                                  search_stats=None,
                                  config_pack=None) -> dict:
    """Re-shard-on-escalation (JEPSEN_TPU_RESHARD): the sharded search
    with the elastic capacity ladder. Where check_encoded_sharded
    answers every overflow by doubling per-device tables on a FIXED
    device set, this arm starts on a narrow slice of the mesh
    (``start_devices``, default 2) and each overflow first RECRUITS
    devices along MeshPlan.ladder's rungs — wider 1-D within the first
    slice, then whole extra slices via the hierarchical 2-D exchange —
    holding per-device capacity flat, so escalation costs ICI/DCN
    fan-out instead of per-device HBM. Only once the full mesh is
    recruited does capacity growth fall back to the historical
    table-doubling; ``max_capacity`` and the overflow->unknown
    semantics are unchanged.

    The scan runs in checkpointed chunks (the resumable machinery —
    CONTRACT TWIN of check_encoded_sharded_resumable's loop: same
    supervised dispatch, same overflow re-run-the-chunk rule). A
    re-shard re-dispatches the current chunk on the wider rung; the
    restore route's owner-routed all-to-all is what redistributes the
    checkpointed visited set onto the new device slice. Results carry
    the verdict fields of check_encoded_sharded plus a ``"reshard"``
    block ({start-devices, events: [{event, devices, capacity}, ...]})
    — the key exists only on this arm, so flag-off results stay
    byte-identical. Per-event search-stats blocks are not produced on
    the resumable jits (the resumable-arm precedent); ``search_stats``
    is accepted for signature compatibility and ignored."""
    from time import perf_counter as _pc

    from jepsen_tpu.parallel.engine import (FrontierCheckpoint,
                                            carry_fields_np,
                                            history_digest)
    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    del search_stats   # no stats outputs on the resumable jits
    dedupe = _resolve_dedupe(dedupe)
    probe_limit = _resolve_probe_limit(probe_limit)
    pack_req = _resolve_config_pack(config_pack)
    C_enc = e.slot_f.shape[1]
    pack = pack_spec_for(e) if pack_req else ()
    plan_full = MeshPlan.from_mesh(mesh, "route")
    if start_devices <= 0:
        start_devices = min(2, plan_full.n_dev)
    rungs = plan_full.ladder(start_devices)
    rung = 0
    n_dev = rungs[0].n_dev
    # per-device capacity held flat across the recruiting rungs: the
    # global capacity of rung r is Nd0 * n_dev(r)
    Nd0 = -(-max(64, capacity) // n_dev)
    N = Nd0 * n_dev
    platform = plan_full.platform
    digest = history_digest(e)
    cp = FrontierCheckpoint(
        0, N, e.step_name, digest,
        np.full(N, e.state0, np.int32), np.zeros(N, np.uint32),
        np.zeros(N, np.uint32), np.arange(N) < 1, True, -1, 1, 0)
    reshard_events: list = []
    xs_np = {
        "slot_f": e.slot_f, "slot_a0": e.slot_a0, "slot_a1": e.slot_a1,
        "slot_wild": e.slot_wild, "slot_occ": e.slot_occ,
        "ev_slot": e.ev_slot,
    }
    R = e.n_returns
    mode, note = "off", None
    led = _ledger.active()
    t_start = _pc()
    with obs.span("sharded.elastic", devices=plan_full.n_dev,
                  dedupe=dedupe, returns=R) as sp:
        while cp.event_index < R and cp.ok:
            plan = rungs[rung]
            n_dev = plan.n_dev
            sub_mesh = plan.mesh()
            Nd = N // n_dev
            mode, note = _resolve_sparse_pallas(
                sparse_pallas, Nd, C_enc, plan.n_chip, plan.n_slice,
                "route", platform, dedupe, pack)
            lo = cp.event_index
            hi = min(R, lo + checkpoint_every)
            rep_sh = NamedSharding(sub_mesh, P())
            shard = NamedSharding(
                sub_mesh, P((AX_SLICE, AX_CHIP) if plan.hierarchical
                            else AXIS))

            def _chunk(cp=cp, Nd=Nd, plan=plan, mode=mode, lo=lo,
                       hi=hi, sub_mesh=sub_mesh, rep_sh=rep_sh,
                       shard=shard):
                chunk = {k: jax.device_put(np.asarray(v[lo:hi]),
                                           rep_sh)
                         for k, v in xs_np.items()}
                if pack:
                    rows = pack_rows_np(pack, C_enc, cp.st, cp.ml,
                                        cp.mh)
                else:
                    rows = (cp.st, cp.ml, cp.mh)
                # owned placement before the resume jit donates the
                # carry (engine._place_owned documents the hazard)
                carry_in = jax.tree.map(jnp.copy, tuple(
                    jax.device_put(np.asarray(r), shard)
                    for r in rows)
                    + (jax.device_put(cp.live, shard),
                       jax.device_put(np.bool_(cp.ok), rep_sh),
                       jax.device_put(np.int32(cp.fail_r), rep_sh),
                       jax.device_put(np.int32(cp.event_index),
                                      rep_sh),
                       jax.device_put(np.int32(cp.maxf), rep_sh),
                       jax.device_put(np.int32(cp.stepped), rep_sh)))
                if plan.hierarchical:
                    carry, overflow = _check_sharded_resume2d(
                        chunk, carry_in, e.step_name, Nd,
                        plan.n_slice, plan.n_chip, sub_mesh, dedupe,
                        probe_limit, mode, pack)
                else:
                    carry, overflow = _check_sharded_resume(
                        chunk, carry_in, e.step_name, Nd, n_dev,
                        sub_mesh, dedupe, probe_limit, mode, pack)
                return [np.asarray(x) for x in carry], bool(overflow)

            try:
                carry, overflow = sup.dispatch("sharded", _chunk,
                                               backend=platform)
            except sup.DISPATCH_FAILURES as err:
                err.checkpoint = cp
                raise
            if bool(overflow):
                if rung + 1 < len(rungs):
                    # recruit devices: per-device capacity stays Nd0,
                    # the wider rung's restore route redistributes the
                    # checkpointed visited set over the new slice
                    rung += 1
                    new_n = rungs[rung].n_dev
                    N = Nd0 * new_n
                    reshard_events.append(
                        {"event": cp.event_index,
                         "devices": [n_dev, new_n], "capacity": N})
                    obs.counter("engine.reshard_escalations").inc()
                    if led is not None:
                        led.record(
                            "reshard", engine="sharded",
                            shape={"family": e.step_name, "R": R,
                                   "C": C_enc},
                            rung=rung, devices=[n_dev, new_n],
                            capacity=N, event=cp.event_index)
                    if N > cp.capacity:
                        cp = cp.grown(N)
                    continue
                # full mesh recruited: the historical table-doubling
                if N * 2 > max_capacity:
                    out = _tag_sparse_closure(
                        {"valid?": "unknown",
                         "error": f"frontier overflow at capacity {N}",
                         "capacity": N, "devices": n_dev,
                         "dedupe": dedupe, "checkpoint": cp}, mode,
                        note)
                    out["reshard"] = {"start-devices": start_devices,
                                      "events": reshard_events}
                    return out
                Nd0 *= 2
                N *= 2
                obs.counter("engine.capacity_escalations").inc()
                cp = cp.grown(N)
                continue
            st, ml, mh, live, ok, fail_r, r_idx, maxf, stepped = \
                carry_fields_np(carry, pack, C_enc)
            cp = FrontierCheckpoint(int(r_idx), N, e.step_name, digest,
                                    st, ml, mh, live, bool(ok),
                                    int(fail_r), int(maxf), cp.steps_n,
                                    int(stepped))
        sp.set(capacity=N, devices=n_dev)
    obs.counter("engine.configs_stepped").inc(int(cp.stepped))
    out = {"valid?": cp.ok and bool(cp.live.any()),
           "max-frontier": cp.maxf, "capacity": cp.capacity,
           "devices": n_dev, "dedupe": dedupe,
           "configs-stepped": cp.stepped,
           "reshard": {"start-devices": start_devices,
                       "events": reshard_events}}
    _tag_sparse_closure(out, mode, note)
    _tag_config_pack(out, pack, pack_req, C_enc)
    if led is not None:
        led.record(
            "dispatch", engine="sharded",
            shape={"family": e.step_name, "N": N, "R": R,
                   "C": C_enc, "tier": len(reshard_events),
                   "pack": bool(pack)},
            strategy={"dedupe": dedupe, "closure": mode,
                      "pack": pack_req, "probe_limit": probe_limit,
                      "reshard": True, "devices": start_devices},
            secs=round(_pc() - t_start, 6), keys=1,
            outcome={"verdict": _ledger.verdict_class(out),
                     "devices": n_dev,
                     "resharded": len(reshard_events)})
    if not out["valid?"]:
        from jepsen_tpu.parallel.encode import fail_op_fields
        out.update(fail_op_fields(e, cp.fail_r))
    return out


def _resolve_sparse_pallas(sparse_pallas, Nd: int, C: int, n_chip: int,
                           n_slice: int, exchange: str, platform: str,
                           dedupe: str, pack=()):
    """Sharded arm of engine._resolve_sparse_pallas — same flag, same
    tri-state, but gated on the per-device INSERT shapes: the largest
    candidate buffer a device receives from the exchange (flat route:
    n_dev buckets of the 2x-uniform width; hierarchical: the stage-2
    receive; gather: every candidate on every device) plus its own
    Nd-row frontier tile. Width-aware like the engine's (packed rows
    clear the gate at larger Nd), but with no tiled arm — the
    received candidate buffer is transient exchange output, so a
    past-gate tier degrades to the XLA insert with a note, as before.
    Returns (mode, note) like the engine's."""
    from jepsen_tpu.parallel.engine import \
        _resolve_sparse_pallas as engine_resolve
    # flag / tri-state / platform / dedupe-contradiction resolution on
    # a trivially-supported shape; the buffer gate below is the
    # sharded-specific part
    mode, _ = engine_resolve(sparse_pallas, 1, 1, platform, dedupe)
    if mode == "off":
        return mode, None
    mode = "on" if mode in ("on", "tiled") else "interpret"
    n_dev = n_chip * n_slice
    if exchange == "gather":
        M = n_dev * Nd * C
    elif n_slice > 1:
        B1 = max(64, -(-2 * Nd * C // n_chip))
        M = n_slice * max(64, -(-2 * n_chip * B1 // n_slice))
    else:
        M = n_dev * max(64, -(-2 * Nd * C // n_dev))
    from jepsen_tpu.parallel import sparse_kernels as sk
    lanes = pack_lanes(pack, C)
    if not sk.insert_supported(M, Nd, lanes):
        obs.counter("engine.sparse_pallas_fallbacks").inc()
        note = (f"sparse insert kernel skipped at per-device capacity "
                f"{Nd} (C={C}, exchange buffer {M} rows, {lanes} row "
                f"lanes): probe state would exceed the kernel's VMEM "
                f"budget — fell back to the XLA hash insert for this "
                f"tier")
        _log.warning("%s", note)
        return "off", note
    return mode, None


def check_encoded_sharded_resumable(e: EncodedHistory, mesh: Mesh,
                                    capacity: int = 8192,
                                    max_capacity: int = 1 << 22,
                                    checkpoint_every: int = 256,
                                    checkpoint_cb=None,
                                    resume=None,
                                    dedupe=None,
                                    probe_limit: int = 0,
                                    sparse_pallas=None,
                                    config_pack=None) -> dict:
    """check_encoded_sharded with mid-search checkpointing — the
    sharded arm of the checker's checkpoint/resume capability
    (SURVEY.md §5.4; engine.check_encoded_resumable is the single-
    device arm). Events run in chunks of `checkpoint_every`; after
    each chunk the GLOBAL frontier is gathered to host and handed to
    checkpoint_cb(engine.FrontierCheckpoint). The checkpoint is
    topology-independent: `capacity` is the GLOBAL frontier size, rows
    are stored unsharded, and resuming re-routes every row to its
    hash-owner on the CURRENT mesh — a search checkpointed on D
    devices resumes on any other device count (elastic recovery).
    Overflow inside a chunk (including the restore re-route) re-runs
    that chunk at doubled capacity; the prior checkpoint stays valid.

    Topology caveat: this path always runs the FLAT 1-D exchange — a
    2-D multi-slice mesh is flattened (with a warning), unlike
    check_encoded_sharded, which would pick the hierarchical DCN-aware
    exchange for it. `explored` is likewise not tracked across arms:
    sharded checkpoints carry the resume's steps_n through unchanged."""
    from jepsen_tpu.parallel.engine import (FrontierCheckpoint,
                                            history_digest)

    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    devs = np.asarray(mesh.devices)
    if devs.ndim == 2 and devs.shape[0] > 1 and devs.shape[1] > 1:
        _log.warning(
            "resumable sharded check flattens the 2-D mesh to the flat "
            "1-D exchange — the hierarchical multi-slice routing of "
            "check_encoded_sharded is not used on this path")
    devs = devs.reshape(-1)
    mesh = Mesh(devs, (AXIS,))
    n_dev = devs.size
    dedupe = _resolve_dedupe(dedupe)
    probe_limit = _resolve_probe_limit(probe_limit)
    pack_req = _resolve_config_pack(config_pack)
    C_enc = e.slot_f.shape[1]
    pack = pack_spec_for(e) if pack_req else ()
    platform = devs[0].platform
    digest = history_digest(e)
    if resume is not None:
        if resume.history_digest != digest:
            raise ValueError(
                f"checkpoint is for a different history "
                f"(digest {resume.history_digest} != {digest})")
        if resume.step_name != e.step_name:
            raise ValueError("checkpoint is for a different model")
        cp = resume
    else:
        N0 = max(64 * n_dev, capacity)
        cp = FrontierCheckpoint(
            0, N0, e.step_name, digest,
            np.full(N0, e.state0, np.int32), np.zeros(N0, np.uint32),
            np.zeros(N0, np.uint32), np.arange(N0) < 1,
            True, -1, 1, 0)

    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(AXIS))
    xs_np = {
        "slot_f": e.slot_f, "slot_a0": e.slot_a0, "slot_a1": e.slot_a1,
        "slot_wild": e.slot_wild, "slot_occ": e.slot_occ,
        "ev_slot": e.ev_slot,
    }
    R = e.n_returns
    mode, note = "off", None
    while cp.event_index < R and cp.ok:
        # global capacity must divide the mesh; grow to the next
        # multiple when the checkpoint came from a different topology
        N = -(-cp.capacity // n_dev) * n_dev
        if N != cp.capacity:
            cp = cp.grown(N)
        Nd = N // n_dev
        # re-resolve per chunk: capacity growth can cross the kernel's
        # VMEM gate mid-search (degrade-with-note, never an error)
        mode, note = _resolve_sparse_pallas(
            sparse_pallas, Nd, e.slot_f.shape[1], n_dev, 1, "route",
            platform, dedupe, pack)
        lo, hi = cp.event_index, min(R, cp.event_index + checkpoint_every)

        def _chunk(cp=cp, Nd=Nd, mode=mode, lo=lo, hi=hi):
            chunk = {k: jax.device_put(np.asarray(v[lo:hi]), rep)
                     for k, v in xs_np.items()}
            # the checkpoint is canonical-unpacked; rows pack at this
            # boundary when the engine runs the packed layout. The
            # jnp.copy makes every buffer device-OWNED before the
            # resume jit DONATES it — a zero-copy device_put would
            # hand XLA a window onto memory the live checkpoint still
            # owns (engine._place_owned documents the hazard).
            if pack:
                rows = pack_rows_np(pack, C_enc, cp.st, cp.ml, cp.mh)
            else:
                rows = (cp.st, cp.ml, cp.mh)
            carry_in = jax.tree.map(jnp.copy, tuple(
                jax.device_put(np.asarray(r), shard) for r in rows)
                + (jax.device_put(cp.live, shard),
                   jax.device_put(np.bool_(cp.ok), rep),
                   jax.device_put(np.int32(cp.fail_r), rep),
                   jax.device_put(np.int32(cp.event_index), rep),
                   jax.device_put(np.int32(cp.maxf), rep),
                   jax.device_put(np.int32(cp.stepped), rep)))
            carry, overflow = _check_sharded_resume(
                chunk, carry_in, e.step_name, Nd, n_dev, mesh, dedupe,
                probe_limit, mode, pack)
            # materialize inside the supervised window
            return [np.asarray(x) for x in carry], bool(overflow)

        try:
            carry, overflow = sup.dispatch("sharded", _chunk,
                                           backend=platform)
        except sup.DISPATCH_FAILURES as err:
            # the mid-search contract: no work lost — the checkpoint
            # taken before this chunk rides the exception so the
            # caller can resume (on any topology; the checkpoint is
            # topology-independent) once the runtime recovers
            err.checkpoint = cp
            raise
        if bool(overflow):
            if N * 2 > max_capacity:
                return _tag_sparse_closure(
                    {"valid?": "unknown",
                     "error": f"frontier overflow at capacity {N}",
                     "capacity": N, "devices": n_dev,
                     "dedupe": dedupe, "checkpoint": cp}, mode, note)
            cp = cp.grown(N * 2)    # N extra dead rows
            continue                # re-run the same chunk
        from jepsen_tpu.parallel.engine import carry_fields_np
        st, ml, mh, live, ok, fail_r, r_idx, maxf, stepped = \
            carry_fields_np(carry, pack, C_enc)
        cp = FrontierCheckpoint(int(r_idx), N, e.step_name, digest,
                                st, ml, mh, live, bool(ok),
                                int(fail_r), int(maxf), cp.steps_n,
                                int(stepped))
        if checkpoint_cb is not None:
            checkpoint_cb(cp)
    out = {"valid?": cp.ok and bool(cp.live.any()),
           "max-frontier": cp.maxf, "capacity": cp.capacity,
           "devices": n_dev, "dedupe": dedupe,
           "configs-stepped": cp.stepped}
    _tag_sparse_closure(out, mode, note)
    _tag_config_pack(out, pack, pack_req, C_enc)
    if not out["valid?"]:
        from jepsen_tpu.parallel.encode import fail_op_fields
        out.update(fail_op_fields(e, cp.fail_r))
    return out


def _sharded_stats_block(stats, N: int, Nd: int, n_dev: int,
                         dedupe: str, n_esc: int) -> dict:
    """The sharded arm of the JEPSEN_TPU_SEARCH_STATS block:
    mesh-reduced trajectories (global width/peak per event = sum over
    devices) plus the per-device variants skew questions need (which
    device's table runs hottest; whether bucket skew idles part of
    the mesh)."""
    width = np.asarray(stats["width"])          # [n_dev, R]
    peak = np.asarray(stats["peak"])
    phist = np.asarray(stats["phist"])          # [n_dev, R, B]
    iters = np.asarray(stats["iters"]).reshape(-1)
    stepped = np.asarray(stats["stepped"]).reshape(-1)
    swork = np.asarray(stats["swork"]).reshape(-1)
    mask = width[0] >= 0   # run is psum-synchronized: all rows agree
    g_width = width[:, mask].sum(axis=0)
    g_peak = peak[:, mask].sum(axis=0)
    frontier_peak = int(g_peak.max()) if g_peak.size else 0
    stepped_total = int(stepped[mask].sum())
    swork_total = int(swork[mask].sum())
    block = {
        "engine": "sharded",
        "events": int(mask.sum()),
        "frontier-width": [int(x) for x in g_width],
        "closure-iters": [int(x) for x in iters[mask]],
        "configs-stepped-per-event": [int(x) for x in stepped[mask]],
        "closure-peak": [int(x) for x in g_peak],
        "frontier-peak": frontier_peak,
        "capacity": N,
        "capacity-tier": n_esc,
        "peak-occupancy": round(frontier_peak / N, 6) if N else None,
        "dedupe": dedupe,
        "devices": n_dev,
        "delta-split-ratio": (round(stepped_total / swork_total, 6)
                              if swork_total else None),
        "table-capacity": None,
        "load-factor-peak": None,
        "load-factor-final": None,
        "probe-hist": None,
        "probes": None,
        "per-device": {
            "width-peak": [int(width[d, mask].max()) if mask.any()
                           else 0 for d in range(width.shape[0])],
        },
    }
    if dedupe == "hash":
        from jepsen_tpu.parallel.engine import PROBE_HIST_LABELS
        Td = _next_pow2(2 * Nd)
        dev_peak = [int(peak[d, mask].max()) if mask.any() else 0
                    for d in range(peak.shape[0])]
        block["table-capacity"] = Td * n_dev   # union of owned tables
        block["per-device"]["table-capacity"] = Td
        block["per-device"]["load-factor-peak"] = [
            round(p / Td, 6) for p in dev_peak]
        block["load-factor-peak"] = (round(max(dev_peak) / Td, 6)
                                     if dev_peak else None)
        if mask.any():
            block["load-factor-final"] = round(
                int(peak[:, mask][:, -1].max()) / Td, 6)
        hist = phist[:, mask].sum(axis=(0, 1)).astype(np.int64)
        block["probe-hist"] = {lab: int(n) for lab, n in
                               zip(PROBE_HIST_LABELS, hist)}
        block["probes"] = int(hist.sum())
    return block


def check_encoded_sharded(e: EncodedHistory, mesh: Mesh,
                          capacity: int = 8192,
                          max_capacity: int = 1 << 22,
                          exchange: str = "route",
                          dedupe=None,
                          probe_limit: int = 0,
                          sparse_pallas=None,
                          search_stats=None,
                          config_pack=None,
                          reshard=None) -> dict:
    """Check one encoded history with the frontier sharded over `mesh`.

    Topology: a mesh whose device array is 2-D (both dims > 1) with
    exchange="route" selects the HIERARCHICAL multi-slice path — axis 0
    is treated as the slice (DCN) axis, axis 1 as intra-slice chips
    (ICI), candidates route in two stages (see the module docstring),
    and the result carries a "mesh" key. Any other mesh is flattened
    onto a 1-D axis; exchange="gather" (the all-gather A/B measurement
    path) always flattens.

    `capacity` is the GLOBAL frontier capacity; it doubles on overflow
    (frontier past capacity, an owner bucket past its 2x-uniform
    slack, or — under dedupe="hash" — a visited-set probe exhaustion)
    by re-jitting at the next tier, like `engine.check_encoded`.

    `dedupe` picks the per-iteration dedupe: "sort" (owner-filtered
    lexsort) or "hash" (delta-frontier closure over per-device
    open-addressed visited sets — the device-sharded hash set of
    BASELINE.json); None defers to JEPSEN_TPU_DEDUPE. Verdicts and
    counterexample fields are identical; "configs-stepped" records
    the global closure work actually paid.

    `sparse_pallas` (None = JEPSEN_TPU_SPARSE_PALLAS) fuses each
    closure iteration's per-device visited-set transaction into one
    pallas kernel (sparse_kernels.hash_insert_call) — probe, claim
    arbitration, and fresh-row append run VMEM-resident; the
    owner-routing collectives stay in XLA. `probe_limit` as in
    engine.check_encoded (one knob for every hash path).

    `reshard` (None = JEPSEN_TPU_RESHARD) replaces the grow-the-table
    escalation with the elastic device ladder: the search starts on a
    NARROW slice of the mesh and each overflow recruits more devices
    (per-device capacity held flat) before it ever grows per-device
    tables — check_encoded_sharded_elastic's docstring has the
    contract. Flag off = the historical ladder, byte-identical."""
    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    if _resolve_reshard(reshard) and exchange == "route" \
            and np.asarray(mesh.devices).size > 1:
        return check_encoded_sharded_elastic(
            e, mesh, capacity=capacity, max_capacity=max_capacity,
            dedupe=dedupe, probe_limit=probe_limit,
            sparse_pallas=sparse_pallas, search_stats=search_stats,
            config_pack=config_pack)
    dedupe = _resolve_dedupe(dedupe)
    probe_limit = _resolve_probe_limit(probe_limit)
    ss = _resolve_search_stats(search_stats)
    pack_req = _resolve_config_pack(config_pack)
    pack = pack_spec_for(e) if pack_req else ()
    led = _ledger.active()
    # A 2-D device array + "route" = the multi-slice topology: axis 0
    # is the slice (DCN) axis, axis 1 the intra-slice chip (ICI) axis,
    # and the exchange goes hierarchical. Anything else flattens onto
    # a 1-D mesh named AXIS. MeshPlan owns that decision (the elastic
    # ladder and the multi-host seam read the same one).
    plan = MeshPlan.from_mesh(mesh, exchange)
    hier = plan.hierarchical
    mesh = plan.mesh()
    n_dev = plan.n_dev
    if hier:
        n_slice, n_chip = plan.n_slice, plan.n_chip
    # replicate inputs onto the mesh explicitly: nothing may be created
    # on the default backend (it can be a broken TPU runtime while we
    # deliberately run on a CPU mesh — the MULTICHIP_r01 crash mode)
    rep = NamedSharding(mesh, P())
    platform = np.asarray(mesh.devices).flat[0].platform
    # supervised H2D placement — a wedged runtime hangs here exactly
    # like it does at dispatch (site "transfer")
    xs, state0 = sup.dispatch(
        "transfer",
        lambda: (_xs_from_encoded(e, device=rep),
                 jax.device_put(np.int32(e.state0), rep)),
        backend=platform)
    N = max(64 * n_dev, capacity)
    n_esc = 0
    from time import perf_counter as _pc
    t0 = _pc()
    with obs.span("sharded.search", devices=n_dev, dedupe=dedupe,
                  returns=e.n_returns) as sp:
        while True:
            Nd = (N + n_dev - 1) // n_dev
            mode, note = _resolve_sparse_pallas(
                sparse_pallas, Nd, e.slot_f.shape[1],
                n_chip if hier else n_dev, n_slice if hier else 1,
                exchange, platform, dedupe, pack)
            # one span per capacity-tier attempt, per-device capacity
            # attached — the escalation ladder renders as widening
            # steps in the trace
            with obs.span("sharded.tier", capacity=N, per_device=Nd), \
                    obs.device_annotation(f"sharded N{N} D{n_dev}"):
                def _tier(Nd=Nd, mode=mode):
                    if hier:
                        out = _check_sharded2d(xs, state0, e.step_name,
                                               Nd, n_slice, n_chip,
                                               mesh, dedupe,
                                               probe_limit, mode, ss,
                                               pack)
                    else:
                        out = _check_sharded(xs, state0, e.step_name,
                                             Nd, n_dev, mesh, exchange,
                                             dedupe, probe_limit, mode,
                                             ss, pack)
                    # materialize inside the supervised window: async
                    # failures/hangs surface here, not at a host read
                    return jax.tree.map(np.asarray, out)

                # population tracking only: shard_map programs carry
                # mesh-bound layouts the AOT serializer does not
                # round-trip — the registry counts their shape tuples
                # (per tier) without managing the executables
                programs.track(
                    "sharded.check2d" if hier else "sharded.check",
                    xs,
                    (e.step_name, Nd, n_slice if hier else n_dev,
                     n_chip if hier else 1, exchange, dedupe,
                     probe_limit, mode, ss, pack))
                # supervised dispatch (resilience.supervisor): site
                # "sharded" so the fault matrix can target the tier
                # path; failures degrade at the callers (analysis /
                # engine._escalate_overflow)
                res = sup.dispatch("sharded", _tier, backend=platform)
                valid, fail_r, overflow, maxf, stepped = res[:5]
                overflow = bool(overflow)
            if not overflow:
                break
            if N * 2 > max_capacity:
                return _tag_sparse_closure(
                    {"valid?": "unknown",
                     "error": f"frontier overflow at capacity {N}",
                     "capacity": N, "dedupe": dedupe}, mode, note)
            N *= 2
            n_esc += 1
            obs.counter("engine.capacity_escalations").inc()
        sp.set(capacity=N)
        if mode != "off":
            # only when the kernel was requested (engine.check_encoded
            # precedent): flag-off trace schema stays identical
            sp.set(closure="pallas")
    obs.counter("engine.configs_stepped").inc(int(stepped))
    out = {"valid?": bool(valid), "max-frontier": int(maxf),
           "capacity": N, "devices": n_dev, "dedupe": dedupe,
           "configs-stepped": int(stepped)}
    if ss:
        from jepsen_tpu.parallel import engine as eng_mod
        block = _sharded_stats_block(res[5], N, Nd, n_dev, dedupe,
                                     n_esc)
        out["stats"] = eng_mod.finish_stats_block(block, t0, _pc())
    _tag_sparse_closure(out, mode, note)
    _tag_config_pack(out, pack, pack_req, e.slot_f.shape[1])
    if led is not None:
        led.record(
            "dispatch", engine="sharded",
            shape={"family": e.step_name, "N": N,
                   "R": e.n_returns, "C": e.slot_f.shape[1],
                   "tier": n_esc, "pack": bool(pack)},
            strategy={"dedupe": dedupe, "closure": mode,
                      "pack": pack_req, "probe_limit": probe_limit,
                      "reshard": False, "devices": n_dev,
                      "exchange": exchange},
            secs=round(_pc() - t0, 6), keys=1,
            stats=(_ledger.stats_digest([out["stats"]])
                   if ss else None),
            outcome={"verdict": _ledger.verdict_class(out),
                     "escalations": n_esc})
    if hier:
        out["mesh"] = f"{n_slice}x{n_chip} (hierarchical exchange)"
    if not out["valid?"]:
        from jepsen_tpu.parallel.encode import fail_op_fields
        out.update(fail_op_fields(e, int(fail_r)))
    return out


def analysis(model, history, mesh: Mesh, capacity: int = 8192,
             max_capacity: int = 1 << 22, exchange: str = "route",
             dedupe=None, sparse_pallas=None, search_stats=None,
             config_pack=None) -> dict:
    """knossos-style (model, history) -> result with the frontier
    sharded over `mesh`; on failure, counterexample paths come from the
    same windowed host re-search as `engine.analysis` (the seed frontier
    is re-derived on one device — the sharded union equals the
    single-device frontier by construction)."""
    from jepsen_tpu.history import History
    from jepsen_tpu.parallel import encode as enc, engine
    h = history if isinstance(history, History) else History.wrap(history)
    try:
        e = enc.encode(model, h)
    except enc.EncodeError as err:
        # same host fallback as engine.analysis — the two entry points
        # must be interchangeable for non-packable inputs
        from jepsen_tpu.checker import wgl
        obs.counter("engine.host_fallbacks").inc()
        _log.warning(
            "history not device-checkable (%s) — using the host WGL "
            "engine; expect it to be orders of magnitude slower", err)
        r = wgl.analysis(model, h)
        r["fallback"] = str(err)
        return r
    try:
        r = check_encoded_sharded(e, mesh, capacity=capacity,
                                  max_capacity=max_capacity,
                                  exchange=exchange, dedupe=dedupe,
                                  sparse_pallas=sparse_pallas,
                                  search_stats=search_stats,
                                  config_pack=config_pack)
    except sup.DISPATCH_FAILURES as err:
        # degradation contract (docs/resilience.md): a dead sharded
        # tier degrades to the host WGL engine, verdict preserved,
        # with a structured resilience note — same as engine.analysis
        from jepsen_tpu.resilience import recovery
        return recovery.host_check_encoded(
            model, e, getattr(err, "site", "sharded"),
            f"{type(err).__name__}: {err}")
    if r["valid?"] is False:
        engine.apply_final_paths(r, model, e)
    return r
