"""Pallas TPU kernels for the bit-packed linearizability engine.

The bitdense closure (parallel.bitdense) is a fixpoint of bitwise
algebra over the reachable-set tensor B: uint32[S, W]. Under XLA each
fixpoint iteration is a chain of small VPU kernels with an HBM
round-trip per op and a device-visible `changed` reduction per
while-iteration; for the bench's single-key shapes (S ~ 18, W = 256+)
the loop is dispatch-latency-bound, not compute-bound. This kernel runs
the ENTIRE fixpoint inside one `pallas_call`: B lives in VMEM for all
iterations (B + sel + word tables fit comfortably: S*W words ~ tens of
KB against ~16 MB VMEM), and the word-level "move contributions to
mask | bit_j" gather is the XOR-stride shuffle w ^ 2^(j-5), realised as
a reshape/flip — a pure VMEM permutation, no HBM gathers.

SURVEY.md §7.1 step 4: "Pallas kernels where XLA fuses poorly (hash
probe, bitset ops)". This is the bitset-ops kernel.

Default ON for a real-TPU platform since the r5 on-chip A/B
(tools/perf_ab.py: 18.9x on single-1k, 54.4x on single-10k, 1.42x on
the 84x120 batch vs the XLA while closure, bit-identical results on
every run; JEPSEN_TPU_PALLAS=0 opts out, =1 forces interpret mode
elsewhere). Shapes are gated to W >= 128 (one full lane tile) and
S <= 64 (the s-axis reduction is trace-unrolled). CI
differential-tests the kernel in interpreter mode on CPU; the default
flipped only when the hardware measurement landed — flags do not get
to claim speedups.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

U32 = jnp.uint32


def supported(S: int, C: int) -> bool:
    """Shapes this kernel handles: at least one full lane tile of mask
    words, and a trace-unrollable state axis."""
    W = max(1, (1 << C) // 32)
    return W >= 128 and S <= 64 and C >= 5


def _xor_shuffle(G, jb: int):
    """y[..., w] = x[..., w ^ jb] for power-of-two jb: swap adjacent
    jb-wide halves. Spelled as two lane-rotations + per-lane select:
    Mosaic has no `rev` lowering (jnp.flip dies) and rejects 4-D
    reshapes of the lane axis (vector<SxW> -> vector<SxW/2x2x1> is an
    "unsupported shape cast") — both discovered on the real chip;
    interpret mode accepts either spelling. Verified on v5e: jnp.roll
    lowers to supported lane shifts."""
    S, W = G.shape
    up = jnp.roll(G, -jb, axis=1)               # y[w] = G[w + jb]
    dn = jnp.roll(G, jb, axis=1)                # y[w] = G[w - jb]
    wid = lax.broadcasted_iota(jnp.int32, (S, W), 1)
    return jnp.where((wid & jb) == 0, up, dn)


def _closure_kernel(plan, S: int, C: int, W: int,
                    sel_ref, clw_ref, setw_ref, b_ref, out_ref):
    """One return event's closure fixpoint, entirely in VMEM.

    sel  [C, S, S] u32   transition selects (FULL where legal s->t)
    clw  [J1, W]  u32    word masks: FULL where mask-bit j is clear
    setw [J1, W]  u32    word masks: FULL where mask-bit j is set
    b    [S, W]   u32    reachable set, bit b of word w = mask w*32+b
    """
    J0 = min(5, C)

    def expand(B):
        out = B
        for j in range(J0):
            clear = U32(plan[j]["clear"])
            shift = int(plan[j]["shift"])
            ext = B & clear                          # [S, W]
            G = jnp.zeros((S, W), U32)
            for s in range(S):
                G = G | (sel_ref[j, s][:, None] & ext[s][None, :])
            out = out | ((G & clear) << shift)
        for idx in range(C - J0):
            j = J0 + idx
            jb = 1 << (j - 5)
            ext = B & clw_ref[idx][None, :]
            G = jnp.zeros((S, W), U32)
            for s in range(S):
                G = G | (sel_ref[j, s][:, None] & ext[s][None, :])
            out = out | (_xor_shuffle(G, jb) & setw_ref[idx][None, :])
        return out

    def body(carry):
        B, _ = carry
        B2 = expand(B)
        return B2, jnp.any(B2 != B)

    B0 = b_ref[:]
    B_final, _ = lax.while_loop(lambda c: c[1], body, (B0, jnp.bool_(True)))
    out_ref[:] = B_final


def closure_call(sel, B, C: int, interpret: bool = False):  # jepsen-lint: disable=purity-numpy-call
    """Traceable (un-jitted) pallas invocation — usable inside an outer
    scan/cond. sel [C, S, S] u32, B [S, W] u32 -> B' [S, W].
    np here builds the static word tables only (trace-time constants,
    same rationale as bitdense._plan)."""
    from jepsen_tpu.parallel.bitdense import _plan
    S, W = B.shape
    W_plan, plan = _plan(C)
    assert W_plan == W, (W_plan, W)
    assert supported(S, C), (S, C)
    J1 = C - min(5, C)
    clw = np.stack([plan[j]["clearw"] for j in range(5, C)]) \
        if J1 else np.zeros((1, W), np.uint32)
    setw = np.stack([plan[j]["setw"] for j in range(5, C)]) \
        if J1 else np.zeros((1, W), np.uint32)
    kernel = functools.partial(_closure_kernel, plan, S, C, W)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((S, W), jnp.uint32),
        interpret=interpret,
    )(sel, jnp.asarray(clw), jnp.asarray(setw), B)


@functools.partial(jax.jit, static_argnames=("C", "interpret"))
def closure_fixpoint(sel, B, C: int, interpret: bool = False):
    """Run the closure fixpoint for one event: sel [C, S, S] u32,
    B [S, W] u32 -> B' [S, W]. Requires supported(S, C)."""
    return closure_call(sel, B, C, interpret=interpret)
