"""The TPU linearizability engine — batched frontier expansion under jit.

This is the north star (BASELINE.json): the knossos linear/wgl search
re-designed for the MXU/VPU instead of translated. The algorithm is the
JIT-linearization frontier of `jepsen_tpu.checker.linear` (its docstring
is the spec; differential tests pin the two together), mapped to XLA:

  * a configuration is (state: i32, mask: 2×u32) — 96 bits, fixed width;
  * the frontier is a fixed-capacity struct-of-arrays [N] with a live
    mask; capacity doubles on overflow by re-jitting (SURVEY.md §7.3
    hard part #1: capacity-tiered buffers);
  * one closure round = a single vmap'd evaluation of the model step
    over all N×C (config, open-slot) pairs — millions of candidate
    configs per chip per round;
  * dedupe is sort-based (lexsort + adjacent-compare + cumsum scatter):
    static shapes, no host round-trips. The sorted frontier *is* the
    visited set — in this formulation the full config set at the current
    event subsumes knossos's visited cache;
  * the outer loop over return events is a lax.scan; the inner closure
    a lax.while_loop. Nothing data-dependent escapes the device: the
    host gets back (valid, fail_event, stats) scalars only.

Multi-chip: `check_batch` vmaps over keys and shards the key axis over a
mesh (data parallel — P5 in SURVEY.md §2.20); `jepsen_tpu.parallel.sharded`
shards the *frontier* axis with collective dedupe for giant single keys.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from jepsen_tpu import envflags
from jepsen_tpu import obs
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import planner as _planner
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import programs
from jepsen_tpu.parallel.encode import EncodedHistory, EncodeError
from jepsen_tpu.parallel.steps import STEPS
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)


# ------------------------------------------------------------ device core


def _resolve_probe_limit(probe_limit: int = 0) -> int:
    """Bounded linear-probe length for the hash visited-set. A positive
    argument (the test seam threaded through the jits) wins; otherwise
    the validated JEPSEN_TPU_PROBE_LIMIT flag, default 32. At the
    table's <= 50% load factor (capacity 2N for an N-row frontier) a
    32-probe cluster is vanishingly rare under the mixed hash;
    exhaustion raises the overflow flag and rides the existing
    capacity-escalation retry (doubling N doubles the table, halving
    the load factor) instead of ever dropping a config. One knob for
    BOTH the XLA and the pallas hash paths — the host entry points
    resolve it eagerly so the value keys the jit cache (an env change
    between calls recompiles instead of going stale)."""
    if probe_limit and probe_limit > 0:
        return int(probe_limit)
    return envflags.env_int("JEPSEN_TPU_PROBE_LIMIT", default=32,
                            min_value=1, what="probe limit")


def _resolve_config_pack(config_pack) -> bool:
    """JEPSEN_TPU_CONFIG_PACK: pack each configuration's (state,
    mask_lo, mask_hi) triple into the minimal word the event actually
    needs (docs/performance.md "VMEM economics"). Strict tri-state
    (envflags.env_bool), default OFF until the chip A/B records the
    win — the PIPELINE/DEDUPE precedent; flag off means the engine
    runs the historical 3-lane layout byte-identically. An explicit
    argument wins over the env flag, like every other perf knob.
    Resolution yields only the REQUEST; whether a given event family
    actually packs is per-encode (pack_spec_for)."""
    if config_pack is None:
        return bool(envflags.env_bool("JEPSEN_TPU_CONFIG_PACK",
                                      default=False))
    return bool(config_pack)


def pack_layout(n_states: int, state_lo: int, C: int):
    """The packed-word layout for an event family whose states live in
    [state_lo, state_lo + n_states) with a C-slot open-call window, or
    None when the family cannot pack. The word is
    ``(state - state_lo) | mask << state_bits`` — state field in the
    low bits, the C mask bits above it — carried as one or two uint32
    lanes (Mosaic's native width). Packable iff the whole word fits 64
    bits and the state field fits one lane:
    ``state_bits + C <= 64 and state_bits <= 32``. Returns the static
    ``(state_bits, state_lo)`` pair that keys the jit cache."""
    if n_states <= 0 or C <= 0:
        return None
    state_bits = max(1, int(n_states - 1).bit_length())
    if state_bits > 32 or state_bits + C > 64:
        return None
    return (state_bits, int(state_lo))


def pack_spec_for(encs, C: Optional[int] = None):
    """The COMMON packed layout for one or more encoded histories that
    will share a device program (a batch pads to one slot width and
    traces one layout), or () when any of them cannot pack. The state
    field must cover every member's domain, so the layout uses the
    union range [min state_lo, max state_lo + n_states)."""
    if not isinstance(encs, (list, tuple)):
        encs = [encs]
    if not encs:
        return ()
    if any(e.n_states <= 0 for e in encs):
        return ()
    lo = min(e.state_lo for e in encs)
    hi = max(e.state_lo + e.n_states for e in encs)
    Cw = C if C is not None else max(e.slot_f.shape[1] for e in encs)
    lay = pack_layout(hi - lo, lo, Cw)
    return lay if lay is not None else ()


def pack_lanes(pack, C: int) -> int:
    """uint32 lanes one configuration row occupies under `pack` (the
    static (state_bits, state_lo) pair, or () for the historical
    unpacked triple). The VMEM gates price probe state per lane, so
    this is the number the width-aware kernel gates consume."""
    if not pack:
        return 3
    return 1 if pack[0] + C <= 32 else 2


def _resolve_sparse_pallas(sparse_pallas, N: int, C: int, platform: str,
                           dedupe: str, pack=()):
    """The sparse engine's fused-frontier-kernel gate -> (mode, note)
    with mode one of "off" / "on" / "interpret" / "tiled" /
    "tiled-interpret".

    `sparse_pallas` None defers to the strict tri-state
    JEPSEN_TPU_SPARSE_PALLAS flag (default OFF until a chip A/B
    records the win — the JEPSEN_TPU_PIPELINE / JEPSEN_TPU_DEDUPE
    precedent; "1" forces it on, in interpret mode off-TPU like
    JEPSEN_TPU_PALLAS). The kernel is the hash path's fused form, so
    requesting it under dedupe="sort" is a contradiction and raises.

    The gate is WIDTH-AWARE: probe state is priced per row lane
    (pack_lanes — 3 unpacked, 1-2 packed), so packed shapes clear it
    at ~3x the capacity. A shape past the whole-event fusion gate no
    longer degrades wholesale: it runs the TILED closure
    (sparse_kernels.tiled_insert_call — the hash table streams
    HBM<->VMEM in double-buffered tiles, mode "tiled"), and only a
    shape past the tiled planner too falls back to the XLA hash
    closure with a note (the bitdense mesh-fallback precedent: the
    default path degrades, never errors)."""
    if dedupe != "hash":
        if sparse_pallas:
            raise ValueError(
                "sparse_pallas=True requires dedupe='hash' — the fused "
                "frontier kernel is the hash path's implementation")
        if sparse_pallas is None and envflags.env_bool(
                "JEPSEN_TPU_SPARSE_PALLAS", default=False):
            # the env-only misconfiguration must be LOUD: "=1 forces it
            # on" with the dedupe flag left at sort would otherwise
            # read as kernel-measured while the kernel never ran — the
            # 'measured and lost' trap the perf_ab typo-guard closes
            _log.warning(
                "JEPSEN_TPU_SPARSE_PALLAS=1 has no effect under "
                "dedupe=%r — the fused frontier kernel is the hash "
                "path's implementation; set JEPSEN_TPU_DEDUPE=hash",
                dedupe)
        return "off", None
    if sparse_pallas is None:
        sparse_pallas = envflags.env_bool("JEPSEN_TPU_SPARSE_PALLAS",
                                          default=False)
    if not sparse_pallas:
        return "off", None
    from jepsen_tpu.parallel import sparse_kernels as sk
    from jepsen_tpu.parallel.bitdense import is_tpu_platform
    lanes = pack_lanes(pack, C)
    on_tpu = is_tpu_platform(platform)
    if sk.supported(N, C, lanes):
        return ("on" if on_tpu else "interpret"), None
    if sk.tiled_plan(N, C, lanes) is not None:
        return ("tiled" if on_tpu else "tiled-interpret"), None
    obs.counter("engine.sparse_pallas_fallbacks").inc()
    note = (f"sparse frontier kernel skipped at capacity {N} "
            f"(C={C}, {lanes} row lanes): probe state would exceed "
            f"the kernel's VMEM budget even tiled — fell back to the "
            f"XLA hash closure for this tier")
    _log.warning("%s", note)
    return "off", note


def _next_pow2(n: int) -> int:
    return 1 << max(1, (int(n) - 1).bit_length())


def _resolve_search_stats(search_stats) -> bool:
    """JEPSEN_TPU_SEARCH_STATS: device-resident search telemetry.
    Strict tri-state (envflags.env_bool), default OFF — the stats-off
    results, bench schema, and trace output are byte-identical to the
    pre-stats engine (parity-pinned). When on, every engine jit
    additionally returns a compact per-event stats block computed ON
    DEVICE (frontier width, closure iterations, delta split, hash
    load, probe-length histogram — docs/observability.md "Search
    telemetry"); an explicit argument wins over the env flag, the
    same contract as the other perf/telemetry knobs."""
    if search_stats is None:
        return bool(envflags.env_bool("JEPSEN_TPU_SEARCH_STATS",
                                      default=False))
    return bool(search_stats)


# Probe-length histogram buckets (final linear-probe offset per
# attempted insert): upper-exclusive split at these edges, plus an
# overflow bucket. Labels are the host-side vocabulary — the result
# "stats" dict, the engine.search.probe_len.* counters, and the bench
# line all use them, so the device bucketing and every sink agree.
PROBE_HIST_EDGES = (1, 2, 4, 8, 16, 32)
PROBE_HIST_LABELS = ("0", "1", "2-3", "4-7", "8-15", "16-31", "32+")
N_PROBE_BUCKETS = len(PROBE_HIST_LABELS)


def _probe_hist(off, attempted):
    """Bucketed histogram [N_PROBE_BUCKETS] of final probe offsets for
    the attempted inserts — the per-iteration increment the stats
    closure accumulates. Scalar-comparison bucketing (not a
    searchsorted over an edge array): this function runs INSIDE the
    fused pallas kernel, which cannot capture non-scalar constants."""
    idx = jnp.zeros_like(off)
    for edge in PROBE_HIST_EDGES:
        idx = idx + (off >= edge).astype(jnp.int32)
    return jnp.zeros(N_PROBE_BUCKETS, jnp.int32).at[
        jnp.where(attempted, idx, N_PROBE_BUCKETS)].add(1, mode="drop")


def _resolve_dedupe(dedupe: Optional[str]) -> str:
    """The frontier dedupe strategy: "sort" (lexsort + adjacent-compare,
    the historical path) or "hash" (delta-frontier closure over a
    device-resident open-addressed visited set). Default: the
    JEPSEN_TPU_DEDUPE env flag, else "sort" — opt-in until bench
    records the win, the same precedent as JEPSEN_TPU_PIPELINE
    (docs/performance.md "Dedup strategies")."""
    if dedupe is None:
        dedupe = envflags.env_choice("JEPSEN_TPU_DEDUPE",
                                     ("sort", "hash"), default="sort",
                                     what="dedupe strategy")
    if dedupe not in ("sort", "hash"):
        raise ValueError(f"unknown dedupe strategy {dedupe!r}")
    return dedupe


# --------------------------------------- configuration representation
#
# A configuration row travels the engine as a TUPLE OF LANE ARRAYS.
# The historical layout is three lanes — (state i32, mask_lo u32,
# mask_hi u32), 96 bits per config. Under JEPSEN_TPU_CONFIG_PACK the
# row is the minimal word the event family actually needs:
# (state - state_lo) in the low state_bits, the C mask bits above it,
# carried as one or two uint32 lanes (docs/performance.md "VMEM
# economics"). Everything that stores or moves rows — the hash
# visited-set, sort-dedupe compaction, frontier carries, the sharded
# owner-routed all-to-all payloads, the fused kernels — is generic
# over the lane tuple; only the few semantic touch points (the model
# step's state input, slot-bit tests) go through the ConfigRep below,
# so the packed and unpacked paths share one implementation and
# cannot diverge.


class _UnpackedRep:
    """The historical (state, mask_lo, mask_hi) triple. Its methods
    reproduce the pre-pack spellings verbatim — the flag-off engine is
    bit-identical by construction, not merely by test pin."""

    lanes = 3
    pack = ()

    def __init__(self, C: int):
        self.C = C

    def zeros(self, n: int):
        return (jnp.zeros(n, jnp.int32), jnp.zeros(n, jnp.uint32),
                jnp.zeros(n, jnp.uint32))

    def initial_at0(self, state0, N: int):
        return (jnp.zeros(N, jnp.int32).at[0].set(state0),
                jnp.zeros(N, jnp.uint32), jnp.zeros(N, jnp.uint32))

    def initial_full(self, state0, N: int):
        return (jnp.full(N, state0, jnp.int32),
                jnp.zeros(N, jnp.uint32), jnp.zeros(N, jnp.uint32))

    def state(self, rows):
        return rows[0]

    def table_hash(self, rows):
        """Slot mixing for the open-addressed visited set. Deliberately
        a DIFFERENT mix than owner_hash: the sharded engine buckets
        ownership by that hash mod n_dev, so a device's owned configs
        all share its low bits — reusing it for table slots would turn
        every per-device table into one giant collision cluster."""
        st, ml, mh = rows
        h = (st.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)) \
            ^ (ml * jnp.uint32(0xC2B2AE35)) \
            ^ (mh * jnp.uint32(0x27D4EB2F))
        h ^= h >> 16
        h = h * jnp.uint32(0x165667B1)
        h ^= h >> 13
        return h

    def owner_hash(self, rows):
        """sharded ownership mix (historically sharded._hash_config)."""
        st, ml, mh = rows
        h = (st.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)) \
            ^ (ml * jnp.uint32(0x85EBCA77)) \
            ^ (mh * jnp.uint32(0xC2B2AE3D))
        h ^= h >> 15
        h = h * jnp.uint32(0x2C1B3C6D)
        h ^= h >> 12
        return h

    def slot_mask_bits(self):
        """Per-slot mask-lane bit arrays ([C] u32 per mask lane)."""
        return _slot_bits(self.C)

    def mask_test(self, rows):
        """[N, C] bool: slot j already linearized in row n."""
        _, ml, mh = rows
        bit_lo, bit_hi = self.slot_mask_bits()
        return ((ml[:, None] & bit_lo[None, :])
                | (mh[:, None] & bit_hi[None, :])) != 0

    def candidates(self, rows, cand_st):
        """Flattened [N*C] candidate rows: state from the model step,
        mask with slot j's bit set."""
        _, ml, mh = rows
        bit_lo, bit_hi = self.slot_mask_bits()
        return (cand_st.reshape(-1),
                (ml[:, None] | bit_lo[None, :]).reshape(-1),
                (mh[:, None] | bit_hi[None, :]).reshape(-1))

    def event_bits(self, s):
        """Per-mask-lane bit of the (traced u32 scalar) slot s."""
        one = jnp.uint32(1)
        blo = jnp.where(s < 32, one << jnp.minimum(s, 31),
                        jnp.uint32(0)).astype(jnp.uint32)
        bhi = jnp.where(s >= 32,
                        one << jnp.minimum(jnp.where(s >= 32, s - 32, 0),
                                           jnp.uint32(31)),
                        jnp.uint32(0)).astype(jnp.uint32)
        return blo, bhi

    def has_event_bit(self, rows, bits):
        _, ml, mh = rows
        blo, bhi = bits
        return ((ml & blo) | (mh & bhi)) != 0

    def clear_event_bit(self, rows, bits, where):
        st, ml, mh = rows
        blo, bhi = bits
        return (st, jnp.where(where, ml & ~blo, ml),
                jnp.where(where, mh & ~bhi, mh))


class _PackedRep:
    """The packed single-word layout: state field in bits
    [0, state_bits), mask bits at [state_bits, state_bits + C), one
    uint32 lane when the word fits 32 bits, two lanes (lo, hi of the
    uint64 word) otherwise."""

    def __init__(self, state_bits: int, state_lo: int, C: int):
        self.s_bits = int(state_bits)
        self.state_lo = int(state_lo)
        self.C = C
        self.width = self.s_bits + C
        assert self.s_bits <= 32 and self.width <= 64
        self.lanes = 1 if self.width <= 32 else 2
        self.pack = (self.s_bits, self.state_lo)
        self._smask = (1 << self.s_bits) - 1

    @property
    def smask(self):
        # constructed lazily so kernel bodies create the constant
        # INSIDE their trace — a stored jnp scalar would be a captured
        # constant, which pallas_call rejects
        return jnp.uint32(self._smask)

    def zeros(self, n: int):
        return tuple(jnp.zeros(n, jnp.uint32)
                     for _ in range(self.lanes))

    def _field(self, st):
        # legal states are in [state_lo, state_lo + n_states) — the
        # same bound bitdense's bitmap indexing relies on; the mask
        # keeps a garbage state on a dead candidate from spilling into
        # the mask bits (dead rows are never inserted, but their lanes
        # must not poison scatters' defensive reads)
        return (st - self.state_lo).astype(jnp.uint32) & self.smask

    def initial_at0(self, state0, N: int):
        lo = jnp.zeros(N, jnp.uint32).at[0].set(self._field(state0))
        return (lo,) if self.lanes == 1 else (lo,
                                              jnp.zeros(N, jnp.uint32))

    def initial_full(self, state0, N: int):
        lo = jnp.full(N, 1, jnp.uint32) * self._field(state0)
        return (lo,) if self.lanes == 1 else (lo,
                                              jnp.zeros(N, jnp.uint32))

    def state(self, rows):
        return (rows[0] & self.smask).astype(jnp.int32) + self.state_lo

    def table_hash(self, rows):
        h = rows[0] * jnp.uint32(0x85EBCA6B)
        if self.lanes == 2:
            h = h ^ (rows[1] * jnp.uint32(0xC2B2AE35))
        h ^= h >> 16
        h = h * jnp.uint32(0x165667B1)
        h ^= h >> 13
        return h

    def owner_hash(self, rows):
        h = rows[0] * jnp.uint32(0x9E3779B1)
        if self.lanes == 2:
            h = h ^ (rows[1] * jnp.uint32(0x85EBCA77))
        h ^= h >> 15
        h = h * jnp.uint32(0x2C1B3C6D)
        h ^= h >> 12
        return h

    def slot_mask_bits(self):
        js = jnp.arange(self.C, dtype=jnp.uint32) \
            + jnp.uint32(self.s_bits)
        one = jnp.uint32(1)
        blo = jnp.where(js < 32, one << jnp.minimum(js, 31),
                        jnp.uint32(0)).astype(jnp.uint32)
        if self.lanes == 1:
            return (blo,)
        bhi = jnp.where(js >= 32,
                        one << jnp.minimum(js - 32, jnp.uint32(31)),
                        jnp.uint32(0)).astype(jnp.uint32)
        return blo, bhi

    def mask_test(self, rows):
        bits = self.slot_mask_bits()
        acc = (rows[0][:, None] & bits[0][None, :])
        if self.lanes == 2:
            acc = acc | (rows[1][:, None] & bits[1][None, :])
        return acc != 0

    def candidates(self, rows, cand_st):
        bits = self.slot_mask_bits()
        lo = (((rows[0][:, None] & ~self.smask)
               | self._field(cand_st)) | bits[0][None, :]).reshape(-1)
        if self.lanes == 1:
            return (lo,)
        hi = (rows[1][:, None] | bits[1][None, :]).reshape(-1)
        return lo, hi

    def event_bits(self, s):
        p = s + jnp.uint32(self.s_bits)
        one = jnp.uint32(1)
        blo = jnp.where(p < 32, one << jnp.minimum(p, 31),
                        jnp.uint32(0)).astype(jnp.uint32)
        if self.lanes == 1:
            return (blo,)
        bhi = jnp.where(p >= 32,
                        one << jnp.minimum(jnp.where(p >= 32, p - 32, 0),
                                           jnp.uint32(31)),
                        jnp.uint32(0)).astype(jnp.uint32)
        return blo, bhi

    def has_event_bit(self, rows, bits):
        acc = rows[0] & bits[0]
        if self.lanes == 2:
            acc = acc | (rows[1] & bits[1])
        return acc != 0

    def clear_event_bit(self, rows, bits, where):
        return tuple(jnp.where(where, r & ~b, r)
                     for r, b in zip(rows, bits))


def _rep(pack, C: int):
    """The ConfigRep for a static (pack, C) pair — pack is () for the
    historical triple, (state_bits, state_lo) for the packed word."""
    if pack:
        return _PackedRep(pack[0], pack[1], C)
    return _UnpackedRep(C)


def pack_rows_np(pack, C: int, st, ml, mh):
    """Host-side (numpy) packing of canonical (st, ml, mh) rows into
    the lane tuple the (pack, C) layout describes — the
    FrontierCheckpoint boundary: checkpoints store the canonical
    triple (so v1/v2 files, serve freeze/thaw, host_resume seeds, and
    cross-representation resume all keep working) and the engine packs
    at the carry build. Lane count is STATIC (1 when the word fits 32
    bits, else 2) — it must match what the traced program expects."""
    s_bits, s_lo = pack
    word = ((np.asarray(st).astype(np.int64) - s_lo)
            .astype(np.uint64) & np.uint64((1 << s_bits) - 1))
    mask = (np.asarray(ml).astype(np.uint64)
            | (np.asarray(mh).astype(np.uint64) << np.uint64(32)))
    word = word | (mask << np.uint64(s_bits))
    lo = (word & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    if s_bits + C <= 32:
        return (lo,)
    return lo, (word >> np.uint64(32)).astype(np.uint32)


def unpack_rows_np(pack, C: int, rows):
    """Inverse of pack_rows_np: lane tuple -> canonical (st, ml, mh)
    numpy triple."""
    s_bits, s_lo = pack
    lo = np.asarray(rows[0]).astype(np.uint64)
    word = lo if len(rows) == 1 else \
        lo | (np.asarray(rows[1]).astype(np.uint64) << np.uint64(32))
    st = (word & np.uint64((1 << s_bits) - 1)).astype(np.int64) + s_lo
    mask = (word >> np.uint64(s_bits)) \
        & np.uint64((1 << C) - 1 if C < 64 else 0xFFFFFFFFFFFFFFFF)
    ml = (mask & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    mh = (mask >> np.uint64(32)).astype(np.uint32)
    return st.astype(np.int32), ml, mh


def _rows_eq(a_rows, b_rows):
    acc = a_rows[0] == b_rows[0]
    for a, b in zip(a_rows[1:], b_rows[1:]):
        acc = acc & (a == b)
    return acc


def _rows_take(rows, idx):
    return tuple(r[idx] for r in rows)


def _rows_concat(a_rows, b_rows):
    return tuple(jnp.concatenate([a, b])
                 for a, b in zip(a_rows, b_rows))


def _rows_where(cond, a_rows, b_rows):
    return tuple(jnp.where(cond, a, b)
                 for a, b in zip(a_rows, b_rows))


def _rows_at_set(rows, pos, vals):
    return tuple(r.at[pos].set(v, mode="drop")
                 for r, v in zip(rows, vals))


def _empty_table(T: int, rep):
    return (rep.zeros(T), jnp.zeros(T, bool))


def _hash_insert(c_rows, c_live, table, probe_limit: int, rep,
                 h0=None):
    """Parallel bounded-linear-probe insert of candidate config rows
    (a lane tuple, `rep`'s layout) into the open-addressed visited set
    `table` ((rows, occ) with lane arrays of one power-of-two length
    T).

    Each live candidate probes from rep.table_hash(row) & (T-1) (or
    from the caller-supplied `h0` start slots — the tiled kernel
    probes within a table tile); per round it drops on an equal
    occupant (already visited), claims an empty slot (racing claimants
    are arbitrated by a scatter-min of the candidate index; losers
    RE-CHECK the same slot next round, because the winner there may
    hold an equal key — a duplicate inside this same batch), or
    advances past an occupied different slot. The loop runs until
    every candidate resolves or exhausts `probe_limit` probes
    (<= 2*probe_limit rounds: every pending candidate resolves or
    advances at least every second round).

    Returns (table', fresh, overflow, off): `fresh` flags candidates
    that claimed a slot (first sighting), `overflow` that some
    candidate exhausted its probes — the caller escalates capacity, it
    never silently drops a config. `off` is each candidate's final
    probe offset (the stats path histograms it; other callers ignore
    it — dead code under jit)."""
    t_rows, t_occ = table
    M = c_rows[0].shape[0]
    T = t_rows[0].shape[0]
    maskT = jnp.uint32(T - 1)
    if h0 is None:
        h0 = rep.table_hash(c_rows)
    idx = jnp.arange(M, dtype=jnp.int32)

    def cond(s):
        return jnp.any(s["pending"] & (s["off"] < probe_limit))

    def body(s):
        t_rows, t_occ = s["table"]
        pending, off, fresh = s["pending"], s["off"], s["fresh"]
        act = pending & (off < probe_limit)
        slot = ((h0 + off.astype(jnp.uint32)) & maskT).astype(jnp.int32)
        occ = t_occ[slot]
        same = occ & _rows_eq(_rows_take(t_rows, slot), c_rows)
        try_claim = act & ~occ
        claim = jnp.full(T, M, jnp.int32).at[
            jnp.where(try_claim, slot, T)].min(idx, mode="drop")
        won = try_claim & (claim[slot] == idx)
        wslot = jnp.where(won, slot, T)
        t_rows = _rows_at_set(t_rows, wslot, c_rows)
        t_occ = t_occ.at[wslot].set(True, mode="drop")
        return {"table": (t_rows, t_occ),
                "pending": pending & ~(act & same) & ~won,
                "off": off + (act & occ & ~same).astype(jnp.int32),
                "fresh": fresh | won}

    out = lax.while_loop(cond, body, {
        "table": (t_rows, t_occ), "pending": c_live,
        "off": jnp.zeros(M, jnp.int32), "fresh": jnp.zeros(M, bool)})
    return out["table"], out["fresh"], jnp.any(out["pending"]), out["off"]


def _append_fresh(c_rows, fresh, f_rows, count, N: int):
    """The append half of one visited-set transaction: fresh rows land
    contiguously after `count` in the frontier lane arrays. Returns
    (rows2, count2, n_fresh, append_ovf)."""
    n_fresh = jnp.sum(fresh)
    pos = jnp.where(fresh, count + jnp.cumsum(fresh) - 1, N)
    rows2 = _rows_at_set(f_rows, pos, c_rows)
    return (rows2, jnp.minimum(count + n_fresh, N), n_fresh,
            count + n_fresh > N)


def _hash_insert_append(c_rows, c_live, f_rows, count, table,
                        probe_limit: int, N: int, rep,
                        stats: bool = False):
    """_hash_insert plus the contiguous append of the fresh rows after
    `count` — one closure iteration's whole visited-set transaction.
    Shared verbatim by the XLA hash path, the fused frontier kernel
    (sparse_kernels.frontier_closure_call via _hash_event_closure), and
    the sharded per-device insert kernel (sparse_kernels.
    hash_insert_call), so the implementations cannot diverge.

    Returns (rows2, table2, count2, n_fresh, ovf): `ovf` is probe
    exhaustion OR the append running past the N-row frontier (rows
    past N scatter-drop; the flag aborts before anything consumes
    them). With `stats` (static; JEPSEN_TPU_SEARCH_STATS), a sixth
    element: the bucketed probe-length histogram [N_PROBE_BUCKETS] of
    this transaction's attempted inserts."""
    table2, fresh, p_ovf, off = _hash_insert(c_rows, c_live, table,
                                             probe_limit, rep)
    rows2, count2, n_fresh, a_ovf = _append_fresh(c_rows, fresh,
                                                  f_rows, count, N)
    out = (rows2, table2, count2, n_fresh, p_ovf | a_ovf)
    if stats:
        return out + (_probe_hist(off, c_live),)
    return out


def _hash_event_closure(rep, step_cc, ev, rows, live, run, N: int,
                        T: int, probe_limit: int, stats: bool = False,
                        insert=None):
    """The whole per-event delta-frontier closure (dedupe="hash") on
    plain lane arrays: seed the fresh visited set with the live
    frontier (compacting it in the same pass — post-filter frontiers
    have holes; iteration 0's delta is the whole frontier, exactly the
    rows the sort path would step first), then expand only the delta
    until no fresh configs appear. Shared VERBATIM by the XLA path
    (_scan_step_factory), the fused pallas kernel
    (sparse_kernels.frontier_closure_call runs exactly this function
    over VMEM-resident values), and — via the `insert` hook — the
    tiled closure, whose per-iteration visited-set transaction streams
    the table through sparse_kernels.tiled_insert_call while the
    expansion stays here. The implementations cannot diverge.

    Returns (rows2, count, ovf, iters, stepped) with `stepped` the
    configs expanded during THIS event's closure. With `stats`
    (static), two more: `swork` — the configs a SORT closure would
    have stepped for the same event (whole frontier per iteration; the
    delta-split ratio's denominator) — and the probe-length histogram
    [N_PROBE_BUCKETS] accumulated over the seed insert and every
    iteration's transaction."""
    if insert is None:
        def insert(c_rows, c_live, f_rows, count, table):
            return _hash_insert_append(c_rows, c_live, f_rows, count,
                                       table, probe_limit, N, rep,
                                       stats=stats)
    seed = insert(rows, live, rep.zeros(N), jnp.int32(0),
                  _empty_table(T, rep))
    rows0, table, m0, _, p0 = seed[:5]

    def cond(c):
        return c["changed"] & ~c["ovf"]

    def body(c):
        rows = c["rows"]
        n_old, count = c["n_old"], c["count"]
        cand_st, cand_ok = step_cc(rep.state(rows), ev["slot_f"],
                                   ev["slot_a0"], ev["slot_a1"],
                                   ev["slot_wild"])
        row = jnp.arange(N)
        delta = (row >= n_old) & (row < count)
        already = rep.mask_test(rows)
        legal = (delta[:, None] & ev["slot_occ"][None, :]
                 & ~already & cand_ok)
        ins = insert(rep.candidates(rows, cand_st), legal.reshape(-1),
                     rows, count, c["table"])
        rows2, table2, count2, n_fresh, ins_ovf = ins[:5]
        out = {"rows": rows2,
               "n_old": count, "count": count2, "table": table2,
               "changed": n_fresh > 0,
               "ovf": c["ovf"] | ins_ovf,
               "iters": c["iters"] + 1,
               "stepped": c["stepped"] + (count - n_old)}
        if stats:
            # swork: what sort would have re-stepped — the WHOLE live
            # frontier this iteration, not just the delta
            out["swork"] = c["swork"] + count
            out["phist"] = c["phist"] + ins[5]
        return out

    carry0 = {
        "rows": rows0,
        "n_old": jnp.int32(0), "count": m0, "table": table,
        "changed": run, "ovf": p0, "iters": jnp.int32(0),
        "stepped": jnp.int32(0)}
    if stats:
        carry0["swork"] = jnp.int32(0)
        carry0["phist"] = seed[5]
    out = lax.while_loop(cond, body, carry0)
    base = (out["rows"], out["count"], out["ovf"], out["iters"],
            out["stepped"])
    if stats:
        return base + (out["swork"], out["phist"])
    return base


def _slot_bits(C: int):
    js = jnp.arange(C, dtype=jnp.uint32)
    one = jnp.uint32(1)
    bit_lo = jnp.where(js < 32, one << jnp.minimum(js, 31),
                       jnp.uint32(0)).astype(jnp.uint32)
    bit_hi = jnp.where(js >= 32, one << jnp.minimum(js - 32, jnp.uint32(31)),
                       jnp.uint32(0)).astype(jnp.uint32)
    return bit_lo, bit_hi


def _rows_prev_same(rows_s):
    acc = rows_s[0][1:] == rows_s[0][:-1]
    for r in rows_s[1:]:
        acc = acc & (r[1:] == r[:-1])
    return jnp.concatenate([jnp.zeros(1, bool), acc])


def _dedupe_compact(rows, live, N, rep):
    """Sort rows by (dead, lanes major-to-minor), flag first
    occurrences, compact into a fresh [N] frontier. Returns (rows,
    live, count, overflow). Lane-generic: the unpacked triple sorts by
    (state, mask_lo, mask_hi) exactly as before; the packed word sorts
    by its lanes."""
    M = rows[0].shape[0]
    order = jnp.lexsort((*reversed(rows), (~live).astype(jnp.int8)))
    rows_s = _rows_take(rows, order)
    live_s = live[order]
    uniq = live_s & ~_rows_prev_same(rows_s)
    count = jnp.sum(uniq)
    pos = jnp.where(uniq, jnp.cumsum(uniq) - 1, M + N)  # OOB -> dropped
    new_rows = _rows_at_set(rep.zeros(N), pos, rows_s)
    new_live = jnp.arange(N) < count
    return new_rows, new_live, count, count > N


def _initial_carry(state0, N: int, rep):
    """The scan carry at event 0: one live config (the initial model
    state, nothing linearized). The trailing int32 is the
    configs-stepped counter (closure work actually paid, in configs
    expanded — see _scan_step_factory). The carry is
    (*row_lanes, live, ok, fail_r, r_idx, maxf, steps_n, stepped) —
    lane count is the representation's (3 unpacked, 1-2 packed)."""
    rows0 = rep.initial_at0(state0, N)
    live0 = jnp.arange(N) < 1
    return rows0 + (live0, jnp.array(True), jnp.int32(-1),
                    jnp.int32(0), jnp.int32(1), jnp.int32(0),
                    jnp.int32(0))


def _scan_step_factory(step_name: str, N: int, C: int,
                       dedupe: str = "sort", probe_limit: int = 0,
                       sparse_pallas: str = "off",
                       search_stats: bool = False,
                       pack: tuple = ()):
    """The per-return-event scan step, parameterized by model step,
    frontier capacity, slot-window width, and dedupe strategy. Shared
    by the one-shot and the resumable (checkpointed) entry points.

    dedupe="sort": every closure iteration re-steps the WHOLE live
    frontier and dedupes by a full lexsort over all N*(C+1) candidate
    rows — the historical path.

    dedupe="hash": the delta-frontier closure (_hash_event_closure).
    The frontier is kept compacted, the closure carry holds a split
    index (rows [0, n_old) were expanded in earlier iterations, rows
    [n_old, count) are the delta discovered last iteration), only the
    delta expands, and membership is an open-addressed hash
    visited-set (capacity _next_pow2(2N), _hash_insert) reused across
    all closure iterations of one return event — each configuration is
    expanded exactly once per event, the Wing&Gong/Lowe seen-set
    realised on-device. Probe exhaustion raises the overflow flag and
    rides the same capacity-escalation retry as a full frontier.
    Verdicts, counterexample localization, max-frontier and iteration
    counts are identical to the sort path (frontier ROW ORDER differs;
    tests pin everything order-independent).

    `sparse_pallas` ("off"/"on"/"interpret", resolved by
    _resolve_sparse_pallas) fuses the whole per-event hash closure into
    ONE pallas_call (parallel.sparse_kernels): candidate rows, the
    visited-set table, and the event's slot tables stay VMEM-resident
    for every closure iteration, so the N*(C+1) candidate arrays never
    round-trip HBM and the probe/claim while_loops cost no
    per-iteration dispatch. The kernel body IS _hash_event_closure, so
    results are identical by construction.

    Both strategies accumulate a configs-stepped counter (sort: the
    whole live frontier per iteration; hash: the delta) — the counter
    that makes the delta win measurable even on CPU advisory runs.

    `search_stats` (static; JEPSEN_TPU_SEARCH_STATS) switches the
    scan's per-event output from the bare overflow flag to a dict of
    per-event device-computed stats: post-filter frontier width (-1 on
    events that did not run — pads, post-failure), closure peak (the
    pre-filter frontier, which under hash equals the visited-table
    occupancy), iterations, per-event configs-stepped, the
    sort-equivalent work (delta-split denominator), and the bucketed
    probe-length histogram (zeros under sort). Verdict-carrying
    outputs are untouched — stats-on/off parity is pinned.

    `pack` (static; JEPSEN_TPU_CONFIG_PACK via pack_spec_for) selects
    the configuration-row layout: () is the historical (state,
    mask_lo, mask_hi) triple; (state_bits, state_lo) the packed word
    carried as 1-2 uint32 lanes. The scan carry is
    (*row_lanes, live, ok, fail_r, r_idx, maxf, steps_n, stepped) —
    every path below is lane-generic, so verdicts, counterexample
    localization, max-frontier, and configs-stepped are identical
    across layouts (parity-pinned)."""
    step = STEPS[step_name]
    rep = _rep(pack, C)
    if probe_limit <= 0:
        # host entry points resolve eagerly; this is the safety net for
        # internal callers (e.g. _frontier_at's default-arg path)
        probe_limit = _resolve_probe_limit(0)
    T = _next_pow2(2 * N)

    # model step vmapped over configs x slots
    step_cc = jax.vmap(
        jax.vmap(step, in_axes=(None, 0, 0, 0, 0)),  # over slots
        in_axes=(0, None, None, None, None),         # over configs
    )

    def closure_cond(c):
        return c["changed"] & ~c["ovf"]

    def make_closure_body(ev):
        def body(c):
            rows, live = c["rows"], c["live"]
            cand_st, cand_ok = step_cc(
                rep.state(rows), ev["slot_f"], ev["slot_a0"],
                ev["slot_a1"], ev["slot_wild"]
            )
            already = rep.mask_test(rows)
            legal = (live[:, None] & ev["slot_occ"][None, :]
                     & ~already & cand_ok)
            all_rows = _rows_concat(rows, rep.candidates(rows, cand_st))
            all_live = jnp.concatenate([live, legal.reshape(-1)])
            old_count = jnp.sum(live)
            rows2, live2, count, ovf = _dedupe_compact(
                all_rows, all_live, N, rep)
            return {"rows": rows2, "live": live2,
                    "changed": count > old_count, "ovf": ovf,
                    "iters": c["iters"] + 1,
                    "stepped": c["stepped"] + old_count}
        return body

    zero_hist = jnp.zeros(N_PROBE_BUCKETS, jnp.int32)
    tiled_mode = sparse_pallas in ("tiled", "tiled-interpret")
    if tiled_mode:
        from jepsen_tpu.parallel import sparse_kernels as sk
        tiled_plan = sk.tiled_plan(N, C, rep.lanes)

    def make_tiled_insert(interpret: bool):
        """The `insert` hook that streams the visited-set transaction
        through the tiled kernel (probe/claim in VMEM tiles) while the
        append stays XLA-side — the closure around it is byte-for-byte
        _hash_event_closure."""
        from jepsen_tpu.parallel import sparse_kernels as sk

        def insert(c_rows, c_live, f_rows, count, table):
            table2, fresh, off, p_ovf = sk.tiled_insert_call(
                c_rows, c_live, table, probe_limit, tiled_plan, pack,
                C, interpret=interpret)
            rows2, count2, n_fresh, a_ovf = _append_fresh(
                c_rows, fresh, f_rows, count, N)
            out = (rows2, table2, count2, n_fresh, p_ovf | a_ovf)
            if search_stats:
                return out + (_probe_hist(off, c_live),)
            return out
        return insert

    def run_closure(ev, rows, live, run, stepped):
        """-> (rows2, live2, ovf, iters, stepped2, extras) where
        extras is (swork_delta, probe_hist) under search_stats (sort:
        swork == the stepped delta, hist zeros) and None otherwise."""
        if dedupe == "sort":
            out = lax.while_loop(
                closure_cond, make_closure_body(ev),
                {"rows": rows, "live": live, "changed": run,
                 "ovf": jnp.array(False), "iters": jnp.int32(0),
                 "stepped": stepped})
            stepped2 = out["stepped"]
            extras = ((stepped2 - stepped, zero_hist)
                      if search_stats else None)
            return (out["rows"], out["live"], out["ovf"], out["iters"],
                    stepped2, extras)
        if sparse_pallas in ("on", "interpret"):
            # the fused kernel: the whole per-event closure inside one
            # pallas_call, frontier + table + slot tables VMEM-resident
            from jepsen_tpu.parallel import sparse_kernels as sk
            out = sk.frontier_closure_call(
                step_name, ev, rows, live, run, N, C,
                probe_limit, pack,
                interpret=(sparse_pallas == "interpret"),
                stats=search_stats)
        elif tiled_mode:
            out = _hash_event_closure(
                rep, step_cc, ev, rows, live, run, N, T, probe_limit,
                stats=search_stats,
                insert=make_tiled_insert(
                    sparse_pallas == "tiled-interpret"))
        else:
            out = _hash_event_closure(
                rep, step_cc, ev, rows, live, run, N, T,
                probe_limit, stats=search_stats)
        rows2, count, ovf, iters, d = out[:5]
        extras = (out[5], out[6]) if search_stats else None
        live2 = jnp.arange(N) < count
        return rows2, live2, ovf, iters, stepped + d, extras

    L = rep.lanes

    def scan_step(carry, ev):
        rows = carry[:L]
        live, ok, fail_r, r_idx, maxf, steps_n, stepped = carry[L:]
        is_pad = ev["ev_slot"] < 0
        run = ok & ~is_pad

        # closure: expand until no new configs (skipped when run=False:
        # the initial `changed` flag is `run`)
        rows2, live2, ovf, iters, stepped2, extras = run_closure(
            ev, rows, live, run, stepped)
        # the hash prologue runs unconditionally (lax.scan cannot skip
        # an event) — a pad/settled event's probe flag must not leak
        # into the host's capacity-escalation decision
        ovf = run & ovf

        # filter: returning call must have linearized; then free its slot
        s = jnp.maximum(ev["ev_slot"], 0).astype(jnp.uint32)
        bits = rep.event_bits(s)
        has = rep.has_event_bit(rows2, bits)
        live3 = live2 & has
        rows3 = rep.clear_event_bit(rows2, bits, live3)
        n_live = jnp.sum(live3)
        failed_here = run & (n_live == 0)

        new_ok = jnp.where(run, ~failed_here & ~ovf, ok)
        new_fail = jnp.where(failed_here & (fail_r < 0), r_idx, fail_r)
        rows_o = _rows_where(run, rows3, rows)
        live_o = jnp.where(run, live3, live)
        maxf = jnp.maximum(maxf, jnp.where(run, jnp.sum(live2), 0))
        # count closure iterations only; the host multiplies by N*C in
        # Python (int32 would overflow at large capacities). The
        # configs-stepped counter is the TRUE work: configs actually
        # expanded (sort: whole frontier per iteration; hash: the
        # delta) — both strategies record it so the reduction is
        # visible in the same units.
        steps_n = steps_n + jnp.where(run, iters, 0)
        stepped_o = jnp.where(run, stepped2, stepped)
        # pad events do not advance the return-event index: a resumed
        # carry's r_idx must equal the number of REAL events processed
        # so a checkpoint taken after a quantum-padded chunk (the
        # streaming extension pads chunks to few jit shapes, and the
        # batched form interleaves per-key pads) resumes at the right
        # event. Identical for the historical paths — their pads only
        # ever trail the last real event.
        carry_o = rows_o + (live_o, new_ok, new_fail,
                            r_idx + jnp.where(is_pad, 0, 1), maxf,
                            steps_n, stepped_o)
        if not search_stats:
            return carry_o, ovf
        # per-event device stats: width -1 marks "did not run" (pad or
        # post-failure) — the host filters on it, so padded chunks and
        # batch interleaving need no extra bookkeeping
        y = {
            "ovf": ovf,
            "width": jnp.where(run, n_live, -1).astype(jnp.int32),
            "peak": jnp.where(run, jnp.sum(live2), 0).astype(jnp.int32),
            "iters": jnp.where(run, iters, 0).astype(jnp.int32),
            "stepped": jnp.where(run, stepped2 - stepped,
                                 0).astype(jnp.int32),
            "swork": jnp.where(run, extras[0], 0).astype(jnp.int32),
            "phist": jnp.where(run, extras[1], 0).astype(jnp.int32),
        }
        return carry_o, y

    return scan_step


def _check_impl(xs, state0, step_name: str, N: int,
                dedupe: str = "sort", probe_limit: int = 0,
                sparse_pallas: str = "off",
                search_stats: bool = False, pack: tuple = ()):
    """Scan over all return events from scratch. xs: dict of [R, ...]
    arrays. Returns (valid, fail_event, overflow, max_frontier,
    steps_evaluated, configs_stepped) — plus, under `search_stats`,
    the per-event stats dict of [R]-stacked arrays."""
    C = xs["slot_f"].shape[1]
    rep = _rep(pack, C)
    carry0 = _initial_carry(state0, N, rep)
    carry, ys = lax.scan(
        _scan_step_factory(step_name, N, C, dedupe, probe_limit,
                           sparse_pallas, search_stats, pack),
        carry0, xs)
    live, ok, fail_r, _, maxf, steps_n, stepped = carry[rep.lanes:]
    ovfs = ys["ovf"] if search_stats else ys
    overflow = jnp.any(ovfs)
    valid = ok & (jnp.sum(live) > 0) & ~overflow
    base = (valid, fail_r, overflow, maxf, steps_n, stepped)
    if search_stats:
        return base + (ys,)
    return base


# donation decision (recompile-donate-argnums), DECIDED: the resumable
# jits DONATE their frontier carry — it is rebuilt per call from the
# host-side FrontierCheckpoint (cp.carry / extend's _stack_carries
# place fresh device arrays every dispatch, including the
# overflow-retry and _frontier_at paths), the output carry aliases it
# exactly (same shapes/dtypes), and at the top capacity tiers the
# carry IS the peak-HBM buffer — donation halves it. xs/state0 are NOT
# donated anywhere: the one-shot escalation loop re-dispatches the
# SAME xs arrays at doubled N after an overflow, and no output aliases
# the event tables (donating them would only trade the retry inputs
# for an unusable-donation warning).
@functools.partial(jax.jit,
                   donate_argnames=("carry0",),
                   static_argnames=("step_name", "N", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "search_stats", "pack"))
def _check_device_resumable(xs, carry0, step_name: str, N: int,
                            dedupe: str = "sort", probe_limit: int = 0,
                            sparse_pallas: str = "off",
                            search_stats: bool = False,
                            pack: tuple = ()):
    """One chunk of events from an explicit carry; returns the final
    carry plus the overflow flag so the host can checkpoint between
    chunks. Under `search_stats` a third output: the chunk's
    per-event stats dict (pad rows carry width=-1; the stats arrays
    are per-call scan outputs, NOT part of the carry, so checkpoints
    and their format are untouched)."""
    C = xs["slot_f"].shape[1]
    carry, ys = lax.scan(
        _scan_step_factory(step_name, N, C, dedupe, probe_limit,
                           sparse_pallas, search_stats, pack),
        carry0, xs)
    if search_stats:
        return carry, jnp.any(ys["ovf"]), ys
    return carry, jnp.any(ys)


# donation decision, DECIDED: nothing donatable — see the block
# comment above _check_device_resumable (xs is reused across the
# capacity-escalation retries; every output is a scalar)
_check_device = jax.jit(_check_impl,
                        donate_argnums=(),
                        static_argnames=("step_name", "N", "dedupe",
                                         "probe_limit", "sparse_pallas",
                                         "search_stats", "pack"))


# donation decision, DECIDED: nothing donatable — the batch tier loop
# re-dispatches pending keys from freshly placed arrays, but every
# output is a per-key scalar, so no input buffer can alias an output
@functools.partial(jax.jit,
                   donate_argnums=(),
                   static_argnames=("step_name", "N", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "search_stats", "pack"))
def _check_device_batch(xs, state0, step_name: str, N: int,
                        dedupe: str = "sort", probe_limit: int = 0,
                        sparse_pallas: str = "off",
                        search_stats: bool = False, pack: tuple = ()):
    return jax.vmap(
        lambda x, s0: _check_impl(x, s0, step_name, N, dedupe,
                                  probe_limit, sparse_pallas,
                                  search_stats, pack)
    )(xs, state0)


# donation decision, DECIDED: the stacked per-key carry donates — same
# rationale as _check_device_resumable (extend builds it fresh per
# dispatch; overflowed members fall back to their solo path from the
# host-side checkpoint, never from these device arrays)
@functools.partial(jax.jit,
                   donate_argnames=("carry0",),
                   static_argnames=("step_name", "N", "dedupe",
                                    "probe_limit", "sparse_pallas",
                                    "search_stats", "pack"))
def _check_device_batch_resumable(xs, carry0, step_name: str, N: int,
                                  dedupe: str = "sort",
                                  probe_limit: int = 0,
                                  sparse_pallas: str = "off",
                                  search_stats: bool = False,
                                  pack: tuple = ()):
    """The streaming extension's batched scan: one chunk of events per
    key from an explicit per-key carry — jepsen_tpu.parallel.extend
    stacks shape-compatible sessions' frontiers and advances them in
    ONE device program (the cross-key delta batching the serve layer
    dispatches). Pad events (ev_slot < 0) leave a key's carry
    untouched, event index included, so per-key chunks of different
    real lengths share the padded shape. Returns (carry_batch,
    overflow[K]) — plus the per-key per-event stats dict under
    `search_stats` (width=-1 rows are that key's pads)."""
    C = xs["slot_f"].shape[2]
    step = _scan_step_factory(step_name, N, C, dedupe, probe_limit,
                              sparse_pallas, search_stats, pack)

    if search_stats:
        def one_s(x, c):
            carry, ys = lax.scan(step, c, x)
            return carry, jnp.any(ys["ovf"]), ys
        return jax.vmap(one_s)(xs, carry0)

    def one(x, c):
        carry, ovfs = lax.scan(step, c, x)
        return carry, jnp.any(ovfs)

    return jax.vmap(one)(xs, carry0)


# ------------------------------------------- compile-economics seam

# The AOT-managed engine entries (jepsen_tpu.parallel.programs): name
# -> (entry attr, traced-arg count, static names in the positional
# order every call site uses). Attrs resolve through globals() at
# call time so a test that monkeypatches an entry keeps its patch —
# and a patched entry without .lower() falls back to the plain call.
_PROGRAM_STATICS = ("step_name", "N", "dedupe", "probe_limit",
                    "sparse_pallas", "search_stats", "pack")
_PROGRAM_ENTRIES = {
    "engine.check": ("_check_device", 2, _PROGRAM_STATICS),
    "engine.check_resumable": ("_check_device_resumable", 2,
                               _PROGRAM_STATICS),
    "engine.check_batch": ("_check_device_batch", 2,
                           _PROGRAM_STATICS),
    "engine.check_batch_resumable": ("_check_device_batch_resumable",
                                     2, _PROGRAM_STATICS),
}


def program_entries() -> dict:
    """name -> (jitted entry, n_traced, static_names): what
    programs.ProgramRegistry.warm_manifest pre-warms from (the serve
    adopter's rehome path)."""
    return {name: (globals()[attr], n, statics)
            for name, (attr, n, statics) in _PROGRAM_ENTRIES.items()}


def _run_program(name: str, *args):
    """Dispatch one engine jit entry through the program registry when
    JEPSEN_TPU_COMPILE_CACHE arms it — AOT lower().compile(), the
    hit/miss/compile ledger, disk persistence, ladder precompile —
    else the plain jit call. Flag off is byte-identical: same entry,
    same args, no registry, no new metrics."""
    attr, n_traced, static_names = _PROGRAM_ENTRIES[name]
    entry = globals()[attr]
    reg = programs.registry()
    if reg is None or not hasattr(entry, "lower"):
        return entry(*args)
    return reg.call(name, entry, args, n_traced, static_names)


# ------------------------------------------------------------- host API


def _place(tree, device=None):
    """Host arrays -> device arrays. With `device` (a Device or
    Sharding) every array is *explicitly* placed there — never on the
    default backend, which may be a broken TPU runtime while the caller
    is deliberately running on a CPU mesh (the MULTICHIP_r01 failure
    mode: jnp.asarray landing on the poisoned default backend). Every
    engine entry point that accepts `device` routes through here."""
    if device is not None:
        return jax.device_put(tree, device)
    return jax.tree.map(jnp.asarray, tree)


def _place_owned(tree, device=None):
    """_place for buffers that will be DONATED: guarantees device-
    OWNED allocations. jnp.asarray / device_put can be ZERO-COPY on
    the CPU backend — the ArrayImpl then merely windows host numpy
    memory — and donating such a view is unsound: XLA aliases its
    output into memory it does not own (observed as
    nondeterministically corrupt counters on resumed searches). The
    post-placement jnp.copy runs on the placed array's OWN
    device/sharding, so the never-the-default-backend invariant of
    _place(device=...) is preserved."""
    return jax.tree.map(jnp.copy, _place(tree, device))


def _xs_from_encoded(e: EncodedHistory, device=None,
                     canon: bool = False) -> dict:
    """Event arrays as device arrays, placed via _place. ``canon``
    quantizes the event-row count onto the EVENT_QUANTUM ladder when
    JEPSEN_TPU_CANON_SHAPES arms it (pad rows are scan no-ops —
    parity-safe; docs/performance.md "Compile economics"); only the
    one-shot sparse path opts in — the sharded tier's xs feed
    shard_map layouts that size to the exact R."""
    xs = {
        "slot_f": e.slot_f,
        "slot_a0": e.slot_a0,
        "slot_a1": e.slot_a1,
        "slot_wild": e.slot_wild,
        "slot_occ": e.slot_occ,
        "ev_slot": e.ev_slot,
    }
    if canon:
        xs = programs.maybe_canon_rows(xs)
    return _place(xs, device)


class FrontierCheckpoint:
    """A resumable snapshot of the search frontier — the checker-side
    checkpoint/resume capability (SURVEY.md §5.4: the reference's
    resume is re-analysis of a stored history; long device searches
    additionally checkpoint mid-search so a crash or preemption loses
    at most one chunk of events).

    Saved as .npz; history identity is guarded by a digest of the
    encoded event arrays — resuming against a different history is an
    error, not silent corruption. Format versioning rides the meta
    array's LENGTH: v1 checkpoints carried 6 scalars, v2 appends the
    configs-stepped counter — v1 files load with stepped=0 (the
    counter is advisory; the search state is complete without it)."""

    def __init__(self, event_index: int, capacity: int, step_name: str,
                 history_digest: str, st, ml, mh, live, ok, fail_r,
                 maxf, steps_n, stepped: int = 0):
        self.event_index = int(event_index)
        self.capacity = int(capacity)
        self.step_name = step_name
        self.history_digest = history_digest
        self.st = np.asarray(st)
        self.ml = np.asarray(ml)
        self.mh = np.asarray(mh)
        self.live = np.asarray(live)
        self.ok = bool(ok)
        self.fail_r = int(fail_r)
        self.maxf = int(maxf)
        self.steps_n = int(steps_n)
        self.stepped = int(stepped)

    @classmethod
    def fresh(cls, e, capacity: int,
              digest: Optional[str] = None) -> "FrontierCheckpoint":
        """The event-0 checkpoint for an encoded history: one live
        config (the initial model state, nothing linearized) — shared
        by the resumable entry point and the streaming extension
        (parallel.extend) so the two cannot diverge."""
        N = max(64, capacity)
        cp = cls(0, N, e.step_name,
                 digest if digest is not None else history_digest(e),
                 np.zeros(N, np.int32), np.zeros(N, np.uint32),
                 np.zeros(N, np.uint32), np.arange(N) < 1,
                 True, -1, 1, 0)
        cp.st[0] = e.state0
        return cp

    def carry(self, device=None, pack=(), C: int = 0):
        """The device scan carry this checkpoint resumes from. With
        `device` every array is explicitly placed there (same
        invariant as _xs_from_encoded: never the default backend).

        Checkpoints store the CANONICAL (st, ml, mh) triple whatever
        layout the engine runs — the representation-independent
        interchange format (v1/v2 files, serve freeze/thaw, host
        resume seeds, and resuming a packed search unpacked or vice
        versa all just work, even when a delta grows the slot window
        and shifts the packed bit positions). With `pack` (and the
        traced program's slot width `C`) the rows pack at this
        boundary — cheap host numpy over N rows, once per chunk."""
        if pack:
            rows = pack_rows_np(pack, C, self.st, self.ml, self.mh)
        else:
            rows = (self.st, self.ml, self.mh)
        # _place_owned, not _place: the resumable jits DONATE this
        # carry, and a zero-copy placement would hand XLA a window
        # onto memory this live checkpoint still owns
        return _place_owned(tuple(rows) + (self.live,
                            np.bool_(self.ok), np.int32(self.fail_r),
                            np.int32(self.event_index),
                            np.int32(self.maxf),
                            np.int32(self.steps_n),
                            np.int32(self.stepped)),
                            device)

    def grown(self, new_capacity: int) -> "FrontierCheckpoint":
        """Re-embed the frontier into a larger capacity (overflow
        doubling across a resume)."""
        pad = new_capacity - self.capacity
        assert pad >= 0
        return FrontierCheckpoint(
            self.event_index, new_capacity, self.step_name,
            self.history_digest,
            np.concatenate([self.st, np.zeros(pad, np.int32)]),
            np.concatenate([self.ml, np.zeros(pad, np.uint32)]),
            np.concatenate([self.mh, np.zeros(pad, np.uint32)]),
            np.concatenate([self.live, np.zeros(pad, bool)]),
            self.ok, self.fail_r, self.maxf, self.steps_n,
            self.stepped)

    def save(self, path: str) -> str:
        # np.savez appends .npz to suffix-less paths; normalize so
        # load(save(p)) always works.
        if not path.endswith(".npz"):
            path = path + ".npz"
        np.savez_compressed(
            path, st=self.st, ml=self.ml, mh=self.mh, live=self.live,
            meta=np.array([self.event_index, self.capacity,
                           int(self.ok), self.fail_r, self.maxf,
                           self.steps_n, self.stepped], np.int64),
            step_name=np.array(self.step_name),
            history_digest=np.array(self.history_digest))
        return path

    @classmethod
    def load(cls, path: str) -> "FrontierCheckpoint":
        if not path.endswith(".npz"):
            path = path + ".npz"
        z = np.load(path, allow_pickle=False)
        meta = z["meta"].tolist()
        # v1 checkpoints predate the configs-stepped counter: 6 meta
        # scalars instead of 7 — load with stepped=0 rather than
        # rejecting a resumable search state over an advisory counter
        ev, cap, ok, fail_r, maxf, steps_n = meta[:6]
        stepped = meta[6] if len(meta) > 6 else 0
        return cls(ev, cap, str(z["step_name"]), str(z["history_digest"]),
                   z["st"], z["ml"], z["mh"], z["live"], bool(ok),
                   fail_r, maxf, steps_n, stepped)


def carry_fields_np(carry, pack=(), C: int = 0):
    """A returned device scan carry -> the canonical numpy 10-tuple
    (st, ml, mh, live, ok, fail_r, r_idx, maxf, steps_n, stepped) —
    the inverse of FrontierCheckpoint.carry's packing boundary, shared
    by the resumable entry point and the streaming extension."""
    lanes = pack_lanes(pack, C) if pack else 3
    rows = [np.asarray(x) for x in carry[:lanes]]
    rest = tuple(np.asarray(x) for x in carry[lanes:])
    if pack:
        st, ml, mh = unpack_rows_np(pack, C, rows)
    else:
        st, ml, mh = rows
    return (st, ml, mh) + rest


def history_digest(e: EncodedHistory) -> str:
    """Stable identity of an encoded history, for checkpoint safety."""
    import hashlib
    h = hashlib.sha256()
    for a in (e.slot_f, e.slot_a0, e.slot_a1, e.slot_wild, e.slot_occ,
              e.ev_slot):
        h.update(np.ascontiguousarray(a).tobytes())
    h.update(str(e.state0).encode())
    return h.hexdigest()[:16]


def check_encoded_resumable(e: EncodedHistory, capacity: int = 1024,
                            max_capacity: int = 1 << 20,
                            checkpoint_every: int = 256,
                            checkpoint_cb=None,
                            resume: Optional[FrontierCheckpoint] = None,
                            device=None,
                            dedupe: Optional[str] = None,
                            probe_limit: int = 0,
                            sparse_pallas: Optional[bool] = None,
                            model=None,
                            search_stats: Optional[bool] = None,
                            config_pack: Optional[bool] = None) -> dict:
    """check_encoded with mid-search checkpointing: events are processed
    in chunks of `checkpoint_every`; after each chunk the frontier is
    pulled to host and handed to checkpoint_cb(FrontierCheckpoint) (e.g.
    cp.save(path)). Pass `resume` to continue a prior search. Overflow
    inside a chunk re-runs that chunk at doubled capacity — the
    checkpoint taken before the chunk stays valid. With `device`, every
    chunk and resumed carry is explicitly placed there — same invariant
    as check_encoded(device=...): never the default backend.

    Degradation contract (docs/resilience.md): every chunk dispatch
    runs through the supervised seam. A dispatch failure mid-search
    never loses work or flips a verdict — the checkpoint taken before
    the failing chunk is the recovery point: first ONE device retry
    (the breaker's half-open probe gets to readmit a recovered
    runtime), then, with `model` given, the remaining events resume on
    the host from the checkpoint (resilience.recovery.host_resume);
    without a model the failure re-raises with ``.checkpoint``
    attached so the caller can resume later."""
    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    dedupe = _resolve_dedupe(dedupe)
    probe_limit = _resolve_probe_limit(probe_limit)
    ss = _resolve_search_stats(search_stats)
    pack_req = _resolve_config_pack(config_pack)
    C_enc = e.slot_f.shape[1]
    pack = pack_spec_for(e) if pack_req else ()
    platform = getattr(device, "platform", None) or jax.default_backend()
    digest = history_digest(e)
    if resume is not None:
        if resume.history_digest != digest:
            raise ValueError(
                f"checkpoint is for a different history "
                f"(digest {resume.history_digest} != {digest})")
        if resume.step_name != e.step_name:
            raise ValueError("checkpoint is for a different model")
        cp = resume
        N = cp.capacity
    else:
        cp = FrontierCheckpoint.fresh(e, capacity, digest)
        N = cp.capacity
    xs_np = {
        "slot_f": e.slot_f, "slot_a0": e.slot_a0, "slot_a1": e.slot_a1,
        "slot_wild": e.slot_wild, "slot_occ": e.slot_occ,
        "ev_slot": e.ev_slot,
    }
    R = e.n_returns
    mode, note = "off", None
    recovered = None
    acc = SearchStats(dedupe) if ss else None
    from time import perf_counter as _pc
    t0 = _pc()
    while cp.event_index < R and cp.ok:
        lo = cp.event_index
        hi = min(R, lo + checkpoint_every)
        # re-resolve per chunk: capacity may have grown past the
        # kernel's VMEM gate mid-search (same degrade-with-note
        # contract as check_encoded's tier loop)
        mode, note = _resolve_sparse_pallas(
            sparse_pallas, cp.capacity, e.slot_f.shape[1], platform,
            dedupe, pack)

        def _chunk(lo=lo, hi=hi, cp=cp, mode=mode):
            chunk = _place(programs.maybe_canon_rows(
                {k: v[lo:hi] for k, v in xs_np.items()}), device)
            out = _run_program(
                "engine.check_resumable",
                chunk, cp.carry(device, pack, C_enc), e.step_name,
                cp.capacity, dedupe, probe_limit, mode, ss, pack)
            # materialize inside the supervised window: async dispatch
            # must fail (or hang) here, not at a later host read
            if ss:
                carry, overflow, ys = out
                return ([np.asarray(x) for x in carry], bool(overflow),
                        jax.tree.map(np.asarray, ys))
            carry, overflow = out
            return ([np.asarray(x) for x in carry], bool(overflow))

        try:
            res = sup.dispatch("search", _chunk, backend=platform)
        except sup.DISPATCH_FAILURES as err:
            # the checkpoint taken before this chunk is the recovery
            # point: one device retry first (a recovered runtime —
            # half-open probe passed, transient cleared — resumes
            # right where it stopped, zero work lost) ...
            try:
                obs.counter("resilience.retries").inc()
                with obs.span("resilience.device_resume",
                              event=cp.event_index):
                    res = sup.dispatch("search", _chunk,
                                       backend=platform)
                recovered = {
                    "degraded": "device-resume",
                    "site": getattr(err, "site", "search"),
                    "reason": f"{type(err).__name__}: {err}",
                    "resumed-from-event": cp.event_index}
            except sup.DISPATCH_FAILURES as err2:
                # ... then the host: with a model the remaining events
                # resume from the checkpoint on the WGL path — verdict
                # preserved, device progress kept
                if model is not None:
                    from jepsen_tpu.resilience import recovery
                    return recovery.host_resume(
                        model, e, cp, getattr(err2, "site", "search"),
                        f"{type(err2).__name__}: {err2}",
                        backend=platform)
                err2.checkpoint = cp
                raise
        carry, overflow = res[0], res[1]
        if bool(overflow):
            if cp.capacity * 2 > max_capacity:
                return _tag_sparse_closure(
                    {"valid?": "unknown",
                     "error": f"frontier overflow at capacity "
                              f"{cp.capacity}",
                     "capacity": cp.capacity,
                     "checkpoint": cp}, mode, note)
            cp = cp.grown(cp.capacity * 2)
            if acc is not None:
                acc.escalations += 1
            continue  # re-run the same chunk at doubled capacity
        if acc is not None:
            # only successful chunks count: a retried chunk's partial
            # stats would double its events
            acc.add_chunk(res[2], cp.capacity)
        st, ml, mh, live, ok, fail_r, r_idx, maxf, steps_n, stepped = \
            carry_fields_np(carry, pack, C_enc)
        cp = FrontierCheckpoint(int(r_idx), cp.capacity, e.step_name,
                                digest, st, ml, mh, live, bool(ok),
                                int(fail_r), int(maxf), int(steps_n),
                                int(stepped))
        if checkpoint_cb is not None:
            checkpoint_cb(cp)
    out = {"valid?": cp.ok and bool(cp.live.any()),
           "max-frontier": cp.maxf,
           "capacity": cp.capacity,
           "dedupe": dedupe,
           "configs-stepped": cp.stepped,
           # approximate when capacity grew mid-search: iterations from
           # earlier chunks ran at smaller capacities
           "explored": cp.steps_n * cp.capacity * len(e.slot_f[0])}
    if recovered is not None:
        out["resilience"] = recovered
    if acc is not None:
        out["stats"] = _finish_search_stats(acc, t0, _pc())
    _tag_sparse_closure(out, mode, note)
    _tag_config_pack(out, pack, pack_req, C_enc)
    if not out["valid?"]:
        out.update(_fail_op(e, cp.fail_r))
    return out


_fail_op = enc_mod.fail_op_fields


def _tag_sparse_closure(out: dict, mode: str, note) -> dict:
    """Stamp which hash-closure implementation ran — bitdense's
    "closure"/"closure-note" vocabulary. Only when the kernel was
    REQUESTED (mode on, or a downgrade note): the flag-off result dict
    stays byte-identical to the pre-kernel schema. "pallas-tiled" =
    the per-iteration insert streamed the table through VMEM tiles
    (sparse_kernels.tiled_insert_call) because the whole-event fusion
    was past the width-aware gate."""
    if mode in ("tiled", "tiled-interpret"):
        out["closure"] = "pallas-tiled"
    elif mode != "off":
        out["closure"] = "pallas"
    elif note is not None:
        out["closure"] = "xla-hash"
        out["closure-note"] = note
    return out


def _tag_config_pack(out: dict, pack, requested: bool, C: int) -> dict:
    """Stamp the configuration-row layout that actually ran — only
    when packing was REQUESTED (argument or JEPSEN_TPU_CONFIG_PACK),
    so the flag-off result dict stays byte-identical. "unpacked" on a
    requested run is the overflow-to-unpacked path: the event family's
    state_bits + C exceeded 64 bits (or its state space is unknown),
    so the engine ran the historical triple."""
    if not requested:
        return out
    if pack:
        out["config-pack"] = f"packed:{pack[0] + C}b/" \
                             f"{pack_lanes(pack, C)}-lane"
    else:
        out["config-pack"] = "unpacked"
    return out


# metric-name-safe spellings of PROBE_HIST_LABELS (dots/dashes would
# sanitize ambiguously in the Prometheus mapping)
PROBE_METRIC_LABELS = ("0", "1", "le3", "le7", "le15", "le31", "over")

# counter-track sample cap per search: a 50k-event trajectory must not
# bloat the trace file — past this the exporter strides
STATS_TRACK_MAX_SAMPLES = 512


# Per-key trajectory bound: a streamed key's lifetime stats (and a
# giant one-shot search's) stop growing past this many events — the
# serve mode's every other per-key structure is deliberately bounded,
# and its telemetry must not be the one exception. Aggregates freeze
# with the lists; the block says "truncated": True.
SEARCH_STATS_MAX_EVENTS = 32768


class SearchStats:
    """Host-side accumulator over the device's per-event stats arrays
    (JEPSEN_TPU_SEARCH_STATS) — one per searched key, fed one chunk at
    a time (the one-shot paths feed a single chunk; the resumable /
    streaming paths feed every successful chunk, so streamed keys
    report LIFETIME stats). Rows with width < 0 (pads, post-failure)
    are dropped here, so callers never re-derive pad bookkeeping.
    Trajectories cap at SEARCH_STATS_MAX_EVENTS (bounded host memory
    per live key; the block is then marked truncated)."""

    def __init__(self, dedupe: str):
        self.dedupe = dedupe
        self.width: list = []
        self.peak: list = []
        self.iters: list = []
        self.stepped: list = []
        self.swork: list = []     # per-event sort-equivalent work
        self.phist: list = []     # per-event [N_PROBE_BUCKETS] rows
        self.capacity = 0
        self.escalations = 0
        self.truncated = False

    def splice(self, resume_ev: int, leg: "SearchStats") -> None:
        """Replace events [resume_ev, ...) with a re-scanned leg's —
        the streaming session's accumulation: a delta re-opens the
        tail at the stable boundary and re-scans from `resume_ev`, so
        the stale tail rows are superseded, not appended. Events below
        `resume_ev` are bit-identical under extension (the settled
        certificate), which is what makes the spliced lifetime stats
        EXACTLY a one-shot check's of the current prefix."""
        for attr in ("width", "peak", "iters", "stepped", "swork",
                     "phist"):
            rows = getattr(self, attr)
            del rows[resume_ev:]
            keep = SEARCH_STATS_MAX_EVENTS - len(rows)
            rows.extend(getattr(leg, attr)[:max(0, keep)])
        if leg.truncated or len(self.width) >= SEARCH_STATS_MAX_EVENTS:
            self.truncated = True
        self.capacity = max(self.capacity, leg.capacity)
        self.escalations += leg.escalations

    def add_chunk(self, ys, N: int) -> None:
        """One chunk's stats dict (np arrays, [R] / [R, B])."""
        room = SEARCH_STATS_MAX_EVENTS - len(self.width)
        w = np.asarray(ys["width"]).reshape(-1)
        real = w >= 0
        if int(real.sum()) > max(0, room):
            # freeze at the cap: aggregates and lists stay mutually
            # consistent (both describe the first MAX events)
            self.truncated = True
            keep = np.flatnonzero(real)[:max(0, room)]
            real = np.zeros_like(real)
            real[keep] = True
        self.width.extend(int(x) for x in w[real])
        self.peak.extend(int(x) for x in
                         np.asarray(ys["peak"]).reshape(-1)[real])
        self.iters.extend(int(x) for x in
                          np.asarray(ys["iters"]).reshape(-1)[real])
        self.stepped.extend(int(x) for x in
                            np.asarray(ys["stepped"]).reshape(-1)[real])
        self.swork.extend(int(x) for x in
                          np.asarray(ys["swork"]).reshape(-1)[real])
        self.phist.extend(
            [int(v) for v in row] for row in
            np.asarray(ys["phist"]).reshape(-1, N_PROBE_BUCKETS)[real])
        self.capacity = max(self.capacity, int(N))

    def block(self) -> dict:
        """The result-dict ``"stats"`` block — the schema every sink
        (result dicts, /metrics, counter tracks, `jepsen report
        --search`) reads; pinned by tests/test_search_stats.py."""
        peak = max(self.peak, default=0)
        stepped = sum(self.stepped)
        swork = sum(self.swork)
        phist = np.asarray(self.phist, np.int64).reshape(
            -1, N_PROBE_BUCKETS).sum(axis=0)
        out = {
            "events": len(self.width),
            "frontier-width": list(self.width),
            "closure-iters": list(self.iters),
            "configs-stepped-per-event": list(self.stepped),
            "closure-peak": list(self.peak),
            "frontier-peak": peak,
            "capacity": self.capacity,
            "capacity-tier": self.escalations,
            "peak-occupancy": (round(peak / self.capacity, 6)
                               if self.capacity else None),
            "dedupe": self.dedupe,
            "delta-split-ratio": (round(stepped / swork, 6)
                                  if swork else None),
            "table-capacity": None,
            "load-factor-peak": None,
            "load-factor-final": None,
            "probe-hist": None,
            "probes": None,
        }
        if self.truncated:
            # no silent caps: the block covers the FIRST
            # SEARCH_STATS_MAX_EVENTS events only
            out["truncated"] = True
        if self.dedupe == "hash" and self.capacity:
            # under hash the closure peak IS the visited-table
            # occupancy (every config inserted exactly once per event)
            T = _next_pow2(2 * self.capacity)
            out["table-capacity"] = T
            out["load-factor-peak"] = round(peak / T, 6)
            out["load-factor-final"] = (round(self.peak[-1] / T, 6)
                                        if self.peak else None)
            out["probe-hist"] = {lab: int(n) for lab, n in
                                 zip(PROBE_HIST_LABELS, phist)}
            out["probes"] = int(phist.sum())
        return out


def _publish_search_stats(block: dict,
                          prefix: str = "engine.search") -> None:
    """Thread one search's stats block into the obs registry under
    ``engine.search.*`` — the names /metrics serves as
    ``jepsen_engine_search_*`` (docs/observability.md)."""
    reg = obs.registry()
    reg.counter(f"{prefix}.events").inc(block["events"])
    reg.gauge(f"{prefix}.frontier_peak").set(block["frontier-peak"])
    if block.get("peak-occupancy") is not None:
        reg.gauge(f"{prefix}.peak_occupancy").set(
            block["peak-occupancy"])
    if block.get("delta-split-ratio") is not None:
        reg.gauge(f"{prefix}.delta_split_ratio").set(
            block["delta-split-ratio"])
    if block.get("load-factor-peak") is not None:
        reg.gauge(f"{prefix}.load_factor_peak").set(
            block["load-factor-peak"])
    if block.get("capacity-tier"):
        reg.counter(f"{prefix}.escalations").inc(block["capacity-tier"])
    if block.get("pad-waste") is not None:
        reg.gauge(f"{prefix}.pad_waste").set(block["pad-waste"])
    hist = block.get("probe-hist")
    if hist:
        for raw, lab in zip(PROBE_HIST_LABELS, PROBE_METRIC_LABELS):
            if hist.get(raw):
                reg.counter(f"{prefix}.probe_len.{lab}").inc(hist[raw])


def _emit_stats_tracks(block: dict, t0: float, t1: float) -> None:
    """The search's per-event trajectories as Perfetto counter tracks:
    event index mapped linearly onto the search's wall window (the
    device scan yields no per-event host timestamps — the x axis is
    the EVENT axis rendered in time units, aligned with the search
    span). No-op when tracing is off."""
    if not obs.enabled():
        return
    widths = block["frontier-width"]
    R = len(widths)
    if not R or t1 <= t0:
        return
    stride = max(1, -(-R // STATS_TRACK_MAX_SAMPLES))
    dt = (t1 - t0) / R
    T = block.get("table-capacity")
    for i in range(0, R, stride):
        t = t0 + (i + 1) * dt
        obs.counter_sample("engine.search.frontier_width", widths[i],
                           t=t)
        if T:
            obs.counter_sample(
                "engine.search.load_factor",
                round(block["closure-peak"][i] / T, 4), t=t)


def finish_stats_block(block: dict, t0: float, t1: float,
                       key=None) -> dict:
    """Fan a ready stats block into the always-on sinks (obs registry,
    run-dir search-stats record) plus the counter tracks when tracing
    is on — shared by the sparse, bitdense, and sharded engines so
    every path feeds the same four sinks."""
    _publish_search_stats(block)
    _emit_stats_tracks(block, t0, t1)
    rec = dict(block)
    if key is not None:
        rec["key"] = key
    obs.record_search_stats(rec)
    return block


def _finish_search_stats(acc: "SearchStats", t0: float, t1: float,
                         key=None, engine: str = "sparse",
                         extra: Optional[dict] = None) -> dict:
    """Build the stats block and fan it into the three always-on sinks
    (result dict via the return value, obs registry, run-dir
    search-stats record) plus the counter tracks when tracing is on."""
    block = acc.block()
    if extra:
        block.update(extra)
    block["engine"] = engine
    return finish_stats_block(block, t0, t1, key=key)


def check_encoded(e: EncodedHistory, capacity: int = 1024,
                  max_capacity: int = 1 << 20, device=None,
                  dedupe: Optional[str] = None,
                  probe_limit: int = 0,
                  sparse_pallas: Optional[bool] = None,
                  search_stats: Optional[bool] = None,
                  config_pack: Optional[bool] = None) -> dict:
    """Check one encoded history, doubling frontier capacity on overflow
    (re-jit per capacity tier; tiers are cached by jax.jit). With
    `device` every input is explicitly placed there and the search runs
    on it — never on the default backend, which may be a broken TPU
    runtime while the caller deliberately runs on a CPU mesh.

    `dedupe` picks the frontier dedupe strategy (_resolve_dedupe:
    "sort"/"hash"/None = the JEPSEN_TPU_DEDUPE flag). Verdicts and
    counterexample fields are identical either way; "configs-stepped"
    records the closure work actually paid — strictly less under
    "hash" whenever a closure runs more than one iteration (the delta
    stops re-stepping the settled majority). `probe_limit` bounds the
    hash path's linear probes (0 = the JEPSEN_TPU_PROBE_LIMIT flag,
    default 32; a test seam — probe exhaustion escalates capacity
    exactly like a full frontier).

    `sparse_pallas` routes the hash closure through the fused VMEM
    frontier kernel (parallel.sparse_kernels; None = the
    JEPSEN_TPU_SPARSE_PALLAS flag, default off until the chip A/B).
    Results are identical by construction — the kernel body is the
    same _hash_event_closure trace; the gate re-resolves per capacity
    tier, so an escalation past the kernel's VMEM budget degrades to
    the XLA hash closure with a "closure-note" rather than erroring.

    `search_stats` (None = the JEPSEN_TPU_SEARCH_STATS flag) adds a
    device-computed per-event ``"stats"`` block to the result — the
    frontier-width trajectory, closure iterations, hash-table load,
    probe-length histogram, capacity tier (docs/observability.md
    "Search telemetry"). Off: the result dict is byte-identical to the
    pre-stats schema.

    `config_pack` (None = the JEPSEN_TPU_CONFIG_PACK flag) packs each
    configuration row into the minimal word the event family needs
    (docs/performance.md "VMEM economics") — verdicts,
    counterexamples, max-frontier, and configs-stepped are identical
    either way (parity-pinned); a family whose word exceeds 64 bits
    runs unpacked, tagged "config-pack": "unpacked"."""
    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    C = e.slot_f.shape[1]
    pl = _planner.active()
    plan_prov = None
    if pl is not None:
        # JEPSEN_TPU_AUTO: axes the caller left unresolved are picked
        # from the per-shape decision table — explicit arguments are
        # never overridden, and every arm is parity-pinned, so a plan
        # can only change wall-clock, never the verdict
        dec = pl.decide("sparse", e.step_name, C,
                        {"dedupe": dedupe, "pallas": sparse_pallas,
                         "pack": config_pack}, keys=1)
        if dec is not None:
            chosen = dec["strategy"]
            dedupe = chosen.get("dedupe", dedupe)
            sparse_pallas = chosen.get("pallas", sparse_pallas)
            config_pack = chosen.get("pack", config_pack)
            plan_prov = dec["plan"]
    dedupe = _resolve_dedupe(dedupe)
    probe_limit = _resolve_probe_limit(probe_limit)
    ss = _resolve_search_stats(search_stats)
    pack_req = _resolve_config_pack(config_pack)
    pack = pack_spec_for(e) if pack_req else ()
    platform = getattr(device, "platform", None) or jax.default_backend()
    # H2D placement and the search both run through the supervised
    # dispatch seam (resilience.supervisor): faults are injectable,
    # the watchdog bounds the wait, and the backend's breaker records
    # the outcome. The search thunk MATERIALIZES its results so async
    # dispatch surfaces failures (and hangs) inside the supervised
    # window, not at a later host read.
    xs, state0 = sup.dispatch(
        "transfer",
        lambda: (_xs_from_encoded(e, device, canon=True),
                 _place(np.int32(e.state0), device)),
        backend=platform)
    N = max(64, capacity)
    n_esc = 0
    from time import perf_counter as _pc
    t0 = _pc()
    with obs.span("engine.search", returns=e.n_returns,
                  dedupe=dedupe) as sp:
        while True:
            mode, note = _resolve_sparse_pallas(sparse_pallas, N, C,
                                                platform, dedupe, pack)

            def _search(N=N, mode=mode):
                out = _run_program(
                    "engine.check", xs, state0, e.step_name, N,
                    dedupe, probe_limit, mode, ss, pack)
                # tree map (not a list comp): the stats output is a
                # dict of arrays riding along under search_stats
                return jax.tree.map(np.asarray, out)

            res = sup.dispatch("search", _search, backend=platform)
            valid, fail_r, overflow, maxf, steps_n, stepped = res[:6]
            if not bool(overflow):
                break
            if N * 2 > max_capacity:
                out = _tag_sparse_closure(
                    {"valid?": "unknown",
                     "error": f"frontier overflow at capacity {N}",
                     "capacity": N, "dedupe": dedupe}, mode, note)
                if plan_prov is not None:
                    out["plan"] = dict(plan_prov)
                return out
            N *= 2
            n_esc += 1
            obs.counter("engine.capacity_escalations").inc()
        sp.set(capacity=N)
        if mode != "off":
            # only when the kernel was requested: the flag-off trace
            # schema stays identical, like the result dict
            sp.set(closure="pallas-tiled"
                   if mode in ("tiled", "tiled-interpret")
                   else "pallas")
    obs.counter("engine.configs_stepped").inc(int(stepped))
    out = {
        "valid?": bool(valid),
        "max-frontier": int(maxf),
        "capacity": N,
        "dedupe": dedupe,
        "configs-stepped": int(stepped),
        # the historical trajectory metric (iters x N x C), preserved
        # under its old key for cross-round comparability; the true
        # work lives in configs-stepped
        "explored": int(steps_n) * N * len(e.slot_f[0]),
    }
    _tag_sparse_closure(out, mode, note)
    _tag_config_pack(out, pack, pack_req, C)
    if pl is not None:
        # every dispatch contributes evidence, planned or not (the
        # below-floor contract); the cell is keyed by the REQUESTED
        # arm so decisions and observations land in the same cell
        pallas_req = (bool(sparse_pallas) if sparse_pallas is not None
                      else envflags.env_bool("JEPSEN_TPU_SPARSE_PALLAS",
                                             default=False))
        pl.observe("sparse", e.step_name, C,
                   {"dedupe": dedupe, "pallas": pallas_req,
                    "pack": pack_req}, _pc() - t0)
    if plan_prov is not None:
        out["plan"] = dict(plan_prov)
    if ss:
        acc = SearchStats(dedupe)
        acc.escalations = n_esc
        acc.add_chunk(res[6], N)
        out["stats"] = _finish_search_stats(acc, t0, _pc())
    if not out["valid?"]:
        out.update(_fail_op(e, int(fail_r)))
    return out


def analysis(model, history, capacity: int = 1024,
             max_capacity: int = 1 << 20, encode_cache=None,
             dedupe: Optional[str] = None,
             sparse_pallas: Optional[bool] = None,
             search_stats: Optional[bool] = None,
             config_pack: Optional[bool] = None) -> dict:
    """knossos-style (model, history) -> result on the device engine.

    Falls back to the host WGL engine when the model can't pack or the
    open-call window exceeds the device limit. On failure, counter-example
    paths are reconstructed host-side on the failing prefix (SURVEY.md
    §7.3 hard part #3: breadcrumbs stay implicit; a host re-search of the
    short failing prefix supplies :final-paths). `max_capacity` caps the
    frontier's double-on-overflow growth; past it the result is
    `{"valid?": "unknown"}` — histories that never prune (e.g. invalid
    queue histories, where every enqueue-order hypothesis stays live)
    otherwise escalate through every tier before deciding.

    `encode_cache` (an EncodeCache, or True for the process default)
    memoizes the host encode across re-analyses of the same history —
    content-keyed, so a mutated history never hits stale (see
    parallel.pipeline). Default: no caching, the historical behavior.

    `dedupe` picks the sparse engine's frontier dedupe strategy
    (check_encoded; None defers to JEPSEN_TPU_DEDUPE) — verdict- and
    counterexample-identical either way; `sparse_pallas` its fused
    VMEM kernel (None defers to JEPSEN_TPU_SPARSE_PALLAS).
    """
    from jepsen_tpu.history import History
    h = history if isinstance(history, History) else History.wrap(history)
    try:
        with obs.span("engine.encode"):
            if encode_cache is not None and encode_cache is not False:
                from jepsen_tpu.parallel import pipeline as pipe_mod
                e = pipe_mod.encode_cached(
                    model, h,
                    cache=None if encode_cache is True else encode_cache)
            else:
                e = enc_mod.encode(model, h)
    except EncodeError as err:
        from jepsen_tpu.checker import wgl
        obs.counter("engine.host_fallbacks").inc()
        _log.warning(
            "history not device-checkable (%s) — using the host WGL "
            "engine; expect it to be orders of magnitude slower", err)
        r = wgl.analysis(model, h)
        r["fallback"] = str(err)
        return r
    from jepsen_tpu.parallel import bitdense
    try:
        if bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots):
            # the dense bitmap IS a complete visited set — the sparse
            # dedupe strategy has nothing to select there (its result
            # says dedupe="dense"); the flag governs the sparse
            # dispatch below
            r = bitdense.check_encoded_bitdense(
                e, search_stats=search_stats)
        else:
            r = check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe=dedupe,
                              sparse_pallas=sparse_pallas,
                              search_stats=search_stats,
                              config_pack=config_pack)
    except sup.DISPATCH_FAILURES as err:
        # the degradation contract (docs/resilience.md): a dead device
        # dispatch — wedged, crashed, or breaker-refused — degrades to
        # the host WGL engine with the verdict preserved and a
        # structured note saying so, instead of crashing the check
        from jepsen_tpu.resilience import recovery
        return recovery.host_check_encoded(
            model, e, getattr(err, "site", "dispatch"),
            f"{type(err).__name__}: {err}")
    if r["valid?"] is False:
        apply_final_paths(r, model, e)
    return r


# --------------------------------------- counterexample extraction

# Host re-search window for long histories: events before the failure
# covered by the seeded re-search (the reference emits full paths but
# truncates to 10 — checker.clj:203-213; for histories the host could
# never search whole, a window ending at the failure is the useful part)
PATHS_WINDOW_EVENTS = 64
PATHS_MAX_SEEDS = 8

# Bounds for the full-host recheck run when the host path re-search
# CONTRADICTS a device-invalid (below): big enough to decide any key a
# per-key batch realistically carries, small enough that a pathological
# key cannot stall the checker.
DISAGREEMENT_RECHECK_MAX_STATES = 5_000_000
DISAGREEMENT_RECHECK_SECS = 30.0


def _disagreement_recheck(model, e: EncodedHistory, note: str) -> dict:
    """The host re-search contradicted a device-invalid. Before shipping
    "invalid, no paths", re-check the WHOLE key host-side under a
    bounded budget: a device false-invalid must not become the verdict
    when the host can decide the key. Decisive host verdicts win (WGL
    searches exhaustively; the device engine's approximations — padded
    slots, packed states — are the suspect side of a disagreement). An
    over-budget recheck keeps the device verdict, tagged."""
    import time as _time

    from jepsen_tpu.checker import wgl
    n_history = max(c.complete_index for c in e.calls) + 1
    host = wgl.check_calls(
        model, list(e.calls), n_history,
        max_states=DISAGREEMENT_RECHECK_MAX_STATES,
        deadline=_time.monotonic() + DISAGREEMENT_RECHECK_SECS)
    if host.get("valid?") is False:
        # the key IS invalid — the disagreement was about the failure
        # site; take the host's whole failure report so op/paths/configs
        # describe one consistent stuck point
        out = {"final-paths": host.get("final-paths", []),
               "configs": host.get("configs", []),
               "engine-disagreement": note + "; full-host recheck "
                                             "confirms invalid"}
        if host.get("op"):
            out["op"] = host["op"]
        return out
    if host.get("valid?") is True:
        # counted, not just logged: a false-invalid override is the
        # loudest possible device-engine signal, and the registry makes
        # it greppable in telemetry exports across a whole run
        obs.counter("engine.false_invalid").inc()
        _log.error("device engine false-invalid: %s, and the bounded "
                   "full-host recheck says VALID — overriding the device "
                   "verdict (this may hide a device-engine bug; please "
                   "report the history)", note)
        return {"valid?": True, "final-paths": [], "configs": [],
                "engine-disagreement": note + "; full-host recheck says "
                                              "valid — device verdict "
                                              "overridden"}
    _log.warning("final-paths: %s; the bounded full-host recheck was "
                 "indecisive (%s) — keeping the device verdict",
                 note, host.get("error", "?"))
    return {"final-paths": [], "configs": [],
            "final-paths-note": note + "; bounded full-host recheck "
                                       "indecisive — device verdict "
                                       "kept"}


def apply_final_paths(r: dict, model, e: EncodedHistory) -> dict:
    """Merge extract_final_paths into a device-invalid result `r`, in
    place. When the disagreement recheck OVERRIDES the verdict to
    valid, the device's stale counterexample fields are dropped — a
    valid result must not carry a phantom failing op.

    A supervised-dispatch failure DURING extraction (the seed-frontier
    re-scan is a device dispatch too) must not crash a verdict that is
    already decided: the result keeps its verdict with an empty-paths
    note instead (the same loud-but-not-fatal policy as _empty)."""
    try:
        fp = extract_final_paths(model, e, int(r["fail-event"]))
    except sup.DISPATCH_FAILURES as err:
        obs.counter("engine.final_paths_missing").inc()
        _log.warning("final-paths extraction lost its device dispatch "
                     "(%s) — verdict kept, paths empty", err)
        r.setdefault("final-paths", [])
        r.setdefault("configs", [])
        r["final-paths-note"] = (f"extraction dispatch failed: "
                                 f"{type(err).__name__}: {err}")
        return r
    if fp.get("valid?") is True:
        for k in ("op", "fail-event"):
            r.pop(k, None)
    r.update(fp)
    return r


def extract_final_paths(model, e: EncodedHistory, fail_r: int,
                        window: int = PATHS_WINDOW_EVENTS,
                        max_seeds: int = PATHS_MAX_SEEDS) -> dict:
    """knossos-style :final-paths / :configs for a failing return event.

    Short histories (<= 500 calls) re-search the whole failing prefix on
    the host. Longer ones re-run the device scan up to a checkpoint
    `window` return-events before the failure, decode the frontier into
    (model state, linearized-open-calls) seeds, and host-search only the
    window from each seed — exact counterexamples at any history length,
    with the device doing the long prefix."""
    from jepsen_tpu.checker import wgl
    fail_idx = e.calls[int(e.ret_call[fail_r])].complete_index
    if e.n_calls <= 500:
        host = wgl.check_calls(model, _prefix_calls(e.calls, fail_idx),
                               fail_idx + 1)
        if host.get("valid?") is False:
            return {"final-paths": host.get("final-paths", []),
                    "configs": host.get("configs", [])}
        # the host can linearize the prefix the device failed on:
        # escalate to a bounded full-host recheck of the key rather
        # than shipping "invalid, no paths" on a possible device
        # false-invalid
        return _disagreement_recheck(
            model, e, "host re-search of the failing prefix came back "
                      "valid while the device said invalid")

    def _empty(note: str) -> dict:
        # an invalid history with no paths is a loud event, same policy
        # as the device-fallback tagging in independent.py — silence
        # here would look like "no counterexample available" by design;
        # the counter makes it visible in the run's telemetry too
        obs.counter("engine.final_paths_missing").inc()
        _log.warning("final-paths extraction returned nothing for an "
                     "invalid history: %s", note)
        return {"final-paths": [], "configs": [], "final-paths-note": note}

    from jepsen_tpu import models as model_ns
    # the encoded history carries its *prepared* spec — for models with
    # history-dependent packing (gset lanes, queue widths) a fresh
    # pack_spec could not unpack device states
    spec = e.spec or model_ns.pack_spec(model, e.intern)
    if spec is None or spec.unpack_state is None:
        return _empty("model has no unpack_state; cannot seed a window "
                      "re-search")
    start_ev = max(0, fail_r - window)
    if start_ev == 0:
        seeds = [(e.state0, frozenset())]
        occupants: dict = {}
    else:
        rows = _frontier_at(e, start_ev)
        if rows is None:
            return _empty("seed-frontier re-scan overflowed max capacity")
        occupants = _slot_occupants_before(e, start_ev)
        seeds = []
        for stc, ml, mh in rows[:max_seeds]:
            mask = ml | (mh << 32)
            seeds.append((stc, frozenset(
                cid for s, cid in occupants.items() if (mask >> s) & 1)))

    boundary = (e.calls[int(e.ret_call[start_ev])].complete_index
                if start_ev > 0 else -1)
    paths: list = []
    configs: list = []
    # Every sampled seed runs BEFORE any paths are trusted: a failing
    # seed may just be a dead-end config (reachable but unextendable —
    # normal in a valid history), while a seed that linearizes through
    # the failure proves a valid linearization of the whole prefix
    # EXISTS — a direct contradiction of the device's
    # empty-frontier-at-fail_r. Only an all-seeds-fail outcome
    # corroborates the device verdict.
    for seed_i, (stc, linearized) in enumerate(seeds):
        seed_model = spec.unpack_state(stc, e.intern)
        cs = _window_calls(e.calls, boundary, fail_idx, linearized)
        host = wgl.check_calls(seed_model, cs, fail_idx + 1)
        if host.get("valid?") is True:
            return _disagreement_recheck(
                model, e, "window re-search from device seed %d "
                          "linearized through the failure "
                          "(window [%d, %d])"
                          % (seed_i, start_ev, fail_r))
        if host.get("valid?") is False:
            paths.extend(host.get("final-paths", []))
            configs.extend(host.get("configs", []))
    if not paths:
        # no seed failed and none decisively linearized either (all
        # indecisive): the window/seed machinery itself may be the
        # wrong side, so the recheck covers the whole key
        return _disagreement_recheck(
            model, e, "none of the %d window re-searches from device "
                      "seeds produced a verdict (window [%d, %d])"
                      % (len(seeds), start_ev, fail_r))
    out = {"final-paths": paths[:10], "configs": configs[:10]}
    if start_ev > 0:
        # paths cover the failure window only; the device verified the
        # prefix and supplied the seed states
        out["final-paths-window"] = [start_ev, fail_r]
    return out


def _frontier_at(e: EncodedHistory, start_ev: int):
    """Re-run the device scan over return events [0, start_ev) and pull
    the live frontier rows to host as (state, mask_lo, mask_hi)."""
    xs_np = {
        "slot_f": e.slot_f[:start_ev], "slot_a0": e.slot_a0[:start_ev],
        "slot_a1": e.slot_a1[:start_ev], "slot_wild": e.slot_wild[:start_ev],
        "slot_occ": e.slot_occ[:start_ev], "ev_slot": e.ev_slot[:start_ev],
    }
    chunk = {k: jnp.asarray(v) for k, v in xs_np.items()}
    N = 1024
    while True:
        def _rescan(N=N):
            # always the unpacked layout: this re-scan feeds host-side
            # seed decoding (the canonical triple), and extraction
            # correctness must never depend on a perf flag
            carry0 = _initial_carry(jnp.int32(e.state0), N,
                                    _rep((), e.slot_f.shape[1]))
            carry, overflow = _check_device_resumable(
                chunk, carry0, e.step_name, N)
            return ([np.asarray(x) for x in carry], bool(overflow))

        # supervised like every dispatch, but with no breaker backend:
        # this re-scan runs INSIDE recovery/extraction paths, and its
        # failure must not double-count against the breaker that is
        # already handling the original one
        carry, overflow = sup.dispatch("search", _rescan)
        if not bool(overflow):
            break
        if N * 2 > (1 << 20):
            return None
        N *= 2
    st, ml, mh, live = [np.asarray(x) for x in carry[:4]]
    idx = np.nonzero(live)[0]
    return [(int(st[i]), int(ml[i]), int(mh[i])) for i in idx]


def _slot_occupants_before(e: EncodedHistory, r_target: int) -> dict:
    """slot -> call id of the snapshot taken just before return event
    r_target — the same walk encode() performs (same heap discipline,
    so slot numbers match the device masks)."""
    import heapq
    events = []
    for c in e.calls:
        events.append((c.invoke_index, 0, c.index))
        if not c.crashed:
            events.append((c.complete_index, 1, c.index))
    events.sort()
    free: list = []
    n_slots = 0
    slot_of: dict = {}
    occ: dict = {}
    r = 0
    for _, kind, cid in events:
        if kind == 0:
            s = heapq.heappop(free) if free else n_slots
            if s == n_slots:
                n_slots += 1
            slot_of[cid] = s
            occ[s] = cid
        else:
            if r == r_target:
                return dict(occ)
            s = slot_of[cid]
            del occ[s]
            heapq.heappush(free, s)
            r += 1
    return dict(occ)


def _window_calls(cs, boundary: int, fail_idx: int, linearized):
    """Calls active in the window (boundary, fail_idx]: drops calls
    fully completed before the boundary and calls the seed already
    linearized; clamps completions past fail_idx to still-open."""
    from jepsen_tpu.history import Call
    out = []
    for c in cs:
        if c.invoke_index > fail_idx:
            continue
        if (not c.crashed) and c.complete_index < boundary:
            continue  # returned before the window: effect is in the seed
        if c.index in linearized:
            continue  # already applied in the seed state
        if c.complete_index > fail_idx:
            c2 = Call(c.index, c.process, c.f, c.value, None,
                      c.invoke_index, fail_idx + 1, True)
        else:
            c2 = Call(c.index, c.process, c.f, c.value, c.result,
                      c.invoke_index, c.complete_index, c.crashed)
        out.append(c2)
    for j, c in enumerate(out):
        c.index = j
    return out


def _prefix_calls(cs, fail_idx):
    """Calls restricted to the failing prefix: everything invoked up to
    fail_idx, with completions after it treated as still-open (crashed)."""
    return _window_calls(cs, -1, fail_idx, frozenset())


# ----------------------------------------------------- batched (per-key)


def encode_batch(model, histories, pad_slots: Optional[int] = None,
                 encs: Optional[list] = None, mesh=None):
    """Encode many per-key histories to one padded batch (the reference's
    per-key data parallelism, jepsen.independent — SURVEY.md §2.20 P5:
    'one key's history per TPU program instance'). With `mesh`, the
    arrays are explicitly device_put onto the mesh (key axis sharded
    when divisible, replicated otherwise) so the default backend is
    never touched."""
    if encs is None:
        encs = [enc_mod.encode(model, h, pad_slots=pad_slots)
                for h in histories]
    elif pad_slots is not None:
        # a pre-encoded history's slot tables are already allocated at
        # their final width — silently ignoring pad_slots here (the old
        # behavior) would hand back a batch narrower than the caller
        # asked for, which only surfaces later as a shape mismatch in
        # whatever program the caller compiled for the requested width.
        # The one legal case: every enc was already padded to exactly
        # the requested width (the streaming extension pre-allocates
        # its group tier's width — parallel.extend), in which case the
        # request is a no-op rather than a conflict.
        if any(e.slot_f.shape[1] != pad_slots for e in encs):
            raise ValueError(
                "encode_batch: pad_slots conflicts with pre-encoded "
                "encs whose slot tables are at a different width (their "
                "tables are already final) — re-encode with pad_slots, "
                "or grow them through the extension API "
                "(jepsen_tpu.parallel.extend.extend_encoded / "
                "HistorySession), which pre-allocates matching widths")
    xs, state0, _, _, _ = enc_mod.pad_batch(encs, mesh=mesh)
    return encs, xs, state0


def check_batch(model, histories, capacity: int = 512,
                max_capacity: int = 1 << 18, mesh=None,
                bucket: Optional[str] = None,
                pipeline: Optional[bool] = None, cache=None,
                pipeline_stats: Optional[dict] = None,
                dedupe: Optional[str] = None,
                sparse_pallas: Optional[bool] = None,
                search_stats: Optional[bool] = None,
                config_pack: Optional[bool] = None,
                steal: Optional[bool] = None,
                reshard: Optional[bool] = None,
                steal_stats: Optional[dict] = None) -> list:
    """Check many per-key histories in one device program per
    slot-window bucket: vmap over the key axis; with a mesh (and K
    divisible by its size) the key axis is sharded across devices —
    data parallelism over ICI.

    `bucket` picks the grouping strategy before padding (default: the
    JEPSEN_TPU_BUCKET env var, else "tier"):

    - "tier" (default): power-of-two slot-window tiers — one wide key
      (say C=20) must not force every narrow key through a 2^20-mask
      program (measured on v5e: a 336-key batch with a C=20 straggler
      ran ~6x slower un-bucketed).
    - "exact": one bucket per exact slot count. Tiers are coarse at
      the top of a tier: the reference workload's 84 keys span slots
      11..15 — one tier — so all pad to W=1024 while most need 256 or
      less (~2.9x the word-work). Exact buckets trade that against one
      compile + dispatch per distinct C. tools/perf_ab.py measures the
      trade ("batch ... exact-C bucketed" line); stays opt-in until an
      on-chip win is recorded there — flags do not get to claim
      speedups.

    Each bucket independently dispatches to the bit-packed dense
    engine (parallel.bitdense) when its combined padded dims fit,
    sparse frontier mode otherwise.

    `pipeline` routes the batch through the pipelined executor
    (parallel.pipeline): host encode, H2D transfer, and device search
    overlap instead of running as three serial phases, and encodings
    come from the digest-keyed encode cache (`cache`; pass False to
    disable, None for the process default). Default: the
    JEPSEN_TPU_PIPELINE env flag, else off — opt-in until bench
    records a win (flags do not get to claim speedups). Results are
    bit-identical to the serial path either way (docs/performance.md).
    `pipeline_stats`, when a dict, receives the per-bucket
    encode/transfer/device split the bench reports.

    `sparse_pallas` routes the sparse buckets' hash closure through the
    fused VMEM frontier kernel (check_encoded's docstring; None = the
    JEPSEN_TPU_SPARSE_PALLAS flag).

    `steal` (None = JEPSEN_TPU_STEAL) routes the batch through the
    elastic round-based executor (parallel.elastic): keys dispatch in
    device-aligned rounds and a skew-aware placement loop migrates
    pending keys between per-device queues from the observed
    search-stats/cost signal of completed rounds — results
    bit-identical to the static path (verdict, op/fail-event,
    max-frontier, capacity, configs-stepped; docs/performance.md
    "Elastic scheduling"). `steal_stats`, when a dict, receives the
    scheduler's per-device cost/steal accounting. `reshard` (None =
    JEPSEN_TPU_RESHARD) makes capacity escalation recruit mesh devices
    (sharded elastic ladder) instead of only growing tables."""
    bucket = _resolve_bucket(bucket)   # fail-fast: before the encode
    pl = _planner.active()
    from time import perf_counter as _pc
    if pl is None:
        dedupe = _resolve_dedupe(dedupe)   # likewise fail-fast
    else:
        _resolve_dedupe(dedupe)   # fail-fast validation only — with
        # the planner armed the dedupe REQUEST stays raw so each
        # sparse bucket plans its own arm per shape
        # (_check_batch_sparse); the batch-level axes (executor
        # choice) are planned here, where they route
        dec = pl.decide("batch", type(model).__name__, None,
                        {"pipeline": pipeline, "steal": steal},
                        keys=len(histories))
        if dec is not None:
            pipeline = dec["strategy"].get("pipeline", pipeline)
            steal = dec["strategy"].get("steal", steal)
        t0_plan = _pc()
    run_pipeline = _resolve_pipeline(pipeline)
    run_steal = bool(_resolve_steal(steal))
    if run_pipeline:
        from jepsen_tpu.parallel import pipeline as pipe_mod
        res = pipe_mod.check_batch_pipelined(
            model, histories, capacity=capacity,
            max_capacity=max_capacity, mesh=mesh, bucket=bucket,
            cache=cache, stats=pipeline_stats, dedupe=dedupe,
            sparse_pallas=sparse_pallas, search_stats=search_stats,
            config_pack=config_pack, steal=steal, reshard=reshard,
            steal_stats=steal_stats)
    elif run_steal:
        from jepsen_tpu.parallel import elastic
        with obs.span("engine.check_batch", keys=len(histories),
                      bucket=bucket), obs.maybe_jax_profile():
            with obs.span("engine.encode_batch", keys=len(histories)):
                pre = [enc_mod.encode(model, h) for h in histories]
            res = elastic.check_batch_stealing(
                model, pre, capacity=capacity,
                max_capacity=max_capacity, mesh=mesh, bucket=bucket,
                dedupe=dedupe, sparse_pallas=sparse_pallas,
                search_stats=search_stats, config_pack=config_pack,
                reshard=reshard, stats=steal_stats)
    else:
        if steal_stats is not None:
            # same loud contract as cache/pipeline_stats below: the
            # static path runs no scheduler and would silently leave
            # the dict empty while the caller believes stealing was
            # measured
            raise ValueError(
                "check_batch: steal_stats is an elastic-executor "
                "argument — pass steal=True (or set "
                "JEPSEN_TPU_STEAL=1) to use it")
        if (cache is not None and cache is not False) \
                or pipeline_stats is not None:
            # the serial path consults no cache and fills no stats —
            # silently ignoring these arguments would be the same trap
            # this PR closed in encode_batch(pad_slots, encs): the
            # caller clearly wanted the pipelined executor, so say so.
            # cache=False ("no caching") is exempt: the serial path
            # already satisfies it by doing nothing, so it must not
            # crash env-flag-dependently
            raise ValueError(
                "check_batch: cache/pipeline_stats are "
                "pipelined-executor arguments — pass pipeline=True "
                "(or set JEPSEN_TPU_PIPELINE=1) to use them")
        with obs.span("engine.check_batch", keys=len(histories),
                      bucket=bucket), obs.maybe_jax_profile():
            with obs.span("engine.encode_batch",
                          keys=len(histories)):
                pre = [enc_mod.encode(model, h) for h in histories]
            res = check_batch_encoded(model, pre, capacity=capacity,
                                      max_capacity=max_capacity,
                                      mesh=mesh,
                                      bucket=bucket, dedupe=dedupe,
                                      sparse_pallas=sparse_pallas,
                                      search_stats=search_stats,
                                      config_pack=config_pack,
                                      reshard=reshard)
    if pl is not None:
        pl.observe("batch", type(model).__name__, None,
                   {"pipeline": run_pipeline, "steal": run_steal},
                   _pc() - t0_plan)
    return res


def _resolve_bucket(bucket: Optional[str]) -> str:
    if bucket is None:
        # JEPSEN_TPU_BUCKET gives deployments the lever without a code
        # change, same opt-in philosophy as the other perf flags; the
        # validated accessor raises on values outside the contract
        bucket = envflags.env_choice("JEPSEN_TPU_BUCKET",
                                     ("tier", "exact"), default="tier",
                                     what="bucket strategy")
    if bucket not in ("tier", "exact"):
        raise ValueError(f"unknown bucket strategy {bucket!r}")
    return bucket


def _resolve_pipeline(pipeline: Optional[bool]) -> bool:
    if pipeline is None:
        pipeline = envflags.env_bool("JEPSEN_TPU_PIPELINE",
                                     default=False)
    return bool(pipeline)


def _resolve_steal(steal: Optional[bool]) -> bool:
    """JEPSEN_TPU_STEAL: skew-driven key work-stealing in the
    multi-key executors (parallel.elastic). Opt-in until the recorded
    A/B (tools/perf_ab.py steal arm) flips it — flags do not get to
    claim speedups."""
    if steal is None:
        steal = envflags.env_bool("JEPSEN_TPU_STEAL", default=False)
    return bool(steal)


def _resolve_reshard(reshard: Optional[bool]) -> bool:
    """JEPSEN_TPU_RESHARD: capacity escalation recruits devices
    (parallel.sharded.check_encoded_sharded_elastic) instead of only
    growing per-device tables. Opt-in, same contract as STEAL."""
    if reshard is None:
        reshard = envflags.env_bool("JEPSEN_TPU_RESHARD",
                                    default=False)
    return bool(reshard)


def bucket_key(n_slots: int, bucket: str) -> int:
    """The bucket a key with `n_slots` open-call slots lands in under
    the given strategy — shared by the serial (check_batch_encoded)
    and pipelined (parallel.pipeline) executors so their grouping, and
    therefore their padded programs and per-key result dicts, match
    exactly."""
    if bucket == "exact":
        # floor at bitdense's min_slots=5: narrower keys pad to
        # the same C=5 program anyway, so splitting them would be
        # pure dispatch overhead (and perf_ab's measured grouping
        # uses the same floor)
        return max(5, n_slots)
    return 1 << max(2, (max(1, n_slots) - 1).bit_length())


def check_batch_encoded(model, pre, capacity: int = 512,
                        max_capacity: int = 1 << 18, mesh=None,
                        bucket: Optional[str] = None,
                        dedupe: Optional[str] = None,
                        sparse_pallas: Optional[bool] = None,
                        search_stats: Optional[bool] = None,
                        config_pack: Optional[bool] = None,
                        reshard: Optional[bool] = None) -> list:
    """check_batch on ALREADY-ENCODED keys (the bucketing + dispatch
    half without the encode half). Public so callers that time or
    cache the encode separately — bench.sec_multikey's encode/device
    split, re-analysis over a stored columnar history — drive the
    same bucketing policy as check_batch. Results keep `pre`'s
    order. `dedupe` governs the sparse buckets (bitdense buckets are
    a complete visited set by construction; their results say
    dedupe="dense")."""
    if not pre:
        _resolve_bucket(bucket)
        _resolve_dedupe(dedupe)
        return []
    bucket = _resolve_bucket(bucket)
    if _planner.active() is None:
        dedupe = _resolve_dedupe(dedupe)
    else:
        # fail-fast validation only: with the planner armed the dedupe
        # REQUEST stays raw (None = plannable) so each sparse bucket
        # picks its own arm per padded shape in _check_batch_sparse;
        # bitdense buckets never consult dedupe either way
        _resolve_dedupe(dedupe)
    from jepsen_tpu.parallel import bitdense
    out: list = [None] * len(pre)
    buckets: dict = {}
    for i, e in enumerate(pre):
        buckets.setdefault(bucket_key(e.n_slots, bucket), []).append(i)
    for tier in sorted(buckets):
        idxs = buckets[tier]
        sub = [pre[i] for i in idxs]
        S_max = max(bitdense.n_states(e) for e in sub)
        C_max = max(e.n_slots for e in sub)
        if bitdense.fits_bitdense(S_max, C_max):
            try:
                rs = bitdense.check_batch_bitdense(
                    sub, mesh=mesh, search_stats=search_stats)
            except sup.DISPATCH_FAILURES as err:
                # degradation contract: a dead bitdense dispatch costs
                # this bucket the device path, not the batch the
                # verdict — each key re-checks on the host WGL engine
                # with a structured resilience note
                from jepsen_tpu.resilience import recovery
                reason = f"{type(err).__name__}: {err}"
                rs = [recovery.host_check_encoded(
                          model, e, getattr(err, "site", "dispatch"),
                          reason) for e in sub]
        else:
            rs = _check_batch_sparse(model, sub, capacity, max_capacity,
                                     mesh, dedupe=dedupe,
                                     sparse_pallas=sparse_pallas,
                                     search_stats=search_stats,
                                     config_pack=config_pack,
                                     reshard=reshard)
        for i, r in zip(idxs, rs):
            out[i] = r
    return out


def _check_batch_sparse(model, pre, capacity: int, max_capacity: int,
                        mesh=None, dedupe: Optional[str] = None,
                        probe_limit: int = 0,
                        sparse_pallas: Optional[bool] = None,
                        search_stats: Optional[bool] = None,
                        config_pack: Optional[bool] = None,
                        reshard: Optional[bool] = None) -> list:
    """Sparse-frontier batch path with per-key capacity-tier retry."""
    step_name = pre[0].step_name
    K = len(pre)
    out: list = [None] * K
    probe_limit = _resolve_probe_limit(probe_limit)
    ss = _resolve_search_stats(search_stats)
    C = max(e.slot_f.shape[1] for e in pre)
    pl = _planner.active()
    plan_prov = None
    if pl is not None:
        # the plan routes this padded shape between parity-pinned
        # strategy arms; axes the caller fixed (explicit arg or env)
        # are never overridden — decide() only fills the None ones
        dec = pl.decide("sparse", step_name, C,
                        {"dedupe": dedupe, "pallas": sparse_pallas,
                         "pack": config_pack}, keys=K)
        if dec is not None:
            chosen = dec["strategy"]
            dedupe = chosen.get("dedupe", dedupe)
            sparse_pallas = chosen.get("pallas", sparse_pallas)
            config_pack = chosen.get("pack", config_pack)
            plan_prov = dec["plan"]
    dedupe = _resolve_dedupe(dedupe)
    pack_req = _resolve_config_pack(config_pack)
    led = _ledger.active()
    from time import perf_counter as _pc
    # the padded batch runs one program: gate the kernel on where the
    # batch actually lives (the mesh when given), like bitdense does
    platform = (np.asarray(mesh.devices).flat[0].platform
                if mesh is not None else jax.default_backend())
    # one COMMON layout for the whole padded program: the state field
    # must cover every member's domain (pack_spec_for unions them)
    pack = pack_spec_for(pre, C) if pack_req else ()
    # Per-key capacity retry: keys are bucketed by the capacity tier
    # they need — only keys that overflowed re-run (at doubled
    # capacity), so one hot key never drags the whole batch through
    # re-padding and re-search at 2-512x capacity.
    pending = list(range(K))
    N = max(64, capacity)
    n_tier = 0
    while pending:
        encs_t = [pre[i] for i in pending]
        mode, note = _resolve_sparse_pallas(sparse_pallas, N, C,
                                            platform, dedupe, pack)
        t0 = _pc()
        try:
            with obs.span("engine.sparse_batch", keys=len(pending),
                          capacity=N, dedupe=dedupe):
                _, xs, state0 = sup.dispatch(
                    "transfer",
                    lambda encs_t=encs_t: encode_batch(
                        model, [], encs=encs_t, mesh=mesh),
                    backend=platform)

                def _search(xs=xs, state0=state0, N=N, mode=mode):
                    out = _run_program(
                        "engine.check_batch", xs, state0, step_name,
                        N, dedupe, probe_limit, mode, ss, pack)
                    # materialize inside the supervised window
                    return jax.tree.map(np.asarray, out)

                res = sup.dispatch("search", _search, backend=platform)
                valid, fail_r, overflow, maxf, steps_n, stepped = \
                    res[:6]
        except sup.DISPATCH_FAILURES as err:
            # degradation contract: the keys still pending at the
            # failure degrade to the host WGL path, each with a
            # structured resilience note — keys already decided on
            # the device keep their device results
            from jepsen_tpu.resilience import recovery
            reason = f"{type(err).__name__}: {err}"
            for i in pending:
                out[i] = recovery.host_check_encoded(
                    model, pre[i], getattr(err, "site", "search"),
                    reason, backend=platform)
            break
        t1 = _pc()
        if pl is not None:
            # evidence lands on the REQUESTED arm (what decide() would
            # hand out again), not the resolved closure mode — the
            # platform fallback inside _resolve_sparse_pallas is the
            # same for every arm, so the comparison stays fair
            pallas_req = (bool(sparse_pallas)
                          if sparse_pallas is not None
                          else envflags.env_bool(
                              "JEPSEN_TPU_SPARSE_PALLAS",
                              default=False))
            pl.observe("sparse", step_name, C,
                       {"dedupe": dedupe, "pallas": pallas_req,
                        "pack": pack_req}, t1 - t0)
        if ss or led is not None:
            # padded program dims for this tier: the pad-waste the
            # stats block reports is measured against what actually
            # shipped to the device
            R_pad = max(e.n_returns for e in encs_t)
            C_pad = max(e.slot_f.shape[1] for e in encs_t)
        retry = []
        n_valid = n_invalid = 0
        tier_stats: list = []
        for j, i in enumerate(pending):
            if bool(overflow[j]):
                retry.append(i)
                continue
            e = pre[i]
            r = {"valid?": bool(valid[j]), "max-frontier": int(maxf[j]),
                 "capacity": N, "dedupe": dedupe,
                 "configs-stepped": int(stepped[j])}
            _tag_sparse_closure(r, mode, note)
            _tag_config_pack(r, pack, pack_req, C)
            if plan_prov is not None:
                r["plan"] = dict(plan_prov)
            obs.counter("engine.configs_stepped").inc(int(stepped[j]))
            if r["valid?"]:
                n_valid += 1
            else:
                n_invalid += 1
            if ss:
                acc = SearchStats(dedupe)
                acc.escalations = n_tier
                acc.add_chunk(
                    jax.tree.map(lambda a, j=j: a[j], res[6]), N)
                waste = 1.0 - ((e.n_returns * e.slot_f.shape[1])
                               / max(1, R_pad * C_pad))
                r["stats"] = _finish_search_stats(
                    acc, t0, t1, key=i,
                    extra={"pad-waste": round(waste, 6),
                           "pad-events": int(R_pad - e.n_returns),
                           "pad-slots": int(C_pad - e.slot_f.shape[1])})
                tier_stats.append(r["stats"])
            if not r["valid?"]:
                r.update(enc_mod.fail_op_fields(e, int(fail_r[j])))
            out[i] = r
        if led is not None:
            # one evidence record per device dispatch (not per key):
            # the padded program's shape fingerprint + the strategy
            # vector that ran it, with the SAME perf_counter reads
            # the span/bench splits use
            led.record(
                "dispatch", engine="sparse",
                shape={"family": step_name, "N": N, "R": int(R_pad),
                       "C": int(C_pad), "tier": n_tier,
                       "pack": bool(pack)},
                strategy={"dedupe": dedupe, "closure": mode,
                          "pack": pack_req,
                          "probe_limit": probe_limit},
                secs=round(t1 - t0, 6), keys=len(pending),
                stats=_ledger.stats_digest(tier_stats),
                outcome={"valid": n_valid, "invalid": n_invalid,
                         "overflow": len(retry)})
        if not retry:
            break
        if N * 2 > max_capacity:
            for i in retry:
                out[i] = _escalate_overflow(pre[i], N, mesh,
                                            dedupe=dedupe,
                                            sparse_pallas=sparse_pallas,
                                            search_stats=ss,
                                            config_pack=pack_req,
                                            reshard=reshard)
            break
        # keys that overflowed re-dispatch at the doubled tier — the
        # counter the capacity-retry ladder's cost is visible through
        obs.counter("engine.overflow_redispatch").inc(len(retry))
        pending = retry
        N *= 2
        n_tier += 1
    return out


def _escalate_overflow(e: EncodedHistory, batch_cap: int, mesh,
                       dedupe: str = "sort",
                       sparse_pallas: Optional[bool] = None,
                       search_stats: Optional[bool] = None,
                       config_pack: Optional[bool] = None,
                       reshard: Optional[bool] = None) -> dict:
    """Ledger-instrumented wrapper around the escalation ladder: when
    the decision ledger is armed, each escalation lands one evidence
    record — which tier decided (single/sharded/none), under what
    strategy vector, and how long the whole ladder took. Semantics
    are exactly ``_escalate_overflow_impl``'s (its docstring is the
    contract)."""
    led = _ledger.active()
    if led is None:
        return _escalate_overflow_impl(
            e, batch_cap, mesh, dedupe=dedupe,
            sparse_pallas=sparse_pallas, search_stats=search_stats,
            config_pack=config_pack, reshard=reshard)
    from time import perf_counter as _pc
    t0 = _pc()
    r = _escalate_overflow_impl(
        e, batch_cap, mesh, dedupe=dedupe,
        sparse_pallas=sparse_pallas, search_stats=search_stats,
        config_pack=config_pack, reshard=reshard)
    t1 = _pc()
    led.record(
        "escalation", engine="sparse",
        shape={"family": e.step_name, "R": int(e.n_returns),
               "C": int(e.slot_f.shape[1])},
        strategy={"dedupe": dedupe, "reshard": bool(reshard)
                  if reshard is not None else _resolve_reshard(None)},
        secs=round(t1 - t0, 6), batch_cap=batch_cap,
        outcome={"escalated": r.get("escalated"),
                 "verdict": _ledger.verdict_class(r),
                 "error": bool(r.get("error")
                               or r.get("escalation-error"))})
    return r


def _escalate_overflow_impl(e: EncodedHistory, batch_cap: int, mesh,
                            dedupe: str = "sort",
                            sparse_pallas: Optional[bool] = None,
                            search_stats: Optional[bool] = None,
                            config_pack: Optional[bool] = None,
                            reshard: Optional[bool] = None) -> dict:
    """A key too wide for the batch program escalates instead of dying
    as "unknown": first the single-key sparse engine at 4x the batch
    ceiling, then — with a mesh — the frontier-sharded engine, whose
    aggregate capacity scales with the device count (the dp -> sp
    escalation SURVEY.md §5.7 frames as the long-history story:
    per-key batching until a key outgrows a chip, frontier sharding
    beyond). Ceilings scale from the caller's batch bound — batch_cap
    x4 on one device, a further xD across the mesh — so a tight bound
    set for latency/memory reasons stays meaningful. Reports which
    tier decided via "escalated". The first batch run already proved
    batch_cap overflows, so every tier starts at 2x.

    Under `reshard` (None = JEPSEN_TPU_RESHARD) the sharded tier runs
    the elastic device ladder (sharded.check_encoded_sharded_elastic
    via check_encoded_sharded's delegation): the retry recruits a
    widening slice of the mesh at flat per-device capacity — idle
    devices, not bigger tables, absorb the overflow — with the same
    ceilings and the same overflow->unknown semantics."""
    obs.counter("engine.capacity_escalations").inc()
    ceil_single = min(batch_cap * 4, 1 << 21)
    # pin the single tier to the caller's mesh: check_encoded on the
    # default backend would break the invariant the batch and sharded
    # paths maintain (nothing on the default backend — it can be a
    # wedged TPU runtime while we deliberately run on a CPU mesh), and
    # a batch-overflow key would hang in escalation
    dev = None if mesh is None else np.asarray(mesh.devices).flat[0]
    r = check_encoded(e, capacity=min(batch_cap * 2, ceil_single),
                      max_capacity=ceil_single, device=dev,
                      dedupe=dedupe, sparse_pallas=sparse_pallas,
                      search_stats=search_stats,
                      config_pack=config_pack)
    if r["valid?"] != "unknown":
        r["escalated"] = "single"
        return r
    if mesh is not None \
            and min(batch_cap * 4 * np.asarray(mesh.devices).size,
                    1 << 24) > ceil_single:
        # the tier only runs when its aggregate ceiling can actually
        # exceed what the single tier just proved overflows — on a
        # 1-device mesh the two ceilings coincide and a re-run would
        # be pure waste
        try:
            from jepsen_tpu.parallel import sharded
            n_dev = np.asarray(mesh.devices).size
            # pass the caller's mesh through untouched: the sharded
            # engine picks the hierarchical exchange on 2-D (multi-
            # slice) meshes and flattens anything else itself. Start
            # past the single tier's proven-overflowing 4x ceiling —
            # frontier occupancy is a property of the history, so
            # re-running smaller global capacities is pure waste.
            ceil_sharded = min(batch_cap * 4 * n_dev, 1 << 24)
            rs = sharded.check_encoded_sharded(
                e, mesh, capacity=min(batch_cap * 8, ceil_sharded),
                max_capacity=ceil_sharded, dedupe=dedupe,
                sparse_pallas=sparse_pallas,
                search_stats=search_stats,
                config_pack=config_pack, reshard=reshard)
            if rs["valid?"] != "unknown":
                rs["escalated"] = "sharded"
                return rs
            r = rs
        except Exception as err:  # noqa: BLE001 — escalation must not
            # turn a decidable batch into a crash; but a broken sharded
            # engine must be LOUD (the same rule as independent.py's
            # device-fallback), not a buried result key
            obs.counter("engine.escalation_errors").inc()
            _log.warning(
                "sharded escalation tier crashed (%r) — key left "
                "unknown; this may hide a sharded-engine regression",
                err)
            r = dict(r)
            r["escalation-error"] = repr(err)
    r["error"] = (f"frontier overflow: batch capacity {batch_cap}, "
                  f"escalation tiers exhausted ({r.get('error')})")
    return r
