"""The TPU linearizability engine — batched frontier expansion under jit.

This is the north star (BASELINE.json): the knossos linear/wgl search
re-designed for the MXU/VPU instead of translated. The algorithm is the
JIT-linearization frontier of `jepsen_tpu.checker.linear` (its docstring
is the spec; differential tests pin the two together), mapped to XLA:

  * a configuration is (state: i32, mask: 2×u32) — 96 bits, fixed width;
  * the frontier is a fixed-capacity struct-of-arrays [N] with a live
    mask; capacity doubles on overflow by re-jitting (SURVEY.md §7.3
    hard part #1: capacity-tiered buffers);
  * one closure round = a single vmap'd evaluation of the model step
    over all N×C (config, open-slot) pairs — millions of candidate
    configs per chip per round;
  * dedupe is sort-based (lexsort + adjacent-compare + cumsum scatter):
    static shapes, no host round-trips. The sorted frontier *is* the
    visited set — in this formulation the full config set at the current
    event subsumes knossos's visited cache;
  * the outer loop over return events is a lax.scan; the inner closure
    a lax.while_loop. Nothing data-dependent escapes the device: the
    host gets back (valid, fail_event, stats) scalars only.

Multi-chip: `check_batch` vmaps over keys and shards the key axis over a
mesh (data parallel — P5 in SURVEY.md §2.20); `jepsen_tpu.parallel.sharded`
shards the *frontier* axis with collective dedupe for giant single keys.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel.encode import EncodedHistory, EncodeError
from jepsen_tpu.parallel.steps import STEPS


# ------------------------------------------------------------ device core


def _slot_bits(C: int):
    js = jnp.arange(C, dtype=jnp.uint32)
    one = jnp.uint32(1)
    bit_lo = jnp.where(js < 32, one << jnp.minimum(js, 31),
                       jnp.uint32(0)).astype(jnp.uint32)
    bit_hi = jnp.where(js >= 32, one << jnp.minimum(js - 32, jnp.uint32(31)),
                       jnp.uint32(0)).astype(jnp.uint32)
    return bit_lo, bit_hi


def _dedupe_compact(st, ml, mh, live, N):
    """Sort rows by (dead, state, mask), flag first occurrences, compact
    into a fresh [N] frontier. Returns (state, ml, mh, live, count,
    overflow)."""
    M = st.shape[0]
    order = jnp.lexsort((mh, ml, st, (~live).astype(jnp.int8)))
    st_s = st[order]
    ml_s = ml[order]
    mh_s = mh[order]
    live_s = live[order]
    prev_same = jnp.concatenate([
        jnp.zeros(1, bool),
        (st_s[1:] == st_s[:-1]) & (ml_s[1:] == ml_s[:-1])
        & (mh_s[1:] == mh_s[:-1]),
    ])
    uniq = live_s & ~prev_same
    count = jnp.sum(uniq)
    pos = jnp.where(uniq, jnp.cumsum(uniq) - 1, M + N)  # OOB -> dropped
    new_st = jnp.zeros(N, jnp.int32).at[pos].set(st_s, mode="drop")
    new_ml = jnp.zeros(N, jnp.uint32).at[pos].set(ml_s, mode="drop")
    new_mh = jnp.zeros(N, jnp.uint32).at[pos].set(mh_s, mode="drop")
    new_live = jnp.arange(N) < count
    return new_st, new_ml, new_mh, new_live, count, count > N


def _check_impl(xs, state0, step_name: str, N: int):
    """Scan over return events. xs: dict of [R, ...] arrays. Returns
    (valid, fail_event, overflow, max_frontier, steps_evaluated)."""
    step = STEPS[step_name]
    C = xs["slot_f"].shape[1]
    bit_lo, bit_hi = _slot_bits(C)

    # model step vmapped over configs x slots
    step_cc = jax.vmap(
        jax.vmap(step, in_axes=(None, 0, 0, 0, 0)),  # over slots
        in_axes=(0, None, None, None, None),         # over configs
    )

    def closure_cond(c):
        _, _, _, _, changed, overflow, _ = c
        return changed & ~overflow

    def make_closure_body(ev):
        def body(c):
            st, ml, mh, live, _, _, iters = c
            cand_st, cand_ok = step_cc(
                st, ev["slot_f"], ev["slot_a0"], ev["slot_a1"], ev["slot_wild"]
            )
            already = ((ml[:, None] & bit_lo[None, :])
                       | (mh[:, None] & bit_hi[None, :])) != 0
            legal = (live[:, None] & ev["slot_occ"][None, :]
                     & ~already & cand_ok)
            cand_ml = ml[:, None] | bit_lo[None, :]
            cand_mh = mh[:, None] | bit_hi[None, :]
            all_st = jnp.concatenate([st, cand_st.reshape(-1)])
            all_ml = jnp.concatenate([ml, cand_ml.reshape(-1)])
            all_mh = jnp.concatenate([mh, cand_mh.reshape(-1)])
            all_live = jnp.concatenate([live, legal.reshape(-1)])
            old_count = jnp.sum(live)
            st2, ml2, mh2, live2, count, ovf = _dedupe_compact(
                all_st, all_ml, all_mh, all_live, N)
            return st2, ml2, mh2, live2, count > old_count, ovf, iters + 1
        return body

    def scan_step(carry, ev):
        st, ml, mh, live, ok, fail_r, r_idx, maxf, steps_n = carry
        is_pad = ev["ev_slot"] < 0
        run = ok & ~is_pad

        # closure: expand until no new configs (skipped when run=False:
        # the initial `changed` flag is `run`)
        st2, ml2, mh2, live2, _, ovf, iters = lax.while_loop(
            closure_cond, make_closure_body(ev),
            (st, ml, mh, live, run, jnp.array(False), jnp.int32(0)),
        )

        # filter: returning call must have linearized; then free its slot
        s = jnp.maximum(ev["ev_slot"], 0).astype(jnp.uint32)
        one = jnp.uint32(1)
        blo = jnp.where(s < 32, one << jnp.minimum(s, 31),
                        jnp.uint32(0)).astype(jnp.uint32)
        bhi = jnp.where(s >= 32,
                        one << jnp.minimum(jnp.where(s >= 32, s - 32, 0),
                                           jnp.uint32(31)),
                        jnp.uint32(0)).astype(jnp.uint32)
        has = ((ml2 & blo) | (mh2 & bhi)) != 0
        live3 = live2 & has
        ml3 = jnp.where(live3, ml2 & ~blo, ml2)
        mh3 = jnp.where(live3, mh2 & ~bhi, mh2)
        n_live = jnp.sum(live3)
        failed_here = run & (n_live == 0)

        new_ok = jnp.where(run, ~failed_here & ~ovf, ok)
        new_fail = jnp.where(failed_here & (fail_r < 0), r_idx, fail_r)
        st_o = jnp.where(run, st2, st)
        ml_o = jnp.where(run, ml3, ml)
        mh_o = jnp.where(run, mh3, mh)
        live_o = jnp.where(run, live3, live)
        maxf = jnp.maximum(maxf, jnp.where(run, jnp.sum(live2), 0))
        # count closure iterations only; the host multiplies by N*C in
        # Python (int32 would overflow at large capacities)
        steps_n = steps_n + jnp.where(run, iters, 0)
        return (st_o, ml_o, mh_o, live_o, new_ok, new_fail,
                r_idx + 1, maxf, steps_n), ovf

    st0 = jnp.zeros(N, jnp.int32).at[0].set(state0)
    ml0 = jnp.zeros(N, jnp.uint32)
    mh0 = jnp.zeros(N, jnp.uint32)
    live0 = jnp.arange(N) < 1
    carry0 = (st0, ml0, mh0, live0, jnp.array(True), jnp.int32(-1),
              jnp.int32(0), jnp.int32(1), jnp.int32(0))
    carry, ovfs = lax.scan(scan_step, carry0, xs)
    _, _, _, live, ok, fail_r, _, maxf, steps_n = carry
    overflow = jnp.any(ovfs)
    valid = ok & (jnp.sum(live) > 0) & ~overflow
    return valid, fail_r, overflow, maxf, steps_n


_check_device = jax.jit(_check_impl, static_argnames=("step_name", "N"))


@functools.partial(jax.jit, static_argnames=("step_name", "N"))
def _check_device_batch(xs, state0, step_name: str, N: int):
    return jax.vmap(
        lambda x, s0: _check_impl(x, s0, step_name, N)
    )(xs, state0)


# ------------------------------------------------------------- host API


def _xs_from_encoded(e: EncodedHistory) -> dict:
    return {
        "slot_f": jnp.asarray(e.slot_f),
        "slot_a0": jnp.asarray(e.slot_a0),
        "slot_a1": jnp.asarray(e.slot_a1),
        "slot_wild": jnp.asarray(e.slot_wild),
        "slot_occ": jnp.asarray(e.slot_occ),
        "ev_slot": jnp.asarray(e.ev_slot),
    }


def check_encoded(e: EncodedHistory, capacity: int = 1024,
                  max_capacity: int = 1 << 20) -> dict:
    """Check one encoded history, doubling frontier capacity on overflow
    (re-jit per capacity tier; tiers are cached by jax.jit)."""
    if e.n_returns == 0:
        return {"valid?": True, "max-frontier": 0, "capacity": 0}
    xs = _xs_from_encoded(e)
    N = max(64, capacity)
    while True:
        valid, fail_r, overflow, maxf, steps_n = _check_device(
            xs, jnp.int32(e.state0), e.step_name, N)
        if not bool(overflow):
            break
        if N * 2 > max_capacity:
            return {"valid?": "unknown",
                    "error": f"frontier overflow at capacity {N}",
                    "capacity": N}
        N *= 2
    out = {
        "valid?": bool(valid),
        "max-frontier": int(maxf),
        "capacity": N,
        "explored": int(steps_n) * N * len(e.slot_f[0]),
    }
    if not out["valid?"]:
        r = int(fail_r)
        cid = int(e.ret_call[r])
        c = e.calls[cid]
        out["op"] = {"process": c.process, "f": c.f,
                     "value": c.result if c.f == "read" else c.value,
                     "index": c.invoke_index}
        out["fail-event"] = r
    return out


def analysis(model, history, capacity: int = 1024) -> dict:
    """knossos-style (model, history) -> result on the device engine.

    Falls back to the host WGL engine when the model can't pack or the
    open-call window exceeds the device limit. On failure, counter-example
    paths are reconstructed host-side on the failing prefix (SURVEY.md
    §7.3 hard part #3: breadcrumbs stay implicit; a host re-search of the
    short failing prefix supplies :final-paths).
    """
    from jepsen_tpu.history import History
    h = history if isinstance(history, History) else History.wrap(history)
    try:
        e = enc_mod.encode(model, h)
    except EncodeError as err:
        from jepsen_tpu.checker import wgl
        r = wgl.analysis(model, h)
        r["fallback"] = str(err)
        return r
    from jepsen_tpu.parallel import bitdense
    if bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots):
        r = bitdense.check_encoded_bitdense(e)
    else:
        r = check_encoded(e, capacity=capacity)
    if r["valid?"] is False and e.n_calls <= 500:
        from jepsen_tpu.checker import wgl
        fail_idx = e.calls[int(e.ret_call[r["fail-event"]])].complete_index
        host = wgl.check_calls(model, _prefix_calls(e.calls, fail_idx),
                               fail_idx + 1)
        if host.get("valid?") is False:
            r["final-paths"] = host.get("final-paths", [])
            r["configs"] = host.get("configs", [])
    return r


def _prefix_calls(cs, fail_idx):
    """Calls restricted to the failing prefix: everything invoked up to
    fail_idx, with completions after it treated as still-open (crashed)."""
    from jepsen_tpu.history import Call
    out = []
    for c in cs:
        if c.invoke_index > fail_idx:
            continue
        if c.complete_index > fail_idx:
            c2 = Call(c.index, c.process, c.f, c.value, None,
                      c.invoke_index, fail_idx + 1, True)
        else:
            c2 = Call(c.index, c.process, c.f, c.value, c.result,
                      c.invoke_index, c.complete_index, c.crashed)
        out.append(c2)
    for j, c in enumerate(out):
        c.index = j
    return out


# ----------------------------------------------------- batched (per-key)


def encode_batch(model, histories, pad_slots: Optional[int] = None,
                 encs: Optional[list] = None):
    """Encode many per-key histories to one padded batch (the reference's
    per-key data parallelism, jepsen.independent — SURVEY.md §2.20 P5:
    'one key's history per TPU program instance')."""
    if encs is None:
        encs = [enc_mod.encode(model, h, pad_slots=pad_slots)
                for h in histories]
    C = max(e.slot_f.shape[1] for e in encs)
    R = max(e.n_returns for e in encs)
    K = len(encs)

    def pad(attr, fill, dtype):
        out = np.full((K, R, C), fill, dtype)
        for k, e in enumerate(encs):
            arr = getattr(e, attr)
            out[k, : arr.shape[0], : arr.shape[1]] = arr
        return jnp.asarray(out)

    xs = {
        "slot_f": pad("slot_f", -1, np.int32),
        "slot_a0": pad("slot_a0", -1, np.int32),
        "slot_a1": pad("slot_a1", -1, np.int32),
        "slot_wild": pad("slot_wild", False, bool),
        "slot_occ": pad("slot_occ", False, bool),
    }
    ev = np.full((K, R), -1, np.int32)
    for k, e in enumerate(encs):
        ev[k, : e.n_returns] = e.ev_slot
    xs["ev_slot"] = jnp.asarray(ev)
    state0 = jnp.asarray(np.array([e.state0 for e in encs], np.int32))
    return encs, xs, state0


def check_batch(model, histories, capacity: int = 512,
                max_capacity: int = 1 << 18, mesh=None) -> list:
    """Check many per-key histories in one device program: vmap over the
    key axis; with a mesh (and K divisible by its size) the key axis is
    sharded across devices — data parallelism over ICI. Dispatches to the
    bit-packed dense engine (parallel.bitdense) when the COMBINED padded
    batch dims fit its budget, sparse frontier mode otherwise."""
    if not histories:
        return []
    from jepsen_tpu.parallel import bitdense
    pre = [enc_mod.encode(model, h) for h in histories]
    # the batch pads every key to (max S, max C): gate on the combined
    # dims, not per key — individually-fitting keys can combine into an
    # over-budget program
    S_max = max(bitdense.n_states(e) for e in pre)
    C_max = max(e.n_slots for e in pre)
    if bitdense.fits_bitdense(S_max, C_max):
        return bitdense.check_batch_bitdense(pre, mesh=mesh)
    encs, xs, state0 = encode_batch(model, histories, encs=pre)
    step_name = encs[0].step_name
    K = len(encs)
    N = max(64, capacity)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        ax = mesh.axis_names[0]
        n_dev = mesh.shape[ax]
        if K % n_dev == 0:
            xs = {k: jax.device_put(v, NamedSharding(
                mesh, P(*((ax,) + (None,) * (v.ndim - 1)))))
                for k, v in xs.items()}
            state0 = jax.device_put(state0, NamedSharding(mesh, P(ax)))
    while True:
        valid, fail_r, overflow, maxf, steps_n = _check_device_batch(
            xs, state0, step_name, N)
        if not bool(jnp.any(overflow)) or N * 2 > max_capacity:
            break
        N *= 2
    valid = np.asarray(valid)
    fail_r = np.asarray(fail_r)
    overflow = np.asarray(overflow)
    maxf = np.asarray(maxf)
    out = []
    for k, e in enumerate(encs):
        if bool(overflow[k]):
            out.append({"valid?": "unknown",
                        "error": f"frontier overflow at capacity {N}"})
            continue
        r = {"valid?": bool(valid[k]), "max-frontier": int(maxf[k]),
             "capacity": N}
        if not r["valid?"]:
            ri = int(fail_r[k])
            cid = int(e.ret_call[ri])
            c = e.calls[cid]
            r["op"] = {"process": c.process, "f": c.f,
                       "value": c.result if c.f == "read" else c.value,
                       "index": c.invoke_index}
        out.append(r)
    return out
