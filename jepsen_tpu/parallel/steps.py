"""Jit'd pure model step functions — the device tier of jepsen_tpu.models.

Each step has signature

    step(state: i32, f: i32, a0: i32, a1: i32, wild: bool) -> (state': i32, ok: bool)

operating on scalars (the engine vmaps over configs × slots). The
`# jepsen-lint: device` pragmas mark each step as a traced root for the
static purity pass: dispatch rides the STEPS dict, which a call graph
cannot see (docs/linting.md). States and
args are interned int32s (nil = -1). `wild` marks calls whose outcome is
unknown (crashed reads): they apply as the identity and always succeed.

Branch-free by construction — everything is jnp.where over the handful
of f-codes (models.F_*), exactly what the VPU wants; no data-dependent
control flow survives into XLA (SURVEY.md §7: "No data-dependent Python
control flow inside jit").
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from jepsen_tpu.models import (
    F_ACQUIRE, F_ADD, F_CAS, F_DEQ, F_ENQ, F_READ, F_RELEASE, F_WRITE,
)


def register_step(state, f, a0, a1, wild):  # jepsen-lint: device
    """Register / CAS-register family (models.Register, models.CASRegister;
    knossos.model register/cas-register semantics).

    read  a0=observed value: ok iff wild or state == a0; state unchanged
    write a0=new value:      always ok; state = a0
    cas   a0=old, a1=new:    ok iff state == a0; state = a1
    """
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    ok = jnp.where(
        wild,
        True,
        jnp.where(is_read, state == a0,
                  jnp.where(is_write, True,
                            jnp.where(is_cas, state == a0, False))),
    )
    new_state = jnp.where(
        wild | is_read, state,
        jnp.where(is_write, a0, jnp.where(is_cas, a1, state)),
    )
    return jnp.where(ok, new_state, state), ok


def mutex_step(state, f, a0, a1, wild):  # jepsen-lint: device
    """Mutex (models.Mutex): state 0=unlocked, 1=locked."""
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = jnp.where(
        wild, True,
        jnp.where(is_acq, state == 0, jnp.where(is_rel, state == 1, False)),
    )
    new_state = jnp.where(wild, state, jnp.where(is_acq, 1, 0))
    return jnp.where(ok, new_state, state), ok


def gset_step(state, f, a0, a1, wild):  # jepsen-lint: device
    """Grow-only set (models.GSet; knossos.model/set): state is the
    element bitmask itself — bit b set iff element with lane b has been
    added. Lanes are assigned by the encoder's prepare pass; histories
    with more than 31 distinct elements fall back to the host engine.

    add  a0=element lane:        always ok; state |= 1 << a0
    read a0=observed-set mask:   ok iff wild or state == a0; unchanged
    """
    is_add = f == F_ADD
    is_read = f == F_READ
    bit = jnp.int32(1) << jnp.maximum(a0, 0)  # a0=-1 only on masked rows
    ok = jnp.where(
        wild, True,
        jnp.where(is_add, True, jnp.where(is_read, state == a0, False)),
    )
    new_state = jnp.where(wild | is_read, state,
                          jnp.where(is_add, state | bit, state))
    return jnp.where(ok, new_state, state), ok


def uqueue_step(state, f, a0, a1, wild):  # jepsen-lint: device
    """Unordered queue (models.UnorderedQueue; knossos.model/
    unordered-queue): state packs one count lane per distinct value —
    a0 is the lane's bit offset, a1 its unshifted mask. Lane widths are
    sized by the encoder from the history's total enqueues per value, so
    counts cannot overflow their lane; > 31 total bits falls back to the
    host engine.

    enqueue a0=offset:        always ok; count += 1
    dequeue a0=offset a1=mask: ok iff count > 0; count -= 1
    (dequeues with unknown results arrive as wildcards: identity, ok —
    the same unconstrained treatment the host model gives value=None)
    """
    is_enq = f == F_ENQ
    is_deq = f == F_DEQ
    off = jnp.maximum(a0, 0)
    one = jnp.int32(1) << off
    cnt = (state >> off) & a1
    ok = jnp.where(
        wild, True,
        jnp.where(is_enq, True, jnp.where(is_deq, cnt > 0, False)),
    )
    new_state = jnp.where(
        wild, state,
        jnp.where(is_enq, state + one,
                  jnp.where(is_deq, state - one, state)),
    )
    return jnp.where(ok, new_state, state), ok


def fifo_step(state, f, a0, a1, wild):  # jepsen-lint: device
    """Strict FIFO queue (models.FIFOQueue; knossos.model/fifo-queue):
    state is a sequence of v-bit value-code lanes, head at the LOW
    bits, code 0 = empty lane — so the occupied depth is implicit in
    the state's bit length (no separate counter field). The encoder's
    prepare pass assigns codes 1..K, picks the lane width v, and
    proves a depth bound B with B*v <= 31 from the history (falling
    back to the host engine otherwise), so enqueues can never shift
    past bit 30.

    enqueue a0=code a1=v:  always ok; state |= code << (v * depth)
    dequeue a0=code|-1 a1=v: ok iff head != 0 and (code < 0 or
                             head == code); state >>= v
    (a dequeue with unknown result pops ANY head — the host model's
    value=None semantics — so it is a -1 match-any, NOT a wildcard
    identity.)
    """
    is_enq = f == F_ENQ
    is_deq = f == F_DEQ
    v = jnp.maximum(a1, 1)
    head = state & ((jnp.int32(1) << v) - 1)
    bitlen = 32 - lax.clz(state)          # state >= 0 by construction
    depth = (bitlen + v - 1) // v
    enq_state = state | (jnp.maximum(a0, 0) << (v * depth))
    deq_ok = (head != 0) & ((a0 < 0) | (head == a0))
    ok = jnp.where(
        wild, True,
        jnp.where(is_enq, True, jnp.where(is_deq, deq_ok, False)),
    )
    new_state = jnp.where(
        wild, state,
        jnp.where(is_enq, enq_state,
                  jnp.where(is_deq, state >> v, state)),
    )
    return jnp.where(ok, new_state, state), ok


STEPS = {
    "register": register_step,
    "mutex": mutex_step,
    "gset": gset_step,
    "uqueue": uqueue_step,
    "fifo": fifo_step,
}
