"""Jit'd pure model step functions — the device tier of jepsen_tpu.models.

Each step has signature

    step(state: i32, f: i32, a0: i32, a1: i32, wild: bool) -> (state': i32, ok: bool)

operating on scalars (the engine vmaps over configs × slots). States and
args are interned int32s (nil = -1). `wild` marks calls whose outcome is
unknown (crashed reads): they apply as the identity and always succeed.

Branch-free by construction — everything is jnp.where over the handful
of f-codes (models.F_*), exactly what the VPU wants; no data-dependent
control flow survives into XLA (SURVEY.md §7: "No data-dependent Python
control flow inside jit").
"""

from __future__ import annotations

import jax.numpy as jnp

from jepsen_tpu.models import F_ACQUIRE, F_CAS, F_READ, F_RELEASE, F_WRITE


def register_step(state, f, a0, a1, wild):
    """Register / CAS-register family (models.Register, models.CASRegister;
    knossos.model register/cas-register semantics).

    read  a0=observed value: ok iff wild or state == a0; state unchanged
    write a0=new value:      always ok; state = a0
    cas   a0=old, a1=new:    ok iff state == a0; state = a1
    """
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    ok = jnp.where(
        wild,
        True,
        jnp.where(is_read, state == a0,
                  jnp.where(is_write, True,
                            jnp.where(is_cas, state == a0, False))),
    )
    new_state = jnp.where(
        wild | is_read, state,
        jnp.where(is_write, a0, jnp.where(is_cas, a1, state)),
    )
    return jnp.where(ok, new_state, state), ok


def mutex_step(state, f, a0, a1, wild):
    """Mutex (models.Mutex): state 0=unlocked, 1=locked."""
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = jnp.where(
        wild, True,
        jnp.where(is_acq, state == 0, jnp.where(is_rel, state == 1, False)),
    )
    new_state = jnp.where(wild, state, jnp.where(is_acq, 1, 0))
    return jnp.where(ok, new_state, state), ok


STEPS = {
    "register": register_step,
    "mutex": mutex_step,
}
