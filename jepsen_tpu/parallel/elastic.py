"""Elastic multi-key scheduling: skew-driven key work-stealing over
the batched device engines (JEPSEN_TPU_STEAL).

The static executors fix key->device placement up front: the batched
jits shard the key axis in contiguous blocks, so whatever order keys
arrive in IS the placement, for the whole batch. That is the wrong
shape for skewed workloads: the vmapped per-event closures run in
lockstep (a while-loop iterates until EVERY lane converges) and the
sparse capacity ladder re-dispatches whole padded programs per tier —
so one hot key's deep closure or escalation drags every light key
sharing its dispatch, while the devices holding only light keys idle
in the masked lanes. PR 9's ``JEPSEN_TPU_SEARCH_STATS`` telemetry
(per-key closure-iteration trajectories, load-factor peaks, per-key
escalation counts) was built as exactly the skew signal a scheduler
needs; this module is the consumer.

The executor dispatches each slot-window bucket in device-aligned
ROUNDS instead of one monolithic program:

  * :class:`KeyScheduler` keeps one pending-key queue per device,
    seeded with the same contiguous blocks the static key-axis
    sharding would pin (steal off = the static placement, round by
    round);
  * every round takes ``round_keys`` keys per device, so the round's
    sharded dispatch places each queue's keys on its own device;
  * when a round completes, the scheduler reads each key's observed
    cost — the search-stats block when armed, else the
    configs-stepped counter and the capacity tier the key actually
    needed (free on every sparse result) — updates a per-origin-cohort
    EWMA, and REBALANCES the pending queues: predicted-heavy keys
    (those whose origin device ran hot) migrate across the idle
    devices and into the SAME rounds, so a hot device's backlog
    drains in a few all-heavy rounds instead of poisoning every
    remaining round with one straggler lane. Keys are independent
    (jepsen.independent), so migration is pure re-bucketing — no
    state moves mid-search.

Results are bit-identical to the static path in every pinned field
(verdict, op/fail-event, max-frontier, capacity, configs-stepped,
dedupe): per-key overflow and closure work are placement-independent,
so scheduling changes wall-clock only. The parity suite
(tests/test_elastic.py) pins this across the packable families,
clean+corrupted, both dedupe strategies, packed+unpacked. Opt-in via
``check_batch(steal=True)`` / ``JEPSEN_TPU_STEAL=1`` until the
recorded A/B (tools/perf_ab.py steal arm) flips it — flags do not get
to claim speedups (docs/performance.md "Elastic scheduling").
"""

from __future__ import annotations

import logging
import math
from collections import deque
from typing import Optional

import numpy as np

from jepsen_tpu import envflags, obs
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine
from jepsen_tpu.parallel import planner
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)

DEFAULT_ROUND_KEYS = 1   # keys per device per round — small rounds
# give the scheduler more observation points; JEPSEN_TPU_STEAL_ROUND
# widens them when dispatch overhead dominates


def _resolve_round_keys(round_keys: int = 0) -> int:
    if round_keys and round_keys > 0:
        return int(round_keys)
    return envflags.env_int("JEPSEN_TPU_STEAL_ROUND",
                            default=DEFAULT_ROUND_KEYS, min_value=1,
                            what="keys per device per round")


def key_cost(r: dict, base_capacity: int) -> Optional[float]:
    """A key's observed search cost from its result dict — the
    scheduler's skew signal. Preference order: the search-stats block
    (closure-iteration total x the capacity each iteration's padded
    work scales with, times the escalation re-runs), else the
    configs-stepped counter plus the capacity-ladder tiers the key
    forced (both free on every sparse result). Returns None when the
    result carries no signal at all (a bitdense key with
    JEPSEN_TPU_SEARCH_STATS off) — the scheduler then leaves that
    cohort's prediction alone rather than fabricating one."""
    if not isinstance(r, dict):
        return None
    cap = r.get("capacity") or 0
    tiers = 0
    if cap and base_capacity:
        tiers = max(0, int(round(math.log2(
            max(1.0, cap / max(1, base_capacity))))))
    st = r.get("stats") or {}
    iters = st.get("closure-iters")
    if iters:
        return float((1 + tiers) * max(1, cap) * (sum(iters)
                                                  + len(iters)))
    stepped = r.get("configs-stepped")
    if stepped is not None:
        return float((1 + tiers) * max(1, cap) + stepped)
    if st.get("events"):
        return float((1 + tiers) * max(1, cap) + st["events"])
    return None


class KeyScheduler:
    """Per-device pending-key queues with skew-driven rebalancing
    (module docstring). ``idxs`` seed the queues in contiguous blocks
    — the static sharded key-axis placement — so ``steal=False`` is
    the static baseline with identical round structure."""

    def __init__(self, idxs, n_dev: int, round_keys: int = 1,
                 steal: bool = True, ewma: float = 0.5):
        self.n_dev = max(1, int(n_dev))
        self.round_keys = max(1, int(round_keys))
        self.steal = bool(steal)
        self.ewma = float(ewma)
        idxs = list(idxs)
        Q = -(-len(idxs) // self.n_dev) if idxs else 0
        self.queues = [deque(idxs[d * Q:(d + 1) * Q])
                       for d in range(self.n_dev)]
        # origin cohort: the device the static placement pinned the
        # key to. Cost predictions attach to the cohort (its keys
        # share provenance, the locality the stealer exploits), not
        # to wherever a steal later ran the key.
        self.cohort = {i: d for d, q in enumerate(self.queues)
                       for i in q}
        self.pred = [None] * self.n_dev    # per-cohort cost EWMA
        self.observed = [0.0] * self.n_dev  # per RUN device (busy acct)
        self.lf_peak = [None] * self.n_dev  # per RUN device max
        # visited-table load factor (search-stats armed only) — the
        # perf_ab evidence record's before/after spread
        self.steals = 0
        self.rounds = 0
        self.observed_keys = 0
        self._last = None   # [(idx, run_device)] of the in-flight round

    def pending(self) -> int:
        return sum(len(q) for q in self.queues)

    def next_round(self) -> Optional[list]:
        """The next round's placement — [(key_idx, device)] pairs,
        device-major, so device d's ``round_keys`` keys occupy the
        contiguous positions the sharded key axis places on device d.
        None when drained. The placement is also what a deferred
        :meth:`observe` (an executor with multiple rounds in flight)
        must hand back."""
        placement = []
        for d, q in enumerate(self.queues):
            for _ in range(self.round_keys):
                if q:
                    placement.append((q.popleft(), d))
        if not placement:
            return None
        self.rounds += 1
        self._last = placement
        return placement

    def observe(self, costs: dict, placement=None, lf=None) -> None:
        """Feed a completed round's per-key observed costs
        ({idx: cost|None}), update the cohort EWMAs, and rebalance
        the pending queues (no-op with ``steal=False``).
        ``placement`` defaults to the last round issued — executors
        that keep several rounds in flight pass the round's own
        placement back explicitly. ``lf``, when given, carries per-key
        visited-table load-factor peaks for the per-device evidence
        accounting."""
        if placement is None:
            placement = self._last
            self._last = None
        for i, d in placement or []:
            c = costs.get(i)
            if c is not None:
                self.observed[d] += c
                self.observed_keys += 1
                coh = self.cohort.get(i, d)
                # the planner's shared smoothing (planner.ewma_update):
                # the stealing scheduler's cohort predictions and the
                # JEPSEN_TPU_AUTO table cells decay identically
                self.pred[coh] = planner.ewma_update(
                    self.pred[coh], c, self.ewma)
                # the planner-relevant cost signal, visible on
                # /metrics per cohort (docs/observability.md)
                obs.gauge(obs.labeled("elastic.ewma_cost",
                                      cohort=str(coh))
                          ).set(self.pred[coh])
            v = None if lf is None else lf.get(i)
            if v is not None:
                cur = self.lf_peak[d]
                self.lf_peak[d] = v if cur is None else max(cur, v)
        if self.steal:
            self.rebalance()

    def rebalance(self) -> None:
        """Deal the pending keys back out by predicted cost,
        heaviest-first and round-major: similar-cost keys land in the
        SAME round spread across ALL devices, so a hot cohort's
        backlog migrates off its origin device and drains wide instead
        of straggling one lane per round. Deterministic: the sort is
        stable over the current queue order."""
        pending = [i for q in self.queues for i in q]
        if len(pending) <= 1:
            return
        known = [p for p in self.pred if p is not None]
        if not known:
            return   # nothing observed yet: keep the static placement
        fallback = sum(known) / len(known)

        def pred_of(i):
            p = self.pred[self.cohort.get(i, 0)]
            return fallback if p is None else p

        old_dev = {i: d for d, q in enumerate(self.queues) for i in q}
        order = sorted(pending, key=pred_of, reverse=True)
        new_queues = [deque() for _ in range(self.n_dev)]
        rk = self.round_keys
        moved = 0
        for j, i in enumerate(order):
            d = (j // rk) % self.n_dev
            new_queues[d].append(i)
            if old_dev[i] != d:
                moved += 1
        self.queues = new_queues
        if moved:
            self.steals += moved
            obs.counter("elastic.keys_stolen").inc(moved)
            # counter track (no-op with tracing off): the steal
            # trajectory lines up with the elastic.round spans
            obs.counter_sample("elastic.keys_stolen", self.steals)
            led = _ledger.active()
            if led is not None:
                led.record(
                    "steal", engine="elastic", moved=moved,
                    steals=self.steals, pending=len(pending),
                    devices=self.n_dev,
                    round_keys=self.round_keys)

    def stats(self) -> dict:
        """The scheduler's accounting for steal_stats / the bench
        advisory: per-device observed cost, busy fractions (cost
        relative to the hottest device — 1.0 everywhere means the
        mesh never idled), rounds, and keys stolen."""
        peak = max(self.observed) if self.observed else 0.0
        busy = [round(c / peak, 4) if peak else None
                for c in self.observed]
        mean = (sum(self.observed) / len(self.observed)
                if self.observed else 0.0)
        known_lf = [v for v in self.lf_peak if v is not None]
        lf_mean = sum(known_lf) / len(known_lf) if known_lf else 0.0
        return {"rounds": self.rounds, "steals": self.steals,
                "observed_keys": self.observed_keys,
                "per_device_cost": [round(c, 3) for c in self.observed],
                "per_device_busy": busy,
                "busy_frac": round(mean / peak, 4) if peak else None,
                "per_device_load_factor_peak": [
                    None if v is None else round(v, 6)
                    for v in self.lf_peak],
                "load_factor_spread": (round(max(known_lf) / lf_mean, 4)
                                       if lf_mean else None),
                "cohort_pred": [None if p is None else round(p, 3)
                                for p in self.pred]}


# ----------------------------------------------------------- executor


def check_batch_stealing(model, pre, capacity: int = 512,
                         max_capacity: int = 1 << 18, mesh=None,
                         bucket: Optional[str] = None,
                         dedupe: Optional[str] = None,
                         sparse_pallas: Optional[bool] = None,
                         search_stats: Optional[bool] = None,
                         config_pack: Optional[bool] = None,
                         reshard: Optional[bool] = None,
                         steal: bool = True, round_keys: int = 0,
                         stats: Optional[dict] = None) -> list:
    """check_batch_encoded with each bucket dispatched in
    device-aligned rounds under a :class:`KeyScheduler` (module
    docstring). ``steal=False`` keeps the static placement with the
    identical round structure — the honest A/B baseline the bench
    advisory and tools/perf_ab.py time against. ``stats``, when a
    dict, receives ``{"buckets": [{tier, engine, keys, ...scheduler
    accounting...}]}``. Results keep ``pre``'s order and match the
    static executors bit-for-bit on every pinned field."""
    bucket = engine._resolve_bucket(bucket)
    dedupe = engine._resolve_dedupe(dedupe)
    ss = engine._resolve_search_stats(search_stats)
    round_keys = _resolve_round_keys(round_keys)
    if stats is None:
        stats = {}
    stats.update({"n_keys": len(pre), "bucket": bucket,
                  "dedupe": dedupe, "steal": bool(steal),
                  "round_keys": round_keys, "buckets": []})
    if not pre:
        return []
    from jepsen_tpu.parallel import bitdense
    n_dev = 1 if mesh is None else int(np.asarray(mesh.devices).size)
    platform = (np.asarray(mesh.devices).flat[0].platform
                if mesh is not None else None)
    out: list = [None] * len(pre)
    buckets: dict = {}
    for i, e in enumerate(pre):
        buckets.setdefault(engine.bucket_key(e.n_slots, bucket),
                           []).append(i)
    with obs.span("elastic.check_batch", keys=len(pre),
                  devices=n_dev, steal=bool(steal)):
        for tier in sorted(buckets):
            idxs = buckets[tier]
            sub = [pre[i] for i in idxs]
            S_max = max(bitdense.n_states(e) for e in sub)
            C_max = max(e.n_slots for e in sub)
            is_dense = bitdense.fits_bitdense(S_max, C_max)
            sched = KeyScheduler(idxs, n_dev, round_keys, steal=steal)
            bstat = {"tier": tier, "keys": len(idxs),
                     "engine": "bitdense" if is_dense else "sparse"}
            stats["buckets"].append(bstat)
            if is_dense:
                _rounds_bitdense(model, pre, sched, out, mesh,
                                 S_max, C_max, sub, ss, capacity)
            else:
                _rounds_sparse(model, pre, sched, out, mesh, platform,
                               capacity, max_capacity, dedupe,
                               sparse_pallas, ss, config_pack,
                               reshard, sub)
            bstat.update(sched.stats())
    return out


def _rounds_bitdense(model, pre, sched: KeyScheduler, out, mesh,
                     S_max: int, C_max: int, sub, ss: bool,
                     capacity: int) -> None:
    """Bitdense bucket rounds: every round pads to the BUCKET's
    (S, C, R) dims (one jit shape per round size — the pipelined
    executor's chunking precedent). The dense engine carries no free
    cost counter, so the skew signal here is the search-stats block —
    with JEPSEN_TPU_SEARCH_STATS off the scheduler observes nothing
    and the rounds keep the static placement (documented; the sparse
    buckets, where the ladders live, self-signal)."""
    from jepsen_tpu.parallel import bitdense
    R_max = max(e.n_returns for e in sub)
    n_dev = 1 if mesh is None else int(np.asarray(mesh.devices).size)
    while True:
        placement = sched.next_round()
        if placement is None:
            break
        rnd = [i for i, _d in placement]
        encs = [pre[i] for i in rnd]
        # device-aligned like the sparse rounds: a ragged round would
        # REPLICATE every lane onto every device (place_batch shards
        # only divisible K) — pad lanes are duplicates, their results
        # dropped by the zip below
        if mesh is not None and len(encs) % n_dev:
            encs = encs + [encs[-1]] * (n_dev - len(encs) % n_dev)
        try:
            with obs.span("elastic.round", engine="bitdense",
                          keys=len(rnd), round=sched.rounds):
                pb = sup.dispatch(
                    "pipeline",
                    lambda encs=encs: bitdense.dispatch_batch_bitdense(
                        encs, mesh=mesh, min_states=S_max,
                        min_slots=max(5, C_max), min_returns=R_max,
                        search_stats=ss))
                rs = sup.dispatch("pipeline", pb.finalize)
        except sup.DISPATCH_FAILURES as err:
            _degrade_round(model, pre, rnd, out, err)
            sched.observe({}, placement)
            continue
        costs, lf = {}, {}
        for i, r in zip(rnd, rs):
            out[i] = r
            costs[i] = key_cost(r, capacity)
            lf[i] = (r.get("stats") or {}).get("load-factor-peak")
        sched.observe(costs, placement, lf=lf)


def _rounds_sparse(model, pre, sched: KeyScheduler, out, mesh,
                   platform, capacity: int, max_capacity: int,
                   dedupe: str, sparse_pallas, ss: bool, config_pack,
                   reshard, sub) -> None:
    """Sparse bucket rounds through the per-round capacity ladder
    (_round_sparse). Pad dims, the packed layout, and the probe limit
    are fixed ONCE per bucket so every round of a size shares one jit
    shape per capacity tier and every round reports the layout the
    whole bucket would."""
    pack_req = engine._resolve_config_pack(config_pack)
    C_pad = max(e.slot_f.shape[1] for e in sub)
    R_pad = max(e.n_returns for e in sub)
    pack = engine.pack_spec_for(sub, C_pad) if pack_req else ()
    probe_limit = engine._resolve_probe_limit(0)
    plat = platform
    if plat is None:
        import jax
        plat = jax.default_backend()
    while True:
        placement = sched.next_round()
        if placement is None:
            break
        rnd = [i for i, _d in placement]
        encs = [pre[i] for i in rnd]
        with obs.span("elastic.round", engine="sparse",
                      keys=len(rnd), round=sched.rounds):
            rs = _round_sparse(model, encs, capacity, max_capacity,
                               mesh, dedupe, probe_limit,
                               sparse_pallas, ss, pack, pack_req,
                               reshard, C_pad, R_pad, plat)
        costs, lf = {}, {}
        for i, r in zip(rnd, rs):
            out[i] = r
            costs[i] = key_cost(r, capacity)
            lf[i] = (r.get("stats") or {}).get("load-factor-peak")
        sched.observe(costs, placement, lf=lf)


def _degrade_round(model, pre, rnd, out, err) -> None:
    """A dead round degrades ONLY ITS KEYS to the host WGL path with
    structured resilience notes (the degradation contract,
    docs/resilience.md) — the scheduler keeps draining the rest."""
    from jepsen_tpu.resilience import recovery
    reason = f"{type(err).__name__}: {err}"
    obs.counter("elastic.rounds_degraded").inc()
    for i in rnd:
        out[i] = recovery.host_check_encoded(
            model, pre[i], getattr(err, "site", "pipeline"), reason)


def _round_sparse(model, encs, capacity: int, max_capacity: int,
                  mesh, dedupe: str, probe_limit: int, sparse_pallas,
                  ss: bool, pack, pack_req: bool, reshard,
                  C_pad: int, R_pad: int, platform: str) -> list:
    """One round through the sparse per-key capacity-tier ladder.

    CONTRACT TWIN of engine._check_batch_sparse — same supervised
    dispatch, same per-key overflow retry at doubled capacity, same
    degradation and escalation hand-offs — differing only in that the
    padded program dims (R_pad, C_pad) and the packed layout are the
    BUCKET's, passed in, rather than re-derived per dispatch (the
    scheduler's rounds must share jit shapes per tier, and every key
    must report the layout the whole bucket ran). A change to the
    ladder's retry/overflow contract must land in BOTH (test_elastic
    pins the parity)."""
    from time import perf_counter as _pc
    step_name = encs[0].step_name
    K = len(encs)
    n_dev = 1 if mesh is None else int(np.asarray(mesh.devices).size)
    out: list = [None] * K
    pending = list(range(K))
    N = max(64, capacity)
    n_tier = 0
    led = _ledger.active()
    while pending:
        encs_t = [encs[i] for i in pending]
        # keep every tier's dispatch DEVICE-ALIGNED: place_batch only
        # shards the key axis when K divides the mesh, and a
        # replicated retry runs every pending lane on every device —
        # n_dev times the CPU/flop work of the sharded form, which is
        # exactly the skew cost this executor exists to remove. Pad
        # lanes are duplicates of the last key; their results are
        # discarded by position.
        n_fill = 0
        if mesh is not None and len(encs_t) % n_dev:
            n_fill = n_dev - len(encs_t) % n_dev
            encs_t = encs_t + [encs_t[-1]] * n_fill
        mode, note = engine._resolve_sparse_pallas(
            sparse_pallas, N, C_pad, platform, dedupe, pack)
        t0 = _pc()
        try:
            with obs.span("engine.sparse_batch", keys=len(pending),
                          capacity=N, dedupe=dedupe):
                xs, state0 = sup.dispatch(
                    "transfer",
                    lambda encs_t=encs_t: enc_mod.pad_batch(
                        encs_t, mesh=mesh, min_slots=C_pad,
                        min_returns=R_pad)[:2],
                    backend=platform)

                def _search(xs=xs, state0=state0, N=N, mode=mode):
                    import jax
                    res = engine._run_program(
                        "engine.check_batch",
                        xs, state0, step_name, N, dedupe, probe_limit,
                        mode, ss, pack)
                    return jax.tree.map(np.asarray, res)

                res = sup.dispatch("search", _search, backend=platform)
                valid, fail_r, overflow, maxf, steps_n, stepped = \
                    res[:6]
        except sup.DISPATCH_FAILURES as err:
            from jepsen_tpu.resilience import recovery
            reason = f"{type(err).__name__}: {err}"
            for i in pending:
                out[i] = recovery.host_check_encoded(
                    model, encs[i], getattr(err, "site", "search"),
                    reason, backend=platform)
            break
        t1 = _pc()
        retry = []
        n_valid = n_invalid = 0
        tier_stats: list = []
        for j, i in enumerate(pending):
            if bool(overflow[j]):
                retry.append(i)
                continue
            e = encs[i]
            r = {"valid?": bool(valid[j]), "max-frontier": int(maxf[j]),
                 "capacity": N, "dedupe": dedupe,
                 "configs-stepped": int(stepped[j])}
            engine._tag_sparse_closure(r, mode, note)
            engine._tag_config_pack(r, pack, pack_req, C_pad)
            obs.counter("engine.configs_stepped").inc(int(stepped[j]))
            if ss:
                acc = engine.SearchStats(dedupe)
                acc.escalations = n_tier
                acc.add_chunk(_chunk_at(res[6], j), N)
                waste = 1.0 - ((e.n_returns * e.slot_f.shape[1])
                               / max(1, R_pad * C_pad))
                r["stats"] = engine._finish_search_stats(
                    acc, t0, t1,
                    extra={"pad-waste": round(waste, 6),
                           "pad-events": int(R_pad - e.n_returns),
                           "pad-slots": int(C_pad
                                            - e.slot_f.shape[1])})
            if not r["valid?"]:
                r.update(enc_mod.fail_op_fields(e, int(fail_r[j])))
            out[i] = r
            if r["valid?"]:
                n_valid += 1
            else:
                n_invalid += 1
            if r.get("stats"):
                tier_stats.append(r["stats"])
        if led is not None:
            # CONTRACT TWIN of engine._check_batch_sparse's dispatch
            # record — the advisor compares the two executors on the
            # `engine=` axis of the shape group
            led.record(
                "dispatch", engine="elastic",
                shape={"family": step_name, "N": N, "R": int(R_pad),
                       "C": int(C_pad), "tier": n_tier,
                       "pack": bool(pack)},
                strategy={"dedupe": dedupe, "closure": mode,
                          "pack": pack_req,
                          "probe_limit": probe_limit},
                secs=round(t1 - t0, 6), keys=len(pending),
                stats=_ledger.stats_digest(tier_stats),
                outcome={"valid": n_valid, "invalid": n_invalid,
                         "overflow": len(retry)})
        if not retry:
            break
        if N * 2 > max_capacity:
            for i in retry:
                out[i] = engine._escalate_overflow(
                    encs[i], N, mesh, dedupe=dedupe,
                    sparse_pallas=sparse_pallas, search_stats=ss,
                    config_pack=pack_req, reshard=reshard)
            break
        obs.counter("engine.overflow_redispatch").inc(len(retry))
        pending = retry
        N *= 2
        n_tier += 1
    return out


def _chunk_at(tree, j: int):
    import jax
    return jax.tree.map(lambda a: a[j], tree)


# --------------------------------------------- the recorded A/B shape


# Scanned-and-pinned seeds for the forced-skew CPU shape (the bench
# advisory, the perf_ab steal arm, and the wall-clock regression test
# all run the same shape so their numbers compare): heavy seeds are
# crash-riddled unordered-queue histories that each climb the capacity
# ladder 64 -> 256 with deep closures (2^crashed wildcard frontiers);
# light seeds stay at the base tier with shallow closures. All land in
# the SAME slot-window bucket (5-8 slots -> tier 8) and the queue
# model's multiset state space keeps the bucket on the sparse engine,
# where the ladder-and-lockstep skew the stealer attacks lives.
_SKEW_HEAVY_SEEDS = (1, 7, 11, 14, 18, 27, 47, 53)
_SKEW_LIGHT_SEEDS = (101, 102, 103, 104, 105, 106, 108, 111, 113, 116,
                     117, 118, 119, 120, 121, 122, 123, 129, 131, 132,
                     134, 135, 137, 138, 139, 142, 144, 147, 150, 151,
                     156, 157, 158, 159, 160, 161, 162, 163, 165, 168)
SKEW_CAPACITY = 32   # the ladder's base tier for the pinned shape


def forced_skew_histories(n_heavy: int = 8, n_light: int = 40,
                          n_ops: int = 32):
    """(model, histories) for the forced-skew shape, heavy keys FIRST
    — arrival order is the static placement, so the contiguous
    per-device queues pin every heavy key onto the first devices and
    each static round drags a heavy straggler lane."""
    from jepsen_tpu.histories import rand_queue_history
    from jepsen_tpu.models import UnorderedQueue
    if n_heavy > len(_SKEW_HEAVY_SEEDS) \
            or n_light > len(_SKEW_LIGHT_SEEDS):
        raise ValueError("forced_skew_histories: not enough pinned "
                         "seeds for the requested shape")
    hs = [rand_queue_history(n_ops=n_ops, n_processes=6, n_values=3,
                             crash_p=0.22, seed=s)
          for s in _SKEW_HEAVY_SEEDS[:n_heavy]]
    hs += [rand_queue_history(n_ops=n_ops, n_processes=6, n_values=3,
                              crash_p=0.0, seed=s)
           for s in _SKEW_LIGHT_SEEDS[:n_light]]
    return UnorderedQueue(), hs


STEAL_PIN = ("valid?", "op", "fail-event", "max-frontier", "capacity",
             "configs-stepped", "dedupe")


def steal_ab(model, pre, mesh, capacity: int = SKEW_CAPACITY,
             max_capacity: int = 1 << 16, warm: bool = True,
             **kw) -> dict:
    """The recorded steal A/B: the SAME round-based executor with the
    scheduler's rebalancing off (static placement) then on, verdict
    parity asserted — a stolen speedup that changed answers would be a
    bug report, not a result. Returns the dict the bench advisory and
    perf_ab emit: static/steal seconds, the win ratio, the
    scheduler's per-device busy/steal accounting for both arms, and
    the parity flag."""
    from time import perf_counter

    def arm(steal):
        st: dict = {}
        t0 = perf_counter()
        rs = check_batch_stealing(model, pre, capacity=capacity,
                                  max_capacity=max_capacity, mesh=mesh,
                                  steal=steal, stats=st, **kw)
        return perf_counter() - t0, rs, st

    if warm:
        arm(True)    # compiles every tier shape both arms will touch
    t_s, rs_s, st_s = arm(False)
    t_e, rs_e, st_e = arm(True)
    pin = lambda r: {k: r.get(k) for k in STEAL_PIN}  # noqa: E731
    parity = [pin(a) for a in rs_s] == [pin(b) for b in rs_e]
    assert parity, "steal A/B verdict mismatch — scheduling must " \
                   "never change results"
    return {"static_secs": round(t_s, 3), "steal_secs": round(t_e, 3),
            "steal_speedup": round(t_s / max(t_e, 1e-9), 3),
            "verdicts_identical": parity,
            "static": st_s["buckets"], "steal": st_e["buckets"]}
