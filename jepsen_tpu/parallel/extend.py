"""Incremental frontier extension: append ops to a checked history and
resume the device search from its settled prefix.

The batch engine assumes a complete history — every return event's
slot tables are fixed at encode time, which is exactly why
``engine.encode_batch`` refuses pre-encoded encs at a different width.
Streaming (ROADMAP item 1: check histories while the test is still
running) needs the opposite: per-key history *deltas* arrive over
time, and each delta's verdict must be **bit-identical to a one-shot
check of the current prefix** without re-searching what is already
settled.

Three facts make that possible:

  1. The scan carry after return event r depends only on rows
     ``[0, r]`` of the encoded event tables. If those rows are
     bit-identical between the old and the extended encode, a
     :class:`~jepsen_tpu.parallel.engine.FrontierCheckpoint` taken at
     r resumes the extended search exactly (``settled_events`` is the
     ground-truth array diff that certifies this).
  2. Appending ops can only change rows at or after the first return
     event that an as-yet-open call participates in: a completion can
     tighten an open observed-f op from wildcard to a concrete
     constraint, un-prune an open crashed-wildcard call (shifting slot
     assignment), or re-open the tail event with a new return.
     ``stable_events`` computes that immutable boundary from the raw
     op stream, so each scan leaves a checkpoint that the NEXT delta
     is guaranteed to be able to resume from.
  3. Linearizability is prefix-closed: an invalid prefix stays invalid
     under any extension, so early counterexamples are final verdicts.

The re-encode itself is host work (``prepare_encode``/``finish_encode``
— the same split the pipelined executor streams through, and
``EncodeCache`` makes repeats cheap); what extension saves is the
expensive part, the device search over the settled prefix.

:class:`HistorySession` is the per-key stateful wrapper;
:func:`extend_encoded` the functional core; :func:`advance_sessions`
batches shape-compatible sessions' pending scans into one device
program (``engine._check_device_batch_resumable``) — the cross-key
delta batching ``jepsen_tpu.serve`` dispatches.

Import-safe: importing this module must not touch a JAX backend (the
same contract as the other engine modules).
"""

from __future__ import annotations

import bisect
import logging
from time import perf_counter
from typing import Optional

import numpy as np

import jax

from jepsen_tpu import envflags, obs
from jepsen_tpu.history import TYPES, History
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine
from jepsen_tpu.parallel import planner as _planner
from jepsen_tpu.parallel.encode import EncodedHistory, EncodeError
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)

# Chunk scan lengths are padded up to a multiple of this quantum so a
# stream of arbitrary-sized deltas compiles a handful of jit shapes
# instead of one per delta length (pad events skip: run=False, and the
# event index does not advance on them — see engine._scan_step_factory).
# The quantum now lives in parallel.programs — the compile-economics
# layer generalized this ladder to the one-shot paths
# (JEPSEN_TPU_CANON_SHAPES) — and is re-exported here for its
# historical importers.
from jepsen_tpu.parallel.programs import EVENT_QUANTUM  # noqa: E402


class FrontierOverflowError(RuntimeError):
    """The frontier outgrew max_capacity mid-extension; carries the
    last checkpoint so callers can report the same structured
    ``{"valid?": "unknown"}`` the one-shot ladder does."""

    def __init__(self, checkpoint):
        super().__init__(f"frontier overflow at capacity "
                         f"{checkpoint.capacity}")
        self.checkpoint = checkpoint


# ------------------------------------------------------------ settling


def _pad_cols(a, C: int, fill):
    if a.shape[1] == C:
        return a
    out = np.full((a.shape[0], C), fill, a.dtype)
    out[:, : a.shape[1]] = a
    return out


def settled_events(old: Optional[EncodedHistory],
                   new: EncodedHistory) -> int:
    """Number of leading return events whose encoded rows are
    bit-identical between ``old`` and ``new`` — the ground truth for
    how far a checkpoint taken against ``old`` may resume a search
    over ``new``. Width growth is fine (extra columns are unoccupied);
    a changed model/state0 settles nothing."""
    if old is None or old.step_name != new.step_name \
            or old.state0 != new.state0:
        return 0
    R = min(old.n_returns, new.n_returns)
    if R == 0:
        return 0
    C = max(old.slot_f.shape[1], new.slot_f.shape[1])
    same = np.ones(R, bool)
    for attr, fill in (("slot_f", -1), ("slot_a0", -1), ("slot_a1", -1),
                       ("slot_wild", False), ("slot_occ", False)):
        a = _pad_cols(getattr(old, attr)[:R], C, fill)
        b = _pad_cols(getattr(new, attr)[:R], C, fill)
        same &= (a == b).all(axis=1)
    same &= old.ev_slot[:R] == new.ev_slot[:R]
    if same.all():
        return R
    return int(np.argmin(same))


def stable_events(ops, e: Optional[EncodedHistory]) -> int:
    """The immutable row boundary: the largest r such that rows
    ``[0, r)`` of the current encode can NEVER change under future
    appends. Future ops only complete currently-open invocations (or
    add new calls, whose rows are all past the current tail), and a
    completion can only perturb rows from the first return event after
    that invocation — so the boundary is the earliest such row over
    all still-open invocations. Checkpoints retained at or below it
    are guaranteed resumable by the next delta."""
    if e is None:
        return 0
    open_at: dict = {}
    for i, o in enumerate(ops):
        p = o.get("process")
        if not isinstance(p, int):
            continue
        t = o.get("type")
        if t == "invoke":
            open_at[p] = i
        elif t in ("ok", "fail", "info"):
            # every completion kind is final: ok/fail fix the packing,
            # info pins the call crashed forever
            open_at.pop(p, None)
    if not open_at:
        return e.n_returns
    completes = sorted(c.complete_index for c in e.calls if not c.crashed)
    return bisect.bisect_left(completes, min(open_at.values()))


def _restamp(cp, digest: str):
    """A checkpoint re-bound to an extended history whose settled
    prefix it certifiably covers (settled_events is the caller's
    proof) — same frontier, new identity."""
    return engine.FrontierCheckpoint(
        cp.event_index, cp.capacity, cp.step_name, digest,
        cp.st, cp.ml, cp.mh, cp.live, cp.ok, cp.fail_r, cp.maxf,
        cp.steps_n, cp.stepped)


def extend_encoded(model, old_e: Optional[EncodedHistory], ops,
                   new_ops, pad_slots: Optional[int] = None):
    """Functional core of extension: re-encode ``ops + new_ops``
    through the prepare/finish split and report how much of ``old_e``
    the new encode settles. Returns ``(new_e, n_settled)`` where
    rows ``[0, n_settled)`` are bit-identical to ``old_e``'s — a
    FrontierCheckpoint at or below ``n_settled`` (restamped to the new
    digest) resumes the extended search exactly. Raises EncodeError
    where ``encode`` would (host fallback)."""
    full = list(ops) + list(new_ops)
    prep = enc_mod.prepare_encode(model, History.wrap(full))
    new_e = enc_mod.finish_encode(prep, pad_slots)
    return new_e, settled_events(old_e, new_e)


# ------------------------------------------------------------ scanning


def _quantize(n: int) -> int:
    return max(EVENT_QUANTUM, -(-n // EVENT_QUANTUM) * EVENT_QUANTUM)


def _xs_slice(e: EncodedHistory, lo: int, hi: int, R_pad: int,
              C_pad: int) -> dict:
    """Event rows [lo, hi) as a (R_pad, C_pad) chunk; pad rows carry
    ev_slot=-1 / unoccupied slots, which the scan skips without
    advancing its event index."""
    n = hi - lo
    out = {}
    for attr, fill in (("slot_f", -1), ("slot_a0", -1), ("slot_a1", -1),
                       ("slot_wild", False), ("slot_occ", False)):
        a = getattr(e, attr)
        buf = np.full((R_pad, C_pad), fill, a.dtype)
        buf[:n, : a.shape[1]] = a[lo:hi]
        out[attr] = buf
    ev = np.full(R_pad, -1, np.int32)
    ev[:n] = e.ev_slot[lo:hi]
    out["ev_slot"] = ev
    return out


def _cp_from_carry(carry, cp, step_name: str, pack=(), C: int = 0):
    st, ml, mh, live, ok, fail_r, r_idx, maxf, steps_n, stepped = \
        engine.carry_fields_np(carry, pack, C)
    return engine.FrontierCheckpoint(
        int(r_idx), cp.capacity, step_name, cp.history_digest,
        st, ml, mh, live, bool(ok), int(fail_r), int(maxf),
        int(steps_n), int(stepped))


def _advance_cp(e: EncodedHistory, cp, target: int, *, dedupe: str,
                probe_limit: int, sparse_pallas, device, platform: str,
                max_capacity: int, C_pad: Optional[int] = None,
                stats_acc=None, config_pack: bool = False):
    """Advance ``cp`` over return events [cp.event_index, target) of
    ``e``, doubling capacity on overflow. Supervised like every device
    dispatch, with the resumable path's degradation ladder: one device
    retry (a recovered runtime resumes exactly where it stopped), then
    the failure re-raises with ``.checkpoint`` attached so the caller
    can degrade to the host from the same recovery point. Returns
    (cp2, mode, note, recovered_note).

    CONTRACT TWIN of engine.check_encoded_resumable's chunk loop —
    same retry/overflow/degradation semantics, differing only in the
    target-bounded quantum-padded chunks (vs checkpoint_every slices)
    and in degrading at the caller (HistorySession keeps the
    checkpoint live across deltas) instead of inline. A change to the
    retry or overflow contract must land in BOTH (test_checkpoint and
    test_serve pin each side)."""
    C = C_pad or e.slot_f.shape[1]
    ss = stats_acc is not None
    # the pack layout rides the CURRENT encode (a delta that grows the
    # slot window shifts the packed bit positions — safe, because the
    # checkpoints in hand are canonical-unpacked and re-pack here)
    pack = engine.pack_spec_for(e, C) if config_pack else ()
    mode, note = "off", None
    recovered = None
    while cp.event_index < target and cp.ok:
        lo = cp.event_index
        R_pad = _quantize(target - lo)
        mode, note = engine._resolve_sparse_pallas(
            sparse_pallas, cp.capacity, C, platform, dedupe, pack)

        def _chunk(lo=lo, cp=cp, mode=mode, R_pad=R_pad):
            import jax as _jax
            xs = engine._place(_xs_slice(e, lo, target, R_pad, C),
                               device)
            out = engine._run_program(
                "engine.check_resumable",
                xs, cp.carry(device, pack, C), e.step_name,
                cp.capacity, dedupe, probe_limit, mode, ss, pack)
            # materialize inside the supervised window (async dispatch
            # must fail or hang here, not at a later host read)
            if ss:
                carry, overflow, ys = out
                return ([np.asarray(x) for x in carry], bool(overflow),
                        _jax.tree.map(np.asarray, ys))
            carry, overflow = out
            return ([np.asarray(x) for x in carry], bool(overflow))

        try:
            res = sup.dispatch("search", _chunk, backend=platform)
        except sup.DISPATCH_FAILURES as err:
            # the checkpoint in hand is the recovery point: one device
            # retry first (a half-open breaker probe may have
            # readmitted a recovered runtime) ...
            try:
                obs.counter("resilience.retries").inc()
                with obs.span("resilience.device_resume",
                              event=cp.event_index):
                    res = sup.dispatch("search", _chunk,
                                       backend=platform)
                recovered = {
                    "degraded": "device-resume",
                    "site": getattr(err, "site", "search"),
                    "reason": f"{type(err).__name__}: {err}",
                    "resumed-from-event": cp.event_index}
            except sup.DISPATCH_FAILURES as err2:
                # ... then hand the checkpoint to the caller's
                # degradation contract (host resume keeps the verdict)
                err2.checkpoint = cp
                raise
        carry, overflow = res[0], res[1]
        if overflow:
            if cp.capacity * 2 > max_capacity:
                raise FrontierOverflowError(cp)
            obs.counter("engine.capacity_escalations").inc()
            cp = cp.grown(cp.capacity * 2)
            if ss:
                stats_acc.escalations += 1
            continue
        if ss:
            # only successful chunks: a re-run chunk's discarded
            # attempt must not double its events
            stats_acc.add_chunk(res[2], cp.capacity)
        cp = _cp_from_carry(carry, cp, e.step_name, pack, C)
    return cp, mode, note, recovered


# ------------------------------------------------------------- session


class HistorySession:
    """One key's streaming check state: the accumulated op stream, its
    current encode, and the frontier checkpoints that let each delta's
    verdict resume from the settled prefix.

    Contract (pinned by tests/test_serve.py): after any sequence of
    :meth:`extend` calls, :meth:`check` returns a result whose
    verdict, counterexample fields, max-frontier, and configs-stepped
    are identical to ``engine.check_encoded(encode(model, ops))`` over
    the same prefix with the same dedupe strategy — delta feeding is
    an optimization, never a semantics change. Invalid verdicts are
    early counterexamples and final (prefix closure).

    Not thread-safe; the serve layer serializes access per key.
    """

    def __init__(self, model, *, capacity: int = 1024,
                 max_capacity: int = 1 << 20,
                 dedupe: Optional[str] = None, probe_limit: int = 0,
                 sparse_pallas: Optional[bool] = None, device=None,
                 key=None, search_stats: Optional[bool] = None,
                 config_pack: Optional[bool] = None):
        self.model = model
        self.key = key
        self.ops: list = []
        self.enc: Optional[EncodedHistory] = None
        # with the planner armed (JEPSEN_TPU_AUTO), axes the caller
        # left None are plannable: the decision waits for the first
        # scan that has an encode (the plan is per padded shape) and
        # then pins for the session's lifetime — every delta and the
        # advance_sessions group key see ONE stable vector
        self._auto_axes: tuple = ()
        self._plan = None
        if _planner.active() is not None:
            self._auto_axes = tuple(
                ax for ax, v in (("dedupe", dedupe),
                                 ("pallas", sparse_pallas),
                                 ("pack", config_pack)) if v is None)
        self.dedupe = engine._resolve_dedupe(dedupe)
        self.probe_limit = engine._resolve_probe_limit(probe_limit)
        self.sparse_pallas = sparse_pallas
        self.search_stats = engine._resolve_search_stats(search_stats)
        # the packed-row REQUEST (JEPSEN_TPU_CONFIG_PACK); the layout
        # itself is re-derived per scan from the current encode, since
        # deltas can grow the slot window (checkpoints stay canonical)
        self.config_pack = engine._resolve_config_pack(config_pack)
        # lifetime device-search stats across every delta's legs
        # (JEPSEN_TPU_SEARCH_STATS); _leg_acc is the in-flight check's
        # accumulator, merged in at _finish. NOT persisted by
        # freeze/thaw — an evicted key's stats restart at thaw.
        self._stats_acc = (engine.SearchStats(self.dedupe)
                           if self.search_stats else None)
        self._leg_acc = None
        self._leg_t0 = None
        self.device = device
        self.capacity = max(64, capacity)
        self.max_capacity = max_capacity
        self.host_only: Optional[str] = None  # EncodeError text
        self.finalized = False
        self._cp = None          # the next scan's resume point
        self._cp_stable = None   # retained at the immutable boundary
        self._cp_tail = None     # retained at the last scanned event
        self._scan_cp = None     # in-flight cursor (advance_sessions)
        self._stable_ev = 0
        self._digest = None
        self._dirty = False
        self._last_result = None

    # -- introspection

    @property
    def n_ops(self) -> int:
        return len(self.ops)

    @property
    def n_returns(self) -> int:
        return 0 if self.enc is None else self.enc.n_returns

    @property
    def resume_event(self) -> int:
        """Where the next scan will resume (0 = from scratch)."""
        return self._cp.event_index if self._cp is not None else 0

    # -- extension

    def extend(self, new_ops) -> None:
        """Append a delta of invoke/ok/fail/info ops and re-encode.
        Host work only — the device scan runs at the next
        :meth:`check`/:func:`advance_sessions`. Raises ValueError on a
        malformed delta (the op stream must stay a well-formed
        history) BEFORE mutating any state."""
        if self.finalized:
            raise RuntimeError("session is finalized; no more deltas")
        new_ops = list(new_ops)
        for o in new_ops:
            t = o.get("type") if hasattr(o, "get") else None
            if t not in TYPES:
                raise ValueError(
                    f"delta op {o!r}: type must be one of {TYPES}")
        self.ops.extend(new_ops)
        self._dirty = True
        if self.host_only is not None:
            return  # once unpackable, always host-checked
        old_e = self.enc
        try:
            with obs.span("stream.encode", key=self.key,
                          ops=len(self.ops)):
                self.enc = enc_mod.encode(self.model,
                                          History.wrap(self.ops))
        except EncodeError as err:
            # same contract as engine.analysis: not device-checkable
            # degrades to the host WGL engine — and stays there (the
            # open-call window that overflowed is a historical fact)
            self.host_only = str(err)
            self.enc = None
            self._cp = self._cp_stable = self._cp_tail = None
            obs.counter("stream.host_only_keys").inc()
            return
        if self.enc.n_returns == 0:
            self._cp = self._cp_stable = self._cp_tail = None
            self._stable_ev = 0
            return
        settled = settled_events(old_e, self.enc)
        self._digest = engine.history_digest(self.enc)
        self._stable_ev = stable_events(self.ops, self.enc)
        best = None
        for cp in (self._cp_tail, self._cp_stable, self._cp):
            if cp is not None and cp.event_index <= settled \
                    and (best is None
                         or cp.event_index > best.event_index):
                best = cp
        if best is None:
            if self._cp_tail is not None or self._cp_stable is not None:
                # the delta perturbed rows below every retained
                # checkpoint (packing shifted wholesale — e.g. a
                # model whose prepared widths grew): rescan from
                # scratch, loudly countable, never wrong
                obs.counter("stream.rescans").inc()
            self._cp = None
        else:
            self._cp = _restamp(best, self._digest)
            obs.counter("stream.resumed_events").inc(best.event_index)
        self._cp_stable = self._cp_tail = None

    # -- checking

    def _fresh_cp(self):
        return engine.FrontierCheckpoint.fresh(self.enc, self.capacity,
                                               self._digest)

    def _host_check(self) -> dict:
        from jepsen_tpu.checker import wgl
        with obs.span("stream.host_check", key=self.key):
            r = wgl.analysis(self.model, History.wrap(self.ops))
        r["fallback"] = self.host_only
        self._last_result = dict(r)
        self._dirty = False
        return r

    def _result_from(self, cp, mode, note, resume_ev: int,
                     pack=None, pack_C: Optional[int] = None) -> dict:
        e = self.enc
        out = {"valid?": cp.ok and bool(np.asarray(cp.live).any()),
               "max-frontier": cp.maxf,
               "capacity": cp.capacity,
               "dedupe": self.dedupe,
               "configs-stepped": cp.stepped,
               "explored": cp.steps_n * cp.capacity * e.slot_f.shape[1],
               "stream": {"resumed-from-event": resume_ev,
                          "events": e.n_returns}}
        engine._tag_sparse_closure(out, mode, note)
        # tag the layout that actually RAN: the batched path passes its
        # group's union layout (over the group's padded width), which
        # can differ from this session's solo layout — a group with an
        # unpackable member runs unpacked, and the evidence trail must
        # say so. Solo scans (pack=None) re-derive their own.
        if pack is None:
            pack_C = e.slot_f.shape[1]
            pack = (engine.pack_spec_for(e, pack_C)
                    if self.config_pack else ())
        engine._tag_config_pack(out, pack, self.config_pack, pack_C)
        if self._plan is not None:
            out["plan"] = dict(self._plan)
        if not out["valid?"]:
            out.update(engine._fail_op(e, cp.fail_r))
        return out

    def _leg_stats(self):
        """The in-flight check's stats accumulator (created on first
        use so a batched advance's earlier legs and the solo fallback
        share one), or None with stats off."""
        if not self.search_stats:
            return None
        if self._leg_acc is None:
            from time import perf_counter
            self._leg_acc = engine.SearchStats(self.dedupe)
            self._leg_t0 = perf_counter()
        return self._leg_acc

    def _apply_plan(self) -> None:
        """One-shot strategy planning for this session
        (JEPSEN_TPU_AUTO): fill the axes the caller left None from
        the planner's decision table, keyed on the first encode's
        padded shape. Runs before the first scan — the stats
        accumulator and the advance_sessions group key both see the
        planned vector, and it stays pinned for the session's
        lifetime (a thawed key re-plans against the ADOPTING fleet's
        table, since thaw rebuilds the session from ops)."""
        if not self._auto_axes or self.enc is None:
            return
        pl = _planner.active()
        if pl is None:
            self._auto_axes = ()
            return
        req = {"dedupe": self.dedupe, "pallas": self.sparse_pallas,
               "pack": self.config_pack}
        for ax in self._auto_axes:
            req[ax] = None
        dec = pl.decide("stream", self.enc.step_name,
                        self.enc.slot_f.shape[1], req, keys=1)
        self._auto_axes = ()
        if dec is None:
            return
        chosen = dec["strategy"]
        if "dedupe" in chosen:
            self.dedupe = chosen["dedupe"]
            if self._stats_acc is not None:
                # no chunks accumulated yet — this runs before the
                # first scan, so swapping the strategy label is safe
                self._stats_acc = engine.SearchStats(self.dedupe)
        if "pallas" in chosen:
            self.sparse_pallas = chosen["pallas"]
        if "pack" in chosen:
            self.config_pack = chosen["pack"]
        self._plan = dec["plan"]

    def _finish(self, tcp, mode, note, resume_ev: int,
                recovered, pack=None,
                pack_C: Optional[int] = None) -> dict:
        """Bookkeeping shared by check() and advance_sessions() once
        the tail leg's carry is in hand."""
        resume_stepped = self._cp.stepped if self._cp is not None else 0
        obs.counter("engine.configs_stepped").inc(
            max(0, tcp.stepped - resume_stepped))
        self.capacity = max(self.capacity, tcp.capacity)
        self._cp = self._cp_stable or tcp
        r = self._result_from(tcp, mode, note, resume_ev, pack, pack_C)
        if recovered is not None:
            r["resilience"] = recovered
        if self._stats_acc is not None and self._leg_acc is not None:
            # registry + counter tracks get THIS check's leg only (a
            # stream republishing its lifetime totals every delta
            # would inflate every counter); the result block and the
            # run-dir record carry the LIFETIME stats — the leg is
            # SPLICED in at its resume event, superseding the stale
            # re-opened tail, so lifetime == a one-shot check of the
            # current prefix (parity-pinned). `jepsen report --search`
            # dedupes by key, newest record wins.
            leg_block = self._leg_acc.block()
            leg_block["engine"] = "stream"
            engine._publish_search_stats(leg_block)
            engine._emit_stats_tracks(leg_block, self._leg_t0,
                                      perf_counter())
            self._stats_acc.splice(resume_ev, self._leg_acc)
            block = self._stats_acc.block()
            block["engine"] = "stream"
            block["resumed-from-event"] = resume_ev
            rec = dict(block)
            if self.key is not None:
                rec["key"] = self.key
            obs.record_search_stats(rec)
            r["stats"] = block
            self._leg_acc = None
        led = _ledger.active()
        if led is not None:
            # CONTRACT TWIN of the one-shot engines' dispatch records:
            # engine="stream" is the serve fleet's device executor, so
            # the advisor can weigh the incremental scan against a
            # one-shot re-check on the same shape axis
            t0 = getattr(self, "_scan_t0", None)
            e = self.enc
            led.record(
                "dispatch", engine="stream",
                shape={"family": e.step_name, "N": tcp.capacity,
                       "R": e.n_returns, "C": e.slot_f.shape[1]},
                strategy={"dedupe": self.dedupe, "closure": mode,
                          "pack": self.config_pack,
                          "probe_limit": self.probe_limit,
                          "batched": pack is not None},
                secs=(round(perf_counter() - t0, 6)
                      if t0 is not None else None),
                keys=1,
                key=(str(self.key) if self.key is not None else None),
                resume=resume_ev,
                stats=(_ledger.stats_digest([r["stats"]])
                       if "stats" in r else None),
                outcome={"verdict": _ledger.verdict_class(r),
                         "degraded": recovered is not None})
        pl = _planner.active()
        scan_t0 = getattr(self, "_scan_t0", None)
        if pl is not None and scan_t0 is not None:
            # evidence on the REQUESTED arm, same convention as the
            # batch engines — the platform fallback inside the
            # closure resolution is identical for every arm
            e = self.enc
            pallas_req = (bool(self.sparse_pallas)
                          if self.sparse_pallas is not None
                          else envflags.env_bool(
                              "JEPSEN_TPU_SPARSE_PALLAS",
                              default=False))
            pl.observe("stream", e.step_name, e.slot_f.shape[1],
                       {"dedupe": self.dedupe, "pallas": pallas_req,
                        "pack": self.config_pack},
                       perf_counter() - scan_t0)
        self._last_result = dict(r)
        self._dirty = False
        return r

    def _overflow_result(self, err: FrontierOverflowError) -> dict:
        self._leg_acc = None   # no stats on an undecided check
        r = {"valid?": "unknown",
             "error": f"frontier overflow at capacity "
                      f"{err.checkpoint.capacity}",
             "capacity": err.checkpoint.capacity,
             "dedupe": self.dedupe,
             "checkpoint": err.checkpoint}
        self._last_result = dict(r)
        self._dirty = False
        return r

    def _degraded_result(self, err, cp, platform: str) -> dict:
        """The PR-6 degradation contract for a dead streamed dispatch:
        resume the remaining suffix on the host WGL engine from the
        checkpoint in hand — verdict preserved, device progress kept,
        structured ``resilience`` note attached."""
        from jepsen_tpu.resilience import recovery
        cp_at = getattr(err, "checkpoint", None) or cp
        self._leg_acc = None   # device stats end where the device died
        obs.counter("stream.degraded_checks").inc()
        r = recovery.host_resume(
            self.model, self.enc, cp_at, getattr(err, "site", "search"),
            f"{type(err).__name__}: {err}", backend=platform)
        # keep the device-side progress: the next delta retries the
        # device from this same checkpoint (the breaker's half-open
        # probe decides when that is allowed again)
        self._cp = cp_at
        self._last_result = dict(r)
        self._dirty = False
        return r

    def check(self, degrade: bool = True) -> dict:
        """The current prefix's verdict — bit-identical (verdict,
        op/fail-event, max-frontier, configs-stepped) to a one-shot
        ``engine.check_encoded`` of the same prefix. Scans only
        [resume_event, R); retains checkpoints at the immutable
        boundary and the tail so the next delta resumes as far forward
        as its content allows. ``degrade=False`` re-raises dispatch
        failures (with ``.checkpoint``) instead of host-resuming."""
        if self.host_only is not None:
            if not self._dirty and self._last_result is not None:
                return dict(self._last_result)
            return self._host_check()
        if self.enc is None or self.enc.n_returns == 0:
            r = {"valid?": True, "max-frontier": 0, "capacity": 0}
            self._last_result = dict(r)
            self._dirty = False
            return r
        if not self._dirty and self._last_result is not None:
            return dict(self._last_result)
        self._apply_plan()
        e = self.enc
        platform = getattr(self.device, "platform", None) \
            or jax.default_backend()
        cp = self._cp if self._cp is not None else self._fresh_cp()
        resume_ev = cp.event_index
        R = e.n_returns
        stable = max(self._stable_ev, cp.event_index)
        kw = dict(dedupe=self.dedupe, probe_limit=self.probe_limit,
                  sparse_pallas=self.sparse_pallas, device=self.device,
                  platform=platform, max_capacity=self.max_capacity,
                  stats_acc=self._leg_stats(),
                  config_pack=self.config_pack)
        recovered = None
        mode, note = "off", None
        self._scan_t0 = perf_counter()
        with obs.span("stream.check", key=self.key, returns=R,
                      resume=resume_ev):
            try:
                if cp.ok and cp.event_index < stable:
                    cp, mode, note, rec = _advance_cp(e, cp, stable,
                                                      **kw)
                    recovered = recovered or rec
                self._cp_stable = cp
                tcp = cp
                if tcp.ok and tcp.event_index < R:
                    tcp, mode, note, rec = _advance_cp(e, tcp, R, **kw)
                    recovered = recovered or rec
                self._cp_tail = tcp
            except FrontierOverflowError as err:
                return self._overflow_result(err)
            except sup.DISPATCH_FAILURES as err:
                if not degrade:
                    raise
                return self._degraded_result(err, cp, platform)
        return self._finish(tcp, mode, note, resume_ev, recovered)

    def finalize(self, final_paths: bool = True) -> dict:
        """Mark the stream complete and return the final verdict —
        identical to the one-shot check of the whole history. With
        ``final_paths``, an invalid verdict additionally gets the
        knossos-style counterexample extraction (the same
        ``apply_final_paths`` the analysis entry point runs)."""
        r = self.check()
        if final_paths and r.get("valid?") is False \
                and self.enc is not None and "final-paths" not in r:
            engine.apply_final_paths(r, self.model, self.enc)
            self._last_result = dict(r)
        self.finalized = True
        return r

    # -- eviction support (the serve layer's checkpoint store)

    def freeze(self, path: str) -> dict:
        """Persist the best resume checkpoint to ``path`` (.npz) and
        return the metadata the thaw needs. The op stream is NOT
        persisted here — the caller owns it (the serve layer's WAL is
        the durable op record)."""
        best = None
        for cp in (self._cp_tail, self._cp_stable, self._cp):
            if cp is not None and (best is None
                                   or cp.event_index > best.event_index):
                best = cp
        meta = {"n_ops": len(self.ops),
                "capacity": self.capacity,
                "host_only": self.host_only,
                "finalized": self.finalized,
                "checkpoint": None}
        if best is not None:
            meta["checkpoint"] = best.save(path)
            meta["event_index"] = best.event_index
            meta["digest"] = best.history_digest
        return meta

    def thaw(self, ops, cp) -> None:
        """Restore an evicted session: the full op stream (replayed
        from the WAL) plus the frozen checkpoint. The re-encode is
        deterministic, so the checkpoint's digest must match the
        re-encoded history's — a mismatch degrades to a from-scratch
        rescan (counted), never a stale frontier."""
        if self.ops:
            raise RuntimeError("thaw into a fresh session only")
        self.extend(ops)
        if cp is None or self.host_only is not None or self.enc is None:
            return
        if cp.history_digest == self._digest \
                and cp.step_name == self.enc.step_name \
                and cp.event_index <= self.enc.n_returns:
            self._cp = cp
            self.capacity = max(self.capacity, cp.capacity)
        else:
            obs.counter("stream.thaw_rescans").inc()
            _log.warning(
                "thawed checkpoint does not match the replayed "
                "history (digest/model drift) — rescanning key %r "
                "from scratch", self.key)

    # -- elastic migration (the serve layer's work-stealing)

    def migrate(self, device) -> None:
        """Re-place the session's device search onto ``device`` — the
        mid-stream half of elastic key work-stealing
        (JEPSEN_TPU_STEAL). The canonical host-side FrontierCheckpoint
        IS the migration primitive: every retained checkpoint stores
        unsharded numpy rows, so moving a streamed key between devices
        is pure re-placement — the next scan's ``cp.carry(device)``
        lands on the new device and resumes bit-identically, exactly
        as the freeze/thaw eviction path already proves. Keys are
        independent; no device state moves."""
        if device is self.device:
            return
        self.device = device
        obs.counter("stream.migrated_keys").inc()


# ----------------------------------------------- cross-key batching


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def _stack_carries(cps, K_pad: int, pack=(), C: int = 0):
    members = list(cps) + [cps[-1]] * (K_pad - len(cps))
    if pack:
        lanes = [engine.pack_rows_np(pack, C, c.st, c.ml, c.mh)
                 for c in members]
        row_stacks = tuple(
            np.stack([ln[i] for ln in lanes])
            for i in range(len(lanes[0])))
    else:
        row_stacks = (np.stack([c.st for c in members]),
                      np.stack([c.ml for c in members]),
                      np.stack([c.mh for c in members]))
    return row_stacks + (
        np.stack([c.live for c in members]),
        np.array([c.ok for c in members], bool),
        np.array([c.fail_r for c in members], np.int32),
        np.array([c.event_index for c in members], np.int32),
        np.array([c.maxf for c in members], np.int32),
        np.array([c.steps_n for c in members], np.int32),
        np.array([c.stepped for c in members], np.int32))


def _batch_leg(pairs, N: int, C_pad: int, dedupe: str,
               probe_limit: int, sparse_pallas, device,
               platform: str, search_stats: bool = False,
               pack: tuple = ()):
    """One batched scan leg: advance each (session, target) pair's
    in-flight cursor over its own rows in ONE device program. Returns
    (mode, note, overflowed_sessions); overflowed members keep their
    pre-leg cursor (their capacity retry runs individually). Under
    `search_stats`, each successful member's per-key stats rows feed
    its session's leg accumulator — batched legs report the same
    per-event telemetry solo scans do. `pack` is the GROUP's common
    layout (advance_sessions computes it once over every member, so
    all legs trace one layout and the result tag says exactly what
    ran); a member set that cannot share a 64-bit word runs the leg
    unpacked — representation never changes results, so
    solo-vs-batched parity holds either way."""
    R_pad = _quantize(max(t - s._scan_cp.event_index
                          for s, t in pairs))
    K = len(pairs)
    K_pad = _next_pow2(K)
    mode, note = engine._resolve_sparse_pallas(
        sparse_pallas, N, C_pad, platform, dedupe, pack)
    step_name = pairs[0][0].enc.step_name

    def _thunk():
        chunks = [_xs_slice(s.enc, s._scan_cp.event_index, t, R_pad,
                            C_pad) for s, t in pairs]
        chunks += [chunks[-1]] * (K_pad - K)   # shape filler, discarded
        xs = {k: np.stack([c[k] for c in chunks])
              for k in chunks[0]}
        carry0 = _stack_carries([s._scan_cp for s, _ in pairs], K_pad,
                                pack, C_pad)
        xs = engine._place(xs, device)
        # owned placement: the batched-resumable jit donates carry0
        carry0 = engine._place_owned(carry0, device)
        out = engine._run_program(
            "engine.check_batch_resumable",
            xs, carry0, step_name, N, dedupe, probe_limit, mode,
            search_stats, pack)
        if search_stats:
            carry, ovf, ys = out
            return ([np.asarray(x) for x in carry], np.asarray(ovf),
                    jax.tree.map(np.asarray, ys))
        carry, ovf = out
        return ([np.asarray(x) for x in carry], np.asarray(ovf))

    with obs.span("stream.batch_scan", keys=K, events=R_pad,
                  capacity=N):
        res = sup.dispatch("search", _thunk, backend=platform)
    carry, ovf = res[0], res[1]
    overflowed = []
    for k, (s, _t) in enumerate(pairs):
        if bool(ovf[k]):
            overflowed.append(s)
            continue
        if search_stats:
            s._leg_stats().add_chunk(
                jax.tree.map(lambda a, k=k: a[k], res[2]), N)
        st, ml, mh, live, ok, fail_r, r_idx, maxf, steps_n, stepped = \
            engine.carry_fields_np(
                tuple(a[k] for a in carry), pack, C_pad)
        s._scan_cp = engine.FrontierCheckpoint(
            int(r_idx), N, step_name,
            s._scan_cp.history_digest, st, ml, mh, live, bool(ok),
            int(fail_r), int(maxf), int(steps_n), int(stepped))
    return mode, note, overflowed


def advance_sessions(sessions, bucket: Optional[str] = None) -> list:
    """Run every session's pending scan, batching shape-compatible
    keys (same model step, capacity tier, slot-window bucket, and
    dedupe knobs) into one device program per leg — the serve layer's
    cross-key/tenant delta batching. Results are identical to calling
    ``session.check()`` one by one (the batched scan runs the same
    per-key rows from the same carries; padding is skipped work).
    Any per-key overflow or dispatch failure falls back to that
    session's individual path, which owns the capacity ladder and the
    degradation contract. Returns results in ``sessions`` order."""
    bucket = engine._resolve_bucket(bucket)
    results: dict = {}
    groups: dict = {}
    for s in sessions:
        if id(s) in results:
            continue
        if (s.host_only is not None or s.enc is None
                or s.enc.n_returns == 0
                or (not s._dirty and s._last_result is not None)):
            results[id(s)] = s.check()
            continue
        # the plan must land BEFORE the group key is computed: planned
        # sessions join batches on the vector that will actually run
        s._apply_plan()
        cp = s._cp if s._cp is not None else s._fresh_cp()
        s._scan_cp = cp
        s._scan_t0 = perf_counter()
        gk = (s.enc.step_name, cp.capacity,
              engine.bucket_key(s.enc.n_slots, bucket), s.dedupe,
              s.probe_limit, s.sparse_pallas, s.search_stats,
              s.config_pack, id(s.device))
        groups.setdefault(gk, []).append(s)

    for (step_name, N, tier, dedupe, probe_limit, sparse_pallas,
         search_stats, config_pack, _dev), members in groups.items():
        if len(members) == 1:
            s = members[0]
            results[id(s)] = s.check()
            continue
        device = members[0].device
        platform = getattr(device, "platform", None) \
            or jax.default_backend()
        C_pad = min(enc_mod.MAX_SLOTS,
                    max(tier, max(m.enc.slot_f.shape[1]
                                  for m in members)))
        # ONE union layout for the whole group, computed before any
        # leg: every leg traces the same representation and the
        # per-session result tag reports exactly what ran
        pack = (engine.pack_spec_for([m.enc for m in members], C_pad)
                if config_pack else ())
        obs.counter("stream.batched_keys").inc(len(members))
        live = list(members)

        def _fallback(ss):
            for s in ss:
                # resume from wherever the batched legs got it to
                s._cp = s._scan_cp
                results[id(s)] = s.check()

        try:
            for targets in ("stable", "tail"):
                pairs = []
                for s in live:
                    t = (max(s._stable_ev, s._scan_cp.event_index)
                         if targets == "stable" else s.enc.n_returns)
                    if s._scan_cp.ok and s._scan_cp.event_index < t:
                        pairs.append((s, t))
                if pairs:
                    mode, note, overflowed = _batch_leg(
                        pairs, N, C_pad, dedupe, probe_limit,
                        sparse_pallas, device, platform,
                        search_stats=search_stats, pack=pack)
                    if overflowed:
                        # the capacity ladder is per key: overflowed
                        # members leave the group and re-run solo
                        _fallback(overflowed)
                        live = [s for s in live
                                if id(s) not in results]
                if targets == "stable":
                    for s in live:
                        s._cp_stable = s._scan_cp
            for s in live:
                s._cp_tail = s._scan_cp
                resume_ev = (s._cp.event_index
                             if s._cp is not None else 0)
                mode_s, note_s = engine._resolve_sparse_pallas(
                    s.sparse_pallas, s._scan_cp.capacity,
                    s.enc.slot_f.shape[1], platform, s.dedupe, pack)
                results[id(s)] = s._finish(s._scan_cp, mode_s, note_s,
                                           resume_ev, None,
                                           pack=pack, pack_C=C_pad)
        except sup.DISPATCH_FAILURES:
            # a dead batched dispatch costs the batch nothing but the
            # batching: each member degrades through its own
            # contract (retry, then host resume from its checkpoint)
            _fallback([s for s in live if id(s) not in results])
    return [results[id(s)] for s in sessions]
