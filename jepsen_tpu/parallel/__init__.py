"""TPU execution tier: packed histories, jit'd model steps, and the
device-sharded linearizability search engine (the north star —
BASELINE.json: batched frontier expansion over (model-state,
linearized-op-bitset) configurations, vmap'd per chip, deduped over the
ICI mesh)."""
