"""Pipelined multi-key checking: overlap host encode, H2D transfer,
and device search.

PERF_R05's on-chip numbers showed the batched checker is no longer
search-bound: device-only throughput beat end-to-end by ~7%, and the
whole gap is the HOST phase — `check_batch` encoded every key serially
in Python before the first device dispatch, so the TPU sat idle
through the entire encode. This module restructures that into a
stream:

  1. **Bucket first.** Stage 1 of the encode (`encode.prepare_encode`:
     call packing + slot assignment — cheap, and where the bulk
     `spec.encode_calls` hook lives) runs for every key on a host
     worker pool. Its `n_slots`/`n_states` are exactly what the serial
     path's bucketing consumes, so the grouping (`engine.bucket_key`,
     tier or exact policy) matches `check_batch_encoded` bit for bit.
  2. **Stream buckets through a bounded double buffer.** Each bucket
     is split into near-equal chunks; a chunk's stage-2 encode
     (`encode.finish_encode`, the allocation-heavy snapshot fill) runs
     on the pool and its padded batch is placed + issued via
     `bitdense.dispatch_batch_bitdense` — JAX async dispatch returns
     immediately, so chunk k+1 encodes and transfers while chunk k's
     program runs on the device. At most `depth` programs are in
     flight; results are consumed (`finalize()`) oldest-first. Chunks
     pad to the BUCKET's (S, C, R) dims so the closure gating (pallas
     included) resolves as the whole bucket would and all chunks of a
     size share one jit shape (the near-equal split keeps a bucket to
     at most two chunk sizes). Sparse
     buckets (dims past the bitdense budget) run whole and
     synchronously through `engine._check_batch_sparse` — same ladder,
     same results; they are the rare tail, not the bench path.
  3. **Encode cache.** Encodings are memoized in a digest-keyed LRU
     (`EncodeCache`) so re-analysis of a stored history, bench
     warm/steady phases, and repeated checker passes stop re-paying
     the encode. The key is a content digest of (model, op stream) —
     mutate a history in place and the digest moves, so a stale hit is
     structurally impossible; the entry carries the ENCODED digest
     (`engine.history_digest`) as a cross-check for tests. Optional
     `store_dir` persistence spills entries to disk (pickle — load
     only from store dirs you wrote; the prepared spec's closures are
     rebuilt from the model on load, not persisted).

Results are bit-identical to serial `check_batch` — verdicts,
counterexample fields, engine/closure tags, and ordering — which the
parity suite (tests/test_pipeline.py) pins across every packable model
family. Opt-in via `check_batch(pipeline=True)` or
JEPSEN_TPU_PIPELINE=1 (validated accessor; flags do not get to claim
speedups until bench records the win — see docs/performance.md).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from time import perf_counter
from typing import Optional

from jepsen_tpu import envflags
from jepsen_tpu import models as model_ns
from jepsen_tpu import obs
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine
from jepsen_tpu.parallel import planner as _planner
from jepsen_tpu.parallel.encode import EncodedHistory
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)

DEFAULT_CACHE_ENTRIES = 256
DEFAULT_CHUNK_KEYS = 32


# ------------------------------------------------------------ cache key


def encode_cache_key(model, history, pad_slots: Optional[int] = None) -> str:
    """Content digest of (model, op stream, pad_slots) — the encode
    cache key. Hashes exactly what the encoder consumes: the model's
    identity and state (repr — stable for the dataclass model
    families) and every op's (process, type, f, value) in stream order
    (invoke/complete pairing is positional, so order IS part of the
    content). In-place mutation of a history therefore yields a new
    key: a stale hit after mutation is structurally impossible, which
    is the cache's invalidation contract (docs/performance.md).

    The contract rides on repr being content-complete, which holds for
    the EDN plain data op values are by framework contract (numbers,
    strings, lists, KV tuples, sets/maps). A custom value object with
    the default address-based repr would weaken it two ways: the key
    changes across processes (persisted entries degrade to misses —
    the safe direction) and an in-place mutation of the object's
    internals does NOT move the key (a stale hit — the unsafe one).
    Don't put such objects in op values; the encoder's Intern table
    would mis-handle them anyway."""
    h = hashlib.sha256()
    h.update(repr((type(model).__module__, type(model).__qualname__,
                   model, pad_slots)).encode())
    for o in history:
        h.update(repr((o.get("process"), o.get("type"), o.get("f"),
                       o.get("value"))).encode())
    return h.hexdigest()[:32]


# ---------------------------------------------------------- EncodeCache


_PERSIST_FIELDS = ("slot_f", "slot_a0", "slot_a1", "slot_wild",
                   "slot_occ", "ev_slot", "ret_call", "state0",
                   "step_name", "n_calls", "n_slots", "calls", "intern",
                   "state_lo", "n_states", "model_pruned")
_PERSIST_VERSION = 2
DEFAULT_CACHE_BYTES = 512 << 20   # in-memory array-byte budget


class EncodeCache:
    """Digest-keyed LRU of EncodedHistory, with optional store-dir
    persistence.

    Thread-safe (the pipeline's worker pool reads and writes it
    concurrently). `max_entries` bounds the in-memory LRU (default:
    JEPSEN_TPU_ENCODE_CACHE via the validated accessor, else
    DEFAULT_CACHE_ENTRIES; 0 disables the cache entirely) and
    `max_bytes` bounds its summed array payload (a 10k-op adversarial
    entry is tens of MB — 256 entries of those must not silently pin
    gigabytes; whichever bound trips first evicts). With `store_dir`,
    entries spill to pickle files and survive the process —
    re-analysis of a stored run re-pays zero encodes. Disk growth is
    deliberate and unbounded, the same posture as the run store: the
    directory is an artifact the operator owns and prunes. The
    prepared spec (history-dependent closures: gset lanes, queue
    widths) is NOT persisted; `get()` rebuilds it from the model +
    stored calls. That rebuild is only deterministic when the stored
    calls equal the list `prepare` originally saw — entries whose
    model-specific wildcard prune dropped calls AFTER prepare
    (EncodedHistory.model_pruned) are therefore kept in memory but
    never persisted, and loads are cross-checked against the stored
    state0/n_states. Pickles are only as trustworthy as whoever wrote
    them: point `store_dir` at directories this framework owns."""

    def __init__(self, max_entries: Optional[int] = None,
                 store_dir: Optional[str] = None,
                 max_bytes: int = DEFAULT_CACHE_BYTES):
        if max_entries is None:
            max_entries = envflags.env_int(
                "JEPSEN_TPU_ENCODE_CACHE",
                default=DEFAULT_CACHE_ENTRIES, min_value=0,
                what="encode-cache capacity")
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.store_dir = store_dir
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.disk_hits = 0
        self.misses = 0
        self.encodes = 0

    # -- accounting

    def counters(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "disk_hits": self.disk_hits,
                    "misses": self.misses, "encodes": self.encodes,
                    "entries": len(self._entries),
                    "bytes": self._bytes}

    def note_encode(self):
        """An encode was actually paid (cache miss path) — the counter
        the zero-re-encode assertions watch."""
        with self._lock:
            self.encodes += 1

    # -- core

    def get(self, key: str, model=None) -> Optional[EncodedHistory]:
        if self.max_entries == 0:
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return e
        e = self._load(key, model)
        if e is not None:
            with self._lock:
                self.disk_hits += 1
            self.put(key, e, persist=False)
            return e
        with self._lock:
            self.misses += 1
        return None

    @staticmethod
    def _entry_bytes(e: EncodedHistory) -> int:
        return sum(getattr(e, f).nbytes for f in
                   ("slot_f", "slot_a0", "slot_a1", "slot_wild",
                    "slot_occ", "ev_slot", "ret_call"))

    def put(self, key: str, e: EncodedHistory, persist: bool = True):
        if self.max_entries == 0:
            return
        nb = self._entry_bytes(e)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= self._entry_bytes(old)
            self._entries[key] = e
            self._bytes += nb
            while self._entries and (len(self._entries) > self.max_entries
                                     or self._bytes > self.max_bytes):
                if len(self._entries) == 1:
                    break  # always keep the newest entry, however big
                _, ev = self._entries.popitem(last=False)
                self._bytes -= self._entry_bytes(ev)
        if persist and self.store_dir is not None:
            self._save(key, e)

    # -- persistence

    def _path(self, key: str) -> str:
        return os.path.join(self.store_dir, f"enc_{key}.pkl")

    def _save(self, key: str, e: EncodedHistory):
        import pickle
        if e.model_pruned and e.spec is not None \
                and getattr(e.spec, "prepare", None) is not None:
            # the stored calls no longer equal the list prepare built
            # its lane tables from (the model-specific wildcard prune
            # ran AFTER prepare) — a disk reload's rebuilt spec could
            # assign different lanes and unpack device states wrongly.
            # Keep such entries in memory (they carry the original
            # spec object) but never on disk.
            return
        payload = {"version": _PERSIST_VERSION,
                   "fields": {f: getattr(e, f) for f in _PERSIST_FIELDS}}
        path = self._path(key)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)
        except Exception as err:  # noqa: BLE001 — persistence is an
            # optimization; a value that won't pickle (exotic op
            # payloads) must not fail the check. But say so: silence
            # would look like the store dir works when it doesn't.
            _log.warning(
                "encode cache: could not persist entry %s (%r) — "
                "in-memory cache unaffected", key, err)

    def _load(self, key: str, model) -> Optional[EncodedHistory]:
        if self.store_dir is None:
            return None
        import pickle
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                payload = pickle.load(f)
            if payload.get("version") != _PERSIST_VERSION:
                return None
            e = EncodedHistory(spec=None, **payload["fields"])
        except Exception as err:  # noqa: BLE001 — a corrupt/stale
            # entry degrades to a miss, loudly
            _log.warning(
                "encode cache: unreadable persisted entry %s (%r) — "
                "treating as a miss", key, err)
            return None
        # rebuild the prepared spec: its closures (gset lanes, queue
        # widths) are not persistable. prepare() is a deterministic
        # function of (model, calls), and _save refused any entry whose
        # stored calls differ from what prepare originally saw
        # (model_pruned), so the rebuild is faithful — the
        # state0/n_states cross-check below is defense in depth against
        # stale files written before that rule (or by other builds).
        if model is not None:
            try:
                spec = model_ns.pack_spec(model, e.intern)
                if spec is not None and spec.prepare is not None:
                    spec.prepare(e.calls, e.intern)
                if spec is not None:
                    rebuilt_n = (spec.n_states(e.intern) if spec.n_states
                                 else len(e.intern) + 1)
                    if spec.state0 != e.state0 or rebuilt_n != e.n_states:
                        return None   # lane/width drift: miss, re-encode
                e.spec = spec
            except Exception:  # noqa: BLE001 — a model that no longer
                # prepares against the stored calls means the entry is
                # for something else: miss, re-encode
                return None
        return e


_default_cache = None
_default_cache_lock = threading.Lock()


def default_cache() -> EncodeCache:
    """The process-wide encode cache the pipelined executor uses when
    the caller passes none (sized by JEPSEN_TPU_ENCODE_CACHE)."""
    global _default_cache
    if _default_cache is None:
        with _default_cache_lock:
            if _default_cache is None:
                _default_cache = EncodeCache()
    return _default_cache


# ------------------------------------------------------- worker stages


@dataclass
class _KeyInfo:
    """Per-key phase-1 outcome: a cache hit (enc) or a stage-1 encode
    (prep) awaiting its fill."""

    ckey: Optional[str]
    enc: Optional[EncodedHistory]
    prep: object
    secs: float
    hit: bool

    @property
    def n_slots(self) -> int:
        return (self.enc or self.prep).n_slots

    @property
    def n_states(self) -> int:
        return (self.enc or self.prep).n_states

    @property
    def n_returns(self) -> int:
        return (self.enc or self.prep).n_returns


def encode_cached(model, history, cache: Optional[EncodeCache] = None,
                  pad_slots: Optional[int] = None) -> EncodedHistory:
    """encode() through the cache: the single-key entry point for
    re-analysis paths (engine.analysis(encode_cache=...), stored-run
    re-checks) that want to stop re-paying the encode without going
    through the batch executor. None -> the process default cache."""
    if cache is None:
        cache = default_cache()
    if cache.max_entries == 0:
        # disabled (JEPSEN_TPU_ENCODE_CACHE=0) must cost nothing:
        # no O(history) digest, no lock, just the encode
        return enc_mod.encode(model, history, pad_slots=pad_slots)
    key = encode_cache_key(model, history, pad_slots)
    e = cache.get(key, model)
    if e is None:
        e = enc_mod.encode(model, history, pad_slots=pad_slots)
        cache.note_encode()
        cache.put(key, e)
    return e


def _lookup_or_prepare(model, h, cache: Optional[EncodeCache],
                       key: Optional[int] = None) -> _KeyInfo:
    # the timer runs on a pool thread; ctx_runner propagation in the
    # executor makes it nest under the pipeline.run root span. timer,
    # not span: the recorded span IS the prep_secs fed to
    # pipeline_stats, so the two can never disagree.
    e = prep = ckey = None
    with obs.timer("pipeline.prepare", key=key) as sp:
        if cache is not None:
            ckey = encode_cache_key(model, h)
            e = cache.get(ckey, model)
        if e is not None:
            sp.set(hit=True)
        else:
            prep = enc_mod.prepare_encode(model, h)
    return _KeyInfo(ckey, e, prep, sp.wall, e is not None)


def _fill(prep, cache: Optional[EncodeCache], ckey: Optional[str],
          key: Optional[int] = None):
    with obs.timer("pipeline.encode", key=key) as sp:
        e = enc_mod.finish_encode(prep)
    if cache is not None:
        cache.note_encode()
        cache.put(ckey, e)
    return e, sp.wall


def _chunks(idxs: list, chunk_keys: int, align: int = 1) -> list:
    """Split a bucket into chunks of <= ~chunk_keys keys.

    Meshless (align=1): near-equal sizes rather than greedy, because
    jit caches by shape — a greedy split of 84 keys at 32 compiles
    K=32 AND K=20 programs, the near-equal split compiles K=28 once.

    With a mesh (align = device count): every full chunk is a MULTIPLE
    of align, because place_batch only shards the key axis when K
    divides the mesh — un-aligned chunks would silently replicate
    every key to every device, ~device-count times the work on the
    executor whose whole point is speed. Only the final remainder
    chunk may be un-aligned (it replicates, exactly as a serial
    whole-bucket dispatch of that K would)."""
    n = len(idxs)
    if align > 1:
        ck = max(align, (max(1, chunk_keys) // align) * align)
        out = [idxs[p:p + ck] for p in range(0, n - n % ck, ck)]
        rem = idxs[n - n % ck:]
        r_aligned = len(rem) - len(rem) % align
        if r_aligned:
            out.append(rem[:r_aligned])   # still shards
        if len(rem) % align:
            out.append(rem[r_aligned:])   # tail replicates, as serial
            # dispatch of the same K would
        return out
    k = max(1, -(-n // max(1, chunk_keys)))  # ceil(n / chunk_keys)
    base, rem = divmod(n, k)
    out = []
    pos = 0
    for j in range(k):
        size = base + (1 if j < rem else 0)
        out.append(idxs[pos:pos + size])
        pos += size
    return out


# ------------------------------------------------------------ executor


def check_batch_pipelined(model, histories, capacity: int = 512,
                          max_capacity: int = 1 << 18, mesh=None,
                          bucket: Optional[str] = None, cache=None,
                          workers: Optional[int] = None,
                          chunk_keys: int = DEFAULT_CHUNK_KEYS,
                          depth: int = 2,
                          stats: Optional[dict] = None,
                          dedupe: Optional[str] = None,
                          sparse_pallas: Optional[bool] = None,
                          search_stats: Optional[bool] = None,
                          config_pack: Optional[bool] = None,
                          steal: Optional[bool] = None,
                          reshard: Optional[bool] = None,
                          steal_stats: Optional[dict] = None) -> list:
    """engine.check_batch with the three host/device phases overlapped
    (module docstring). Same arguments and bit-identical results;
    extras:

    cache       EncodeCache to consult/fill (None -> the process
                default; False -> no caching this call)
    workers     host pool width for the encode stages
    chunk_keys  target keys per dispatched chunk (the double buffer's
                granularity)
    depth       max device programs in flight before the oldest is
                consumed
    stats       optional dict, filled with the per-bucket
                encode/transfer/device split and cache counters —
                the numbers bench.py's multikey section reports
    dedupe      frontier dedupe strategy for sparse buckets
                (engine._resolve_dedupe; None = JEPSEN_TPU_DEDUPE) —
                recorded in stats so the bench lines can say which
                strategy was active
    sparse_pallas  route the sparse buckets' hash closure through the
                fused VMEM frontier kernel (engine.check_encoded's
                docstring; None = JEPSEN_TPU_SPARSE_PALLAS)
    search_stats  per-key device-computed search telemetry in the
                result "stats" dicts (engine._resolve_search_stats;
                None = JEPSEN_TPU_SEARCH_STATS)
    config_pack  packed configuration rows for the sparse buckets
                (engine.check_encoded's docstring; None =
                JEPSEN_TPU_CONFIG_PACK) — bitdense buckets are
                untouched (the dense bitmap has no row triple to pack)
    steal       skew-aware chunk scheduling (None = JEPSEN_TPU_STEAL;
                parallel.elastic): bitdense chunks compose through a
                KeyScheduler that rebalances pending keys from each
                drained chunk's observed costs (the bitdense cost
                signal is the search-stats block, so rebalancing is
                live when JEPSEN_TPU_SEARCH_STATS is armed), and the
                sparse tail runs the elastic round executor instead of
                one monolithic ladder. Results bit-identical; order
                of dispatch is the only thing that moves.
    reshard     device-recruiting escalation for overflow keys (None
                = JEPSEN_TPU_RESHARD; engine._escalate_overflow)
    steal_stats optional dict, filled with the schedulers' per-bucket
                steal/busy accounting
    """
    bucket = engine._resolve_bucket(bucket)
    if _planner.active() is None:
        dedupe = engine._resolve_dedupe(dedupe)
        dedupe_label = dedupe
    else:
        # fail-fast validation only: with the planner armed a raw
        # dedupe request flows through to the sparse tail so each
        # bucket plans its own arm per shape (_check_batch_sparse);
        # stats say "auto" rather than pretending the static default
        # ran — per-key results carry the actual chosen vector in
        # their "plan" block
        engine._resolve_dedupe(dedupe)
        dedupe_label = dedupe if dedupe is not None else "auto"
    search_stats = engine._resolve_search_stats(search_stats)
    steal = engine._resolve_steal(steal)
    if steal_stats is not None and not steal:
        # same loud contract as the serial path's guard: without the
        # scheduler the dict would stay silently empty while the
        # caller believes stealing was measured
        raise ValueError(
            "check_batch: steal_stats is an elastic-executor argument "
            "— pass steal=True (or set JEPSEN_TPU_STEAL=1) to use it")
    if stats is None:
        stats = {}
    K = len(histories)
    stats.update({"n_keys": K, "bucket": bucket,
                  "dedupe": dedupe_label, "buckets": []})
    if K == 0:
        return []
    if cache is None:
        cache = default_cache()
    elif cache is False:
        cache = None
    if cache is not None and cache.max_entries == 0:
        # JEPSEN_TPU_ENCODE_CACHE=0: a disabled cache must cost
        # nothing — without this, every key would still pay the
        # content digest (O(history) in the exact host hot path this
        # executor exists to shrink) just to hit a guaranteed miss
        cache = None
    c0 = cache.counters() if cache is not None else None

    from jepsen_tpu.parallel import bitdense

    root = obs.span("pipeline.run", keys=K, bucket=bucket,
                    dedupe=dedupe_label)
    with root, obs.maybe_jax_profile():
        out = _stream(model, histories, capacity, max_capacity, mesh,
                      bucket, cache, workers, chunk_keys, depth, stats,
                      dedupe, bitdense, sparse_pallas, search_stats,
                      config_pack, steal, reshard, steal_stats)
    if c0 is not None:
        c1 = cache.counters()
        stats["cache"] = {k: c1[k] - c0[k] for k in
                          ("hits", "disk_hits", "misses", "encodes")}
        stats["cache"]["entries"] = c1["entries"]
        # the SAME deltas feed the registry: the bench line's cache
        # block and the telemetry export read one measurement
        reg = obs.registry()
        for k in ("hits", "disk_hits", "misses", "encodes"):
            if stats["cache"][k]:
                reg.counter(f"pipeline.cache.{k}").inc(stats["cache"][k])
    return out


def _stream(model, histories, capacity, max_capacity, mesh, bucket,
            cache, workers, chunk_keys, depth, stats, dedupe,
            bitdense, sparse_pallas=None,
            search_stats: bool = False, config_pack=None,
            steal: bool = False, reshard=None,
            steal_stats: Optional[dict] = None) -> list:
    """The executor body (check_batch_pipelined's docstring), under the
    pipeline.run root span. Telemetry it feeds: pipeline.prepare /
    pipeline.encode spans on the pool threads (nested via ctx_runner),
    pipeline.dispatch / pipeline.finalize spans per chunk on the main
    thread, one synthetic device-track span per chunk's in-flight
    window (the "one track per device bucket" rows in the Chrome
    trace), the pipeline.inflight depth gauge, and the
    pipeline.keys/chunks counters — all from the same clock reads that
    fill the caller-visible `stats` dict."""
    K = len(histories)
    reg = obs.registry()
    reg.counter("pipeline.keys").inc(K)
    inflight = reg.gauge("pipeline.inflight")

    def _depth(n: int):
        """Gauge + counter-track sample from the SAME level read: the
        Perfetto inflight area chart and the registry gauge cannot
        disagree (counter_sample is a no-op with tracing off)."""
        inflight.set(n)
        obs.counter_sample("pipeline.inflight", n)

    wrap = obs.ctx_runner()

    t_wall = perf_counter()
    out: list = [None] * K
    n_workers = workers or min(8, max(2, os.cpu_count() or 2))
    with ThreadPoolExecutor(max_workers=min(n_workers, K)) as pool:
        # ---- phase 1: cache lookups + stage-1 encodes, in parallel.
        # n_slots/n_states land here, so the bucketing below consumes
        # exactly what the serial path's would.
        infos = list(pool.map(
            wrap(lambda ih: _lookup_or_prepare(model, ih[1], cache,
                                               key=ih[0])),
            enumerate(histories)))
        stats["prepare_secs"] = round(perf_counter() - t_wall, 4)

        buckets: dict = {}
        for i, info in enumerate(infos):
            buckets.setdefault(engine.bucket_key(info.n_slots, bucket),
                               []).append(i)

        # ---- phase 2: submit the stage-2 fills in processing order;
        # the pool chews through them while the main thread pads,
        # places, and dispatches earlier chunks — the overlap.
        order = [i for tier in sorted(buckets) for i in buckets[tier]]
        fills = {}
        for i in order:
            if infos[i].enc is None:
                fills[i] = pool.submit(wrap(_fill), infos[i].prep,
                                       cache, infos[i].ckey, i)

        def enc_of(i):
            info = infos[i]
            if info.enc is None:
                e, dt = fills[i].result()
                info.enc = e
                info.secs += dt
            return info.enc

        # ---- phase 3: stream buckets through the double buffer
        pending: deque = deque()
        bstats: list = []
        scheds: list = []   # (bstat, KeyScheduler) of stealing buckets

        def degrade_chunk(chunk_idxs, err, bstat):
            """A failed chunk degrades ONLY ITS KEYS to the host WGL
            path with a structured resilience note (the degradation
            contract, docs/resilience.md) — the rest of the batch
            keeps its device results instead of dying with the chunk."""
            from jepsen_tpu.resilience import recovery
            reason = f"{type(err).__name__}: {err}"
            reg.counter("pipeline.chunks_degraded").inc()
            site = getattr(err, "site", "pipeline")
            for i in chunk_idxs:
                out[i] = recovery.host_check_encoded(
                    model, enc_of(i), site, reason)
            bstat["degraded"] = bstat.get("degraded", 0) + len(chunk_idxs)

        def drain_one():
            (chunk_idxs, pb, bstat, chunk_no, t_issue, sched,
             placement) = pending.popleft()
            try:
                with obs.span("pipeline.finalize", tier=bstat["tier"],
                              chunk=chunk_no, keys=len(chunk_idxs)):
                    rs = sup.dispatch("pipeline", pb.finalize)
            except sup.DISPATCH_FAILURES as err:
                degrade_chunk(chunk_idxs, err, bstat)
                if sched is not None:
                    sched.observe({}, placement)
                _depth(len(pending))
                return
            _depth(len(pending))
            t_done = perf_counter()
            tr = obs.tracer()
            if tr is not None:
                # the chunk's whole in-flight window on a per-bucket
                # device track: issue -> results materialized. An
                # approximation of device occupancy (JAX async dispatch
                # hides the exact kernel window; the jax.profiler
                # capture has ground truth), but the right shape for
                # seeing overlap in Perfetto.
                tr.add_span("device.search", t_issue, t_done,
                            track=f"bucket-{bstat['tier']}",
                            chunk=chunk_no, keys=len(chunk_idxs),
                            engine=bstat["engine"])
            led = _ledger.active()
            if led is not None:
                # one record per drained chunk: the in-flight window
                # (same clock reads as the device.search span) plus
                # the pipeline-level strategy the bitdense record
                # cannot see (depth, steal, chunk sizing)
                led.record(
                    "dispatch", engine="pipeline",
                    shape={"family": enc_of(chunk_idxs[0]).step_name,
                           "tier": bstat["tier"]},
                    strategy={"engine": bstat["engine"],
                              "depth": depth,
                              "steal": sched is not None,
                              "chunk_keys": chunk_keys},
                    secs=round(t_done - t_issue, 6),
                    keys=len(chunk_idxs), chunk=chunk_no,
                    outcome={"valid": sum(1 for r in rs
                                          if r["valid?"] is True),
                             "invalid": sum(1 for r in rs
                                            if r["valid?"] is False)})
            bstat["transfer_secs"] += pb.transfer_secs
            bstat["device_wait_secs"] += pb.device_wait_secs
            for i, r in zip(chunk_idxs, rs):
                out[i] = r
            if sched is not None:
                # the stealer's observation point: the drained chunk's
                # per-key costs rebalance whatever is still queued.
                # With depth > 1 the feedback lags the in-flight
                # window — rounds already dispatched keep their
                # placement; only pending ones migrate.
                from jepsen_tpu.parallel import elastic
                costs = {i: elastic.key_cost(r, capacity)
                         for i, r in zip(chunk_idxs, rs)}
                lf = {i: (r.get("stats") or {}).get("load-factor-peak")
                      for i, r in zip(chunk_idxs, rs)}
                sched.observe(costs, placement, lf=lf)

        for tier in sorted(buckets):
            idxs = buckets[tier]
            S_max = max(infos[i].n_states for i in idxs)
            C_max = max(infos[i].n_slots for i in idxs)
            R_max = max(infos[i].n_returns for i in idxs)
            bstat = {"tier": tier, "keys": len(idxs), "chunks": 0,
                     "encode_secs": 0.0, "transfer_secs": 0.0,
                     "device_wait_secs": 0.0}
            bstats.append(bstat)
            if bitdense.fits_bitdense(S_max, C_max):
                bstat["engine"] = "bitdense"
                align = (1 if mesh is None
                         else int(mesh.shape[mesh.axis_names[0]]))
                sched = None
                if steal:
                    from jepsen_tpu.parallel import elastic
                    sched = elastic.KeyScheduler(
                        idxs, n_dev=align,
                        round_keys=max(1, max(1, chunk_keys)
                                       // max(1, align)))
                    bstat["steal"] = True
                    scheds.append((bstat, sched))

                def chunk_iter(idxs=idxs, sched=sched):
                    # lazy on purpose: with the scheduler active, the
                    # next round's composition must reflect every
                    # rebalance a drain_one ran since the last one
                    if sched is None:
                        for chunk in _chunks(idxs, chunk_keys,
                                             align=align):
                            yield chunk, None
                        return
                    while True:
                        placement = sched.next_round()
                        if placement is None:
                            return
                        yield [i for i, _d in placement], placement

                for chunk, placement in chunk_iter():
                    sub = [enc_of(i) for i in chunk]
                    if sched is not None and align > 1 \
                            and len(sub) % align:
                        # the static _chunks path guarantees aligned
                        # full chunks; scheduler rounds must too — a
                        # ragged chunk would replicate every lane onto
                        # every device. Pad lanes duplicate the last
                        # key; drain_one's zip drops their results.
                        sub = sub + [sub[-1]] * (align
                                                 - len(sub) % align)
                    # pad every chunk to the BUCKET's (S, C, R): the
                    # closure gating resolves as the whole bucket
                    # would (the parity tests rely on this) and every
                    # chunk shares one jit shape per chunk size — the
                    # R floor matters most, since per-chunk local
                    # maxima would otherwise make every chunk its own
                    # compile
                    t_issue = perf_counter()
                    try:
                        with obs.span("pipeline.dispatch", tier=tier,
                                      chunk=bstat["chunks"],
                                      keys=len(chunk)):
                            # site "pipeline" wraps the (itself
                            # supervised) bitdense dispatch so the
                            # fault matrix can target chunk dispatch
                            # specifically; the inner sites own the
                            # breaker bookkeeping
                            pb = sup.dispatch(
                                "pipeline",
                                lambda sub=sub: bitdense.
                                dispatch_batch_bitdense(
                                    sub, mesh=mesh, min_states=S_max,
                                    min_slots=max(5, C_max),
                                    min_returns=R_max,
                                    search_stats=search_stats))
                    except sup.DISPATCH_FAILURES as err:
                        degrade_chunk(chunk, err, bstat)
                        if sched is not None:
                            sched.observe({}, placement)
                        bstat["chunks"] += 1
                        reg.counter("pipeline.chunks").inc()
                        continue
                    pending.append((chunk, pb, bstat, bstat["chunks"],
                                    t_issue, sched, placement))
                    bstat["chunks"] += 1
                    reg.counter("pipeline.chunks").inc()
                    _depth(len(pending))
                    while len(pending) >= depth:
                        drain_one()
            elif steal:
                # sparse tail under the stealer: the elastic round
                # executor owns the ladder — device-aligned rounds,
                # observed-cost rebalancing, identical results
                # (parallel.elastic's parity contract)
                from jepsen_tpu.parallel import elastic
                bstat["engine"] = "sparse"
                bstat["steal"] = True
                est: dict = {}
                sub = [enc_of(i) for i in idxs]
                with obs.span("pipeline.sparse", tier=tier,
                              keys=len(idxs)):
                    rs = elastic.check_batch_stealing(
                        model, sub, capacity=capacity,
                        max_capacity=max_capacity, mesh=mesh,
                        bucket=bucket, dedupe=dedupe,
                        sparse_pallas=sparse_pallas,
                        search_stats=search_stats,
                        config_pack=config_pack, reshard=reshard,
                        stats=est)
                bstat["chunks"] = sum(b.get("rounds", 0)
                                      for b in est.get("buckets", []))
                reg.counter("pipeline.chunks").inc(
                    max(1, bstat["chunks"]))
                if steal_stats is not None:
                    steal_stats.setdefault("buckets", []).extend(
                        est.get("buckets", []))
                for i, r in zip(idxs, rs):
                    out[i] = r
            else:
                # sparse tail: the per-key capacity-retry ladder is
                # host-interactive, so it runs whole and synchronous —
                # identical results, no double buffering (it still
                # overlaps any earlier chunks left in flight)
                bstat["engine"] = "sparse"
                bstat["chunks"] = 1
                reg.counter("pipeline.chunks").inc()
                sub = [enc_of(i) for i in idxs]
                with obs.span("pipeline.sparse", tier=tier,
                              keys=len(idxs)):
                    rs = engine._check_batch_sparse(
                        model, sub, capacity, max_capacity, mesh,
                        dedupe=dedupe, sparse_pallas=sparse_pallas,
                        search_stats=search_stats,
                        config_pack=config_pack, reshard=reshard)
                for i, r in zip(idxs, rs):
                    out[i] = r
        while pending:
            drain_one()
        if steal_stats is not None:
            for bstat_s, sched_s in scheds:
                steal_stats.setdefault("buckets", []).append(
                    {"tier": bstat_s["tier"], "engine": "bitdense",
                     "keys": bstat_s["keys"], **sched_s.stats()})

        for bstat in bstats:
            bstat["encode_secs"] = round(sum(
                infos[i].secs for i in buckets[bstat["tier"]]), 4)
            bstat["transfer_secs"] = round(bstat["transfer_secs"], 4)
            bstat["device_wait_secs"] = round(
                bstat["device_wait_secs"], 4)
            # per-bucket split -> registry histograms: the telemetry
            # export reports the same numbers the stats dict carries
            for key in ("encode_secs", "transfer_secs",
                        "device_wait_secs"):
                reg.histogram(f"pipeline.{key}").observe(bstat[key])

    stats["buckets"] = bstats
    stats["e2e_secs"] = round(perf_counter() - t_wall, 4)
    return out
