"""The self-tuning strategy planner (``JEPSEN_TPU_AUTO``).

ROADMAP item 2's ONLINE half: the engine has ~6 orthogonal strategy
axes (dedupe sort|hash, pallas closure, config pack, pipeline, steal)
and peak speed used to require an operator who knows the whole flag
table. With ``JEPSEN_TPU_AUTO=1`` the engines ask this module, per
slot-window bucket, for the strategy vector to run — chosen from a
small per-shape decision table that is

  seeded    offline from ``bench_results/`` perf_ab JSONL joined with
            the decision ledger by the ``jepsen report --plan``
            advisor (``obs.advisor.build_plan`` — the advisor IS the
            seed loader),
  updated   online from the per-dispatch secs/shape/strategy evidence
            the engines already measure (EWMA per shape×strategy
            cell — the same smoothing ``elastic.KeyScheduler`` uses,
            via :func:`ewma_update`),
  explored  occasionally (every ``JEPSEN_TPU_AUTO_EXPLORE``-th
            decision per shape, default 8, 0 = off): the
            least-sampled non-chosen arm runs instead, so a table
            seeded on stale evidence self-corrects.

A cell below the sample floor (``JEPSEN_TPU_LEDGER_FLOOR``) never
decides: the static defaults run (source ``floor-default``) and the
dispatch merely contributes evidence. Wrong-plan recovery is free by
construction — a plan only routes between already-parity-pinned
paths (verdict/op/fail-event/max-frontier/configs-stepped identical
across every arm), so the planner can never produce a wrong verdict,
only a slower one, and the overflow/fallback/escalation machinery is
untouched.

Provenance: every planned result carries a ``"plan"`` block
({chosen vector, table cell evidence count, source:
seeded|online|floor-default, explored: bool}) which the serve
``/status`` rows surface; every decision mints a ``kind=plan``
decision-ledger record and an ``engine.plan.decisions`` counter.

Durability: the table persists as ``plan_table.json`` beside the
ledger segments (``obs.ledger.plan_table_path``), written atomically
(tmp + ``os.replace``). A truncated/garbage/stale-schema file
degrades to a re-seed (counted ``engine.plan.reseeds``) — never a
crash, never a wrong program. With the ledger off the table is
process-local memory only.

Flag off (unset/"0"): :func:`active` answers None, no file is
touched, no ``engine.plan.*`` metric is minted, and results / bench
lines / ``/status`` / WAL bytes are identical to the pre-planner
tree (parity-pinned by tests/test_planner.py).

Import-safe: no JAX, no engine imports — the ``/plan`` ops endpoint
and ``jepsen report --plan`` read this module on boxes whose device
runtime may be wedged.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

from jepsen_tpu import envflags
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.obs import metrics as _metrics

_log = logging.getLogger(__name__)

TABLE_VERSION = 1
DEFAULT_EXPLORE_EVERY = 8
#: the EWMA smoothing the elastic scheduler settled on — shared via
#: :func:`ewma_update` so the planner's cost cells and the stealing
#: scheduler's cohort predictions decay identically
EWMA_ALPHA = 0.5

#: the strategy axes a plan may set, and the env flag each falls back
#: to below the floor (the axis vocabulary of the dispatch records)
AXES = ("dedupe", "pallas", "pack", "pipeline", "steal")

#: default perf_ab evidence dir for seeding — the same default
#: ``jepsen report --plan`` resolves
DEFAULT_BENCH_DIR = "bench_results"


def ewma_update(prev: Optional[float], cost: float,
                alpha: float = EWMA_ALPHA) -> float:
    """One exponentially-weighted update: ``alpha`` weights the NEW
    observation (``elastic.KeyScheduler``'s convention). First
    observation (prev None) adopts the cost outright."""
    if prev is None:
        return float(cost)
    return alpha * float(cost) + (1.0 - alpha) * float(prev)


def auto_enabled() -> bool:
    """``JEPSEN_TPU_AUTO`` — strict tri-state (the envflags contract:
    unset/"0" off, "1" on, anything else raises loudly)."""
    return envflags.env_bool("JEPSEN_TPU_AUTO", default=False)


def resolve_explore_every(v: Optional[int] = None) -> int:
    """``JEPSEN_TPU_AUTO_EXPLORE``: run the least-sampled non-chosen
    arm every Nth decision per shape group (default 8); 0 disables
    exploration — the table then only ever sharpens what it has."""
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_AUTO_EXPLORE",
                            default=DEFAULT_EXPLORE_EVERY, min_value=0,
                            what="planner exploration period")


def group_key(engine: str, family: str, C: Optional[int] = None) -> str:
    """The decision-table row for a dispatch — the SAME key the
    advisor's ``_shape_group`` derives from ledger records, so seeded
    rows and live decisions land in one table."""
    parts = [f"engine={engine}", f"family={family}"]
    if C is not None:
        parts.append(f"C={int(C)}")
    return ",".join(parts)


def _static_default(axis: str):
    """The value an unplanned dispatch would run: the axis's env flag,
    else its measured-off default (the resolver precedent in
    ``engine._resolve_*`` — same flags, evaluated import-safely)."""
    if axis == "dedupe":
        return envflags.env_choice("JEPSEN_TPU_DEDUPE",
                                   ("sort", "hash"), default="sort",
                                   what="dedupe strategy")
    flag = {"pallas": "JEPSEN_TPU_SPARSE_PALLAS",
            "pack": "JEPSEN_TPU_CONFIG_PACK",
            "pipeline": "JEPSEN_TPU_PIPELINE",
            "steal": "JEPSEN_TPU_STEAL"}[axis]
    return envflags.env_bool(flag, default=False)


def _sanitize(arm: dict) -> dict:
    """Never a wrong program: the fused kernel requires the hash
    dedupe (``engine._resolve_sparse_pallas`` raises on the
    contradiction), so a plan may not combine pallas with sort."""
    if arm.get("pallas") and arm.get("dedupe", "hash") != "hash":
        arm = dict(arm)
        arm["pallas"] = False
    return arm


def _arm_from_detail(detail: dict) -> dict:
    """Map a ledger dispatch record's strategy dict (dedupe, closure
    mode, pack, probe_limit, depth ...) onto the planner's arm
    vocabulary; unknown axes are dropped, an unmappable record
    contributes nothing."""
    arm: dict = {}
    if isinstance(detail.get("dedupe"), str):
        arm["dedupe"] = detail["dedupe"]
    if "closure" in detail:
        arm["pallas"] = detail["closure"] not in (None, "off")
    if "pack" in detail:
        arm["pack"] = bool(detail["pack"])
    if "depth" in detail:
        arm["pipeline"] = True
    if "steal" in detail:
        arm["steal"] = bool(detail["steal"])
    return arm


def _fresh_cell(arm: dict) -> dict:
    return {"arm": dict(arm), "ewma": None, "n": 0, "n_live": 0,
            "seeded": False}


class Planner:
    """One process's decision table (module docstring for the
    lifecycle). Thread-safe: engine dispatch threads and the serve
    worker decide/observe concurrently."""

    def __init__(self, root: Optional[str],
                 explore_every: Optional[int] = None,
                 floor: Optional[int] = None,
                 bench_dir: Optional[str] = None):
        self.root = root
        self.explore_every = resolve_explore_every(explore_every)
        self.floor = _ledger.sample_floor(floor)
        self.bench_dir = (bench_dir if bench_dir is not None
                          else DEFAULT_BENCH_DIR)
        self._lock = threading.Lock()
        #: group -> {"decisions": int, "cells": {sig: cell}}
        self.table: Dict[str, dict] = {}
        self.seeded_groups = 0
        self._load_or_seed()

    # ------------------------------------------------- load and seed

    def _table_path(self) -> Optional[str]:
        if self.root is None:
            return None
        return _ledger.plan_table_path(self.root)

    def _load_or_seed(self) -> None:
        path = self._table_path()
        if path is not None and os.path.exists(path):
            try:
                with open(path) as fh:
                    doc = json.load(fh)
                if (isinstance(doc, dict)
                        and doc.get("version") == TABLE_VERSION
                        and isinstance(doc.get("groups"), dict)):
                    self.table = {
                        g: {"decisions": int(row.get("decisions", 0)),
                            "cells": {sig: dict(c) for sig, c
                                      in (row.get("cells")
                                          or {}).items()}}
                        for g, row in doc["groups"].items()}
                    return
                raise ValueError(
                    f"stale schema (version={doc.get('version')!r})"
                    if isinstance(doc, dict) else "not a table")
            except (OSError, ValueError) as err:
                # corrupt-file contract: degrade to a re-seed,
                # counted, never a crash — the table is derived
                # evidence, the ledger segments are the record
                _metrics.counter("engine.plan.reseeds").inc()
                _log.warning("planner: %s unreadable (%r) — "
                             "re-seeding", path, err)
        self._seed()
        payload = self._snapshot_locked()
        if payload is not None:
            self._write_table(payload)

    def _seed(self) -> None:
        """Seed the table from the advisor join of the ledger
        segments (when durable) and the perf_ab bench JSONL — the
        exact table ``jepsen report --plan`` renders, converted to
        live EWMA cells (source ``seeded``)."""
        from jepsen_tpu.obs import advisor
        led_records: List[dict] = []
        if self.root is not None:
            led_records, _corrupt = _ledger.read_records(self.root)
        bench = (advisor.load_bench_dir(self.bench_dir)
                 if self.bench_dir else [])
        if not led_records and not bench:
            return
        plan = advisor.build_plan(led_records, bench, floor=self.floor)
        for entry in plan.get("shapes") or []:
            cells: Dict[str, dict] = {}
            for row in entry.get("cells") or []:
                arm = _sanitize(_arm_from_detail(row.get("detail")
                                                 or {}))
                if not arm:
                    continue
                sig = _ledger.strategy_sig(arm)
                cell = cells.setdefault(sig, _fresh_cell(arm))
                # two ledger strategies can fold onto one arm (e.g.
                # differing probe_limit): merge their evidence
                cell["ewma"] = ewma_update(
                    cell["ewma"], row.get("mean_secs") or 0.0)
                cell["n"] += int(row.get("count") or 0)
                cell["seeded"] = True
            if cells:
                self.table[entry["shape"]] = {"decisions": 0,
                                              "cells": cells}
                self.seeded_groups += 1

    def _snapshot_locked(self) -> Optional[str]:
        """Serialize the table. Caller holds ``_lock`` (or is the
        single-threaded constructor); the bytes are written OUTSIDE
        the lock so file I/O never stalls other dispatchers."""
        if self._table_path() is None:
            return None
        return json.dumps({"version": TABLE_VERSION,
                           "floor": self.floor,
                           "groups": self.table}, sort_keys=True)

    def _write_table(self, payload: str) -> None:
        """Atomic durable write (tmp + ``os.replace``, the EncodeCache
        idiom). Failure costs durability, never the dispatch."""
        path = self._table_path()
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(payload + "\n")
            os.replace(tmp, path)
        except (OSError, ValueError) as err:
            _log.warning("planner: could not persist %s (%r)", path,
                         err)

    # ------------------------------------------------------ deciding

    def _compatible(self, cell: dict, fixed: dict) -> bool:
        arm = cell.get("arm") or {}
        return all(arm.get(k, v) == v for k, v in fixed.items())

    def decide(self, engine: str, family: str, C: Optional[int],
               requested: dict, keys: Optional[int] = None
               ) -> Optional[dict]:
        """Pick the strategy vector for one dispatch. ``requested``
        maps axis -> the caller's value (None = plannable; an
        explicit argument or pre-resolved value is never overridden).
        Returns ``{"strategy": {axis: value ...}, "plan": provenance}``
        for the plannable axes, or None when nothing was plannable.
        Mints the ``kind=plan`` ledger record + planner metrics."""
        plannable = sorted(k for k, v in requested.items() if v is None)
        if not plannable:
            return None
        fixed = {k: v for k, v in requested.items() if v is not None}
        group = group_key(engine, family, C)
        with self._lock:
            row = self.table.setdefault(group,
                                        {"decisions": 0, "cells": {}})
            row["decisions"] += 1
            cells = row["cells"]
            candidates = sorted(
                (sig for sig, c in cells.items()
                 if self._compatible(c, fixed)))
            eligible = [sig for sig in candidates
                        if cells[sig]["n"] >= self.floor
                        and cells[sig]["ewma"] is not None]
            static = dict(fixed)
            for axis in plannable:
                static[axis] = _static_default(axis)
            static = _sanitize(static)
            explored = False
            if not eligible:
                chosen_arm = static
                source = "floor-default"
            else:
                best = min(eligible,
                           key=lambda s: (cells[s]["ewma"], s))
                chosen_arm = dict(static)
                chosen_arm.update(cells[best]["arm"])
                chosen_arm = _sanitize(chosen_arm)
                source = ("online"
                          if cells[best]["n_live"] >= self.floor
                          else "seeded")
                if (self.explore_every
                        and row["decisions"] % self.explore_every == 0):
                    alt = self._explore_arm(cells, candidates, best,
                                            static, plannable, fixed)
                    if alt is not None:
                        chosen_arm = alt
                        explored = True
            chosen_sig = _ledger.strategy_sig(
                {k: chosen_arm[k] for k in chosen_arm
                 if k in AXES})
            cell_n = (cells[chosen_sig]["n"]
                      if chosen_sig in cells else 0)
            vector = {k: chosen_arm[k]
                      for k in sorted(set(plannable) | set(fixed))
                      if k in chosen_arm}
        prov = {"vector": vector, "cell_n": cell_n,
                "source": source, "explored": explored}
        _metrics.counter("engine.plan.decisions").inc()
        if explored:
            _metrics.counter("engine.plan.explorations").inc()
        shape = {"family": family}
        if C is not None:
            shape["C"] = int(C)
        _ledger.record("plan", engine=engine, shape=shape,
                       strategy=vector, source=source,
                       explored=explored, cell_n=cell_n, keys=keys)
        return {"strategy": {k: chosen_arm[k] for k in plannable
                             if k in chosen_arm},
                "plan": prov}

    def _explore_arm(self, cells: dict, candidates: List[str],
                     best: str, static: dict, plannable: List[str],
                     fixed: dict) -> Optional[dict]:
        """The exploration arm: among every known compatible arm, the
        static default, and the best arm with its dedupe flipped
        (when dedupe is plannable — the headline axis), pick the
        least-live-sampled one that is NOT the current best.
        Deterministic (count then sig order): tests can pin the
        cadence."""
        alts: Dict[str, dict] = {}
        for sig in candidates:
            if sig != best:
                alts[sig] = dict(static, **cells[sig]["arm"])
        alts.setdefault(_ledger.strategy_sig(static), dict(static))
        if "dedupe" in plannable:
            flipped = dict(static, **cells[best]["arm"])
            flipped["dedupe"] = ("sort"
                                 if flipped.get("dedupe") == "hash"
                                 else "hash")
            flipped = _sanitize(flipped)
            alts.setdefault(_ledger.strategy_sig(flipped), flipped)
        alts.pop(best, None)
        alts = {sig: _sanitize(arm) for sig, arm in alts.items()
                if all(_sanitize(arm).get(k, v) == v
                       for k, v in fixed.items())}
        if not alts:
            return None
        sig = min(alts, key=lambda s: (
            cells[s]["n_live"] if s in cells else 0, s))
        return alts[sig]

    # ----------------------------------------------------- observing

    def observe(self, engine: str, family: str, C: Optional[int],
                arm: dict, secs: float) -> None:
        """Fold one dispatch's measured wall secs into its
        shape×strategy cell — every dispatch contributes evidence,
        planned or not (the below-floor contract)."""
        if not isinstance(secs, (int, float)):
            return
        arm = _sanitize({k: v for k, v in arm.items() if k in AXES})
        if not arm:
            return
        group = group_key(engine, family, C)
        sig = _ledger.strategy_sig(arm)
        with self._lock:
            row = self.table.setdefault(group,
                                        {"decisions": 0, "cells": {}})
            cell = row["cells"].setdefault(sig, _fresh_cell(arm))
            cell["ewma"] = round(ewma_update(cell["ewma"], secs), 6)
            cell["n"] += 1
            cell["n_live"] += 1
            _metrics.gauge("engine.plan.table_cells").set(
                sum(len(r["cells"]) for r in self.table.values()))
            payload = self._snapshot_locked()
        if payload is not None:
            self._write_table(payload)

    # ----------------------------------------------------- rendering

    def table_doc(self) -> dict:
        """The ``/plan`` endpoint / ``report --plan`` live-table
        document — deterministic (sorted, rounded, no timestamps)."""
        with self._lock:
            groups = {}
            for g in sorted(self.table):
                row = self.table[g]
                groups[g] = {
                    "decisions": row["decisions"],
                    "cells": {
                        sig: {"ewma_secs": c["ewma"], "n": c["n"],
                              "n_live": c["n_live"],
                              "seeded": bool(c.get("seeded")),
                              "arm": c["arm"]}
                        for sig, c in sorted(row["cells"].items())}}
        return {"auto": {"enabled": True,
                         "dir": self.root,
                         "floor": self.floor,
                         "explore_every": self.explore_every,
                         "seeded_groups": self.seeded_groups},
                "groups": groups}


# ------------------------------------------------- process singleton

_active: Optional[Planner] = None
_resolved = False
_singleton_lock = threading.Lock()


def active() -> Optional[Planner]:
    """The process planner, or None when ``JEPSEN_TPU_AUTO`` is off.
    Resolved once per process (:func:`reset` re-resolves — tests). A
    malformed flag raises loudly at the first dispatch (the envflags
    contract); everything else degrades (module docstring)."""
    global _active, _resolved
    if _resolved:
        return _active
    with _singleton_lock:
        if _resolved:
            return _active
        if auto_enabled():
            _active = Planner(_ledger.resolve_ledger_dir())
        _resolved = True
    return _active


def reset() -> None:
    """Forget the process planner so the next :func:`active` re-reads
    the environment (tests)."""
    global _active, _resolved
    with _singleton_lock:
        _active = None
        _resolved = False


def load_table(root: str) -> Optional[dict]:
    """Read a durable ``plan_table.json`` without constructing a
    planner (the ``report --plan`` live-table view). None when
    absent/corrupt/stale — the reader shows nothing rather than
    guessing."""
    path = _ledger.plan_table_path(root)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not (isinstance(doc, dict)
            and doc.get("version") == TABLE_VERSION
            and isinstance(doc.get("groups"), dict)):
        return None
    return doc


def plan_doc() -> dict:
    """The ``/plan`` ops document. Planner off answers
    ``{"auto": {"enabled": False}, "groups": {}}`` — a valid, empty
    document (the /ledger posture)."""
    pl = active()
    if pl is None:
        return {"auto": {"enabled": False}, "groups": {}}
    return pl.table_doc()
