"""Mesh planning: one place that decides how a device set becomes a
sharded-engine topology.

Before this module, ``sharded.py`` made the topology call inline at
every entry point (2-D device array + the owner-routed exchange =
hierarchical DCN-aware routing; everything else flattens onto one
axis), and the mesh stopped at whatever ``jax.devices()`` returned —
a single host. :class:`MeshPlan` factors those decisions behind one
object so three consumers share them:

  * ``sharded.check_encoded_sharded`` (+ the resumable and elastic
    arms) — the flatten-vs-hierarchical decision, byte-identical to
    the historical inline logic;
  * the elastic re-shard ladder (``JEPSEN_TPU_RESHARD``) — the
    :meth:`ladder` rungs name which device slice each escalation
    recruits (wider 1-D within a slice first, then whole extra
    slices via the hierarchical exchange);
  * the multi-host seam — :meth:`host_slices` / :meth:`key_partition`
    describe how a pod-scale run splits devices and ``independent``
    keys across processes, and :func:`distributed_init` wires
    ``jax.distributed`` behind strict ``JEPSEN_TPU_DIST*`` flags so
    the DCN path has a tested seam (the two-process localhost CPU
    smoke in tests/test_meshplan.py) before a pod exists.

Import-safe: importing this module must not touch a JAX backend.
``jax.distributed.initialize`` runs only inside :func:`distributed_init`
and only when ``JEPSEN_TPU_DIST=1``.
"""

from __future__ import annotations

import logging
import zlib
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh

from jepsen_tpu import envflags

_log = logging.getLogger(__name__)

# the axis names the sharded engine's shard_map regions use; sharded.py
# re-exports them so existing imports keep working
AXIS = "frontier"
AX_SLICE, AX_CHIP = "slice", "chip"


class MeshPlan:
    """A device set plus the topology decision the sharded engine will
    run it with. ``devices`` is kept 2-D exactly when the plan is
    hierarchical (axis 0 = slices / DCN, axis 1 = chips / ICI); every
    other shape is flattened at construction, matching the historical
    inline logic in ``check_encoded_sharded``."""

    def __init__(self, devices, hierarchical: bool = False):
        devices = np.asarray(devices)
        if hierarchical:
            if devices.ndim != 2 or devices.shape[0] < 2 \
                    or devices.shape[1] < 2:
                raise ValueError(
                    "hierarchical MeshPlan needs a 2-D device array "
                    "with both dims > 1")
        else:
            devices = devices.reshape(-1)
        self.devices = devices
        self.hierarchical = bool(hierarchical)

    # -- construction -------------------------------------------------

    @classmethod
    def from_mesh(cls, mesh, exchange: str = "route") -> "MeshPlan":
        """The topology decision ``check_encoded_sharded`` historically
        made inline: a 2-D device array (both dims > 1) under the
        owner-routed exchange goes hierarchical; anything else — and
        always the all-gather A/B path — flattens."""
        devs = np.asarray(mesh.devices if isinstance(mesh, Mesh)
                          else mesh)
        hier = (exchange == "route" and devs.ndim == 2
                and devs.shape[0] > 1 and devs.shape[1] > 1)
        return cls(devs if hier else devs.reshape(-1), hier)

    @classmethod
    def auto(cls) -> "MeshPlan":
        """The process-global plan: every device jax can see — after
        the gated ``jax.distributed`` handshake, that is the whole
        pod's device set, not just this host's."""
        distributed_init()
        return cls(np.array(jax.devices()))

    # -- shape --------------------------------------------------------

    @property
    def n_dev(self) -> int:
        return int(self.devices.size)

    @property
    def n_slice(self) -> int:
        return int(self.devices.shape[0]) if self.hierarchical else 1

    @property
    def n_chip(self) -> int:
        return int(self.devices.shape[1] if self.hierarchical
                   else self.devices.size)

    @property
    def platform(self) -> str:
        return self.devices.flat[0].platform

    def mesh(self) -> Mesh:
        if self.hierarchical:
            return Mesh(self.devices, (AX_SLICE, AX_CHIP))
        return Mesh(self.devices.reshape(-1), (AXIS,))

    def __repr__(self) -> str:  # debugging/report aid
        shape = (f"{self.n_slice}x{self.n_chip}" if self.hierarchical
                 else str(self.n_dev))
        return (f"MeshPlan({shape} {self.platform}"
                f"{', hierarchical' if self.hierarchical else ''})")

    # -- multi-host seam ----------------------------------------------

    def host_slices(self) -> dict:
        """``{process_index: [device, ...]}`` — the per-host slice of
        the global device set. On a single host this is one entry;
        after ``distributed_init`` it is the pod layout the DCN path
        schedules over."""
        out: dict = {}
        for d in self.devices.flat:
            out.setdefault(int(getattr(d, "process_index", 0)),
                           []).append(d)
        return out

    def local_devices(self) -> list:
        """This process's slice of :meth:`host_slices`."""
        try:
            me = jax.process_index()
        except Exception:  # noqa: BLE001 — no backend yet: host 0
            me = 0
        return self.host_slices().get(int(me), [])

    @property
    def n_processes(self) -> int:
        return len(self.host_slices())

    @staticmethod
    def key_home(key, n_parts: int) -> int:
        """Deterministic key -> partition assignment (stable across
        processes and runs: crc32 of the key's repr — the same
        content-keyed posture as the encode cache)."""
        return zlib.crc32(repr(key).encode()) % max(1, int(n_parts))

    def key_partition(self, keys, n_parts: Optional[int] = None) -> dict:
        """Partition ``independent`` keys across hosts: every process
        computes the same ``{part: [key, ...]}`` map from the same key
        list, so a pod run needs no coordinator round to agree who
        checks what (jepsen.independent keys are independent — the
        partition is pure bucketing)."""
        n = self.n_processes if n_parts is None else int(n_parts)
        out = {p: [] for p in range(max(1, n))}
        for k in keys:
            out[self.key_home(k, max(1, n))].append(k)
        return out

    # -- the elastic re-shard ladder ----------------------------------

    def slice_plan(self, n: int) -> "MeshPlan":
        """A flat plan over the first ``n`` devices (row-major) — the
        1-D rungs of the re-shard ladder."""
        n = max(1, min(int(n), self.n_dev))
        return MeshPlan(self.devices.reshape(-1)[:n])

    def promoted(self, n_slice: int) -> "MeshPlan":
        """A hierarchical plan over the first ``n_slice`` slices of a
        2-D device array — the 2-D rungs of the ladder."""
        if not self.hierarchical:
            raise ValueError("promoted() needs a hierarchical plan")
        n_slice = max(2, min(int(n_slice), self.n_slice))
        return MeshPlan(self.devices[:n_slice, :], hierarchical=True)

    def ladder(self, start_devices: int = 1) -> list:
        """The re-shard escalation rungs, narrowest first: widen 1-D
        within the first slice (ICI) by doubling, then — when the plan
        is hierarchical — recruit whole extra slices via the DCN-aware
        2-D exchange (1-D -> wider 1-D, or promote to 2-D). The last
        rung is always the full plan; capacity growth past it falls
        back to table growth (the historical ladder)."""
        rungs = []
        n = max(1, min(int(start_devices), self.n_chip))
        while n < self.n_chip:
            rungs.append(self.slice_plan(n))
            n *= 2
        if self.hierarchical:
            rungs.append(self.slice_plan(self.n_chip))
            s = 2
            while s < self.n_slice:
                rungs.append(self.promoted(s))
                s *= 2
            rungs.append(self)
        else:
            rungs.append(self.slice_plan(self.n_chip))
        return rungs


# ---------------------------------------------------------------- DCN


_initialized = False


def distributed_enabled() -> bool:
    return bool(envflags.env_bool("JEPSEN_TPU_DIST", default=False))


def distributed_init() -> bool:
    """The gated ``jax.distributed`` handshake: a no-op (False) unless
    ``JEPSEN_TPU_DIST=1``; with the flag set, the three companion
    flags are REQUIRED and strictly validated — a half-configured pod
    plan must fail at the read site, not hang in a collective.
    Idempotent: the second call in a process is a no-op (True)."""
    global _initialized
    if not distributed_enabled():
        return False
    if _initialized:
        return True
    coord = envflags.env_raw("JEPSEN_TPU_DIST_COORD")
    nproc = envflags.env_int("JEPSEN_TPU_DIST_NPROC", min_value=1,
                             what="process count")
    proc = envflags.env_int("JEPSEN_TPU_DIST_PROC", min_value=0,
                            what="process id")
    missing = [n for n, v in (("JEPSEN_TPU_DIST_COORD", coord),
                              ("JEPSEN_TPU_DIST_NPROC", nproc),
                              ("JEPSEN_TPU_DIST_PROC", proc))
               if v is None]
    if missing:
        raise envflags.EnvFlagError(
            f"JEPSEN_TPU_DIST=1 needs {', '.join(missing)} set — a "
            f"half-configured distributed plan must not fall back to "
            f"a silent single-host run")
    if ":" not in coord or not coord.strip():
        raise envflags.EnvFlagError(
            f"JEPSEN_TPU_DIST_COORD={coord!r}: must be host:port")
    if proc >= nproc:
        raise envflags.EnvFlagError(
            f"JEPSEN_TPU_DIST_PROC={proc} out of range for "
            f"JEPSEN_TPU_DIST_NPROC={nproc}")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=proc)
    _initialized = True
    _log.info("jax.distributed initialized: process %d/%d via %s",
              proc, nproc, coord)
    return True
