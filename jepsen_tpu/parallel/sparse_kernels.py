"""Pallas TPU kernels for the sparse frontier engine's hash dedupe path.

SURVEY.md §7.1 step 4 names two kernels where XLA fuses poorly: bitset
ops (parallel.pallas_kernels — the r5 18.9x-54.4x bitdense win) and
the HASH PROBE. This module is the hash-probe one. Under
JEPSEN_TPU_DEDUPE=hash the sparse engine's per-event closure
(engine._hash_event_closure) is a fixpoint over small 1-D arrays —
frontier rows, the open-addressed visited set, N*(C+1) candidate
rows — and under plain XLA every closure iteration materialises the
candidate arrays in HBM and runs the probe/claim while_loop of
engine._hash_insert as a chain of tiny dispatches. Three kernels run
those loops inside `pallas_call`s, so the probe state is VMEM-resident
for its whole lifetime:

  * `frontier_closure_call` — one call per RETURN EVENT: seed insert,
    every delta-expansion iteration, every probe round, and the
    survivor append all happen in VMEM. Used by the single-device
    engine (`engine._scan_step_factory`). The kernel body is EXACTLY
    `engine._hash_event_closure` — the XLA path runs the same function
    on HBM-backed arrays — so the two implementations cannot diverge;
    interpret-mode CI pins them bit-identical anyway.
  * `hash_insert_call` — one call per CLOSURE ITERATION: the bounded
    linear probe, scatter-min claim arbitration, loser re-check loop,
    and fresh-row append of `engine._hash_insert_append`, fused. Used
    by the sharded engine, whose owner-routed all-to-all must run
    BETWEEN expansion and insert (a collective cannot live inside a
    pallas kernel), so only the insert side fuses there.
  * `tiled_insert_call` — the coverage kernel for shapes past the
    whole-event fusion gate: ONE visited-set transaction with the
    table partitioned into hash-range tiles that stream HBM<->VMEM
    through the pallas grid pipeline (double-buffered by construction:
    while tile t probes, tile t+1's DMA is in flight). Candidates
    stream in chunks against every tile; each candidate belongs to
    exactly one tile (its hash's low bits) and probes IN-REGISTER
    within that tile, so no probe run ever crosses a tile boundary.
    The engine keeps the rest of the closure (expansion, append) in
    XLA via engine._hash_event_closure's `insert` hook, so shapes past
    the fused gate no longer degrade wholesale to the XLA hash — they
    run `closure:"pallas-tiled"`.

VMEM budget math (`supported`/`insert_supported`), WIDTH-AWARE: a
configuration row is `lanes` uint32 lanes — 3 for the historical
(state, mask_lo, mask_hi) triple, 1-2 for the packed word of
JEPSEN_TPU_CONFIG_PACK (engine.pack_layout). The probe loop holds ~3
u32-sized live values per row LANE (the lane itself, its table read,
its claim-scatter temporary) plus ~3 lane-independent values (hash,
probe offset, pending/fresh flags): `bytes_per_row(lanes) = 12*lanes
+ 12` — 48 B for the unpacked triple (the historical accounting), 24
B at one packed lane, a ~2-3x gate win on top of the ~3-6x config
storage cut. Gated to bytes_per_row*(M + N) <= the VMEM budget
(JEPSEN_TPU_VMEM_BUDGET, default 4 MiB against the ~16 MB VMEM,
leaving the compiler generous headroom for double-buffering and
spills); shapes past this gate get the tiled kernel (tiled_plan picks
tile/chunk sizes that always fit), and only a budget too small to
tile at all falls back to the XLA hash closure with a note
(engine._resolve_sparse_pallas — the bitdense mesh-fallback
precedent).

Tile sizing: tiles are picked from the budget, floored well above the
probe horizon — PR 9's JEPSEN_TPU_SEARCH_STATS probe-length
histograms put p99 probe runs under 8 slots at the table's <=50% load
(the `jepsen report --search` worst-keys evidence), so a >=512-row
tile keeps per-tile load variance negligible and in-tile probe wrap
rare; the default plan uses budget/4 per side, thousands of rows.

Flag: JEPSEN_TPU_SPARSE_PALLAS, strict tri-state, default OFF until a
chip A/B records the win (tools/perf_ab.py's `hash-pallas` strategy
under PERF_AB_DEDUPE owns the flip decision — flags do not get to
claim speedups); "1" forces it on, in interpret mode off-TPU, like
JEPSEN_TPU_PALLAS. The scatter/cumsum spellings inside the probe loop
are interpret-verified on this image; their Mosaic lowerings are
UNMEASURED on a real chip (the same class of gap that produced the r5
jnp.flip / 4-D-reshape finds) — a forced-on run that hits a lowering
gap must surface the real error, which is why only the shape gate,
never a try/except, guards the forced path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jepsen_tpu import envflags

I32 = jnp.int32
U32 = jnp.uint32

# Default probe-state budget (bytes) the gates hold the kernels to —
# see the module docstring for the per-row accounting. Overridable per
# TPU generation via JEPSEN_TPU_VMEM_BUDGET (vmem_budget()).
VMEM_BUDGET = 4 << 20

# Floor for the env override: below ~64 KiB no tile/chunk plan is
# worth a kernel launch, and a typo'd tiny budget must fail loudly at
# the read site, not silently degrade every shape to the XLA hash.
VMEM_BUDGET_MIN = 1 << 16


def vmem_budget() -> int:
    """The active VMEM probe-state budget: JEPSEN_TPU_VMEM_BUDGET
    (validated, min VMEM_BUDGET_MIN) or the 4 MiB default — the one
    knob that re-gates every sparse kernel for a different TPU
    generation without a code edit."""
    return envflags.env_int("JEPSEN_TPU_VMEM_BUDGET",
                            default=VMEM_BUDGET,
                            min_value=VMEM_BUDGET_MIN,
                            what="VMEM budget (bytes)")


def bytes_per_row(lanes: int = 3) -> int:
    """Probe-state bytes per candidate row at `lanes` uint32 row
    lanes: ~3 live u32 per lane plus ~3 lane-independent temporaries
    (hash, offset, flags). lanes=3 (unpacked triple) reproduces the
    historical 48 B accounting exactly."""
    return 12 * lanes + 12


def insert_supported(M: int, N: int, lanes: int = 3) -> bool:
    """Can one fused insert of M candidate rows into an N-row frontier
    (table 2N, probe temporaries per bytes_per_row) stay inside the
    VMEM budget?"""
    return bytes_per_row(lanes) * (M + N) <= vmem_budget()


def supported(N: int, C: int, lanes: int = 3) -> bool:
    """Whole-event closure gate: the per-iteration candidate block is
    M = N*C rows."""
    return insert_supported(N * C, N, lanes)


# ------------------------------------------------------------- tiling


def _pow2_floor(n: int) -> int:
    return 1 << max(0, int(n).bit_length() - 1)


def tiled_plan(N: int, C: int, lanes: int = 3, budget: int = 0):
    """Tile/chunk sizes for the streamed visited-set transaction at
    frontier capacity N, or None when even tiling cannot fit the
    budget (pathologically small JEPSEN_TPU_VMEM_BUDGET — the caller
    then falls back to the XLA hash with a note).

    The table (T = next_pow2(2N) rows) splits into `tiles` hash-range
    sub-tables of `tile` rows; candidates stream in `chunk`-row
    blocks. Budget split: ~1/4 to the resident table tile, ~1/4 to
    the candidate block + its probe scratch, the rest headroom for
    the grid pipeline's double buffering (the in-flight next tile and
    chunk) — the same generous-headroom stance as the fused gate.
    Tiles are floored at 512 rows: PR 9's probe-length histograms put
    p99 probe runs under 8 slots at <=50% load, so 512+ keeps in-tile
    wrap and per-tile load variance negligible."""
    from jepsen_tpu.parallel.engine import _next_pow2
    b = budget or vmem_budget()
    T = _next_pow2(2 * N)
    tile_bytes = 4 * lanes + 4            # lane words + occupancy
    tile = min(T, _pow2_floor(max(1, (b // 4) // tile_bytes)))
    chunk = _pow2_floor(max(1, (b // 4) // (bytes_per_row(lanes) + 12)))
    if tile < 512 or chunk < 512:
        return None
    # only the two sizes the kernel consumes: tiled_insert_call
    # re-derives the tile count from the RUNTIME table shape, so a
    # plan can never disagree with the table it is applied to
    return {"tile": tile, "chunk": chunk}


def gate_coverage(n_states: int, state_lo: int, C: int, N: int) -> dict:
    """HOST-ONLY per-shape gate record — what WOULD run at frontier
    capacity N, per row layout, with no chip (and no tracing) needed:
    the evidence record tools/perf_ab.py ships so the chip flag-flip
    campaign inherits the sizing analysis (ISSUE 11). Schema pinned by
    tests/test_perf_ab.py."""
    from jepsen_tpu.parallel.engine import pack_lanes, pack_layout
    lay = pack_layout(n_states, state_lo, C)
    pack = lay if lay is not None else ()
    out = {"C": C, "capacity": N, "budget": vmem_budget(),
           "packable": bool(pack),
           "state_bits": pack[0] if pack else None,
           "packed_width_bits": (pack[0] + C) if pack else None,
           "would_run": {}, "bytes_per_row": {}}
    for name, lanes in (("unpacked", 3),
                        ("packed", pack_lanes(pack, C) if pack else None)):
        if lanes is None:
            out["would_run"][name] = None
            out["bytes_per_row"][name] = None
            continue
        out["bytes_per_row"][name] = bytes_per_row(lanes)
        if supported(N, C, lanes):
            out["would_run"][name] = "pallas"
        elif tiled_plan(N, C, lanes) is not None:
            out["would_run"][name] = "pallas-tiled"
        else:
            out["would_run"][name] = "xla-hash"
    return out


# ------------------------------------------------------------ kernels


def _lane_structs(rep, n: int):
    return [jax.ShapeDtypeStruct((n,), z.dtype) for z in rep.zeros(1)]


def frontier_closure_call(step_name: str, ev, rows, live, run,
                          N: int, C: int, probe_limit: int,
                          pack: tuple = (),
                          interpret: bool = False,
                          stats: bool = False):
    """Traceable (un-jitted) pallas invocation of one return event's
    whole delta-frontier closure — usable inside the engine's outer
    lax.scan, like pallas_kernels.closure_call. Inputs are the scan
    step's frontier row lanes ([N] per lane — the (pack, C) layout's
    count — plus the live mask), the event's slot tables ([C] rows of
    xs), and the run flag; returns (rows2, count, ovf, iters, stepped)
    exactly as engine._hash_event_closure does — because the kernel
    body IS that function, evaluated on VMEM-resident values. With
    `stats` (static; JEPSEN_TPU_SEARCH_STATS), two more outputs
    exactly as the shared closure returns them: the sort-equivalent
    work scalar and the probe-length histogram — the search-telemetry
    trajectory is computed INSIDE the kernel, not inferred from wall
    clocks."""
    from jepsen_tpu.parallel.engine import (N_PROBE_BUCKETS,
                                            _hash_event_closure,
                                            _next_pow2, _rep)
    from jepsen_tpu.parallel.steps import STEPS
    step = STEPS[step_name]
    rep = _rep(pack, C)
    L = rep.lanes
    step_cc = jax.vmap(
        jax.vmap(step, in_axes=(None, 0, 0, 0, 0)),  # over slots
        in_axes=(0, None, None, None, None),         # over configs
    )
    T = _next_pow2(2 * N)
    n_meta = 5 if stats else 4

    def kernel(*refs):
        f_ref, a0_ref, a1_ref, w_ref, occ_ref = refs[:5]
        row_refs = refs[5:5 + L]
        lv_ref, run_ref = refs[5 + L], refs[6 + L]
        orow_refs = refs[7 + L:7 + 2 * L]
        meta_ref = refs[7 + 2 * L]
        # bool masks travel as int32 (i1 vectors are the shaky corner
        # of Mosaic); reconstructed at the VMEM boundary
        ev_v = {"slot_f": f_ref[:], "slot_a0": a0_ref[:],
                "slot_a1": a1_ref[:], "slot_wild": w_ref[:] != 0,
                "slot_occ": occ_ref[:] != 0}
        out = _hash_event_closure(
            rep, step_cc, ev_v, tuple(r[:] for r in row_refs),
            lv_ref[:] != 0, run_ref[0] != 0, N, T, probe_limit,
            stats=stats)
        rows2, count, ovf, iters, stepped = out[:5]
        for oref, lane in zip(orow_refs, rows2):
            oref[:] = lane
        meta = [count.astype(I32), ovf.astype(I32),
                iters.astype(I32), stepped.astype(I32)]
        if stats:
            meta.append(out[5].astype(I32))   # swork
            refs[8 + 2 * L][:] = out[6].astype(I32)
        meta_ref[:] = jnp.stack(meta)

    out_shape = _lane_structs(rep, N) + [
        jax.ShapeDtypeStruct((n_meta,), I32)]
    if stats:
        out_shape.append(jax.ShapeDtypeStruct((N_PROBE_BUCKETS,), I32))
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(ev["slot_f"], ev["slot_a0"], ev["slot_a1"],
      ev["slot_wild"].astype(I32), ev["slot_occ"].astype(I32),
      *rows, live.astype(I32),
      jnp.reshape(run, (1,)).astype(I32))
    rows2 = tuple(outs[:L])
    meta = outs[L]
    base = (rows2, meta[0], meta[1] != 0, meta[2], meta[3])
    if stats:
        return base + (meta[4], outs[L + 1])
    return base


def hash_insert_call(c_rows, c_live, f_rows, count, table,
                     probe_limit: int, N: int, C: int,
                     pack: tuple = (),
                     interpret: bool = False):
    """Traceable pallas invocation of one fused visited-set
    transaction: engine._hash_insert_append (bounded probe +
    scatter-min claim + loser re-check + fresh-row append) with the
    candidate rows, the frontier tile, and the table VMEM-resident for
    the whole claim loop. Used per closure iteration by the sharded
    engine's per-device owned tables. `table` is the (rows, occ)
    pair; occupancy crosses the kernel boundary as int32 and comes
    back as bool, so the caller's while-carry dtype never changes.
    Returns (rows2, table2, count2, n_fresh, ovf) — the
    _hash_insert_append order."""
    from jepsen_tpu.parallel.engine import _hash_insert_append, _rep
    rep = _rep(pack, C)
    L = rep.lanes
    t_rows, t_occ = table
    T = t_rows[0].shape[0]

    def kernel(*refs):
        c_refs = refs[:L]
        clv_ref = refs[L]
        f_refs = refs[L + 1:2 * L + 1]
        cnt_ref = refs[2 * L + 1]
        tr_refs = refs[2 * L + 2:3 * L + 2]
        tocc_ref = refs[3 * L + 2]
        of_refs = refs[3 * L + 3:4 * L + 3]
        otr_refs = refs[4 * L + 3:5 * L + 3]
        otocc_ref = refs[5 * L + 3]
        meta_ref = refs[5 * L + 4]
        rows2, tbl2, count2, n_fresh, ovf = _hash_insert_append(
            tuple(r[:] for r in c_refs), clv_ref[:] != 0,
            tuple(r[:] for r in f_refs), cnt_ref[0],
            (tuple(r[:] for r in tr_refs), tocc_ref[:] != 0),
            probe_limit, N, rep)
        for oref, lane in zip(of_refs, rows2):
            oref[:] = lane
        for oref, lane in zip(otr_refs, tbl2[0]):
            oref[:] = lane
        otocc_ref[:] = tbl2[1].astype(I32)
        meta_ref[:] = jnp.stack([count2.astype(I32),
                                 n_fresh.astype(I32), ovf.astype(I32)])

    out_shape = tuple(
        _lane_structs(rep, N)
        + _lane_structs(rep, T)
        + [jax.ShapeDtypeStruct((T,), I32),
           jax.ShapeDtypeStruct((3,), I32)])
    outs = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        interpret=interpret,
    )(*c_rows, c_live.astype(I32), *f_rows,
      jnp.reshape(count, (1,)).astype(I32),
      *t_rows, t_occ.astype(I32))
    rows2 = tuple(outs[:L])
    tbl_rows2 = tuple(outs[L:2 * L])
    tocc2, meta = outs[2 * L], outs[2 * L + 1]
    return (rows2, (tbl_rows2, tocc2 != 0), meta[0], meta[1],
            meta[2] != 0)


def tiled_insert_call(c_rows, c_live, table, probe_limit: int,
                      plan: dict, pack: tuple, C: int,
                      interpret: bool = False):
    """One visited-set transaction with the table streamed HBM<->VMEM
    in hash-range tiles (module docstring). The grid is (tiles,
    chunks), tile-major: a table tile stays VMEM-resident while every
    candidate chunk streams past it (the pallas pipeline prefetches
    the next chunk — and, at tile boundaries, the next tile — while
    the current one probes: the double buffering is structural, not
    hand-rolled). A candidate's home tile is its hash's low bits; its
    within-tile probe starts at the hash's next bits and wraps INSIDE
    the tile, so membership stays exact (each config probes exactly
    one sub-table) and no probe run crosses a tile boundary.

    Returns (table2, fresh[M] bool, off[M] i32, probe_ovf scalar
    bool) — the probe half of engine._hash_insert_append; the caller
    (engine's tiled `insert` hook) runs the append in XLA."""
    from jepsen_tpu.parallel.engine import _hash_insert, _rep
    rep = _rep(pack, C)
    L = rep.lanes
    t_rows, t_occ = table
    T = t_rows[0].shape[0]
    n_tt = max(1, T // plan["tile"])
    TS = T // n_tt
    tt_bits = max(0, n_tt.bit_length() - 1)
    M = c_rows[0].shape[0]
    CH = min(plan["chunk"], 1 << max(0, (M - 1).bit_length()))
    M_pad = -(-M // CH) * CH
    n_cc = M_pad // CH

    h0 = rep.table_hash(c_rows)
    tile_of = (h0 & jnp.uint32(n_tt - 1)).astype(I32)
    start = ((h0 >> jnp.uint32(tt_bits)) & jnp.uint32(TS - 1))

    def padM(a, fill=0):
        return jnp.pad(a, (0, M_pad - M), constant_values=fill)

    c_rows_p = tuple(padM(r) for r in c_rows)
    c_live_p = padM(c_live.astype(I32))
    tile_p = padM(tile_of, -1)          # pads belong to no tile
    start_p = padM(start)

    def kernel(*refs):
        c_refs = refs[:L]
        lv_ref, tile_ref, st_ref = refs[L], refs[L + 1], refs[L + 2]
        tr_refs = refs[L + 3:2 * L + 3]
        tocc_ref = refs[2 * L + 3]
        otr_refs = refs[2 * L + 4:3 * L + 4]
        otocc_ref = refs[3 * L + 4]
        fresh_ref, off_ref, pend_ref = refs[3 * L + 5:3 * L + 8]
        t = pl.program_id(0)
        c = pl.program_id(1)

        # first chunk against this tile: bring the HBM tile into the
        # output ref, which stays resident across the chunk loop
        @pl.when(c == 0)
        def _init():
            for oref, iref in zip(otr_refs, tr_refs):
                oref[:] = iref[:]
            otocc_ref[:] = tocc_ref[:]

        mine = (lv_ref[:] != 0) & (tile_ref[:] == t)
        tile_rows = tuple(r[:] for r in otr_refs)
        tbl, fresh, p_ovf, off = _hash_insert(
            tuple(r[:] for r in c_refs), mine,
            (tile_rows, otocc_ref[:] != 0), probe_limit, rep,
            h0=st_ref[:])
        for oref, lane in zip(otr_refs, tbl[0]):
            oref[:] = lane
        otocc_ref[:] = tbl[1].astype(I32)
        fresh_ref[0, :] = fresh.astype(I32)
        off_ref[0, :] = jnp.where(mine, off, 0)
        pend_ref[0, :] = jnp.where(
            mine & ~fresh & (off >= probe_limit), 1, 0).astype(I32)

    lane_dt = [z.dtype for z in rep.zeros(1)]
    grid = (n_tt, n_cc)
    cand_spec = pl.BlockSpec((CH,), lambda t, c: (c,))
    tile_spec = pl.BlockSpec((TS,), lambda t, c: (t,))
    out_chunk_spec = pl.BlockSpec((1, CH), lambda t, c: (t, c))
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[cand_spec] * (L + 3) + [tile_spec] * (L + 1),
        out_specs=[tile_spec] * (L + 1) + [out_chunk_spec] * 3,
        out_shape=tuple(
            [jax.ShapeDtypeStruct((T,), dt) for dt in lane_dt]
            + [jax.ShapeDtypeStruct((T,), I32)]
            + [jax.ShapeDtypeStruct((n_tt, M_pad), I32)] * 3),
        interpret=interpret,
    )(*c_rows_p, c_live_p, tile_p, start_p, *t_rows,
      t_occ.astype(I32))
    tbl_rows2 = tuple(outs[:L])
    tocc2 = outs[L]
    fresh = jnp.any(outs[L + 1] != 0, axis=0)[:M]
    off = jnp.max(outs[L + 2], axis=0)[:M]
    pend = jnp.any(outs[L + 3] != 0)
    return (tbl_rows2, tocc2 != 0), fresh, off, pend
