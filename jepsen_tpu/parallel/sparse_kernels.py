"""Pallas TPU kernels for the sparse frontier engine's hash dedupe path.

SURVEY.md §7.1 step 4 names two kernels where XLA fuses poorly: bitset
ops (parallel.pallas_kernels — the r5 18.9x-54.4x bitdense win) and
the HASH PROBE. This module is the hash-probe one. Under
JEPSEN_TPU_DEDUPE=hash the sparse engine's per-event closure
(engine._hash_event_closure) is a fixpoint over small 1-D arrays —
frontier rows, the open-addressed visited set, N*(C+1) candidate
rows — and under plain XLA every closure iteration materialises the
candidate arrays in HBM and runs the probe/claim while_loop of
engine._hash_insert as a chain of tiny dispatches. Both kernels here
run those loops inside a single `pallas_call`, so the probe state is
VMEM-resident for its whole lifetime:

  * `frontier_closure_call` — one call per RETURN EVENT: seed insert,
    every delta-expansion iteration, every probe round, and the
    survivor append all happen in VMEM. Used by the single-device
    engine (`engine._scan_step_factory`). The kernel body is EXACTLY
    `engine._hash_event_closure` — the XLA path runs the same function
    on HBM-backed arrays — so the two implementations cannot diverge;
    interpret-mode CI pins them bit-identical anyway.
  * `hash_insert_call` — one call per CLOSURE ITERATION: the bounded
    linear probe, scatter-min claim arbitration, loser re-check loop,
    and fresh-row append of `engine._hash_insert_append`, fused. Used
    by the sharded engine, whose owner-routed all-to-all must run
    BETWEEN expansion and insert (a collective cannot live inside a
    pallas kernel), so only the insert side fuses there.

VMEM budget math (`supported`/`insert_supported`): the probe loop
holds ~12 u32-sized live values per candidate row (the row triple, its
hash, probe offset, pending/fresh flags, slot/occupancy temporaries)
— 48 bytes per row — plus the 16-byte frontier rows and the 16*T
(= 32N) table. Gated to 48*(M + N) <= 4 MiB against the ~16 MB VMEM,
leaving the compiler generous headroom for double-buffering and
spills; shapes past the gate fall back to the XLA hash closure with a
note (engine._resolve_sparse_pallas — the bitdense mesh-fallback
precedent).

Flag: JEPSEN_TPU_SPARSE_PALLAS, strict tri-state, default OFF until a
chip A/B records the win (tools/perf_ab.py's `hash-pallas` strategy
under PERF_AB_DEDUPE owns the flip decision — flags do not get to
claim speedups); "1" forces it on, in interpret mode off-TPU, like
JEPSEN_TPU_PALLAS. The scatter/cumsum spellings inside the probe loop
are interpret-verified on this image; their Mosaic lowerings are
UNMEASURED on a real chip (the same class of gap that produced the r5
jnp.flip / 4-D-reshape finds) — a forced-on run that hits a lowering
gap must surface the real error, which is why only the shape gate,
never a try/except, guards the forced path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

I32 = jnp.int32
U32 = jnp.uint32

# Probe-state budget (bytes) the gate holds the kernels to — see the
# module docstring for the per-row accounting behind the 48.
VMEM_BUDGET = 4 << 20


def insert_supported(M: int, N: int) -> bool:
    """Can one fused insert of M candidate rows into an N-row frontier
    (table 2N, probe temporaries ~12 u32 per candidate) stay inside
    the VMEM budget?"""
    return 48 * (M + N) <= VMEM_BUDGET


def supported(N: int, C: int) -> bool:
    """Whole-event closure gate: the per-iteration candidate block is
    M = N*C rows."""
    return insert_supported(N * C, N)


def frontier_closure_call(step_name: str, ev, st, ml, mh, live, run,
                          N: int, C: int, probe_limit: int,
                          interpret: bool = False,
                          stats: bool = False):
    """Traceable (un-jitted) pallas invocation of one return event's
    whole delta-frontier closure — usable inside the engine's outer
    lax.scan, like pallas_kernels.closure_call. Inputs are the scan
    step's frontier arrays ([N] st/ml/mh + live mask), the event's
    slot tables ([C] rows of xs), and the run flag; returns
    (st2, ml2, mh2, count, ovf, iters, stepped) exactly as
    engine._hash_event_closure does — because the kernel body IS that
    function, evaluated on VMEM-resident values. With `stats`
    (static; JEPSEN_TPU_SEARCH_STATS), two more outputs exactly as
    the shared closure returns them: the sort-equivalent work scalar
    and the probe-length histogram — the search-telemetry trajectory
    is computed INSIDE the kernel, not inferred from wall clocks."""
    from jepsen_tpu.parallel.engine import (N_PROBE_BUCKETS,
                                            _hash_event_closure,
                                            _next_pow2)
    from jepsen_tpu.parallel.steps import STEPS
    step = STEPS[step_name]
    step_cc = jax.vmap(
        jax.vmap(step, in_axes=(None, 0, 0, 0, 0)),  # over slots
        in_axes=(0, None, None, None, None),         # over configs
    )
    T = _next_pow2(2 * N)
    n_meta = 5 if stats else 4

    def kernel(f_ref, a0_ref, a1_ref, w_ref, occ_ref,
               st_ref, ml_ref, mh_ref, lv_ref, run_ref,
               ost_ref, oml_ref, omh_ref, meta_ref, *phist_ref):
        # bool masks travel as int32 (i1 vectors are the shaky corner
        # of Mosaic); reconstructed at the VMEM boundary
        ev_v = {"slot_f": f_ref[:], "slot_a0": a0_ref[:],
                "slot_a1": a1_ref[:], "slot_wild": w_ref[:] != 0,
                "slot_occ": occ_ref[:] != 0}
        out = _hash_event_closure(
            step_cc, ev_v, st_ref[:], ml_ref[:], mh_ref[:],
            lv_ref[:] != 0, run_ref[0] != 0, N, C, T, probe_limit,
            stats=stats)
        st2, ml2, mh2, count, ovf, iters, stepped = out[:7]
        ost_ref[:] = st2
        oml_ref[:] = ml2
        omh_ref[:] = mh2
        meta = [count.astype(I32), ovf.astype(I32),
                iters.astype(I32), stepped.astype(I32)]
        if stats:
            meta.append(out[7].astype(I32))   # swork
            phist_ref[0][:] = out[8].astype(I32)
        meta_ref[:] = jnp.stack(meta)

    out_shape = [jax.ShapeDtypeStruct((N,), I32),
                 jax.ShapeDtypeStruct((N,), U32),
                 jax.ShapeDtypeStruct((N,), U32),
                 jax.ShapeDtypeStruct((n_meta,), I32)]
    if stats:
        out_shape.append(jax.ShapeDtypeStruct((N_PROBE_BUCKETS,), I32))
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(ev["slot_f"], ev["slot_a0"], ev["slot_a1"],
      ev["slot_wild"].astype(I32), ev["slot_occ"].astype(I32),
      st, ml, mh, live.astype(I32),
      jnp.reshape(run, (1,)).astype(I32))
    st2, ml2, mh2, meta = outs[:4]
    base = (st2, ml2, mh2, meta[0], meta[1] != 0, meta[2], meta[3])
    if stats:
        return base + (meta[4], outs[4])
    return base


def hash_insert_call(c_st, c_ml, c_mh, c_live, st, ml, mh, count,
                     table, probe_limit: int, N: int,
                     interpret: bool = False):
    """Traceable pallas invocation of one fused visited-set
    transaction: engine._hash_insert_append (bounded probe +
    scatter-min claim + loser re-check + fresh-row append) with the
    candidate rows, the frontier tile, and the table VMEM-resident for
    the whole claim loop. Used per closure iteration by the sharded
    engine's per-device owned tables. `table` is the
    (t_st, t_ml, t_mh, t_occ) tuple; occupancy crosses the kernel
    boundary as int32 and comes back as bool, so the caller's
    while-carry dtype never changes. Returns
    (st2, ml2, mh2, table2, count2, n_fresh, ovf)."""
    from jepsen_tpu.parallel.engine import _hash_insert_append
    t_st, t_ml, t_mh, t_occ = table
    T = t_st.shape[0]

    def kernel(cst_ref, cml_ref, cmh_ref, clv_ref,
               st_ref, ml_ref, mh_ref, cnt_ref,
               tst_ref, tml_ref, tmh_ref, tocc_ref,
               ost_ref, oml_ref, omh_ref,
               otst_ref, otml_ref, otmh_ref, otocc_ref, meta_ref):
        st2, ml2, mh2, tbl2, count2, n_fresh, ovf = _hash_insert_append(
            cst_ref[:], cml_ref[:], cmh_ref[:], clv_ref[:] != 0,
            st_ref[:], ml_ref[:], mh_ref[:], cnt_ref[0],
            (tst_ref[:], tml_ref[:], tmh_ref[:], tocc_ref[:] != 0),
            probe_limit, N)
        ost_ref[:] = st2
        oml_ref[:] = ml2
        omh_ref[:] = mh2
        otst_ref[:] = tbl2[0]
        otml_ref[:] = tbl2[1]
        otmh_ref[:] = tbl2[2]
        otocc_ref[:] = tbl2[3].astype(I32)
        meta_ref[:] = jnp.stack([count2.astype(I32),
                                 n_fresh.astype(I32), ovf.astype(I32)])

    outs = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((N,), I32),
                   jax.ShapeDtypeStruct((N,), U32),
                   jax.ShapeDtypeStruct((N,), U32),
                   jax.ShapeDtypeStruct((T,), I32),
                   jax.ShapeDtypeStruct((T,), U32),
                   jax.ShapeDtypeStruct((T,), U32),
                   jax.ShapeDtypeStruct((T,), I32),
                   jax.ShapeDtypeStruct((3,), I32)),
        interpret=interpret,
    )(c_st, c_ml, c_mh, c_live.astype(I32), st, ml, mh,
      jnp.reshape(count, (1,)).astype(I32),
      t_st, t_ml, t_mh, t_occ.astype(I32))
    st2, ml2, mh2, tst2, tml2, tmh2, tocc2, meta = outs
    return (st2, ml2, mh2, (tst2, tml2, tmh2, tocc2 != 0),
            meta[0], meta[1], meta[2] != 0)
