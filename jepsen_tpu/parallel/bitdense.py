"""Bit-packed dense linearizability engine — the fast path.

Same algorithm as parallel.dense (whole config space materialised), but
the mask axis is bit-packed: the reachable-set tensor is

    B: uint32[S, W],  W = 2^C / 32

where bit b of word w encodes mask m = w*32 + b. All closure/filter
operations become VPU-friendly bitwise algebra with *static* index
tables — no sorts, no big float intermediates, no HBM streaming
(B for an entire 84-key batch at C=15 is ~2 MB, vs ~1 GB of f32
intermediates in the unpacked engine):

  * "configs that haven't linearized slot j" = B & clear_j, where
    clear_j is an intra-word constant (j < 5) or a word-index mask
    (j >= 5) — both trace-time constants;
  * the state transition OR_{s -> t} is a tiny [S,S] bitwise select;
  * "OR into m | bit_j" is a left-shift by 2^j inside words (j < 5) or
    a static word gather (j >= 5); the return-filter is the mirror
    right-shift/gather.

This is the engine the bench rides; parallel.dense remains as the
readable unpacked reference and parallel.engine as the sparse fallback
for windows too wide to materialise (C > ~24).
"""

from __future__ import annotations

import functools
import logging

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from jepsen_tpu import envflags
from jepsen_tpu import obs
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.parallel import programs
from jepsen_tpu.parallel.encode import EncodedHistory
from jepsen_tpu.parallel.steps import STEPS
from jepsen_tpu.resilience import supervisor as sup

_log = logging.getLogger(__name__)

MAX_C = 24  # 2^24 masks = 512k words per state row

U32 = jnp.uint32
# np (not jnp): a module-level jnp scalar initializes the default
# backend at import — with a wedged device runtime that turns a bare
# `import bitdense` into a hang before any device call. Engine modules
# must be import-safe; numpy constants fold into traces identically.
FULL = np.uint32(0xFFFFFFFF)


MAX_S = 128  # the closure trace unrolls over slots and states; its sel
# tensor is [C, S, S] per event and its cost O(C*S^2*W) — histories with
# many distinct values (unique-write registers) go to the sparse engine


def fits_bitdense(n_states: int, n_slots: int,
                  budget_words: int = 1 << 22) -> bool:
    if n_slots > MAX_C or n_states > MAX_S:
        return False
    W = max(1, (1 << n_slots) // 32)
    # bound both the reachable-set tensor and the per-round work
    return n_states * W <= budget_words \
        and n_slots * n_states * n_states * W <= (1 << 26)


def _intra_clear(j: int) -> np.uint32:  # jepsen-lint: disable=purity-numpy-call
    """32-bit constant with 1s at bit-positions whose mask-bit j is 0.
    np is deliberate: pure trace-time constants (see module header)."""
    out = 0
    for p in range(32):
        if (p >> j) & 1 == 0:
            out |= 1 << p
    return np.uint32(out)


def _plan(C: int):  # jepsen-lint: disable=purity-numpy-call
    """Static per-slot tables for shift/filter/select, as numpy — np is
    deliberate here: the tables fold into traces as constants and must
    not touch a (possibly wedged) device backend at build time."""
    W = max(1, (1 << C) // 32)
    widx = np.arange(W, dtype=np.int32)
    plan = []
    for j in range(C):
        if j < 5:
            plan.append({
                "intra": True,
                "clear": _intra_clear(j),     # positions with bit j clear
                "shift": np.int32(1 << j),
            })
        else:
            jb = 1 << (j - 5)
            clear_words = ((widx >> (j - 5)) & 1) == 0
            plan.append({
                "intra": False,
                # word-mask: FULL where mask-bit j clear
                "clearw": np.where(clear_words, 0xFFFFFFFF, 0).astype(np.uint32),
                # gather for OR-into-bit-j: target word i (bit set) reads i^jb
                "fwd_idx": (widx ^ jb).astype(np.int32),
                "setw": np.where(~clear_words, 0xFFFFFFFF, 0).astype(np.uint32),
            })
    return W, plan


def is_tpu_platform(platform: str) -> bool:
    """TPU-equivalence for platform-name checks. The axon PJRT plugin
    registers its backend under the name "axon" — canonicalized to
    "tpu" only for MLIR lowering — so jax.default_backend() and
    Device.platform report "axon" on real hardware; a literal
    == "tpu" check would run the pallas kernel in interpret mode ON
    the chip."""
    return platform in ("tpu", "axon")


def _resolve_closure_mode(closure_mode, use_pallas: bool = False):
    """XLA closure loop shape: "while" (converge-and-stop; extra
    device-visible `changed` reduction per iteration) or "fori" (fixed
    ceil(C/2) double-expansions; no convergence sync — the per-event
    cost on tiny tensors is suspected to be dispatch/sync latency, and
    only a hardware A/B (tools/perf_ab.py) gets to flip the default).
    Env override: JEPSEN_TPU_CLOSURE=fori. With pallas the XLA-loop
    branches are dead: the mode is pinned to "while" AFTER validation,
    so a bogus value fails on every platform and env toggles cannot
    split the compile cache."""
    if closure_mode is None:
        closure_mode = envflags.env_choice(
            "JEPSEN_TPU_CLOSURE", ("while", "fori"), default="while",
            what="closure mode")
    if closure_mode not in ("while", "fori"):
        raise ValueError(f"unknown closure mode {closure_mode!r}")
    return "while" if use_pallas else closure_mode


def _resolve_use_pallas(use_pallas, S: int, C: int, platform: str):
    """Shared gate for the single and batch paths: default ON for a
    real-TPU platform (JEPSEN_TPU_PALLAS=0 opts out; =1 forces it on
    elsewhere, in interpret mode), downgraded to False for shapes the
    kernel doesn't support. Returns (use_pallas, interpret) — interpret
    mode whenever the DATA's platform isn't a real TPU (keyed off where
    the arrays actually live, not the process default backend: a batch
    pinned to a CPU mesh must never trace a TPU kernel just because a
    TPU runtime happens to be the default).

    Default history: opt-in until a hardware measurement existed
    ("flags do not get to claim speedups"); flipped to default-on by
    the r5 on-chip tools/perf_ab.py verdict — pallas beat the XLA
    while closure on every measured shape (single-1k 18.9x,
    single-10k 54.4x, batch 84x120 1.42x) with bit-identical results
    on every run, incl. the counterexample fields."""
    if use_pallas is None:
        # strict tri-state read: only "0" opts out, only "1" forces on.
        # Anything else raises (envflags.EnvFlagError) instead of
        # silently counting as an opt-out — with the old `flag == "1"`
        # read, a stray JEPSEN_TPU_PALLAS=yes would have silently
        # reverted the measured r5 54x default.
        flag = envflags.env_bool("JEPSEN_TPU_PALLAS")
        use_pallas = flag if flag is not None \
            else is_tpu_platform(platform)
    if use_pallas:
        from jepsen_tpu.parallel import pallas_kernels as pk
        use_pallas = pk.supported(S, C)
    return use_pallas, not is_tpu_platform(platform)


def _bitdense_impl(xs, state0, step_name: str, S: int, C: int,
                   lo: int = -1, use_pallas: bool = False,
                   pallas_interpret: bool = True,
                   closure_mode: str = "while",
                   search_stats: bool = False):
    step = STEPS[step_name]
    W, plan = _plan(C)
    state_codes = jnp.arange(S, dtype=jnp.int32) + lo

    # per-event transition tables [C, S]
    step_js = jax.vmap(
        jax.vmap(step, in_axes=(0, None, None, None, None)),
        in_axes=(None, 0, 0, 0, 0),
    )

    # trace-time constants, STACKED over slots so the closure is a
    # handful of big tensor ops instead of C*(S+3) kernel launches —
    # the while_loop is dispatch-latency-bound on small [S, W] tiles.
    # np (not jnp) on this block is deliberate: the _plan tables fold
    # into the trace as constants, nothing here derives from a tracer.
    J0 = min(5, C)                    # intra-word slots (bit j < 32)
    J1 = C - J0                       # word-level slots
    # jepsen-lint: disable=purity-numpy-call
    clr5 = jnp.asarray(np.array([plan[j]["clear"] for j in range(J0)],
                                np.uint32))                    # [J0]
    # jepsen-lint: disable=purity-numpy-call
    shift5 = jnp.asarray(np.array([plan[j]["shift"] for j in range(J0)],
                                  np.uint32))                  # [J0]
    if J1:
        # jepsen-lint: disable=purity-numpy-call
        clw = jnp.asarray(np.stack([plan[j]["clearw"]
                                    for j in range(J0, C)]))   # [J1, W]
        # jepsen-lint: disable=purity-numpy-call
        fwd = jnp.asarray(np.stack([plan[j]["fwd_idx"]
                                    for j in range(J0, C)]))   # [J1, W]
        # jepsen-lint: disable=purity-numpy-call
        setw = jnp.asarray(np.stack([plan[j]["setw"]
                                     for j in range(J0, C)]))  # [J1, W]

    def _or_over(x, axis):
        return lax.reduce(x, U32(0), lax.bitwise_or, (axis,))

    def compute_sel(ev):
        nxt, okj = step_js(state_codes, ev["slot_f"], ev["slot_a0"],
                           ev["slot_a1"], ev["slot_wild"])
        legal = okj & ev["slot_occ"][:, None]                  # [C, S]
        # sel[j, s, t] = FULL if legal[j,s] and nxt[j,s]==t
        t_idx = jnp.arange(S)
        return jnp.where(
            legal[:, :, None] & ((nxt - lo)[:, :, None] == t_idx[None, None, :]),
            FULL, U32(0))                                      # [C, S, S]

    def make_expand(sel):
        def expand(B):
            # intra-word slots: ext[j,s,w] = B & clr5[j]; G[j,t,w] =
            # OR_s ext & sel; contribution = (G & clr5) << (1 << j)
            ext5 = B[None, :, :] & clr5[:, None, None]         # [J0, S, W]
            g5 = _or_over(ext5[:, :, None, :] & sel[:J0, :, :, None], 1)
            c5 = _or_over((g5 & clr5[:, None, None])
                          << shift5[:, None, None], 0)         # [S, W]
            out = B | c5
            if J1:
                # word-level slots: same algebra with word masks/gathers
                extw = B[None, :, :] & clw[:, None, :]         # [J1, S, W]
                gw = _or_over(extw[:, :, None, :] & sel[J0:, :, :, None], 1)
                moved = jnp.take_along_axis(
                    gw, jnp.broadcast_to(fwd[:, None, :], gw.shape), axis=2)
                out = out | _or_over(moved & setw[:, None, :], 0)
            return out
        return expand

    def make_closure_body(sel):
        expand = make_expand(sel)

        def body(c):
            B, _ = c
            # Two expansions per while iteration: the loop is latency-
            # bound by the `changed` reduction + condition sync, not by
            # the bitwise algebra, so halving the iteration count wins
            # ~1.5x even when the second expansion is sometimes a no-op
            # (measured on v5e: 8.9k -> 12.9k ops/s on the bench batch).
            B2 = expand(expand(B))
            return B2, jnp.any(B2 != B)
        return body

    def closure_cond(c):
        return c[1]

    # filter tables: per possible returning slot, applied via lax.switch
    # (np builds static index tables — trace-time constants only)
    def filter_at(s: int, B):  # jepsen-lint: disable=purity-numpy-call
        if s < 5:
            clear = U32(_intra_clear(s))
            return (B >> (1 << s)) & clear
        jb = 1 << (s - 5)
        widx = np.arange(max(1, (1 << C) // 32), dtype=np.int32)
        idx = jnp.asarray((widx | jb).astype(np.int32))
        clearw = jnp.asarray(
            np.where(((widx >> (s - 5)) & 1) == 0, 0xFFFFFFFF, 0)
            .astype(np.uint32))
        return jnp.take(B, idx, axis=1) & clearw[None, :]

    filter_branches = [functools.partial(filter_at, s) for s in range(C)]

    def scan_step(carry, ev):
        B, ok, fail_r, r_idx = carry
        run = ok & (ev["ev_slot"] >= 0)
        sel = compute_sel(ev)
        iters = jnp.int32(-1)   # unknown unless a counted loop ran
        if use_pallas:
            # the entire fixpoint runs inside one VMEM-resident pallas
            # kernel (parallel.pallas_kernels); skipped on pad events.
            # Its iteration count never leaves the kernel — the stats
            # block reports closure-iters -1 (unknown) on this path.
            from jepsen_tpu.parallel import pallas_kernels as pk
            B2 = lax.cond(
                run,
                lambda b: pk.closure_call(sel, b, C,
                                          interpret=pallas_interpret),
                lambda b: b, B)
        elif closure_mode == "fori":
            # fixed trip count, no convergence check: the fixpoint is
            # reached in <= C single expansions (each round adds every
            # one-step extension; chains are at most C slots long), so
            # ceil(C/2) double-expansion bodies always suffice. Trades
            # wasted post-convergence expansions for the removal of the
            # per-iteration `changed` reduction + cond sync. Pad events
            # need no guard: their sel is all-zero, expand is identity.
            expand = make_expand(sel)
            B2 = lax.fori_loop(0, (C + 1) // 2,
                               lambda _, b: expand(expand(b)), B)
            iters = jnp.int32(2 * ((C + 1) // 2))
        elif search_stats:
            # counted variant of the while closure: same fixpoint,
            # plus the double-expansion count (x2 = expansions) the
            # stats block reports
            body = make_closure_body(sel)

            def body_n(c):
                B2, changed = body((c[0], c[1]))
                return B2, changed, c[2] + 1

            B2, _, n = lax.while_loop(lambda c: c[1], body_n,
                                      (B, run, jnp.int32(0)))
            iters = 2 * n
        else:
            B2, _ = lax.while_loop(closure_cond, make_closure_body(sel),
                                   (B, run))
        s = jnp.clip(ev["ev_slot"], 0, C - 1)
        B3 = lax.switch(s, filter_branches, B2)
        alive = jnp.any(B3 != 0)
        failed_here = run & ~alive
        B_o = jnp.where(run, B3, B)
        ok_o = jnp.where(run, ~failed_here, ok)
        fail_o = jnp.where(failed_here & (fail_r < 0), r_idx, fail_r)
        carry_o = (B_o, ok_o, fail_o, r_idx + 1)
        if not search_stats:
            return carry_o, jnp.uint8(0)
        # frontier width = popcount of the post-filter reachable-set
        # tensor — the dense engine's exact live-config count
        width = jnp.sum(lax.population_count(B3)).astype(jnp.int32)
        return carry_o, {
            "width": jnp.where(run, width, -1).astype(jnp.int32),
            "iters": jnp.where(run, iters, 0).astype(jnp.int32),
        }

    B0 = jnp.zeros((S, W), U32).at[state0 - lo, 0].set(U32(1))
    carry0 = (B0, jnp.array(True), jnp.int32(-1), jnp.int32(0))
    (B, ok, fail_r, _), ys = lax.scan(scan_step, carry0, xs)
    valid = ok & jnp.any(B != 0)
    if search_stats:
        return valid, fail_r, ys
    return valid, fail_r


# donation decision (recompile-donate-argnums), DECIDED: nothing
# donatable — donate_argnums=() records it. The xs event tables are
# the only frontier-scale inputs and callers reuse them across
# env/closure-mode variants (tools/perf_ab.py runs the same xs through
# while/fori/pallas back to back); the B tensor is built in-trace, so
# there is no caller buffer to reclaim, and every output is a scalar
# no event table could alias.
_check_bitdense = jax.jit(_bitdense_impl,
                          donate_argnums=(),
                          static_argnames=("step_name", "S", "C", "lo",
                                           "use_pallas",
                                           "pallas_interpret",
                                           "closure_mode",
                                           "search_stats"))


# same (decided) donation as _check_bitdense above
@functools.partial(jax.jit,
                   donate_argnums=(),
                   static_argnames=("step_name", "S", "C", "lo",
                                    "use_pallas", "pallas_interpret",
                                    "closure_mode", "search_stats"))
def _check_bitdense_batch(xs, state0, step_name: str, S: int, C: int,
                          lo: int = -1, use_pallas: bool = False,
                          pallas_interpret: bool = True,
                          closure_mode: str = "while",
                          search_stats: bool = False):
    # under vmap the per-event lax.cond around the pallas closure
    # becomes run-both-and-select, so pad events cost one extra kernel
    # run per key — harmless: their result is discarded by the select
    return jax.vmap(
        lambda x, s0: _bitdense_impl(x, s0, step_name, S, C, lo,
                                     use_pallas=use_pallas,
                                     pallas_interpret=pallas_interpret,
                                     closure_mode=closure_mode,
                                     search_stats=search_stats)
    )(xs, state0)


def n_states(e: EncodedHistory) -> int:
    return e.n_states


def _stats_block_bitdense(ys, S: int, C: int,
                          extra: dict = None) -> dict:
    """The bitdense arm of the JEPSEN_TPU_SEARCH_STATS block: the
    reachable-set tensor IS a complete visited set, so the trajectory
    is the per-event popcount (exact live-config count) and occupancy
    is measured against the S * 2^C config space. Hash-table fields
    stay None — there is no table on this engine, and the uniform
    schema keeps the sinks' consumers simple."""
    w = np.asarray(ys["width"]).reshape(-1)
    real = w >= 0
    widths = [int(x) for x in w[real]]
    iters = [int(x) for x in np.asarray(ys["iters"]).reshape(-1)[real]]
    peak = max(widths, default=0)
    space = S * (1 << C)
    block = {
        "engine": "bitdense",
        "events": len(widths),
        "frontier-width": widths,
        "closure-iters": iters,
        "frontier-peak": peak,
        "config-space": space,
        "peak-occupancy": round(peak / space, 9) if space else None,
        "dedupe": "dense",
        "delta-split-ratio": None,
        "table-capacity": None,
        "load-factor-peak": None,
        "probe-hist": None,
    }
    if extra:
        block.update(extra)
    return block


def check_encoded_bitdense(e: EncodedHistory,
                           use_pallas: bool = None,
                           closure_mode: str = None,
                           timings: dict = None,
                           search_stats: bool = None) -> dict:
    """Single-key bit-packed check. `use_pallas` routes the closure
    through the VMEM-resident pallas kernel (parallel.pallas_kernels);
    default: ON for a real-TPU platform (r5 on-chip A/B verdict;
    JEPSEN_TPU_PALLAS=0/1 overrides), and only for shapes the kernel
    supports (the same default governs the batch path).
    `closure_mode` picks the XLA loop shape ("while"/"fori", see
    _resolve_closure_mode); ignored when pallas runs.

    `timings`, when a dict, receives a `transfer_secs`/`device_secs`
    split (bench's per-section JSONL keys): the event tables are then
    explicitly placed and BLOCKED on before the search is issued, so
    the two numbers are a clean H2D / search separation — at the cost
    of serializing transfer against compute, which is why the default
    (timings=None) path is untouched."""
    if e.n_returns == 0:
        return {"valid?": True, "engine": "bitdense"}
    from time import perf_counter

    from jepsen_tpu.parallel import engine as eng_mod
    from jepsen_tpu.parallel.dense import _xs_dense
    S = n_states(e)
    C = max(5, e.n_slots)  # at least one full word
    platform = jax.default_backend()
    use_pallas, interpret = _resolve_use_pallas(
        use_pallas, S, C, platform)
    closure_mode = _resolve_closure_mode(closure_mode, use_pallas)
    ss = eng_mod._resolve_search_stats(search_stats)
    xs = _xs_dense(e, C)
    if timings is not None:
        t0 = perf_counter()
        xs = {k: jnp.asarray(v) for k, v in xs.items()}
        jax.block_until_ready(xs)
        timings["transfer_secs"] = perf_counter() - t0
        t0 = perf_counter()
    ts0 = perf_counter()
    # bitdense programs are not AOT-managed (the pallas closure path);
    # the registry still counts their shape tuples so the fleet-wide
    # program population perf_ab records covers every engine
    programs.track("bitdense.check", xs,
                   (e.step_name, S, C, e.state_lo, use_pallas,
                    interpret, closure_mode, ss))
    with obs.span("bitdense.check", S=S, C=C), \
            obs.device_annotation(f"bitdense single S{S} C{C}"):
        def _search():
            out = _check_bitdense(xs, jnp.int32(e.state0),
                                  e.step_name, S, C,
                                  e.state_lo, use_pallas,
                                  interpret, closure_mode, ss)
            # bool() materializes: async failures/hangs surface inside
            # the supervised window (the device wait ends here)
            if ss:
                valid, fail_r, ys = out
                return bool(valid), fail_r, jax.tree.map(np.asarray, ys)
            valid, fail_r = out
            return bool(valid), fail_r

        res = sup.dispatch("dispatch", _search, backend=platform)
        valid_b, fail_r = res[0], res[1]
    if timings is not None:
        timings["device_secs"] = perf_counter() - t0
    out = {"valid?": valid_b, "engine": "bitdense",
           "states": S, "slots": C,
           # the dense reachable-set tensor IS a complete visited set —
           # the sparse sort/hash strategies (JEPSEN_TPU_DEDUPE) have
           # nothing to select here; the tag keeps result schemas
           # uniform across engines
           "dedupe": "dense",
           "closure": "pallas" if use_pallas
           else f"xla-{closure_mode}"}
    if ss:
        out["stats"] = eng_mod.finish_stats_block(
            _stats_block_bitdense(res[2], S, C), ts0, perf_counter())
    if not out["valid?"]:
        from jepsen_tpu.parallel.encode import fail_op_fields
        out.update(fail_op_fields(e, int(fail_r)))
    return out


def _normalize_cost(ca) -> dict:
    # older jax returns [dict] per device program, newer a flat dict;
    # some PJRT plugins (the axon TPU tunnel) return None entirely —
    # the prior is advisory, so report that rather than raising
    d = ca[0] if isinstance(ca, (list, tuple)) else ca
    if d is None:
        return {"unavailable": "cost_analysis returned None "
                               "(backend does not implement it)"}
    return {"flops": float(d.get("flops", 0.0)),
            "bytes_accessed": float(d.get("bytes accessed", 0.0))}


def cost_analysis_encoded(e: EncodedHistory,
                          use_pallas: bool = None,
                          closure_mode: str = "while") -> dict:
    """Hardware-independent analytical prior: flops / bytes accessed
    from XLA's cost model over the LOWERED (traced, uncompiled) HLO of
    a check of `e` under the given closure variant. No device
    execution — usable on CPU to rank while/fori/pallas before any
    chip measurement exists (tools/perf_ab.py emits this as each
    shape's cost prior and cross-checks it once measured).

    CAVEATS the callers must carry: (1) XLA's HLO cost model counts
    every loop BODY once — trip counts are data-dependent — so these
    numbers are per-iteration work (they rank closure VARIANTS, whose
    bodies differ), not end-to-end totals; model totals by multiplying
    with the known static trip counts (n_returns scan steps, exactly
    ceil(C/2) closure trips for fori). (2) The pallas row is NOT
    backend-independent: off-TPU the interpret-mode EMULATION is
    costed, on TPU the kernel body is a custom call the cost model
    cannot see — the "program" field says which program the numbers
    describe, and cross-backend pallas comparisons are invalid."""
    from jepsen_tpu.parallel.dense import _xs_dense
    S = n_states(e)
    C = max(5, e.n_slots)
    use_pallas, interpret, mode = _resolve_cost_variant(
        use_pallas, S, C, closure_mode)
    lowered = _check_bitdense.lower(
        _xs_dense(e, C), jnp.int32(e.state0), e.step_name, S, C,
        e.state_lo, use_pallas, interpret, mode)
    return _annotate_cost(lowered.cost_analysis(), use_pallas,
                          interpret, mode)


def cost_analysis_batch(encs, use_pallas: bool = None,
                        closure_mode: str = "while") -> dict:
    """Batch-path analogue of cost_analysis_encoded (same padded
    program check_batch_bitdense would run, meshless)."""
    from jepsen_tpu.parallel.encode import pad_batch
    xs, state0, S, C, _ = pad_batch(encs, min_slots=5)
    use_pallas, interpret, mode = _resolve_cost_variant(
        use_pallas, S, C, closure_mode)
    lowered = _check_bitdense_batch.lower(
        xs, state0, encs[0].step_name, S, C, encs[0].state_lo,
        use_pallas, interpret, mode)
    return _annotate_cost(lowered.cost_analysis(), use_pallas,
                          interpret, mode)


def _resolve_cost_variant(use_pallas, S, C, closure_mode):
    """The same gates the execution paths use (no bare kernel asserts
    on unsupported shapes — an explicit use_pallas=True downgrades
    exactly like check_encoded_bitdense would)."""
    use_pallas, interpret = _resolve_use_pallas(
        use_pallas, S, C, jax.default_backend())
    return use_pallas, interpret, _resolve_closure_mode(closure_mode,
                                                        use_pallas)


def _annotate_cost(ca, use_pallas, interpret, mode) -> dict:
    out = _normalize_cost(ca)
    out["program"] = (("pallas-interpret-emulation" if interpret
                       else "pallas-kernel-custom-call "
                            "(body uncounted by the HLO cost model)")
                      if use_pallas else f"xla-{mode}")
    return out


class PendingBitdenseBatch:
    """A batched bitdense check that has been ISSUED but not consumed.

    JAX dispatch is async: construction pads + places the batch
    (`transfer_secs` records that host-side cost) and enqueues the
    device program, returning while it runs; `finalize()` blocks on
    the results and builds the per-key dicts (`device_wait_secs`
    records the blocked wait). The pipelined executor
    (parallel.pipeline) leans on this split to overlap the next
    chunk's host encode with this chunk's device search;
    check_batch_bitdense() is dispatch + finalize back to back."""

    def __init__(self, encs, xs, state0, S, C, up, interpret, mode,
                 n_dev, use_pallas_arg, closure_mode_arg,
                 transfer_secs, platform=None, R=None,
                 search_stats: bool = False):
        self.encs = encs
        self.xs = xs
        self.state0 = state0
        self.S = S
        self.C = C
        self.R = R if R is not None else max(e.n_returns for e in encs)
        self.up = up
        self.interpret = interpret
        self.mode = mode
        self.n_dev = n_dev
        self.use_pallas_arg = use_pallas_arg
        self.closure_mode_arg = closure_mode_arg
        self.transfer_secs = transfer_secs
        self.platform = platform
        self.search_stats = bool(search_stats)
        self.device_wait_secs = None
        self.note = None
        self._results = None
        self._ys = None
        self._t_issue = None
        self._issue()

    def _issue(self):
        # the annotation names this dispatch in a jax.profiler TPU
        # capture (JEPSEN_TPU_JAX_PROFILE) so the device timeline
        # row lines up with the host's bitdense.dispatch span.
        # Built OUTSIDE the try: a telemetry/env-flag error (e.g. a
        # malformed JEPSEN_TPU_JAX_PROFILE) must surface as itself,
        # not be misdiagnosed as a pallas closure failure
        from time import perf_counter
        self._t_issue = perf_counter()
        ann = obs.device_annotation(
            f"bitdense K{len(self.encs)} S{self.S} C{self.C}")
        # population tracking only — the batch closure program is not
        # AOT-managed (see the single-key site)
        programs.track("bitdense.check_batch", self.xs,
                       (self.encs[0].step_name, self.S, self.C,
                        self.encs[0].state_lo, self.up,
                        self.interpret, self.mode, self.search_stats))
        try:
            with ann:
                # supervised (resilience.supervisor): faults inject
                # here, the breaker records the outcome; the program is
                # ISSUED inside the window, the async wait is
                # finalize()'s own supervised window
                out = sup.dispatch(
                    "dispatch",
                    lambda: _check_bitdense_batch(
                        self.xs, self.state0, self.encs[0].step_name,
                        self.S, self.C, self.encs[0].state_lo, self.up,
                        self.interpret, self.mode,
                        search_stats=self.search_stats),
                    backend=self.platform)
                if self.search_stats:
                    self._valid, self._fail_r, self._ys = out
                else:
                    self._valid, self._fail_r = out
        except Exception:  # noqa: BLE001 — see _fallback_or_raise
            self._fallback_or_raise()

    def _fallback_or_raise(self):
        import sys

        err = sys.exc_info()[1]
        # supervised-dispatch failures (injected faults, watchdog
        # wedges, an open breaker) are NOT pallas lowering gaps: they
        # re-raise untouched so the callers' degradation contract —
        # host fallback with a structured resilience note — takes
        # over instead of a misdiagnosed closure fallback. EXCEPT a
        # DeviceUnavailable that merely WRAPS a real thunk error
        # (supervisor retry budget exhausted): the original error may
        # be exactly the Mosaic lowering gap this fallback exists for,
        # and the cheap XLA-closure downgrade must not silently turn
        # into a 100-300x host degrade just because a watchdog was
        # configured — unwrap and judge the original.
        if isinstance(err, sup.DeviceUnavailable) \
                and err.cause is not None:
            err = err.cause
        elif isinstance(err, sup.DISPATCH_FAILURES):
            raise
        # The r5 hardware window measured the SPMD pallas lowering on a
        # 1-device TPU mesh only; the multi-device slicing is
        # differential-tested on CPU meshes but its Mosaic lowering is
        # unmeasured on real multi-chip hardware (the same class of gap
        # that produced the jnp.flip / 4-D-reshape on-chip failures
        # interpret mode had hidden). On the DEFAULT path a lowering
        # gap must degrade to the XLA closure with a note, not crash a
        # batch check; an explicit use_pallas=True argument OR an
        # env-forced JEPSEN_TPU_PALLAS=1 keeps raising — "=1 forces it
        # on" is a contract (module docstring), and force-measuring
        # runs must see the real error, not a silent XLA number.
        # The env read is LAST in the chain: with an explicit arg the
        # flag was never consulted, and a malformed value must not
        # shadow the real pallas error here (short-circuit skips it);
        # with use_pallas=None a malformed value already raised in
        # _resolve_use_pallas before the dispatch.
        if not (self.up and self.use_pallas_arg is None
                and self.n_dev > 1
                and envflags.env_bool("JEPSEN_TPU_PALLAS") is not True):
            raise
        self.up = False
        self.mode = _resolve_closure_mode(self.closure_mode_arg, False)
        obs.counter("bitdense.pallas_fallbacks").inc()
        _log.warning(
            "default-path pallas closure failed on a %d-device mesh "
            "(%r) — falling back to the xla-%s closure for this "
            "batch", self.n_dev, err, self.mode)
        self.note = (f"pallas closure failed on a {self.n_dev}-device "
                     f"mesh ({type(err).__name__}); fell back to the "
                     f"xla-{self.mode} closure (multi-device Mosaic "
                     f"lowering is unmeasured)")
        out = sup.dispatch(
            "dispatch",
            lambda: _check_bitdense_batch(
                self.xs, self.state0, self.encs[0].step_name, self.S,
                self.C, self.encs[0].state_lo, False, self.interpret,
                self.mode, search_stats=self.search_stats),
            backend=self.platform)
        if self.search_stats:
            self._valid, self._fail_r, self._ys = out
        else:
            self._valid, self._fail_r = out

    def finalize(self) -> list:
        if self._results is not None:
            return self._results
        # same single-measurement-site contract as dispatch: the
        # bitdense.finalize span IS the device_wait_secs clock reads
        with obs.timer("bitdense.finalize", keys=len(self.encs)) as tm:
            try:
                # materialize inside the try (and inside a supervised
                # window: this wait is where a wedged runtime actually
                # hangs): async dispatch surfaces runtime failures
                # here, not at the issue
                valid, fail_r = sup.dispatch(
                    "dispatch",
                    lambda: (np.asarray(self._valid),
                             np.asarray(self._fail_r)),
                    backend=self.platform)
            except Exception:  # noqa: BLE001 — same gate as at issue
                self._fallback_or_raise()
                valid = np.asarray(self._valid)
                fail_r = np.asarray(self._fail_r)
        self.device_wait_secs = tm.wall
        closure = "pallas" if self.up else f"xla-{self.mode}"
        ys = None
        if self.search_stats and self._ys is not None:
            import jax as _jax
            ys = _jax.tree.map(np.asarray, self._ys)
        out = []
        from time import perf_counter
        t1 = perf_counter()
        for k, e in enumerate(self.encs):
            r = {"valid?": bool(valid[k]), "engine": "bitdense",
                 "dedupe": "dense",  # complete visited set by
                 "closure": closure}  # construction (see check_encoded)
            if self.note is not None:
                r["closure-note"] = self.note
            if ys is not None:
                from jepsen_tpu.parallel import engine as eng_mod
                waste = 1.0 - ((e.n_returns * max(5, e.n_slots))
                               / max(1, self.R * self.C))
                block = _stats_block_bitdense(
                    {"width": ys["width"][k], "iters": ys["iters"][k]},
                    self.S, self.C,
                    extra={"pad-waste": round(waste, 6),
                           "pad-events": int(self.R - e.n_returns),
                           "pad-slots": int(self.C - max(5, e.n_slots))})
                r["stats"] = eng_mod.finish_stats_block(
                    block, self._t_issue, t1, key=k)
            if not r["valid?"]:
                from jepsen_tpu.parallel.encode import fail_op_fields
                r.update(fail_op_fields(e, int(fail_r[k])))
            out.append(r)
        led = _ledger.active()
        if led is not None:
            # decision-ledger evidence: one record per bitdense batch
            # dispatch — issue-to-materialize wall from the same reads
            # the stats blocks use ("N" is S, the dense table rows)
            n_valid = sum(1 for r in out if r["valid?"])
            led.record(
                "dispatch", engine="bitdense",
                shape={"family": self.encs[0].step_name,
                       "N": int(self.S), "R": int(self.R),
                       "C": int(self.C), "tier": 0, "pack": False},
                strategy={"dedupe": "dense", "closure": closure},
                secs=round(t1 - self._t_issue, 6),
                keys=len(self.encs),
                stats=_ledger.stats_digest(
                    [r["stats"] for r in out if r.get("stats")]),
                outcome={"valid": n_valid,
                         "invalid": len(out) - n_valid,
                         "overflow": 0,
                         "fallback": self.note is not None})
        self._results = out
        return out


def dispatch_batch_bitdense(encs, mesh=None, use_pallas: bool = None,
                            closure_mode: str = None,
                            min_states: int = 0,
                            min_slots: int = 5,
                            min_returns: int = 0,
                            search_stats: bool = None
                            ) -> PendingBitdenseBatch:
    """Pad, place, and ISSUE a batched per-key check without consuming
    the results — returns a PendingBitdenseBatch whose finalize()
    blocks and builds the per-key dicts.
    `min_states`/`min_slots`/`min_returns` floor the padded dims so a
    CHUNK of a larger bucket compiles and resolves (pallas gating
    included) at the bucket's (S, C, R) — without the R floor every
    chunk's local max n_returns would be its own compile."""
    from jepsen_tpu.parallel.encode import pad_batch
    obs.counter("bitdense.dispatches").inc()
    # gate on where the batch actually lives: pad_batch pins it to the
    # mesh when one is given, regardless of the process default backend
    platform = (mesh.devices.flat[0].platform if mesh is not None
                else jax.default_backend())
    # obs.timer: one clock-read pair serves both the recorded span and
    # the transfer_secs the stats/bench lines report — they cannot
    # disagree (the same contract bench.py rides). The placement runs
    # through the supervised seam (site "transfer"): H2D against a
    # wedged runtime hangs exactly like a dispatch does.
    with obs.timer("bitdense.pad_place", keys=len(encs)) as tm:
        xs, state0, S, C, R = sup.dispatch(
            "transfer",
            lambda: pad_batch(encs, mesh=mesh, min_slots=min_slots,
                              min_states=min_states,
                              min_returns=min_returns),
            backend=platform)
    transfer_secs = tm.wall
    # Mesh-sharded TPU batches follow the same default as the rest
    # (_resolve_use_pallas: ON for a real-TPU platform). The guard that
    # used to pin them to XLA came off with the r5 on-chip measurement:
    # the non-interpret SPMD lowering (shard_map -> mosaic) compiled
    # and ran on a real 1-device TPU mesh, agreed with the XLA closure
    # on all 84 keys, and won 1.48x. Provenance caveat: that run's raw
    # JSONL was not retained — no bench_results/ artifact records it;
    # the only committed evidence is the PERF_R05.md session table
    # (its provenance note), below the repo's raw-lines standard, so a
    # future chip session should re-record it. The multi-device
    # slicing logic is differential-tested on the 8-way CPU mesh
    # (tests/test_pallas.py).
    up, interpret = _resolve_use_pallas(use_pallas, S, C, platform)
    mode = _resolve_closure_mode(closure_mode, up)
    from jepsen_tpu.parallel import engine as eng_mod
    ss = eng_mod._resolve_search_stats(search_stats)
    n_dev = 1 if mesh is None else int(np.asarray(mesh.devices).size)
    return PendingBitdenseBatch(encs, xs, state0, S, C, up, interpret,
                                mode, n_dev, use_pallas, closure_mode,
                                transfer_secs, platform=platform,
                                R=R, search_stats=ss)


def check_batch_bitdense(encs, mesh=None, use_pallas: bool = None,
                         closure_mode: str = None,
                         search_stats: bool = None) -> list:
    """Batched per-key check. Callers must ensure the COMBINED padded
    dims fit (fits_bitdense(max S, max C)) — individually-fitting keys
    can combine into an over-budget program; engine.check_batch does
    this check and falls back to per-key dispatch otherwise.
    `use_pallas` routes each key's closure through the VMEM-resident
    kernel (vmapped over keys); default: ON for a real-TPU platform
    (r5 on-chip A/B; JEPSEN_TPU_PALLAS=0/1 overrides), gated to shapes
    the kernel supports at the PADDED dims.
    `closure_mode` picks the XLA loop shape ("while"/"fori")."""
    if not encs:
        return []
    return dispatch_batch_bitdense(encs, mesh=mesh, use_pallas=use_pallas,
                                   closure_mode=closure_mode,
                                   search_stats=search_stats).finalize()
