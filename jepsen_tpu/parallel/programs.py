"""Compile economics for the engine/serve stacks (docs/performance.md
"Compile economics").

At fleet scale the jit compile is the tail: every new (step, capacity,
width, tier, dedupe, pack, probe_limit) tuple compiles on first touch,
a rehomed key's adopter recompiles everything its dead replica had
warm, and the escalation ladder walks shape sequences that each
compile mid-incident. Four cooperating pieces close that, all behind
``JEPSEN_TPU_COMPILE_CACHE``:

**Shape canonicalization** (``JEPSEN_TPU_CANON_SHAPES``) — the scan
step skips pad rows (``ev_slot < 0``) without touching the carry, so
quantizing event-row counts onto the ``EVENT_QUANTUM`` ladder (the
``parallel.extend`` chunk precedent) is parity-safe: verdicts,
counterexamples, max-frontier, and configs-stepped are identical, and
the fleet-wide program population collapses from one-per-history-
length to one-per-quantum-rung. Flag off: byte-identical shapes,
results, and schemas (the PIPELINE/DEDUPE precedent).

**The program registry + AOT** — a per-process table of
shape-tuple -> compiled executable. Armed, the engine's sparse jit
entries dispatch through ``jax.jit(...).lower().compile()`` programs
the registry owns, with ``engine.programs.{hits,misses,compiles,
preloads,load_errors,precompiles,manifest_warms}`` counters and a
``serve.compile_secs`` histogram (every compile/deserialize paid,
prewarm and ladder included) on /metrics.

**Persistence** — ``JEPSEN_TPU_COMPILE_CACHE=<dir>`` additionally
persists serialized executables (``jax.experimental.
serialize_executable``) so a restarted replica cold-starts warm.
Every load is version/fingerprint-guarded: a blob from a different
jax/jaxlib/backend, a foreign shape key, or a torn file degrades to a
fresh compile (counted ``load_errors``) — never a crash, never a
wrong program. Writes land tmp + ``os.replace`` so a kill mid-persist
leaves no torn final file. Pickles here carry the same trust posture
as the run store (docs/performance.md encode-cache precedent): load
only from directories this framework wrote.

**Warm handoff + ladder precompile** — ``manifest()`` serializes the
registry's program population (entry + statics + aval spec) as JSON;
``serve.ring.transfer_key`` ships it with the WAL segments and
``CheckerService.adopt_keys`` pre-warms it before replaying.
``JEPSEN_TPU_PRECOMPILE=1`` adds a background best-effort thread that
pre-compiles the next capacity rung above each live program, so a
mid-incident escalation re-dispatch finds its doubled-``N`` program
already resident.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import threading
from hashlib import sha256
from time import perf_counter
from typing import Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.envflags import env_bool, env_path

_log = logging.getLogger("jepsen_tpu.programs")

# The shape quantum every canonicalized row count snaps to — ONE
# source of truth; parallel.extend re-exports it (its chunk padding
# rode this ladder first).
EVENT_QUANTUM = 16

# capacity ceiling the ladder precompiler respects (the engine's own
# escalation ceiling — compiling past what dispatch can reach is waste)
_LADDER_CEILING = 1 << 20


def quantize_rows(n: int) -> int:
    """Smallest EVENT_QUANTUM multiple >= n (and >= one quantum)."""
    return max(EVENT_QUANTUM, -(-int(n) // EVENT_QUANTUM) * EVENT_QUANTUM)


def canon_armed() -> bool:
    """JEPSEN_TPU_CANON_SHAPES=1: quantize one-shot/resumable chunk
    row counts onto the EVENT_QUANTUM ladder (parity-safe padding)."""
    return bool(env_bool("JEPSEN_TPU_CANON_SHAPES", False))


def precompile_armed() -> bool:
    """JEPSEN_TPU_PRECOMPILE=1: background next-rung precompile."""
    return bool(env_bool("JEPSEN_TPU_PRECOMPILE", False))


def resolve_cache() -> Optional[str]:
    """The JEPSEN_TPU_COMPILE_CACHE destination: None = feature off,
    "" = registry armed with no persistence, path = registry armed +
    executables persisted there."""
    return env_path("JEPSEN_TPU_COMPILE_CACHE", what="cache directory")


def pad_rows(xs: Dict[str, np.ndarray], r_pad: int) -> Dict[str, np.ndarray]:
    """Pad an event-chunk dict's leading (row) axis to ``r_pad`` with
    pad rows — ev_slot=-1 / unoccupied slots, exactly the rows the
    scan step skips without advancing its event index or touching the
    carry (the parallel.extend._xs_slice fill contract), so padding is
    parity-safe by construction."""
    r = len(xs["ev_slot"])
    if r_pad <= r:
        return xs
    out = {}
    for k, v in xs.items():
        v = np.asarray(v)
        fill = False if v.dtype == np.bool_ else -1
        buf = np.full((r_pad,) + v.shape[1:], fill, v.dtype)
        buf[:r] = v
        out[k] = buf
    return out


def maybe_canon_rows(xs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """``pad_rows`` onto the quantum ladder when JEPSEN_TPU_CANON_SHAPES
    is armed; the identity otherwise (flag off = byte-identical)."""
    if not canon_armed():
        return xs
    return pad_rows(xs, quantize_rows(len(xs["ev_slot"])))


# ------------------------------------------------------- shape specs


def _aval_spec(tree):
    """A JSON-able shape/dtype spec of a pytree of arrays — the
    manifest interchange form (tuples and dicts tagged so the spec
    round-trips to the exact treedef ``lower`` needs)."""
    if isinstance(tree, dict):
        return {"t": "d", "v": {k: _aval_spec(tree[k])
                                for k in sorted(tree)}}
    if isinstance(tree, tuple):
        return {"t": "t", "v": [_aval_spec(x) for x in tree]}
    if isinstance(tree, list):
        return {"t": "l", "v": [_aval_spec(x) for x in tree]}
    shape = tuple(int(d) for d in getattr(tree, "shape", ()))
    dtype = getattr(tree, "dtype", None)
    return {"t": "a", "s": list(shape),
            "d": np.dtype(dtype if dtype is not None
                          else type(tree)).name}


def _spec_to_shapes(spec):
    """Manifest spec -> pytree of jax.ShapeDtypeStruct (AOT lowering
    input)."""
    import jax
    t = spec["t"]
    if t == "d":
        return {k: _spec_to_shapes(v) for k, v in spec["v"].items()}
    if t == "t":
        return tuple(_spec_to_shapes(x) for x in spec["v"])
    if t == "l":
        return [_spec_to_shapes(x) for x in spec["v"]]
    return jax.ShapeDtypeStruct(tuple(spec["s"]), np.dtype(spec["d"]))


def _statics_spec(statics: tuple):
    """Statics tuple -> JSON-able form (nested tuples tagged — the
    config-pack spec is a tuple of ints)."""
    def enc(v):
        if isinstance(v, tuple):
            return {"t": "t", "v": [enc(x) for x in v]}
        if isinstance(v, (np.integer,)):
            return {"t": "i", "v": int(v)}
        if isinstance(v, (np.bool_,)):
            return {"t": "b", "v": bool(v)}
        if v is None or isinstance(v, (str, int, float, bool)):
            return {"t": "i", "v": v}
        raise TypeError(f"unserializable static {v!r}")
    return [enc(v) for v in statics]


def _spec_to_statics(spec) -> tuple:
    def dec(e):
        if e["t"] == "t":
            return tuple(dec(x) for x in e["v"])
        if e["t"] == "b":
            return bool(e["v"])
        return e["v"]
    return tuple(dec(e) for e in spec)


def _device_token(traced) -> str:
    """A stable token for where the traced arrays live — part of the
    program key, because an executable is compiled for a specific
    device assignment and must never answer a call placed elsewhere."""
    leaves: list = []

    def walk(t):
        if isinstance(t, dict):
            for v in t.values():
                walk(v)
        elif isinstance(t, (tuple, list)):
            for v in t:
                walk(v)
        else:
            leaves.append(t)
    walk(traced)
    for leaf in leaves:
        devs = getattr(leaf, "devices", None)
        if callable(devs):
            try:
                return ",".join(sorted(f"{d.platform}:{d.id}"
                                       for d in devs()))
            except Exception:  # noqa: BLE001 — abstract avals
                continue
    return "host"


def _versions() -> Tuple[str, str]:
    import jax
    try:
        import jaxlib.version
        jl = jaxlib.version.__version__
    except Exception:  # noqa: BLE001
        jl = "?"
    return jax.__version__, jl


def _no_persistent_cache():
    """Scope under which registry compiles bypass jax's persistent
    compilation cache (``jax_compilation_cache_dir``). An executable
    satisfied from that cache does not survive a
    ``serialize_executable`` round-trip on the CPU backend — the
    deserialized program aborts with "Symbols not found" — so a
    ``.jprog`` persisted from a cache-hit executable is poisoned and
    every restart that preloads it degrades to a fresh compile. The
    registry's own disk layer already covers these programs, so the
    global cache is redundant here anyway. The config state is
    context-managed (thread-local overlay): concurrent non-registry
    jits are unaffected."""
    try:
        from jax._src.config import enable_compilation_cache
        return enable_compilation_cache(False)
    except Exception:  # noqa: BLE001 — private API; degrade to no-op
        import contextlib
        return contextlib.nullcontext()


class _Program:
    __slots__ = ("compiled", "spec", "aot")

    def __init__(self, compiled, spec, aot):
        self.compiled = compiled
        self.spec = spec
        self.aot = aot


class ProgramRegistry:
    """shape tuple -> compiled program, with hit/miss/compile/preload
    counters — the per-process program population ledger.

    AOT entries (the engine's sparse scan jits, proven serializable)
    run through ``call``: miss -> disk load -> ``lower().compile()``,
    hit -> the cached executable (the python jit dispatch layer is
    skipped entirely). Engines whose programs are not AOT-managed
    (shard_map meshes, pallas closures) still ``track`` their shape
    tuples so the population count perf_ab records covers the whole
    fleet surface.

    Lock discipline: the registry lock guards the table and the plain
    int counters ONLY — every compile, file read/write, and obs
    emission runs outside it (losers of a racing compile discard)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or None
        self._lock = threading.Lock()
        self._programs: Dict[tuple, _Program] = {}
        self._stats = {"hits": 0, "misses": 0, "compiles": 0,
                       "preloads": 0, "load_errors": 0,
                       "precompiles": 0, "manifest_warms": 0}
        self._queued: set = set()
        self._queue: list = []
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------ counters

    def _count(self, which: str, n: int = 1) -> None:
        with self._lock:
            self._stats[which] += n
        obs.counter(f"engine.programs.{which}").inc(n)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def population(self) -> int:
        with self._lock:
            return len(self._programs)

    # ---------------------------------------------------------- keys

    def _key(self, name: str, statics: tuple, traced) -> tuple:
        return (name, statics,
                json.dumps(_aval_spec(traced), sort_keys=True),
                _device_token(traced))

    def _digest(self, key: tuple) -> str:
        return sha256(repr(key).encode()).hexdigest()[:32]

    # ------------------------------------------------------ dispatch

    def call(self, name: str, entry, args: tuple, n_traced: int,
             static_names: tuple):
        """Dispatch one engine program through the registry: the first
        ``n_traced`` of ``args`` are traced pytrees, the rest statics
        in ``static_names`` order (exactly how the jit entry is
        declared). Results are the jit entry's, bit for bit — the
        executable is lowered from the same function with the same
        avals and statics."""
        traced = args[:n_traced]
        statics = tuple(args[n_traced:])
        key = self._key(name, statics, traced)
        with self._lock:
            rec = self._programs.get(key)
        if rec is not None and rec.compiled is not None:
            self._count("hits")
            out = rec.compiled(*traced)
        else:
            self._count("misses")
            compiled, spec = self._materialize(
                name, entry, key, statics, static_names,
                _aval_spec(traced), shapes=traced)
            out = compiled(*traced)
        self._maybe_precompile_rung(name, entry, key, statics,
                                    static_names)
        return out

    def track(self, name: str, traced, statics: tuple) -> None:
        """Population tracking for non-AOT engines: count the shape
        tuple's first touch as a miss (the jit layer compiles it) and
        every later touch as a hit, so the fleet-wide program count
        covers every engine."""
        key = self._key(name, tuple(statics), traced)
        with self._lock:
            seen = key in self._programs
            if not seen:
                self._programs[key] = _Program(None, None, aot=False)
        self._count("hits" if seen else "misses")

    # ----------------------------------------------------- materialize

    def _materialize(self, name, entry, key, statics, static_names,
                     aval_spec, shapes):
        """Disk load, else compile; install under the lock (racing
        loser discards its copy). Runs entirely OUTSIDE the registry
        lock."""
        digest = self._digest(key)
        compiled = self._load_disk(digest)
        fresh = compiled is None
        if fresh:
            kw = dict(zip(static_names, statics))
            t0 = perf_counter()
            with obs.span("serve.compile", program=name,
                          digest=digest), _no_persistent_cache():
                compiled = entry.lower(*shapes, **kw).compile()
            dt = perf_counter() - t0
            self._count("compiles")
            obs.histogram("serve.compile_secs").observe(dt)
        spec = {"entry": name, "statics": _statics_spec(statics),
                "avals": aval_spec, "dev": key[3]}
        with self._lock:
            rec = self._programs.get(key)
            if rec is None or rec.compiled is None:
                rec = _Program(compiled, spec, aot=True)
                self._programs[key] = rec
        if fresh and rec.compiled is compiled:
            self._persist(digest, compiled)
        return rec.compiled, rec.spec

    def _fingerprint(self, digest: str) -> dict:
        import jax
        jv, jl = _versions()
        return {"format": 1, "jax": jv, "jaxlib": jl,
                "backend": jax.default_backend(), "key": digest}

    def _path(self, digest: str) -> str:
        return os.path.join(self.cache_dir, f"{digest}.jprog")

    def _persist(self, digest: str, compiled) -> None:
        """Serialize one executable to the cache dir, atomically (tmp
        + os.replace — a kill mid-persist leaves no torn final file,
        only an ignorable tmp). Best-effort: persistence failure never
        fails the dispatch that just succeeded."""
        if not self.cache_dir:
            return
        try:
            from jax.experimental import serialize_executable as se
            payload = se.serialize(compiled)
            blob = {"fingerprint": self._fingerprint(digest),
                    "payload": payload}
            os.makedirs(self.cache_dir, exist_ok=True)
            path = self._path(digest)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(blob, fh)
            os.replace(tmp, path)
        except Exception as err:  # noqa: BLE001 — cache is advisory
            _log.warning("program cache persist failed (%s): %s",
                         self.cache_dir, err)

    def _load_disk(self, digest: str):
        """A persisted executable, or None. Any mismatch — jax/jaxlib
        version, backend, shape-key digest, truncated or unpicklable
        bytes — degrades to a fresh compile with a counted
        load_error: never a crash, never a wrong program."""
        if not self.cache_dir:
            return None
        path = self._path(digest)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as fh:
                blob = pickle.load(fh)
            fp = blob["fingerprint"]
            want = self._fingerprint(digest)
            if fp != want:
                raise ValueError(
                    f"fingerprint mismatch: cached {fp} != {want}")
            from jax.experimental import serialize_executable as se
            t0 = perf_counter()
            with obs.span("serve.compile", program="preload",
                          digest=digest):
                compiled = se.deserialize_and_load(*blob["payload"])
            obs.histogram("serve.compile_secs").observe(
                perf_counter() - t0)
            self._count("preloads")
            return compiled
        except Exception as err:  # noqa: BLE001 — degrade, loudly
            self._count("load_errors")
            _log.warning("program cache load failed (%s) — compiling "
                         "fresh: %s", path, err)
            return None

    # ------------------------------------------------------ manifests

    def manifest(self) -> List[dict]:
        """The AOT program population as JSON-able specs — what
        ``transfer_key`` ships beside the WAL segments."""
        with self._lock:
            return [rec.spec for rec in self._programs.values()
                    if rec.aot and rec.spec is not None]

    def write_manifest(self, path: str) -> int:
        """Persist the population manifest atomically; returns the
        program count (0 writes nothing — no file beats an empty
        one)."""
        specs = self.manifest()
        if not specs:
            return 0
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"format": 1, "programs": specs}, fh)
        os.replace(tmp, path)
        return len(specs)

    def warm_manifest(self, path: str, entries: Dict[str, tuple]) -> int:
        """Pre-warm every program a transferred manifest names —
        BEFORE the adopter replays (docs/streaming.md warm-handoff
        contract). ``entries`` maps entry name -> (jitted, n_traced,
        static_names) (engine.program_entries()). A malformed manifest
        or an unknown entry degrades to the plain first-dispatch
        compile (counted load_errors) — warm handoff is an
        optimization, never a correctness gate. Returns programs
        warmed."""
        try:
            with open(path, encoding="utf-8") as fh:
                specs = json.load(fh).get("programs") or []
        except Exception as err:  # noqa: BLE001
            self._count("load_errors")
            _log.warning("program manifest unreadable (%s): %s",
                         path, err)
            return 0
        warmed = 0
        for spec in specs:
            try:
                if self._warm_spec(spec, entries):
                    warmed += 1
            except Exception as err:  # noqa: BLE001
                self._count("load_errors")
                _log.warning("program manifest entry skipped "
                             "(%s): %s", spec.get("entry"), err)
        if warmed:
            self._count("manifest_warms", warmed)
        return warmed

    def _warm_spec(self, spec: dict, entries: Dict[str, tuple]) -> bool:
        name = spec.get("entry")
        ent = entries.get(name)
        if ent is None:
            return False
        entry, _n_traced, static_names = ent
        if not hasattr(entry, "lower"):
            return False
        statics = _spec_to_statics(spec["statics"])
        key = (name, statics,
               json.dumps(spec["avals"], sort_keys=True),
               spec.get("dev", "host"))
        with self._lock:
            if key in self._programs:
                return False
        shapes = _spec_to_shapes(spec["avals"])
        self._materialize(name, entry, key, statics, static_names,
                          spec["avals"], shapes=shapes)
        return True

    # ------------------------------------------- ladder precompile

    def _maybe_precompile_rung(self, name, entry, key, statics,
                               static_names) -> None:
        """Queue a background compile of the next capacity rung (N
        doubled, same avals) — the program the escalation ladder's
        re-dispatch will ask for. Best-effort and off the dispatch
        path; bounded by the engine's own escalation ceiling."""
        if not precompile_armed() or "N" not in static_names:
            return
        idx = static_names.index("N")
        n = statics[idx]
        if not isinstance(n, int) or n * 2 > _LADDER_CEILING:
            return
        statics2 = statics[:idx] + (n * 2,) + statics[idx + 1:]
        key2 = (name, statics2, key[2], key[3])
        with self._lock:
            if key2 in self._programs or key2 in self._queued:
                return
            self._queued.add(key2)
            self._queue.append((name, entry, key2, statics2,
                                static_names))
            started = self._worker is not None
            if not started:
                self._worker = threading.Thread(
                    target=self._precompile_loop, daemon=True,
                    name="jepsen-programs-precompile")
        if not started:
            self._worker.start()

    def _precompile_loop(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    self._worker = None
                    return
                name, entry, key, statics, static_names = \
                    self._queue.pop(0)
            try:
                shapes = _spec_to_shapes(json.loads(key[2]))
                self._materialize(name, entry, key, statics,
                                  static_names,
                                  json.loads(key[2]), shapes=shapes)
                self._count("precompiles")
            except Exception as err:  # noqa: BLE001 — advisory work
                _log.warning("ladder precompile failed (%s N=%s): %s",
                             name, dict(zip(static_names,
                                            statics)).get("N"), err)
            finally:
                with self._lock:
                    self._queued.discard(key)


# -------------------------------------------------- process singleton

_REG: Optional[ProgramRegistry] = None
_REG_LOCK = threading.Lock()


def registry() -> Optional[ProgramRegistry]:
    """The process ProgramRegistry when JEPSEN_TPU_COMPILE_CACHE arms
    it, else None (every caller then takes the plain jit path — flag
    off is byte-identical)."""
    global _REG
    dest = resolve_cache()
    if dest is None:
        return None
    cache_dir = dest or None
    reg = _REG
    if reg is not None and reg.cache_dir == cache_dir:
        return reg
    # construct outside the module lock (constructor may mkdir), then
    # install; a racing loser's instance is discarded before any use
    fresh = ProgramRegistry(cache_dir)
    with _REG_LOCK:
        if _REG is None or _REG.cache_dir != cache_dir:
            _REG = fresh
        return _REG


def reset() -> None:
    """Drop the process registry — the restart seam tests use to model
    a fresh process against a populated on-disk cache."""
    global _REG
    with _REG_LOCK:
        _REG = None


def track(name: str, traced, statics: tuple) -> None:
    """Population-track a non-AOT engine's program (bitdense, the
    shard_map tiers) when the registry is armed; a no-op otherwise —
    the flag-off path touches nothing."""
    reg = registry()
    if reg is not None:
        reg.track(name, traced, statics)


# ------------------------------------------------- population math


def population_counts(row_counts) -> Dict[str, int]:
    """The program-population arithmetic perf_ab records: distinct
    event-row shapes a workload would compile, exact vs canonicalized
    (no compile, no jax — pure quantum math)."""
    exact = {int(r) for r in row_counts}
    canon = {quantize_rows(r) for r in exact}
    return {"exact": len(exact), "canon": len(canon)}
