"""Host-side encoding: history -> static event/slot tables for the device.

The JIT-linearization search (spec: jepsen_tpu.checker.linear) processes
only **return** events; between two returns the per-config state space is
closed under "linearize any open, unlinearized call". The set of *open*
calls at any moment is determined by the history alone — only *which are
linearized* varies per configuration. So all slot bookkeeping happens
here, once, on the host:

  * every call gets a **window slot** (smallest free at invoke; freed
    after its return filters the frontier; crashed calls hold their slot
    forever),
  * every return event r gets a snapshot of the slot table just before
    it: which slots are occupied and the packed op (f, a0, a1, wild) in
    each.

On device a configuration is then just (state: i32, mask: 2×u32) where
mask bit j = "the call in slot j has linearized" — the fixed-width
replacement for knossos.linear.config's per-config BitSet
(BASELINE.json north_star). Max window = 64 slots; histories needing
more (pathological crash pile-ups) fall back to the host engines
(SURVEY.md §7.3 hard part #1/#4).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Optional

import numpy as np

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import (
    History, Intern, calls as history_calls, prune_wildcard_calls,
)

MAX_SLOTS = 64


@dataclass
class EncodedHistory:
    """Static device input for one key's history. R return events, C slots."""

    slot_f: np.ndarray      # [R, C] i32, f-code of op in slot (-1 empty)
    slot_a0: np.ndarray     # [R, C] i32
    slot_a1: np.ndarray     # [R, C] i32
    slot_wild: np.ndarray   # [R, C] bool
    slot_occ: np.ndarray    # [R, C] bool
    ev_slot: np.ndarray     # [R] i32, slot of the returning call
    ret_call: np.ndarray    # [R] i32, dense call id returning (reporting)
    state0: int
    step_name: str
    n_calls: int
    n_slots: int            # C actually used (<= MAX_SLOTS)
    calls: list             # surviving Call records (host-side reporting)
    intern: Intern          # value table (host-side reporting)
    state_lo: int = -1      # dense state domain: [state_lo, state_lo + S)
    n_states: int = 0
    spec: object = None     # the *prepared* PackedSpec — models whose
    # packing is history-dependent (gset lanes, queue widths) need this
    # exact instance for unpack_state during counterexample extraction
    model_pruned: bool = False  # the model-specific wildcard prune
    # dropped calls AFTER spec.prepare ran — `calls` then no longer
    # equals the list the spec's lane tables were built from, so a
    # prepare re-run over `calls` may assign different lanes (the
    # encode cache refuses to persist such entries: a disk reload
    # could not rebuild an unpack-correct spec)

    @property
    def n_returns(self) -> int:
        return len(self.ev_slot)


class EncodeError(Exception):
    """History can't go to the device; callers fall back to host engines."""


# fs whose constraint is learned at completion, not invocation — the
# counterexample op should report what the system *returned*
OBSERVED_FS = ("read", "dequeue")


def fail_op_fields(e: "EncodedHistory", r: int) -> dict:
    """The knossos-style counterexample op fields for failing return
    event r — shared by every engine's reporting path."""
    c = e.calls[int(e.ret_call[int(r)])]
    v = c.result if (c.f in OBSERVED_FS and not c.crashed) else c.value
    return {"op": {"process": c.process, "f": c.f, "value": v,
                   "index": c.invoke_index},
            "fail-event": int(r)}


@dataclass
class PreparedHistory:
    """Stage-1 encode output: packed per-call ops + slot assignment —
    everything except the [R, C] snapshot tables. The pipelined
    executor (parallel.pipeline) buckets on `n_slots`/`n_states` from
    this stage and defers `finish_encode` (the allocation-heavy table
    fill) into the device-overlapped stream; for any history,
    finish_encode(prepare_encode(model, h)) is array-identical to
    encode(model, h)."""

    cs: list
    intern: Intern
    spec: object
    enc_f: np.ndarray       # [n] per-call packed ops (post-prune)
    enc_a0: np.ndarray
    enc_a1: np.ndarray
    enc_wild: np.ndarray
    r_open: np.ndarray      # [n] first snapshot row while open
    r_close: np.ndarray     # [n] last row (own return / end)
    call_slot: np.ndarray   # [n]
    ev_slot: np.ndarray     # [R]
    ret_call: np.ndarray    # [R]
    n_slots: int
    n_returns: int
    model_pruned: bool = False  # see EncodedHistory.model_pruned

    @property
    def n_states(self) -> int:
        return (self.spec.n_states(self.intern) if self.spec.n_states
                else len(self.intern) + 1)


def prepare_encode(model, history, use_bulk: bool = True) -> PreparedHistory:
    """Stage 1 of encode(): pack the calls and assign window slots.

    Raises EncodeError exactly where encode() would (unpackable model,
    prepare budget, > MAX_SLOTS window). `use_bulk=False` forces the
    row-wise encode_call loop even when the spec has a bulk hook —
    the differential seam tools/perf_encode.py and the parity tests
    drive (both paths must produce identical arrays, including the
    interning order)."""
    intern = Intern()
    spec = model_ns.pack_spec(model, intern)
    if spec is None:
        raise EncodeError(f"model {type(model).__name__} is not device-packable")

    h = history if isinstance(history, History) else History.wrap(history)
    cs = prune_wildcard_calls(history_calls(h))
    if spec.prepare is not None:
        spec.prepare(cs, intern)  # may raise EncodeError (host fallback)

    # per-call packed ops as arrays: the bulk hook when the family has
    # one (the per-call Python loop is the measured constant on the
    # batched e2e path), the row-wise loop otherwise
    if use_bulk and spec.encode_calls is not None:
        enc_f, enc_a0, enc_a1, enc_wild = spec.encode_calls(cs)
        enc_f = np.asarray(enc_f, np.int32)
        enc_a0 = np.asarray(enc_a0, np.int32)
        enc_a1 = np.asarray(enc_a1, np.int32)
        enc_wild = np.asarray(enc_wild, bool)
    else:
        packed = [spec.encode_call(c.f, c.value, c.result, c.crashed)
                  for c in cs]
        enc_f = np.fromiter((pk[0] for pk in packed), np.int32, len(packed))
        enc_a0 = np.fromiter((pk[1] for pk in packed), np.int32, len(packed))
        enc_a1 = np.fromiter((pk[2] for pk in packed), np.int32, len(packed))
        enc_wild = np.fromiter((pk[3] for pk in packed), bool, len(packed))

    # Prune crashed calls that pack to wildcards (identity step, always
    # ok, never returns): they may linearize at any point or never, so
    # dropping them is sound — and each one would otherwise double the
    # frontier's mask space forever. prune_wildcard_calls catches
    # crashed *reads* before the model is known; this generalizes to
    # whatever the model family declares unconstrained (e.g. crashed
    # dequeues with unknown results).
    crashed = np.fromiter((c.crashed for c in cs), bool, len(cs))
    drop = crashed & enc_wild
    model_pruned = bool(drop.any())
    if model_pruned:
        keep = ~drop
        cs = [c for c, k in zip(cs, keep) if k]
        enc_f, enc_a0, enc_a1, enc_wild = (
            enc_f[keep], enc_a0[keep], enc_a1[keep], enc_wild[keep])
        for j, c in enumerate(cs):
            c.index = j

    # events in history order; kind 0=invoke first on ties (an invoke at
    # the same index as a return cannot precede it in a real history —
    # indices are unique — so tie order is moot but deterministic)
    events = []
    for c in cs:
        events.append((c.invoke_index, 0, c.index))
        if not c.crashed:
            events.append((c.complete_index, 1, c.index))
    events.sort()

    # Slot assignment: smallest free slot at invoke, freed after the
    # call's own return row (crashed calls hold theirs to the end).
    free: list = []  # min-heap of free slots
    n_slots = 0
    n = len(cs)
    R = sum(1 for _, k, _ in events if k == 1)
    r_open = np.empty(n, np.int32)    # first snapshot row while open
    r_close = np.full(n, R - 1, np.int32)  # last row (own return / end)
    call_slot = np.empty(n, np.int32)
    ev_slot = np.empty(R, np.int32)
    ret_call = np.empty(R, np.int32)

    r = 0
    for _, kind, cid in events:
        if kind == 0:
            s = heapq.heappop(free) if free else n_slots
            if s == n_slots:
                n_slots += 1
                if n_slots > MAX_SLOTS:
                    raise EncodeError(
                        f"open-call window exceeds {MAX_SLOTS} slots "
                        f"(too many concurrent/crashed calls); use the "
                        f"host engine or partition the history per key")
            call_slot[cid] = s
            r_open[cid] = r
        else:
            s = int(call_slot[cid])
            ev_slot[r] = s
            ret_call[r] = cid
            r_close[cid] = r
            r += 1
            heapq.heappush(free, s)

    return PreparedHistory(
        cs=cs, intern=intern, spec=spec,
        enc_f=enc_f, enc_a0=enc_a0, enc_a1=enc_a1, enc_wild=enc_wild,
        r_open=r_open, r_close=r_close, call_slot=call_slot,
        ev_slot=ev_slot, ret_call=ret_call,
        n_slots=n_slots, n_returns=R, model_pruned=model_pruned)


def finish_encode(prep: PreparedHistory,
                  pad_slots: Optional[int] = None) -> EncodedHistory:
    """Stage 2 of encode(): build the per-return snapshot tables by
    INTERVAL FILL — a call occupying slot s appears identically in
    every snapshot row from the first return after its invoke through
    the row of its own return (snapshots are taken just before the
    returning call is removed, so its own row includes it; crashed
    calls stay to the end). One contiguous slice write per
    (call, column) replaces ten full-width numpy ops per return row —
    encode sits on the e2e bench path, so its constant matters."""
    spec, intern, cs = prep.spec, prep.intern, prep.cs
    n, R, n_slots = len(cs), prep.n_returns, prep.n_slots
    # allocate at the FINAL padded width (pad_slots may exceed n_slots)
    C = max(1, min(MAX_SLOTS, max(pad_slots or n_slots, n_slots)))
    slot_f = np.full((R, C), -1, np.int32)
    slot_a0 = np.full((R, C), -1, np.int32)
    slot_a1 = np.full((R, C), -1, np.int32)
    slot_wild = np.zeros((R, C), bool)
    slot_occ = np.zeros((R, C), bool)
    for cid in range(n):
        a, b = int(prep.r_open[cid]), int(prep.r_close[cid])
        if a > b:
            continue  # invoked after the last return: in no snapshot
        s = int(prep.call_slot[cid])
        slot_occ[a:b + 1, s] = True
        slot_f[a:b + 1, s] = prep.enc_f[cid]
        slot_a0[a:b + 1, s] = prep.enc_a0[cid]
        slot_a1[a:b + 1, s] = prep.enc_a1[cid]
        slot_wild[a:b + 1, s] = prep.enc_wild[cid]

    return EncodedHistory(
        slot_f=slot_f, slot_a0=slot_a0, slot_a1=slot_a1,
        slot_wild=slot_wild, slot_occ=slot_occ,
        ev_slot=prep.ev_slot, ret_call=prep.ret_call,
        state0=spec.state0, step_name=spec.step_name,
        n_calls=len(cs), n_slots=n_slots, calls=cs, intern=intern,
        state_lo=spec.state_lo,
        n_states=prep.n_states,
        spec=spec,
        model_pruned=prep.model_pruned,
    )


def encode(model, history, pad_slots: Optional[int] = None,
           use_bulk: bool = True) -> EncodedHistory:
    """Encode (model, history) for the device engine.

    Raises EncodeError if the model isn't packable or the open-call
    window exceeds MAX_SLOTS. Two stages under the hood
    (prepare_encode -> finish_encode) so the pipelined executor can
    bucket on the cheap stage and overlap the expensive one with
    device work; this one-shot form is their exact composition."""
    return finish_encode(prepare_encode(model, history, use_bulk=use_bulk),
                         pad_slots)


def place_batch(xs: dict, state0, mesh):
    """Explicitly device_put a padded batch onto `mesh`: key axis sharded
    over the first mesh axis when divisible, replicated otherwise. Always
    an *explicit* placement — a batch headed for a mesh must never be
    created on the default backend, which can be a broken TPU runtime
    while the caller is deliberately on a CPU mesh (the MULTICHIP_r01
    crash mode)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ax = mesh.axis_names[0]
    K = len(state0)
    if K % mesh.shape[ax] == 0:
        xs = {k: jax.device_put(v, NamedSharding(
            mesh, P(*((ax,) + (None,) * (v.ndim - 1)))))
            for k, v in xs.items()}
        state0 = jax.device_put(state0, NamedSharding(mesh, P(ax)))
    else:
        rep = NamedSharding(mesh, P())
        xs = jax.device_put(xs, rep)
        state0 = jax.device_put(state0, rep)
    return xs, state0


def pad_batch(encs: list, mesh=None, min_slots: int = 1,
              min_states: int = 0, min_returns: int = 0):
    """Pad per-key encoded histories to one (K, R, C) batch and build the
    scanned arrays; with a mesh the batch is explicitly placed on it via
    `place_batch`. Shared by the sparse, dense, and bitdense batch
    checkers. `min_slots` floors C so engines with a structural minimum
    (bitdense needs one full 32-mask word, C >= 5) get slot tables that
    actually match the C they were compiled for; `min_states` and
    `min_returns` floor S and R the same way (the pipelined executor
    pads every chunk of a bucket to the BUCKET's dims — without the R
    floor each chunk's local max n_returns would be its own jit shape,
    one compile per chunk instead of per bucket). Returns
    (xs, state0, S, C, R)."""
    import jax.numpy as jnp

    S = max(min_states, max(e.n_states for e in encs))
    C = max(min_slots, max(e.slot_f.shape[1] for e in encs))
    R = max(min_returns, max(e.n_returns for e in encs))
    K = len(encs)

    def pad(attr, fill, dtype):
        out = np.full((K, R, C), fill, dtype)
        for k, e in enumerate(encs):
            a = getattr(e, attr)
            out[k, : a.shape[0], : a.shape[1]] = a
        return out

    xs = {
        "slot_f": pad("slot_f", -1, np.int32),
        "slot_a0": pad("slot_a0", -1, np.int32),
        "slot_a1": pad("slot_a1", -1, np.int32),
        "slot_wild": pad("slot_wild", False, bool),
        "slot_occ": pad("slot_occ", False, bool),
    }
    ev = np.full((K, R), -1, np.int32)
    for k, e in enumerate(encs):
        ev[k, : e.n_returns] = e.ev_slot
    xs["ev_slot"] = ev
    state0 = np.array([e.state0 for e in encs], np.int32)

    if mesh is not None:
        xs, state0 = place_batch(xs, state0, mesh)
    else:
        xs = {k: jnp.asarray(v) for k, v in xs.items()}
        state0 = jnp.asarray(state0)
    return xs, state0, S, C, R
