"""Report output helper (reference: jepsen/src/jepsen/report.clj).

`to(filename)` binds stdout to a file for a block:

    with report.to("store/foo/report.txt"):
        print("history:", n, "ops")

Like the reference's thread-local `*out*` rebinding (report.clj:7-16),
the redirect is per-thread: a proxy stdout routes each thread's writes
to that thread's active report file (if any) and everything else to the
real stdout — concurrent worker threads never leak into a report."""

from __future__ import annotations

import contextlib
import io
import os
import sys
import threading

_locals = threading.local()


class _ThreadStdoutProxy(io.TextIOBase):
    """Routes writes to the calling thread's report buffer, else to the
    original stdout."""

    def __init__(self, real):
        self.real = real

    def _target(self):
        return getattr(_locals, "target", None) or self.real

    def write(self, s):
        return self._target().write(s)

    def flush(self):
        self._target().flush()

    def writable(self):
        return True

    def __getattr__(self, name):
        # Delegate everything else (buffer, fileno, encoding, isatty…)
        # to the real stdout so unrelated code keeps working after the
        # proxy is installed.
        return getattr(self.real, name)


_install_lock = threading.Lock()


def _ensure_proxy():
    with _install_lock:
        if not isinstance(sys.stdout, _ThreadStdoutProxy):
            sys.stdout = _ThreadStdoutProxy(sys.stdout)
        return sys.stdout


@contextlib.contextmanager
def to(filename: str):
    """Redirect this thread's stdout to filename for the block,
    creating parent directories (report.clj:7-16)."""
    parent = os.path.dirname(filename)
    if parent:
        os.makedirs(parent, exist_ok=True)
    proxy = _ensure_proxy()
    prev = getattr(_locals, "target", None)
    buf = io.StringIO()
    _locals.target = buf
    try:
        yield
    finally:
        _locals.target = prev
        with open(filename, "w") as f:
            f.write(buf.getvalue())
        (prev or proxy.real).write(f"Report written to {filename}\n")
