"""Per-key independence: lift a single-key test to a map of keys
(reference: jepsen/src/jepsen/independent.clj).

Expensive checkers (linearizability is exponential) only handle short
histories; independence splits one long multi-key history into many
short per-key subhistories. Ops carry `KV(k, v)` tuple values
(independent.clj:21-29); `subhistory` filters + unwraps per key
(independent.clj:250-261); `checker` lifts a checker over every key
(independent.clj:263-314).

TPU mapping (SURVEY.md §2.20 P5): the per-key subhistories are the
natural *batch axis* for the device engine — when the lifted checker is
`Linearizable` with a packable model, all keys are checked in ONE
batched device program (jepsen_tpu.parallel.engine.check_batch) instead
of bounded-pmap'd host processes.
"""

from __future__ import annotations

import logging
from typing import Callable, Iterable, Optional, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import obs
from jepsen_tpu.checker.core import Checker, check_safe, merge_valid
from jepsen_tpu.history import History, Op
from jepsen_tpu.util import bounded_pmap

log = logging.getLogger(__name__)

DIR = "independent"  # results subdirectory (independent.clj:17-19)


class KV(tuple):
    """A [k v] tuple value produced by independent generators — the
    MapEntry analogue (independent.clj:21-29). Subclasses tuple so it
    serializes like a 2-vector, as the reference's history.edn does."""

    __slots__ = ()

    def __new__(cls, k, v):
        return super().__new__(cls, (k, v))

    @property
    def key(self):
        return self[0]

    @property
    def value(self):
        return self[1]


def ktuple(k, v) -> KV:
    return KV(k, v)


def is_tuple(v) -> bool:
    return isinstance(v, KV)


def kv_history(history) -> History:
    """Reinterpret 2-element list/tuple op values as KV tuples — for
    histories loaded from EDN/JSONL, where the reference serializes
    MapEntry values as plain [k v] vectors. Only client ops (integer
    process) are rewrapped: nemesis/info values like ["n1", "n2"] are
    payloads, not keys."""
    out = History()
    for o in history:
        v = o.get("value")
        if (isinstance(o.get("process"), int)
                and not isinstance(v, KV)
                and isinstance(v, (list, tuple)) and len(v) == 2):
            o = Op(o)
            o["value"] = KV(v[0], v[1])
        out.append(o)
    return out


def tuple_gen(k, g):
    """Wraps a generator so its ops carry KV(k, value) values
    (independent.clj:94-99)."""
    def wrap(op):
        o = Op(op)
        o["value"] = KV(k, o.get("value"))
        return o
    return gen.map(wrap, g)


def sequential_generator(keys: Iterable, fgen: Callable):
    """One key at a time: generator for k1 until exhausted, then k2...
    (independent.clj:31-47). fgen must be pure."""
    return [tuple_gen(k, fgen(k)) for k in keys]


def _group_threads(n: int, ctx: gen.Ctx):
    """Partition sorted worker threads into groups of n
    (independent.clj:49-76)."""
    threads = sorted(t for t in ctx.all_threads() if not isinstance(t, str))
    count = len(threads)
    groups = count // n
    assert n <= count, (
        f"With {count} worker threads, concurrent_generator cannot run a "
        f"key with {n} threads concurrently. Raise :concurrency to at "
        f"least {n}.")
    assert count == n * groups, (
        f"concurrent_generator has {count} threads but can only use "
        f"{n * groups} of them to run {groups} concurrent keys with {n} "
        f"threads apiece. Make :concurrency a multiple of {n}.")
    return [threads[i * n:(i + 1) * n] for i in range(groups)]


class LazyKeys:
    """Memoized view over a (possibly infinite) key iterable. Generator
    instances hold an *index* into the shared cache, so a discarded
    generator branch (the soonest-op race in gen.any calls op on every
    alternative and keeps one) never consumes keys — pulling index i
    always yields the same key. Thread-safe."""

    def __init__(self, iterable):
        self._it = iter(iterable)
        self._cache: list = []
        self._done = False
        self._lock = __import__("threading").Lock()

    def get(self, i: int):
        """Key at index i, or None past the end."""
        with self._lock:
            while len(self._cache) <= i and not self._done:
                try:
                    self._cache.append(next(self._it))
                except StopIteration:
                    self._done = True
            return self._cache[i] if i < len(self._cache) else None

    def has(self, i: int) -> bool:
        return self.get(i) is not None


class ConcurrentGenerator(gen.Generator):
    """Splits client threads into groups of n; each group works one key;
    exhausted groups lazily pull the next key. Key sequences may be
    infinite (independent.clj:101-236)."""

    def __init__(self, n, fgen, keys, key_idx=0, group_threads=None,
                 thread_group=None, gens=None):
        self.n = n
        self.fgen = fgen
        self.keys = keys if isinstance(keys, LazyKeys) else LazyKeys(keys)
        self.key_idx = key_idx  # next unconsumed key index
        self.group_threads = group_threads  # list[list[thread]]
        self.thread_group = thread_group    # {thread: group}
        self.gens = gens                    # list[gen|None] per group

    def _init(self, ctx):
        gt = self.group_threads or _group_threads(self.n, ctx)
        tg = self.thread_group or {t: g for g, ts in enumerate(gt) for t in ts}
        idx = self.key_idx
        gens = self.gens
        if gens is None:
            gens = []
            for _ in range(len(gt)):
                k = self.keys.get(idx)
                if k is None:
                    gens.append(None)
                else:
                    gens.append(tuple_gen(k, self.fgen(k)))
                    idx += 1
        return gt, tg, idx, gens

    def op(self, test, ctx):
        gt, tg, idx, gens = self._init(ctx)
        free_groups = {tg[t] for t in ctx.free_threads if t in tg}
        soonest = None
        gens = list(gens)
        for group in free_groups:
            while True:
                g = gens[group]
                gctx = ctx.restrict(lambda t, ts=set(gt[group]): t in ts)
                res = gen.gen_op(g, test, gctx)
                if res is not None:
                    o, g2 = res
                    soonest = gen.soonest_op_map(
                        soonest, {"op": o, "group": group, "gen": g2,
                                  "weight": len(gt[group])})
                    break
                # exhausted: replace with next key's generator, if any
                k = self.keys.get(idx)
                if k is not None:
                    idx += 1
                    gens[group] = tuple_gen(k, self.fgen(k))
                    continue
                gens[group] = None
                break
        if soonest is not None and soonest["op"] is not gen.PENDING:
            out = list(gens)
            out[soonest["group"]] = soonest["gen"]
            return soonest["op"], ConcurrentGenerator(
                self.n, self.fgen, self.keys, idx, gt, tg, out)
        if any(g is not None for g in gens):
            # busy groups may still have ops
            return gen.PENDING, ConcurrentGenerator(
                self.n, self.fgen, self.keys, idx, gt, tg, gens)
        return None

    def update(self, test, ctx, event):
        if self.thread_group is None:
            return self  # not initialized yet; nothing to route
        thread = ctx.process_to_thread(event.get("process"))
        group = self.thread_group.get(thread)
        if group is None or self.gens is None:
            return self
        gens = list(self.gens)
        gens[group] = gen.gen_update(gens[group], test, ctx, event)
        return ConcurrentGenerator(self.n, self.fgen, self.keys,
                                   self.key_idx, self.group_threads,
                                   self.thread_group, gens)



def concurrent_generator(n: int, keys: Iterable, fgen: Callable):
    """Groups of n client threads per key; nemesis excluded by design
    (independent.clj:211-236)."""
    assert n > 0 and isinstance(n, int)
    return gen.clients(ConcurrentGenerator(n, fgen, keys))


# ------------------------------------------------------------ analysis


def history_keys(history) -> list:
    """The set of KV keys in a history, in first-seen order
    (independent.clj:238-248)."""
    seen = set()
    out = []
    for o in history:
        v = o.get("value")
        if isinstance(v, KV) and v.key not in seen:
            seen.add(v.key)
            out.append(v.key)
    return out


def split_history(history) -> dict:
    """One pass over the history, bucketing ops per key (un-keyed ops go
    to every bucket): O(ops + keys), vs. calling subhistory once per key
    which is O(keys * ops). Returns {k: History} in first-seen order."""
    subs: dict = {}
    unkeyed: list = []  # prefix of un-keyed ops for late-appearing keys
    for o in history:
        v = o.get("value")
        if not isinstance(v, KV):
            unkeyed.append(o)
            for h in subs.values():
                h.append(o)
        else:
            k = v.key
            h = subs.get(k)
            if h is None:
                h = subs[k] = History(unkeyed)
            o2 = Op(o)
            o2["value"] = v.value
            h.append(o2)
    return subs


def subhistory(k, history) -> History:
    """All ops without a differing key, tuples unwrapped
    (independent.clj:250-261). Un-keyed ops (nemesis, logging) appear in
    every subhistory."""
    out = History()
    for o in history:
        v = o.get("value")
        if not isinstance(v, KV):
            out.append(o)
        elif v.key == k:
            o2 = Op(o)
            o2["value"] = v.value
            out.append(o2)
    return out


class IndependentChecker(Checker):
    """Lifts a checker over per-key subhistories: valid iff valid for
    all keys; results under {"results": {k: ...}, "failures": [...]}
    (independent.clj:263-314).

    When the wrapped checker is a device-capable Linearizable, the keys
    are checked as one batched device program (the P5 batch axis)
    rather than one host search per key.

    `pipeline` routes that batch through the pipelined executor
    (engine.check_batch(pipeline=...): host encode / transfer / device
    search overlapped, encode cache consulted). None defers to the
    JEPSEN_TPU_PIPELINE env flag — opt-in, results identical either
    way. `dedupe` likewise threads the frontier dedupe strategy to the
    sparse device buckets (None defers to JEPSEN_TPU_DEDUPE; results
    identical either way — engine._resolve_dedupe). `search_stats`
    threads the device-resident search telemetry the same way (None
    defers to JEPSEN_TPU_SEARCH_STATS): each keyed sub-result then
    carries its own per-event "stats" block."""

    def __init__(self, checker: Checker, batch_device: bool = True,
                 pipeline: Optional[bool] = None,
                 dedupe: Optional[str] = None,
                 search_stats: Optional[bool] = None,
                 steal: Optional[bool] = None,
                 reshard: Optional[bool] = None):
        self.checker = checker
        self.batch_device = batch_device
        self.pipeline = pipeline
        self.dedupe = dedupe
        self.search_stats = search_stats
        # elastic scheduling knobs (None = the JEPSEN_TPU_STEAL /
        # JEPSEN_TPU_RESHARD flags): skew-driven key work-stealing in
        # the batched dispatch, device-recruiting escalation for
        # overflow keys — results identical either way
        # (docs/performance.md "Elastic scheduling")
        self.steal = steal
        self.reshard = reshard

    def check(self, test, history, opts=None):
        opts = opts or {}
        # Histories reloaded from EDN/JSONL (the `analyze` path) carry
        # [k v] values as plain lists; by contract every keyed op under
        # an independent checker is a KV, so if none survived
        # serialization, re-wrap — otherwise split_history finds zero
        # keys and the check is vacuously valid.
        if not any(isinstance(o.get("value"), KV) for o in history):
            history = kv_history(history)
        subs = split_history(history)
        ks = list(subs)
        obs.counter("independent.keys").inc(len(ks))

        with obs.span("independent.check", keys=len(ks)):
            results, fallback = self._batched_device_results(test, subs)
            if results is None:
                # per-key host checks run on bounded_pmap threads:
                # propagate the span context so each key's span nests
                # under independent.check
                wrap = obs.ctx_runner()
                checker = self.checker
                if fallback is not None and fallback.get("no-redispatch"):
                    # the backend's breaker is open: the per-key path
                    # must NOT re-dispatch against it (that is the
                    # breaker's whole contract) — per-key checks run a
                    # host-only algorithm until the breaker's recovery
                    # probe readmits the device
                    from jepsen_tpu.checker.linearizable import \
                        Linearizable
                    model = (self.checker.model
                             or (test or {}).get("model"))
                    checker = Linearizable(model, algorithm="packed")

                def check_key(k):
                    with obs.span("independent.key", key=str(k)):
                        return (k, check_safe(
                            checker, test, subs[k],
                            {**opts,
                             "subdirectory":
                                 list(opts.get("subdirectory", []))
                                 + [DIR, k],
                             "history-key": k}))

                pairs = bounded_pmap(wrap(check_key), ks)
                results = dict(pairs)

        self._persist(test, opts, subs, results)
        # only proven-invalid keys; "unknown" (e.g. a crashed per-key
        # checker) is not a failure (independent.clj:305-311)
        failures = [k for k, r in results.items() if r.get("valid?") is False]
        out = {
            "valid?": merge_valid(r.get("valid?") for r in results.values()),
            "results": results,
            "failures": failures,
        }
        if fallback is not None:
            # the reason stays a plain string under the historical key
            # (operators and tests grep it); the structured form —
            # class, backend, breaker interaction — rides "resilience"
            out["device-fallback"] = fallback["reason"]
            out["resilience"] = fallback
        return out

    # -- device batch fast path
    def _batched_device_results(self, test, subs):
        """(results, fallback): results is None when the host per-key
        path should run. A None fallback means the device path was
        simply not applicable (non-device checker, unpackable model);
        otherwise it is a structured dict — {"reason", "class",
        "backend", "no-redispatch"} — saying the device path was
        attempted and FAILED (or was breaker-refused without an
        attempt). That is a loud event (warning + result tag + a
        class-labeled counter), since silently degrading to the host
        checker would hide a TPU regression behind a 100-300x
        slowdown. "no-redispatch" tells check() the backend's breaker
        is open, so the per-key path must not dispatch against it."""
        from jepsen_tpu.checker.linearizable import Linearizable
        c = self.checker
        if not (self.batch_device and isinstance(c, Linearizable)
                and c.algorithm in ("jax", "competition") and subs):
            return None, None
        model = c.model or (test or {}).get("model")
        if model is None:
            return None, None
        from jepsen_tpu import models as model_ns
        from jepsen_tpu.history import Intern
        from jepsen_tpu.parallel import engine
        from jepsen_tpu.parallel.encode import EncodeError
        from jepsen_tpu.resilience import breaker as breaker_mod
        from jepsen_tpu.resilience import supervisor as sup
        try:
            packable = model_ns.pack_spec(model, Intern()) is not None
        except Exception:  # noqa: BLE001 - spec probe blowing up is just
            packable = False  # "not packable": quiet host path, not a crash
        if not packable:
            return None, None
        # a mesh on the test map shards the key axis across devices
        # and lets overflow keys escalate to the frontier-sharded
        # engine (engine._escalate_overflow)
        mesh = (test or {}).get("mesh")
        if mesh is not None:
            import numpy as np
            backend = np.asarray(mesh.devices).flat[0].platform
        else:
            import jax
            backend = jax.default_backend()
        if breaker_mod.any_tripped():
            # consult the breaker BEFORE touching the device: an open
            # breaker means dispatch is refused outright (allow() runs
            # the half-open recovery probe when the backoff elapsed —
            # a recovered runtime readmits itself here)
            allowed, why = breaker_mod.breaker_for(backend).allow()
            if not allowed:
                return None, self._fallback("breaker-open", why,
                                            backend, skip=True)
        try:
            ks = list(subs)
            with obs.span("independent.device_batch", keys=len(ks)):
                rs = engine.check_batch(model, [subs[k] for k in ks],
                                        mesh=mesh, pipeline=self.pipeline,
                                        dedupe=self.dedupe,
                                        search_stats=self.search_stats,
                                        steal=self.steal,
                                        reshard=self.reshard)
            return {k: {**r, "analyzer": "jax"} for k, r in zip(ks, rs)}, None
        except EncodeError as err:
            # legitimately not device-encodable (a gset key past the
            # 31-element budget, a > 64-slot crash pile-up): the host
            # path is correct but 100-300x slower, so still say so
            return None, self._fallback(
                "not-encodable", f"not device-encodable: {err}",
                backend, skip=True)
        except sup.DISPATCH_FAILURES as err:
            cls = ("wedged" if isinstance(err, sup.DispatchWedged)
                   else "breaker-open"
                   if isinstance(err, sup.DeviceUnavailable)
                   else "dispatch-error")
            return None, self._fallback(
                cls, f"{type(err).__name__}: {err}", backend)
        except Exception as err:  # noqa: BLE001 - host path still checks
            return None, self._fallback(
                "dispatch-error", f"{type(err).__name__}: {err}",
                backend)

    @staticmethod
    def _fallback(cls: str, reason: str, backend, skip=False) -> dict:
        """One structured fallback record + its labeled counters.
        `skip` marks paths where the device was never dispatched
        (breaker refusal, un-encodable) vs an attempted-and-FAILED
        dispatch, which warns louder."""
        from jepsen_tpu.resilience import breaker as breaker_mod
        obs.counter("independent.device_fallbacks").inc()
        obs.counter(f"independent.device_fallbacks.{cls}").inc()
        open_now = (cls == "breaker-open"
                    or breaker_mod.breaker_for(backend).state
                    != breaker_mod.CLOSED)
        if skip:
            log.warning("device batch check skipped (%s) — using the "
                        "host per-key checker", reason)
        else:
            log.warning(
                "device batch check FAILED (%s) — falling back to the "
                "host per-key checker; results will be correct but the "
                "TPU path is broken", reason)
        return {"class": cls, "reason": reason, "backend": backend,
                "no-redispatch": open_now}

    # -- results/history persistence per key (independent.clj:292-300)
    def _persist(self, test, opts, subs, results):
        store = (test or {}).get("store")
        if store is None:
            return
        for k in subs:
            try:
                store.write_file([DIR, str(k), "results.edn"],
                                 _edn_pprint(results[k]))
                store.write_file([DIR, str(k), "history.edn"],
                                 subs[k].to_edn())
            except Exception:  # noqa: BLE001
                pass


def _edn_pprint(x) -> str:
    from jepsen_tpu import edn
    return edn.dumps(x) + "\n"


def checker(c: Checker, batch_device: bool = True,
            pipeline: Optional[bool] = None,
            dedupe: Optional[str] = None,
            search_stats: Optional[bool] = None,
            steal: Optional[bool] = None,
            reshard: Optional[bool] = None) -> IndependentChecker:
    return IndependentChecker(c, batch_device, pipeline=pipeline,
                              dedupe=dedupe, search_stats=search_stats,
                              steal=steal, reshard=reshard)
