/* strobe-time-experiment: phase-locked strobe of the system wall clock.
 *
 * Capability parallel of the reference's
 * jepsen/resources/strobe-time-experiment.c:1-205 (its experimental
 * variant of strobe-time, not wired into the nemesis): oscillate the
 * wall clock by +/- delta (ms), flipping every period (ms), for
 * duration (s) — but with ticks PHASE-LOCKED to the monotonic clock:
 * flip k fires at exactly anchor + k*period, by sleeping the remaining
 * distance to the next tick each cycle. A plain sleep(period) loop
 * (strobe-time.c) drifts by the per-iteration syscall cost; over a
 * long strobe the flip frequency sags below 1/period. Phase-locking
 * keeps the long-run flip rate exact, which matters when the strobe
 * period is tuned against a system's clock-sanity window.
 *
 * Like strobe-time.c, the schedule runs on CLOCK_MONOTONIC (immune to
 * our own wall-clock writes) and the flip count is evened out before
 * exit, so a completed strobe is net-zero skew.
 *
 * Exit codes: 0 ok, 1 bad usage, 2 clock syscall failed (needs root).
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <sys/time.h>

static long long NS_PER_MS = 1000000LL;

static long long mono_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static int shift_wall_clock(long long delta_ms) {
  struct timeval tv;
  if (gettimeofday(&tv, NULL) != 0) return -1;
  long long usec = (long long)tv.tv_sec * 1000000LL + tv.tv_usec
                   + delta_ms * 1000LL;
  tv.tv_sec  = usec / 1000000LL;
  tv.tv_usec = usec % 1000000LL;
  if (tv.tv_usec < 0) {
    tv.tv_usec += 1000000LL;
    tv.tv_sec  -= 1;
  }
  return settimeofday(&tv, NULL);
}

/* Sleep until the given monotonic instant (ns); resumes after EINTR. */
static void sleep_until_mono(long long target_ns) {
  for (;;) {
    long long now = mono_ns();
    if (target_ns <= now) return;
    long long left = target_ns - now;
    struct timespec nap = {left / 1000000000LL, left % 1000000000LL};
    if (nanosleep(&nap, NULL) == 0) return;
  }
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
            argv[0]);
    return 1;
  }
  long long delta_ms  = strtoll(argv[1], NULL, 10);
  long long period_ms = strtoll(argv[2], NULL, 10);
  double    duration  = strtod(argv[3], NULL);
  if (period_ms < 1) period_ms = 1;

  long long period_ns = period_ms * NS_PER_MS;
  long long anchor    = mono_ns();
  long long end       = anchor + (long long)(duration * 1e9);
  long long flips     = 0;
  int       sign      = 1;

  /* tick k fires at anchor + k*period: the sleep target is computed
   * from the anchor, never from "now + period", so per-iteration cost
   * cannot accumulate into drift */
  for (long long k = 1; ; k++) {
    long long tick = anchor + k * period_ns;
    if (end < tick) break;
    sleep_until_mono(tick);
    if (shift_wall_clock(sign * delta_ms) != 0) {
      perror("settimeofday");
      return 2;
    }
    sign = -sign;
    flips++;
  }

  if (flips % 2 == 1) { /* undo the dangling half-cycle */
    if (shift_wall_clock(sign * delta_ms) != 0) {
      perror("settimeofday");
      return 2;
    }
  }
  fprintf(stderr, "strobe-time-experiment: %lld flips\n", flips);
  return 0;
}
