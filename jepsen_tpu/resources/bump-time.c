/* bump-time: shift the system wall clock by a signed delta, given in
 * milliseconds, then print the resulting time as decimal unix seconds.
 *
 * Capability parallel of the reference's jepsen/resources/bump-time.c
 * (used by jepsen.nemesis.time, nemesis/time.clj:77-81): the nemesis
 * uploads this source to each node, compiles it with the node's gcc,
 * and invokes it as /opt/jepsen/bump-time <delta-ms>.
 *
 * Exit codes: 0 ok, 1 bad usage, 2 settimeofday failed (needs root).
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 1;
  }

  char *end = NULL;
  long long delta_ms = strtoll(argv[1], &end, 10);
  if (end == argv[1] || *end != '\0') {
    fprintf(stderr, "not a number: %s\n", argv[1]);
    return 1;
  }

  struct timeval tv;
  if (gettimeofday(&tv, NULL) != 0) {
    perror("gettimeofday");
    return 2;
  }

  long long usec = (long long)tv.tv_sec * 1000000LL + tv.tv_usec
                   + delta_ms * 1000LL;
  tv.tv_sec  = usec / 1000000LL;
  tv.tv_usec = usec % 1000000LL;
  if (tv.tv_usec < 0) { /* normalize for negative deltas past a second */
    tv.tv_usec += 1000000LL;
    tv.tv_sec  -= 1;
  }

  if (settimeofday(&tv, NULL) != 0) {
    perror("settimeofday");
    return 2;
  }

  printf("%lld.%06lld\n", (long long)tv.tv_sec, (long long)tv.tv_usec);
  return 0;
}
