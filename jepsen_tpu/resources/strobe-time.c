/* strobe-time: oscillate the system wall clock back and forth by a
 * delta (milliseconds), flipping every period (milliseconds), for a
 * total duration (seconds).
 *
 * Capability parallel of the reference's jepsen/resources/strobe-time.c
 * (invoked by jepsen.nemesis.time, nemesis/time.clj:83-87) as
 * /opt/jepsen/strobe-time <delta-ms> <period-ms> <duration-s>.
 *
 * The strobe is measured against CLOCK_MONOTONIC so the wall-clock
 * manipulation we ourselves perform never confuses the schedule, and
 * the final flip always returns the clock to its original offset
 * (an even number of flips), so a strobe is net-zero skew.
 *
 * Exit codes: 0 ok, 1 bad usage, 2 clock syscall failed (needs root).
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <sys/time.h>

static long long mono_ms(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
}

static int shift_wall_clock(long long delta_ms) {
  struct timeval tv;
  if (gettimeofday(&tv, NULL) != 0) return -1;
  long long usec = (long long)tv.tv_sec * 1000000LL + tv.tv_usec
                   + delta_ms * 1000LL;
  tv.tv_sec  = usec / 1000000LL;
  tv.tv_usec = usec % 1000000LL;
  if (tv.tv_usec < 0) {
    tv.tv_usec += 1000000LL;
    tv.tv_sec  -= 1;
  }
  return settimeofday(&tv, NULL);
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
            argv[0]);
    return 1;
  }
  long long delta_ms  = strtoll(argv[1], NULL, 10);
  long long period_ms = strtoll(argv[2], NULL, 10);
  double    duration  = strtod(argv[3], NULL);
  if (period_ms < 1) period_ms = 1;

  long long start    = mono_ms();
  long long end      = start + (long long)(duration * 1000.0);
  long long flips    = 0;
  int       sign     = 1;

  while (mono_ms() < end) {
    if (shift_wall_clock(sign * delta_ms) != 0) {
      perror("settimeofday");
      return 2;
    }
    sign = -sign;
    flips++;
    struct timespec nap = {period_ms / 1000, (period_ms % 1000) * 1000000L};
    nanosleep(&nap, NULL);
  }

  if (flips % 2 == 1) { /* undo the dangling half-cycle */
    if (shift_wall_clock(sign * delta_ms) != 0) {
      perror("settimeofday");
      return 2;
    }
  }
  return 0;
}
