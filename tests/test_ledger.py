"""Decision ledger + strategy advisor + SLO burn-rate tests (ISSUE 19).

Pins the contracts docs/observability.md "Decision ledger & strategy
advisor" documents:

  * rotation at the byte cap + retention bound (the DeltaWAL-precedent
    segment format);
  * torn-tail tolerance: a restart truncates the never-promised
    partial line and appends cleanly; mid-file garbage is skipped and
    counted, never raised;
  * flag-off byte parity: JEPSEN_TPU_LEDGER unset mints no metric,
    touches no file, and leaves engine results identical;
  * the advisor is deterministic on the committed fixtures (incl. the
    insufficient-evidence floor) — byte-identical to the committed
    golden plan;
  * `jepsen report --plan` exit codes 0 / 1 / 254;
  * the SLO burn-rate tracker's two-window math with an injected
    clock, and its /healthz arming contract.
"""

import json
import os

import pytest

from jepsen_tpu import envflags, obs
from jepsen_tpu.obs import advisor, ledger
from jepsen_tpu.obs import slo

DATA = os.path.join(os.path.dirname(__file__), "data")
LEDGER_FIXTURE = os.path.join(DATA, "ledger_fixture")
BENCH_FIXTURE = os.path.join(DATA, "bench_fixture")


@pytest.fixture(autouse=True)
def _clean_ledger(monkeypatch):
    monkeypatch.delenv("JEPSEN_TPU_LEDGER", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_LEDGER_SEGMENT_BYTES", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_LEDGER_SEGMENTS", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_LEDGER_FLOOR", raising=False)
    ledger.reset()
    obs.registry().reset()
    yield
    ledger.reset()
    obs.registry().reset()


def _fill(led, n, kind="dispatch", **extra):
    for i in range(n):
        led.record(kind, engine="test",
                   shape={"family": "reg", "C": 6},
                   strategy={"dedupe": "hash"},
                   secs=0.01, pad="x" * 64, **extra)


# ------------------------------------------------ writer / durability


def test_rotation_at_byte_cap_and_retention(tmp_path):
    led = ledger.DecisionLedger(str(tmp_path), segment_bytes=512,
                                max_segments=3)
    _fill(led, 60)
    led.close()
    paths = ledger.segment_paths(str(tmp_path))
    # rotation happened (60 records of ~200 bytes >> 512), and
    # retention kept the bound: at most max_segments sealed + the
    # newest active
    assert len(paths) > 1
    assert len(paths) <= 3 + 1
    # every retained segment stays near the cap (one record overshoot)
    for p in paths[:-1]:
        assert os.path.getsize(p) <= 512 + 4096
    assert ledger.size_bytes(str(tmp_path)) \
        <= (3 + 1) * (512 + 4096)
    # the retained tail is still fully readable, newest records last
    recs, corrupt = ledger.read_records(str(tmp_path))
    assert corrupt == 0
    assert recs
    assert recs[-1]["n"] == 60
    # rotation + retention were counted
    snap = obs.registry().snapshot()
    assert snap["obs.ledger.rotations"]["value"] >= 1
    assert snap["obs.ledger.drops"]["value"] >= 1


def test_torn_tail_truncated_on_restart(tmp_path):
    led = ledger.DecisionLedger(str(tmp_path))
    _fill(led, 5)
    led.close()
    active = ledger.segment_paths(str(tmp_path))[-1]
    with open(active, "a") as fh:
        fh.write('{"v": 1, "kind": "disp')   # the torn crash tail
    # restart: the partial line is truncated BEFORE the first append,
    # so the new record never concatenates onto partial bytes
    led2 = ledger.DecisionLedger(str(tmp_path))
    _fill(led2, 1)
    led2.close()
    recs, corrupt = ledger.read_records(str(tmp_path))
    assert corrupt == 0
    assert [r["kind"] for r in recs] == ["dispatch"] * 6
    snap = obs.registry().snapshot()
    assert snap["obs.ledger.corrupt_lines"]["value"] == 1


def test_mid_file_garbage_skipped_and_counted(tmp_path):
    led = ledger.DecisionLedger(str(tmp_path))
    _fill(led, 3)
    led.close()
    active = ledger.segment_paths(str(tmp_path))[-1]
    lines = open(active).read().splitlines()
    lines.insert(1, "%% not json %%")
    with open(active, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    recs, corrupt = ledger.read_records(str(tmp_path))
    assert corrupt == 1
    assert len(recs) == 3          # a hole costs evidence, never a read


def test_record_drops_none_fields_and_sorts_keys(tmp_path):
    led = ledger.DecisionLedger(str(tmp_path))
    led.record("dispatch", engine="test", secs=None, stats=None,
               keys=2)
    led.close()
    line = open(ledger.segment_paths(str(tmp_path))[-1]).read().strip()
    rec = json.loads(line)
    assert "secs" not in rec and "stats" not in rec   # absent, not null
    assert rec["keys"] == 2
    assert line == json.dumps(rec, sort_keys=True)


# ------------------------------------------------ flag / singleton


def test_flag_off_is_byte_parity(tmp_path, monkeypatch):
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import extend

    ops = list(rand_register_history(n_ops=16, n_processes=3,
                                     n_values=3, seed=5))

    def run():
        s = extend.HistorySession(CASRegister(), capacity=64,
                                  key="parity")
        s.extend(ops)
        return s.check()

    assert ledger.active() is None
    r_off = run()
    # nothing minted, nothing written
    snap = obs.registry().snapshot()
    assert not any(k.startswith("obs.ledger") for k in snap)
    assert list(tmp_path.iterdir()) == []

    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    ledger.reset()
    r_on = run()
    assert r_on == r_off            # evidence never changes results
    recs, _ = ledger.read_records(str(tmp_path))
    assert [r["kind"] for r in recs] == ["dispatch"]
    assert recs[0]["engine"] == "stream"
    assert recs[0]["outcome"]["verdict"] in ("valid", "invalid")
    assert isinstance(recs[0]["secs"], float)


def test_malformed_flag_raises_loudly(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", "   ")
    ledger.reset()
    with pytest.raises(envflags.EnvFlagError):
        ledger.active()


def test_flag_1_means_default_dir(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", "1")
    assert ledger.resolve_ledger_dir() == ledger.DEFAULT_DIR
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", "0")
    assert ledger.resolve_ledger_dir() is None


def test_record_helper_noop_when_off():
    ledger.record("dispatch", engine="test")   # must not raise


# ------------------------------------------------ aggregate / doc


def test_aggregate_newest_wins_per_cell():
    recs = [
        {"t": 1.0, "n": 1, "kind": "dispatch", "engine": "e",
         "shape": {"C": 6}, "strategy": {"dedupe": "hash"},
         "secs": 0.1, "outcome": {"verdict": "valid"}},
        {"t": 2.0, "n": 2, "kind": "dispatch", "engine": "e",
         "shape": {"C": 6}, "strategy": {"dedupe": "hash"},
         "secs": 0.3, "outcome": {"verdict": "invalid"}},
        {"t": 1.5, "n": 3, "kind": "dispatch", "engine": "e",
         "shape": {"C": 6}, "strategy": {"dedupe": "sort"},
         "secs": 0.2},
    ]
    cells = ledger.aggregate(recs)
    assert len(cells) == 2
    hash_cell = cells["e/dispatch C=6|dedupe=hash"]
    assert hash_cell["count"] == 2
    assert hash_cell["newest"]["n"] == 2
    assert hash_cell["mean_secs"] == 0.2


def test_ledger_doc_off_and_on(tmp_path, monkeypatch):
    assert ledger.ledger_doc() == {"ledger": {"enabled": False},
                                   "cells": {}}
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    ledger.reset()
    ledger.record("dispatch", engine="e", shape={"C": 4},
                  strategy={"dedupe": "sort"}, secs=0.5)
    doc = ledger.ledger_doc()
    assert doc["ledger"]["enabled"] is True
    assert doc["ledger"]["records"] == 1
    assert doc["ledger"]["segments"] == 1
    assert len(doc["cells"]) == 1


def test_httpd_ledger_endpoint(tmp_path, monkeypatch):
    from jepsen_tpu.obs import httpd

    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    ledger.reset()
    ledger.record("dispatch", engine="e", shape={"C": 4},
                  strategy={"dedupe": "sort"}, secs=0.5)
    srv = httpd.start_ops_server(0)
    try:
        code, body = httpd._fetch(srv.url("/ledger"))
        doc = json.loads(body)
        assert code == 200
        assert doc["ledger"]["enabled"] is True
        assert doc["cells"]
    finally:
        srv.close()


# ------------------------------------------------ the advisor


def _fixture_inputs():
    recs, corrupt = ledger.read_records(LEDGER_FIXTURE)
    assert corrupt == 0
    bench = advisor.load_bench_dir(BENCH_FIXTURE)
    return recs, bench


def test_advisor_plan_matches_committed_golden():
    recs, bench = _fixture_inputs()
    plan = advisor.build_plan(recs, bench, floor=3)
    text = advisor.render_plan(plan)
    golden = open(os.path.join(LEDGER_FIXTURE,
                               "plan_golden.txt")).read()
    assert text == golden
    # and twice over: nothing timestamps or reorders the output
    assert advisor.render_plan(
        advisor.build_plan(recs, bench, floor=3)) == text


def test_advisor_recommends_only_at_the_floor():
    recs, bench = _fixture_inputs()
    plan = advisor.build_plan(recs, bench, floor=3)
    by_shape = {s["shape"]: s for s in plan["shapes"]}
    sparse = by_shape["engine=sparse,family=register_step,C=6"]
    assert sparse["recommend"] == \
        "closure=pallas,dedupe=hash,pack=True,probe_limit=None"
    # the fixture carries a kind=plan record whose newest ONLINE
    # decision picked this vector — the live-table tier outranks
    # bench agreement
    assert sparse["confidence"] == "auto-online"
    dense = by_shape["engine=bitdense,family=register_step,C=6"]
    assert dense["recommend"] is None
    assert "insufficient evidence" in dense["confidence"]
    # raising the floor past every cell refuses everywhere — the
    # advisor never guesses
    plan_hi = advisor.build_plan(recs, bench, floor=100)
    assert all(s["recommend"] is None for s in plan_hi["shapes"])
    # floor=1 lets the 2-sample bitdense cell through
    plan_lo = advisor.build_plan(recs, bench, floor=1)
    by_shape = {s["shape"]: s for s in plan_lo["shapes"]}
    assert by_shape["engine=bitdense,family=register_step,C=6"][
        "recommend"] is not None


def test_advisor_bench_disagreement_is_named():
    recs = [{"t": 1.0, "n": i, "kind": "dispatch", "engine": "e",
             "shape": {"family": "f", "C": 4},
             "strategy": {"dedupe": "sort"}, "secs": 0.1}
            for i in range(3)]
    bench = [{"shape": "s", "sort_secs": 1.0, "hash_secs": 0.2}]
    plan = advisor.build_plan(recs, bench, floor=3)
    assert plan["shapes"][0]["confidence"] == "bench-prefers-hash"


def test_advisor_empty_ledger_renders_hint():
    text = advisor.render_plan(advisor.build_plan([], [], floor=3))
    assert "no dispatch evidence" in text


def test_advisor_auto_online_confidence_tiers():
    # the fourth confidence tier (ISSUE 20): a kind=plan record whose
    # newest ONLINE decision picked the join's winning vector upgrades
    # the group; seeded sources and disagreeing vectors never do
    recs = [{"t": 1.0, "n": i, "kind": "dispatch", "engine": "e",
             "shape": {"family": "f", "C": 4},
             "strategy": {"dedupe": "hash"}, "secs": 0.1}
            for i in range(3)]
    agree = {"t": 2.0, "n": 9, "kind": "plan", "engine": "e",
             "shape": {"family": "f", "C": 4},
             "strategy": {"dedupe": "hash"}, "source": "online",
             "explored": False, "cell_n": 3}
    plan = advisor.build_plan(recs + [agree], [], floor=3)
    assert plan["shapes"][0]["confidence"] == "auto-online"
    # a seeded decision is bench-derived, not fleet-live evidence
    plan = advisor.build_plan(
        recs + [dict(agree, source="seeded")], [], floor=3)
    assert plan["shapes"][0]["confidence"] == "ledger-only"
    # a vector the join does NOT recommend claims no agreement
    disagree = dict(agree, strategy={"dedupe": "sort"})
    plan = advisor.build_plan(recs + [disagree], [], floor=3)
    assert plan["shapes"][0]["confidence"] == "ledger-only"
    # newest wins, the aggregation order: an older agreement
    # superseded by a disagreeing decision reads the newest one
    plan = advisor.build_plan(recs + [agree, disagree], [], floor=3)
    assert plan["shapes"][0]["confidence"] == "ledger-only"


def test_advisor_auto_table_rides_along():
    # report --plan hands the durable plan_table.json through
    # verbatim under "auto", and render_plan gains its section
    table = {"version": 1, "floor": 3,
             "groups": {"engine=e,family=f,C=4": {
                 "decisions": 5, "cells": {"dedupe=hash": {
                     "arm": {"dedupe": "hash"}, "ewma": 0.1,
                     "n": 4, "n_live": 2, "seeded": True}}}}}
    plan = advisor.build_plan([], [], floor=3, auto_table=table)
    assert plan["auto"] == table
    text = advisor.render_plan(plan)
    assert "Auto planner live table" in text
    assert "dedupe=hash" in text
    # without a table the section is absent — historical renders
    # stay byte-identical
    assert "Auto planner live table" not in advisor.render_plan(
        advisor.build_plan([], [], floor=3))


# ------------------------------------------------ report --plan


def test_report_plan_exit_codes(tmp_path, capsys):
    from jepsen_tpu.obs import search_report

    # 0: evidence present (fixture dir; --stdout-only keeps the
    # committed fixture pristine)
    rc = search_report.report_main(
        ["--plan", "--ledger-dir", LEDGER_FIXTURE,
         "--bench-dir", BENCH_FIXTURE, "--stdout-only"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "recommend: closure=pallas,dedupe=hash" in out
    assert "insufficient evidence" in out

    # 1: no records at the named dir
    empty = tmp_path / "empty"
    empty.mkdir()
    assert search_report.report_main(
        ["--plan", "--ledger-dir", str(empty)]) == 1

    # 254: no mode selected at all
    assert search_report.report_main([]) == 254


def test_report_plan_json_output(tmp_path, capsys):
    # --json prints the machine-readable plan document (satellite of
    # ISSUE 20): schema pinned here, exit codes unchanged
    from jepsen_tpu.obs import search_report

    rc = search_report.report_main(
        ["--plan", "--ledger-dir", LEDGER_FIXTURE,
         "--bench-dir", BENCH_FIXTURE, "--stdout-only", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == advisor.PLAN_VERSION
    assert {"floor", "shapes", "bench", "gates",
            "ledger_records"} <= set(doc)
    by = {s["shape"]: s for s in doc["shapes"]}
    sparse = by["engine=sparse,family=register_step,C=6"]
    assert sparse["confidence"] == "auto-online"
    assert sparse["recommend"].startswith("closure=pallas")
    # exit codes unchanged by --json
    empty = tmp_path / "empty"
    empty.mkdir()
    assert search_report.report_main(
        ["--plan", "--ledger-dir", str(empty), "--json"]) == 1


def test_report_plan_writes_artifacts(tmp_path):
    import shutil

    from jepsen_tpu.obs import search_report

    work = tmp_path / "led"
    shutil.copytree(LEDGER_FIXTURE, work)
    rc = search_report.report_main(
        ["--plan", "--ledger-dir", str(work),
         "--bench-dir", BENCH_FIXTURE])
    assert rc == 0
    plan = json.loads((work / "plan.json").read_text())
    assert plan["version"] == advisor.PLAN_VERSION
    assert (work / "plan_report.txt").read_text().startswith(
        "# Strategy plan")


# ------------------------------------------------ SLO burn rates


def _observe(name, values):
    h = obs.histogram(name)
    for v in values:
        h.observe(v)


def test_burn_rate_two_windows_injected_clock():
    name = "test.slo.ack_secs"
    tr = slo.BurnRateTracker(hist_name=name, target_secs=0.1,
                             burn_max=10.0, fast_window=10.0,
                             slow_window=100.0)
    assert tr.armed
    now = 0.0
    tr.sample(now=now)
    # 98 good, 2 bad out of 100: bad fraction 0.02 over the 1% budget
    # = burn 2.0 in both windows
    _observe(name, [0.01] * 98 + [5.0] * 2)
    now = 5.0
    b = tr.sample(now=now)
    assert b == {"fast": 2.0, "slow": 2.0}
    assert tr.check()["ok"] is True       # 2.0 under burn_max 10
    # an all-bad burst: the fast window sees only the burst (burn
    # 100), the slow window still amortizes over everything
    now = 20.0
    tr.sample(now=now)
    _observe(name, [5.0] * 10)
    now = 25.0
    b = tr.sample(now=now)
    assert b["fast"] == 100.0
    assert b["slow"] < b["fast"]
    chk = tr.check()
    assert chk["ok"] is False             # past burn_max
    assert chk["burn_fast"] == 100.0
    # idle: no traffic in the fast window burns nothing
    now = 40.0
    b = tr.sample(now=now)
    assert b["fast"] == 0.0
    assert tr.check()["ok"] is True
    # the gauges were published, labeled per window
    snap = obs.registry().snapshot()
    assert obs.labeled("serve.slo.ack_burn_rate", window="fast") in snap
    assert obs.labeled("serve.slo.ack_burn_rate", window="slow") in snap


def test_burn_rate_off_ladder_target_rounds_down():
    # 0.15 is off the bucket ladder: goodness is judged at the next
    # ladder bound DOWN, so a 0.12s ack counts as bad (conservative)
    name = "test.slo.offladder"
    tr = slo.BurnRateTracker(hist_name=name, target_secs=0.15,
                             fast_window=10.0, slow_window=100.0)
    tr.sample(now=0.0)
    _observe(name, [0.12] * 100)
    assert tr.sample(now=1.0)["fast"] == 100.0


def test_slo_unarmed_mints_nothing():
    tr = slo.BurnRateTracker(hist_name="test.slo.unarmed")
    assert not tr.armed
    assert tr.sample() is None
    snap = obs.registry().snapshot()
    assert not any("slo" in k for k in snap)


def test_service_healthz_slo_arming(monkeypatch):
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.serve.service import CheckerService

    svc = CheckerService(CASRegister(), start_worker=False)
    try:
        assert "slo" not in svc.health()["checks"]   # unarmed: absent
    finally:
        svc.close(drain=False)

    monkeypatch.setenv("JEPSEN_TPU_SLO_ACK_SECS", "0.5")
    monkeypatch.setenv("JEPSEN_TPU_SLO_BURN_MAX", "5")
    svc = CheckerService(CASRegister(), start_worker=False)
    try:
        svc.refresh_gauges()
        h = svc.health()
        chk = h["checks"]["slo"]
        assert chk["ok"] is True
        assert chk["target_secs"] == 0.5
        assert chk["burn_max"] == 5.0
    finally:
        svc.close(drain=False)
