"""Control-plane tests: escaping, local/dummy remotes, fan-out, daemon
helpers, net fault plane, db cycle (reference: control.clj /
control/util.clj / net.clj / db.clj test strategy — dummy remote per
SURVEY.md §4.5)."""

import os
import tempfile
import time

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import db as jdb
from jepsen_tpu import net as jnet
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import (
    DummyRemote, LocalRemote, RemoteError, escape, lit,
)
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op


# ------------------------------------------------------------ escaping


def test_escape_plain():
    assert escape("foo") == "foo"
    assert escape(42) == "42"
    assert escape("a/b-c_d.e") == "a/b-c_d.e"


def test_escape_quoting():
    assert escape("hello world") == "'hello world'"
    assert "it's" in __import__("shlex").split(escape("it's"))
    assert escape("") == "''"


def test_escape_lit_passthrough():
    assert escape(lit("a | b")) == "a | b"


def test_escape_nested_collection():
    assert escape(["a", "b c"]) == "a 'b c'"


# -------------------------------------------------------- local remote


def local_session():
    return LocalRemote().connect({"host": "localhost"})


def test_local_exec():
    with c.on_host(local_session(), "localhost"):
        assert c.exec_("echo", "hello") == "hello"


def test_local_exec_escaping():
    with c.on_host(local_session(), "localhost"):
        assert c.exec_("echo", "two words") == "two words"
        assert c.exec_("printf", "%s", "a;b|c") == "a;b|c"


def test_local_exec_error():
    with c.on_host(local_session(), "localhost"):
        with pytest.raises(RemoteError) as ei:
            c.exec_("false")
        assert ei.value.exit == 1


def test_local_cd():
    with c.on_host(local_session(), "localhost"):
        with c.cd("/tmp"):
            assert c.exec_("pwd") == "/tmp"


def test_local_lit_pipeline():
    with c.on_host(local_session(), "localhost"):
        out = c.exec_("bash", "-c", "echo -e 'b\\na' | sort | head -1")
        assert out == "a"


def test_upload_download(tmp_path):
    src = tmp_path / "src.txt"
    src.write_text("payload")
    dst = tmp_path / "dst.txt"
    s = local_session()
    s.upload([str(src)], str(dst))
    assert dst.read_text() == "payload"
    back = tmp_path / "back.txt"
    s.download([str(dst)], str(back))
    assert back.read_text() == "payload"


# -------------------------------------------------------- dummy remote


def test_dummy_remote_records():
    d = DummyRemote()
    with c.on_host(d.connect({}), "n1"):
        assert c.exec_("rm", "-rf", "/") == ""  # harmless on a dummy
    assert d.log == ["rm -rf /"]


def test_remote_for_test_dummy():
    t = {"ssh": {"dummy": True}}
    assert isinstance(c.remote_for_test(t), DummyRemote)


# -------------------------------------------------------------- fanout


def test_on_nodes_parallel():
    d = DummyRemote()
    test = {"nodes": ["n1", "n2", "n3"], "remote": d}

    def f(t, node):
        return c.exec_("hostname") or node

    out = c.on_nodes(test, f)
    assert set(out) == {"n1", "n2", "n3"}


def test_sessions_context():
    test = {"nodes": ["n1", "n2"], "remote": DummyRemote()}
    with c.with_sessions(test) as s:
        assert set(s.sessions) == {"n1", "n2"}
        s.on("n1", ["uptime"])
    assert "sessions" not in test


# ------------------------------------------------------ daemon helpers


def test_daemon_lifecycle(tmp_path):
    pidfile = str(tmp_path / "d.pid")
    logfile = str(tmp_path / "d.log")
    with c.on_host(local_session(), "localhost"):
        started = cu.start_daemon(
            {"pidfile": pidfile, "logfile": logfile, "chdir": "/tmp"},
            "sleep", "30")
        assert started
        time.sleep(0.2)
        assert cu.daemon_running(pidfile)
        # second start is a no-op
        assert not cu.start_daemon({"pidfile": pidfile}, "sleep", "30")
        cu.stop_daemon(pidfile)
        assert not cu.daemon_running(pidfile)
        assert not os.path.exists(pidfile)


def test_file_exists(tmp_path):
    f = tmp_path / "x"
    with c.on_host(local_session(), "localhost"):
        assert not cu.file_exists(str(f))
        f.write_text("1")
        assert cu.file_exists(str(f))


def test_await_tcp_port_timeout():
    with c.on_host(local_session(), "localhost"):
        with pytest.raises(TimeoutError):
            cu.await_tcp_port(1, timeout_s=0.5, interval_s=0.1)


# ----------------------------------------------------------- net + db


def test_memnet_partition_via_nemesis():
    net = jnet.mem()
    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"], "net": net}
    p = nem.partition_random_halves().setup(test)
    assert not net.partitioned()
    r = p.invoke(test, Op({"type": "invoke", "f": "start", "value": None,
                           "process": "nemesis"}))
    assert r["type"] == "info"
    assert net.partitioned()
    # some cross-half pair is unreachable, intra-half reachable
    dropped = net.dropped
    assert dropped
    r = p.invoke(test, Op({"type": "invoke", "f": "stop", "value": None,
                           "process": "nemesis"}))
    assert not net.partitioned()


def test_majorities_ring_grudge_properties():
    nodes = ["n1", "n2", "n3", "n4", "n5"]
    g = nem.majorities_ring(nodes)
    assert set(g) == set(nodes)
    for node, dropped in g.items():
        visible = set(nodes) - set(dropped)
        assert node in visible
        assert len(visible) >= 3  # every node sees a majority
    # no two nodes see the same majority
    views = {frozenset(set(nodes) - set(d)) for d in g.values()}
    assert len(views) == len(nodes)


def test_db_cycle_with_noop():
    test = {"nodes": ["n1", "n2"], "remote": DummyRemote()}
    jdb.cycle(jdb.noop(), test)


def test_db_cycle_retries_setup_failed():
    class Flaky(jdb.DB):
        def __init__(self):
            self.attempts = 0

        def setup(self, test, node):
            if node == "n1":
                self.attempts += 1
                if self.attempts < 3:
                    raise jdb.SetupFailed("not yet")

        def teardown(self, test, node):
            pass

    test = {"nodes": ["n1"], "remote": DummyRemote()}
    jdb.cycle(Flaky(), test)  # succeeds on third attempt
